"""Configuration: TOML file + environment overlay.

Mirrors the reference's config system (``crates/corro-types/src/config.rs``):
a TOML file with sections ``db / api / gossip / perf / admin / telemetry /
log / consul`` (``config.rs:63-81``), an environment-variable overlay using
the ``__`` separator (``config.rs:326-332``), a ``PerfConfig`` section that
centralizes every queue length / pool size (``config.rs:200-257``), and a
builder for tests (``config.rs:335-456``).

TPU reframing: the ``[sim]`` section (no reference analog) selects the
simulator model and cluster scale; ``[perf]`` holds the bounded-pool
shapes that the reference expresses as channel capacities; ``[gossip]``
carries the protocol knobs plus the network-condition model that the
reference gets implicitly from real sockets.
"""

from __future__ import annotations

import dataclasses
import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: stdlib tomllib is absent
    import tomli as tomllib
from typing import Any, Optional

ENV_PREFIX = "CORRO_TPU"


@dataclasses.dataclass
class DbConfig:
    """Where state lives on the host (``config.rs`` ``db.path`` etc.).

    The SQLite file's role — the durable checkpoint — is played by
    checkpoint directories (see ``checkpoint.py``)."""

    path: str = "./corro_tpu_state"
    schema_paths: tuple = ()
    # auto-checkpoint cadence in rounds (WAL-checkpoint analog); 0 = off
    checkpoint_rounds: int = 0
    # membership persistence (the __corro_members table analog,
    # broadcast/mod.rs:814-949): the maintenance loop dumps the member
    # list here; a booting agent bootstraps its SWIM views from it
    # (initialise_foca's ApplyMany-from-DB, util.rs:69-130). "" = off
    members_path: str = ""


@dataclasses.dataclass
class ApiConfig:
    """HTTP API listener (``config.rs`` ``api.bind_addr``)."""

    addr: str = "127.0.0.1"
    port: int = 8787


@dataclasses.dataclass
class GossipConfig:
    """Protocol + network-model knobs (``config.rs`` ``gossip``)."""

    bootstrap: tuple = ()  # seed node ids (DNS list analog)
    cluster_id: int = 0
    drop_prob: float = 0.01
    n_regions: int = 1  # geographic regions feeding the RTT rings
    idle_rounds: int = 16  # announce interval analog
    plaintext: bool = True  # no TLS in the simulator


@dataclasses.dataclass
class PerfConfig:
    """Bounded-pool shapes (``PerfConfig``, ``config.rs:200-257``)."""

    buf_slots: int = 32  # out-of-order version buffer (processing queue cap)
    bcast_queue: int = 32  # pending-broadcast slots
    recv_slots: int = 96  # per-round apply mailbox (full sim)
    pig_changes: int = 4  # changesets per packet (scale sim)
    sync_chunk: int = 32  # versions per (peer, origin) sync pull
    sync_interval: int = 8
    sync_peers: int = 2
    bcast_fanout: int = 5
    bcast_max_transmissions: int = 4
    # donate the live round loop's scan carry to each dispatch (the
    # boundary never holds two device copies of the state — at flagship
    # scale the carry IS the HBM working set). Readers copy under the
    # agent's state lease; a supervised agent without auto_recover
    # keeps donation off (no re-upload story). Debug switch: False
    # restores the double-buffered (two-copy) round loop.
    donate_rounds: bool = True
    # fused megakernel path (ops/megakernel.py, docs/fused.md):
    # "auto" = pallas kernels on non-CPU backends when the eager probes
    # pass; "on"/"off" pin the fused/XLA path; "interpret" runs the
    # fused kernels in pallas interpret mode on any backend (the
    # tier-1 parity/testing mode). Threaded onto the sim config as
    # ``cfg.fused`` — execution only, results are bitwise identical
    fused: str = "auto"
    # quiescence-aware active-set rounds (corroquiet, docs/fused.md):
    # "auto" = the host plane picks the quiet step for all-quiet
    # segments; "on" pins the active-set scan body; "off" pins dense.
    # Threaded as ``cfg.quiet`` — execution only, quiet == dense bitwise
    quiet: str = "auto"
    # dense-round backstop cadence while quiet; 0 = sync_interval
    quiet_backstop_interval: int = 0


@dataclasses.dataclass
class SimConfigSection:
    """Simulator model + scale (TPU-specific section)."""

    mode: str = "scale"  # "full" (O(N^2) faithful) | "scale" (bounded tables)
    n_nodes: int = 256
    m_slots: int = 64
    n_origins: int = 16
    n_rows: int = 16
    n_cols: int = 4
    seed: int = 0


@dataclasses.dataclass
class PgConfig:
    """PostgreSQL wire listener (``config.rs`` ``api.pg``)."""

    enabled: bool = False
    addr: str = "127.0.0.1"
    port: int = 5432


@dataclasses.dataclass
class ServeConfig:
    """corroguard overload policy for the serving plane
    (``api/admission.py``, docs/overload.md).

    ``max_inflight`` <= 0 disables admission control entirely (the
    unguarded plane); with it on, each route class (write / read /
    stream / pg) admits at most ``max_inflight`` concurrent requests,
    queues up to ``max_queue`` more for ``queue_wait`` seconds, and
    sheds the rest with 503 + Retry-After derived from the live
    latency histograms.

    The non-zero defaults are DERIVED from the committed two-arm
    overload measurement (``BENCH_SERVE_r17.json``): the guarded arm
    held delivery p99 = 1.75 s <= the 2.5 s contract bound while the
    unguarded arm blew it 3.6x (9.0 s) under the same load. The
    arithmetic lives in docs/overload.md ("Default caps"); change a
    default only together with that derivation. ``0`` stays the
    explicit unlimited opt-out per knob — :meth:`unlimited` returns
    the all-off policy (the pre-r18 behavior)."""

    # 2x the concurrency the r17 guarded arm absorbed with zero sheds
    # at cap 3 (stage 0), sized to absorb its breaking stage (8
    # writers) without shedding; <=0 = admission off
    max_inflight: int = 8
    # floor(max_inflight * queue_wait / 0.117 s measured p50 write
    # service): the deepest queue that still drains inside queue_wait
    max_queue: int = 16
    # stream/pg tickets are held for the WHOLE stream / wire connection,
    # so long-lived classes get their own capacity instead of starving
    # one-shot requests out of max_inflight; <=0 inherits max_inflight.
    # 8x the write cap — the r17 rig's stream:inflight ratio (32:3),
    # rounded down to a power of two
    max_streams: int = 64
    queue_wait: float = 0.25  # seconds a queued request waits for a slot
    retry_after_cap: float = 30.0  # ceiling on derived Retry-After hints
    # bounded per-subscription NDJSON delivery queues (pubsub.py):
    shed_policy: str = "shed-oldest"  # or "drop-newest" (legacy)
    # ~ lag_bound / per-frame fanout write time (2.5 s / ~2.4 ms),
    # rounded to a power of two; 0 = unbounded (explicit opt-out)
    sub_queue: int = 1024
    sub_shed_threshold: int = 256  # cumulative sheds before disconnect
    # SO_SNDBUF clamp for NDJSON stream sockets (> 0 to enable): the
    # per-sub queue only bounds delivery lag if the kernel's socket
    # pipeline can't silently absorb the backlog behind it
    stream_sndbuf: int = 0

    @classmethod
    def unlimited(cls) -> "ServeConfig":
        """The explicit all-off opt-out: no admission control, no
        stream caps, unbounded subscription queues (each knob's
        documented ``0 = unlimited`` contract in one place). This is
        what ``serve = None`` meant before the measured defaults
        landed — benches and tests that NEED the unguarded plane say
        so out loud with this."""
        return cls(max_inflight=0, max_queue=0, max_streams=0,
                   sub_queue=0)


@dataclasses.dataclass
class AdminConfig:
    """UDS admin socket (``config.rs`` ``admin.uds_path``)."""

    uds_path: str = "./admin.sock"


@dataclasses.dataclass
class TelemetryConfig:
    """Prometheus exposition + OTLP pipeline (``config.rs`` ``telemetry``)."""

    prometheus_addr: Optional[str] = None  # "host:port" or None = disabled
    # OTLP span export (the reference's open-telemetry pipeline,
    # main.rs:57-150) — a file path here enables the OTLP-JSON file
    # exporter (zero-egress environments have no collector socket)
    otlp_path: str = ""


@dataclasses.dataclass
class ObsConfig:
    """Flight-recorder observability plane for the soak pipeline
    (``corrosion_tpu/obs/``, docs/observability.md).

    Distinct from ``[telemetry]`` (the host agent's always-on
    Prometheus/OTLP endpoints): ``[obs]`` arms the PER-RUN soak plane —
    NDJSON flight records, a dedicated soak metrics listener, and
    device-profiler span annotation."""

    # NDJSON flight-record path ("" = off): crash-safe per-segment
    # records a dead soak leaves behind (obs.replay_flight_record)
    flight_path: str = ""
    # standalone Prometheus listener for the soak registry: -1 = off,
    # 0 = ephemeral (bound port on the server's ``bound_port``), >0 fixed
    prometheus_port: int = -1
    # annotate pipeline spans for jax.profiler device traces
    jax_profile: bool = False


@dataclasses.dataclass
class LogConfig:
    colors: bool = False
    format: str = "plaintext"  # or "json"
    level: str = "info"


@dataclasses.dataclass
class ConsulConfig:
    enabled: bool = False
    addr: str = "127.0.0.1:8500"
    poll_seconds: float = 1.0


@dataclasses.dataclass
class Config:
    db: DbConfig = dataclasses.field(default_factory=DbConfig)
    api: ApiConfig = dataclasses.field(default_factory=ApiConfig)
    gossip: GossipConfig = dataclasses.field(default_factory=GossipConfig)
    perf: PerfConfig = dataclasses.field(default_factory=PerfConfig)
    sim: SimConfigSection = dataclasses.field(default_factory=SimConfigSection)
    pg: PgConfig = dataclasses.field(default_factory=PgConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    admin: AdminConfig = dataclasses.field(default_factory=AdminConfig)
    telemetry: TelemetryConfig = dataclasses.field(default_factory=TelemetryConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    log: LogConfig = dataclasses.field(default_factory=LogConfig)
    consul: ConsulConfig = dataclasses.field(default_factory=ConsulConfig)

    # --- simulator-config bridges ---------------------------------------
    def to_scale_config(self):
        from corrosion_tpu.sim.scale_step import scale_sim_config

        return scale_sim_config(
            self.sim.n_nodes,
            m_slots=self.sim.m_slots,
            n_origins=self.sim.n_origins,
            n_rows=self.sim.n_rows,
            n_cols=self.sim.n_cols,
            buf_slots=self.perf.buf_slots,
            bcast_queue=self.perf.bcast_queue,
            pig_changes=self.perf.pig_changes,
            sync_chunk=self.perf.sync_chunk,
            sync_interval=self.perf.sync_interval,
            sync_peers=self.perf.sync_peers,
            bcast_max_transmissions=self.perf.bcast_max_transmissions,
            announce_interval=self.gossip.idle_rounds,
            fused=self.perf.fused,
            quiet=self.perf.quiet,
            quiet_backstop_interval=self.perf.quiet_backstop_interval,
        )

    def to_full_config(self):
        from corrosion_tpu.sim.config import wan_config

        return wan_config(
            self.sim.n_nodes,
            n_origins=self.sim.n_origins,
            n_rows=self.sim.n_rows,
            n_cols=self.sim.n_cols,
            buf_slots=self.perf.buf_slots,
            bcast_queue=self.perf.bcast_queue,
            recv_slots=self.perf.recv_slots,
            sync_chunk=self.perf.sync_chunk,
            sync_interval=self.perf.sync_interval,
            sync_peers=self.perf.sync_peers,
            bcast_fanout=self.perf.bcast_fanout,
            bcast_max_transmissions=self.perf.bcast_max_transmissions,
            announce_interval=self.gossip.idle_rounds,
            fused=self.perf.fused,
        )

    def sim_config(self):
        if self.sim.mode == "scale":
            return self.to_scale_config()
        if self.sim.mode == "full":
            return self.to_full_config()
        raise ValueError(f"unknown sim.mode {self.sim.mode!r}")


_SECTIONS = {f.name: f.type for f in dataclasses.fields(Config)}


def _coerce(cur: Any, raw: str) -> Any:
    """Coerce an env-var string to the type of the current value."""
    if isinstance(cur, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(cur, int):
        return int(raw)
    if isinstance(cur, float):
        return float(raw)
    if isinstance(cur, tuple):
        return tuple(x.strip() for x in raw.split(",") if x.strip())
    return raw


def _apply_dict(cfg: Config, data: dict) -> Config:
    for section, values in data.items():
        if section not in _SECTIONS:
            raise ValueError(f"unknown config section [{section}]")
        sec = getattr(cfg, section)
        if not isinstance(values, dict):
            raise ValueError(f"section [{section}] must be a table")
        for k, v in values.items():
            if not hasattr(sec, k):
                raise ValueError(f"unknown key {k!r} in section [{section}]")
            if isinstance(v, list):
                v = tuple(v)
            setattr(sec, k, v)
    return cfg


def _apply_env(cfg: Config, environ=None) -> Config:
    """Overlay ``CORRO_TPU__SECTION__KEY=value`` env vars (the reference's
    ``__``-separator overlay, ``config.rs:326-332``)."""
    environ = os.environ if environ is None else environ
    prefix = ENV_PREFIX + "__"
    for name, raw in environ.items():
        if not name.startswith(prefix):
            continue
        parts = name[len(prefix):].lower().split("__")
        if len(parts) != 2:
            raise ValueError(f"bad config env var {name} (want SECTION__KEY)")
        section, key = parts
        if section not in _SECTIONS:
            raise ValueError(f"unknown config section {section!r} from {name}")
        sec = getattr(cfg, section)
        if not hasattr(sec, key):
            raise ValueError(f"unknown key {key!r} from {name}")
        setattr(sec, key, _coerce(getattr(sec, key), raw))
    return cfg


def load_config(path: Optional[str] = None, environ=None) -> Config:
    """TOML file (optional) + env overlay -> validated Config."""
    cfg = Config()
    if path is not None:
        with open(path, "rb") as f:
            _apply_dict(cfg, tomllib.load(f))
    return _apply_env(cfg, environ)


def default_toml() -> str:
    """An example config file (``config.example.toml`` analog)."""
    lines = []
    for f in dataclasses.fields(Config):
        lines.append(f"[{f.name}]")
        sec = getattr(Config(), f.name)
        for sf in dataclasses.fields(sec):
            v = getattr(sec, sf.name)
            if v is None:
                lines.append(f"# {sf.name} = <unset>")
            elif isinstance(v, bool):
                lines.append(f"{sf.name} = {str(v).lower()}")
            elif isinstance(v, (int, float)):
                lines.append(f"{sf.name} = {v}")
            elif isinstance(v, tuple):
                lines.append(f"{sf.name} = {list(v)!r}")
            else:
                lines.append(f'{sf.name} = "{v}"')
        lines.append("")
    return "\n".join(lines)
