"""DB maintenance loop: periodic durability + host-state housekeeping.

Mirrors the reference's maintenance machinery (SURVEY §2.4): WAL
checkpoint(TRUNCATE) when the WAL grows past a threshold
(``spawn_handle_db_maintenance``, ``agent/handlers.rs:455-540``),
incremental vacuum when the freelist grows (``handlers.rs:398-452``), and
the buffered-meta GC loop (``clear_buffered_meta_loop``,
``util.rs:430-490``).

TPU reframing — the durable artifact is the checkpoint directory, so:

- **auto-checkpoint**: every ``checkpoint_rounds`` rounds, if the cluster
  advanced, write a full checkpoint (the WAL-checkpoint analog: bounded
  recovery replay). Rotated: ``<path>/auto-{a,b}`` alternate so a crash
  mid-write never corrupts the only copy.
- **heap compaction** (round 5, the ``vacuum_db`` analog,
  ``handlers.rs:398-452``): every ``heap_compact_rounds`` rounds — or
  immediately past the soft limit — free heap ids referenced nowhere in
  device state (stable ids, free-list reuse). The warn fires only if the
  heap is STILL past the soft limit after compacting (genuinely that
  many live values).
- **matcher-log GC** runs inline in the pubsub layer (``max_log``); this
  loop reports its sizes as metrics.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from corrosion_tpu.utils.tracing import logger


class MaintenanceLoop:
    def __init__(self, agent, db=None, subs=None,
                 checkpoint_path: Optional[str] = None,
                 checkpoint_rounds: int = 512,
                 heap_soft_limit: int = 1_000_000,
                 heap_compact_rounds: int = 256,
                 # above the longest expected streaming reader: an id a
                 # stale snapshot has not dereferenced yet is protected
                 # only by this window (values.py lookup contract)
                 heap_grace_seconds: float = 300.0,
                 interval_seconds: float = 2.0):
        self.agent = agent
        self.db = db
        self.subs = subs
        self.checkpoint_path = checkpoint_path
        self.checkpoint_rounds = checkpoint_rounds
        self.heap_soft_limit = heap_soft_limit
        self.heap_compact_rounds = heap_compact_rounds
        self.heap_grace_seconds = heap_grace_seconds
        # first tick is immediately "due": boot-time compaction settles
        # the post-restore heap before the cadence takes over
        self._last_compact_round = agent.round_no - heap_compact_rounds
        self.interval = interval_seconds
        self._last_ckpt_round = agent.round_no
        # seed rotation AWAY from the newest complete side, so the first
        # write after a restart never overwrites the copy just restored
        self._flip = False
        if checkpoint_path:
            latest = self.latest_auto_checkpoint(checkpoint_path)
            if latest and latest.endswith("auto-a"):
                self._flip = True  # next write goes to auto-b
        self._warned_heap = False
        self._last_members_round = -1
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MaintenanceLoop":
        from corrosion_tpu.utils.lifecycle import spawn_counted

        self._thread = spawn_counted(self._loop, name="corro-db-maintenance")
        return self

    def _loop(self) -> None:
        while not self.agent.tripwire.wait(self.interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — maintenance must not die
                logger.exception("maintenance tick failed")

    def tick(self) -> Optional[str]:
        """One maintenance pass; returns the checkpoint path if one was
        written."""
        written = None
        rounds = self.agent.round_no
        if (self.checkpoint_path
                and rounds - self._last_ckpt_round >= self.checkpoint_rounds):
            from corrosion_tpu.checkpoint import save_checkpoint

            side = "auto-b" if self._flip else "auto-a"
            target = os.path.join(self.checkpoint_path, side)
            # flip/cadence advance only on SUCCESS: a failed write retries
            # the same side (whose manifest save_checkpoint already
            # removed, marking it incomplete) and never touches the other
            written = save_checkpoint(self.agent, db=self.db, path=target)
            self._flip = not self._flip
            self._last_ckpt_round = rounds
            self.agent.metrics.counter("corro.db.checkpoint.count")
            logger.info("auto-checkpoint at round %d -> %s", rounds, target)
        members_path = getattr(self.agent.config.db, "members_path", "")
        if members_path and rounds != self._last_members_round:
            # the __corro_members upsert (foca-state diff persistence,
            # broadcast/mod.rs:814-949): keep the restart-bootstrap list
            # fresh; a booting agent replays it (util.rs:69-130)
            self.agent.persist_members(members_path)
            self._last_members_round = rounds
        if self.db is not None:
            heap = self.db.heap
            live = heap.live_count
            self.agent.metrics.gauge("corro.db.value_heap.len", len(heap))
            self.agent.metrics.gauge("corro.db.value_heap.live", live)
            due = rounds - self._last_compact_round >= self.heap_compact_rounds
            # over-limit triggers an early pass, but spaced — a workload
            # whose LIVE set legitimately exceeds the limit must not pay
            # a full device-state scan every 2 s tick for ~0 freed ids
            spacing = max(1, self.heap_compact_rounds // 8)
            over = (live > self.heap_soft_limit
                    and rounds - self._last_compact_round >= spacing)
            if due or over:
                freed = self.db.compact_heap(
                    grace_seconds=self.heap_grace_seconds)
                self._last_compact_round = rounds
                if freed:
                    self.agent.metrics.counter(
                        "corro.db.value_heap.compacted", freed)
                    logger.info("heap compaction freed %d value ids "
                                "(%d live)", freed, heap.live_count)
                if (heap.live_count > self.heap_soft_limit
                        and not self._warned_heap):
                    # still over AFTER compacting: genuinely that many
                    # live values — the operator must raise the limit
                    self._warned_heap = True
                    logger.warning(
                        "value heap holds %d LIVE values after compaction "
                        "(soft limit %d) — raise the limit or shrink the "
                        "working set", heap.live_count, self.heap_soft_limit,
                    )
        if self.subs is not None:
            for mid in self.subs.ids():
                m = self.subs.get(mid)
                if m is not None:
                    self.agent.metrics.gauge(
                        "corro.subs.change_log.len", len(m._log),
                        labels={"matcher": mid[:8]},
                    )
        return written

    @staticmethod
    def latest_auto_checkpoint(checkpoint_path: str) -> Optional[str]:
        """The newest complete rotated checkpoint, for boot-time resume."""
        sides = MaintenanceLoop._sides_newest_first(checkpoint_path)
        return sides[0] if sides else None

    @staticmethod
    def _sides_newest_first(checkpoint_path: str) -> list:
        found = []
        for side in ("auto-a", "auto-b"):
            p = os.path.join(checkpoint_path, side)
            manifest = os.path.join(p, "manifest.json")
            if os.path.exists(manifest):
                found.append((os.path.getmtime(manifest), p))
        return [p for _, p in sorted(found, reverse=True)]

    @staticmethod
    def resume_latest(agent, checkpoint_path: str, db=None) -> Optional[dict]:
        """Boot-time resume — a thin alias for ``Agent.recover_latest``,
        the ONE recovery path (integrity scan, sim-config gate,
        restore-failure fallback to the next-newest candidate): rotated
        auto-a/auto-b sides and soak segments alike, and a half-written,
        tampered, or config-drifted side can never brick startup or mask
        an older good one. Returns the restored manifest, or None when
        nothing restorable exists."""
        return agent.recover_latest(root=checkpoint_path, db=db)
