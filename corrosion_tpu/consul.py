"""Consul bridge: mirror a Consul agent's services/checks into cluster
tables.

Mirrors ``crates/consul-client`` (minimal agent HTTP client,
``consul-client/src/lib.rs``) and ``corrosion consul sync``
(``crates/corrosion/src/command/consul/sync.rs:23-983``): poll the local
Consul agent every second, hash each service/check, and upsert only the
diffs into the ``consul_services`` / ``consul_checks`` tables in a single
transaction, tracking applied hashes in a local cache (the reference's
``__corro_consul_*`` tables).

Rows carry the full object as JSON (``data``) plus the hash — the
reference stores parsed columns; the JSON payload keeps the bridge
schema-independent of the grid's column budget.
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

from corrosion_tpu.utils.backoff import Backoff, retry_call
from corrosion_tpu.utils.tracing import logger

CONSUL_SCHEMA = """
CREATE TABLE consul_services (id TEXT PRIMARY KEY, data TEXT, hash TEXT);
CREATE TABLE consul_checks (id TEXT PRIMARY KEY, data TEXT, hash TEXT);
"""


class ConsulClient:
    """Minimal Consul agent HTTP client (services + checks)."""

    def __init__(self, addr: str = "127.0.0.1:8500", timeout: float = 10.0):
        self.base = f"http://{addr}"
        self.timeout = timeout

    def _get(self, path: str):
        req = urllib.request.Request(self.base + path)
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:  # noqa: S310 — operator-configured local agent addr
            return json.loads(resp.read())

    def agent_services(self) -> Dict[str, dict]:
        return self._get("/v1/agent/services")

    def agent_checks(self) -> Dict[str, dict]:
        return self._get("/v1/agent/checks")


def _hash(obj: dict) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()
    ).hexdigest()[:16]


class ConsulSync:
    """The sync loop: diff-and-upsert services/checks each poll."""

    def __init__(self, consul: ConsulClient, execute, node: int = 0):
        """``execute(statements, node)`` — the write path (HTTP client's
        ``execute`` or ``Database.execute`` adapted)."""
        self.consul = consul
        self.execute = execute
        self.node = node
        # applied-hash caches (the reference's __corro_consul_* tables)
        self._svc_hashes: Dict[str, str] = {}
        self._chk_hashes: Dict[str, str] = {}
        self._stop = threading.Event()

    def sync_once(self) -> Tuple[int, int]:
        """One poll: returns (services_changed, checks_changed). The
        applied-hash caches only advance after the write succeeds, so a
        failed transaction is retried on the next poll."""
        services = self.consul.agent_services()
        checks = self.consul.agent_checks()
        stmts: list = []
        svc_updates = self._diff("consul_services", services,
                                 self._svc_hashes, stmts)
        chk_updates = self._diff("consul_checks", checks,
                                 self._chk_hashes, stmts)
        if stmts:
            self.execute(stmts, self.node)
        for cache, updates in ((self._svc_hashes, svc_updates),
                               (self._chk_hashes, chk_updates)):
            for cid, h in updates.items():
                if h is None:
                    cache.pop(cid, None)
                else:
                    cache[cid] = h
        return len(svc_updates), len(chk_updates)

    def _diff(self, table: str, fresh: Dict[str, dict],
              cache: Dict[str, str], stmts: list) -> Dict[str, Optional[str]]:
        """-> proposed cache updates (id -> hash, None = removal); applied
        by the caller only after the statements commit."""
        updates: Dict[str, Optional[str]] = {}
        for cid, obj in fresh.items():
            h = _hash(obj)
            if cache.get(cid) == h:
                continue
            stmts.append((
                f"INSERT INTO {table} (id, data, hash) VALUES (?, ?, ?)",
                [cid, json.dumps(obj, sort_keys=True), h],
            ))
            updates[cid] = h
        for cid in cache:
            if cid not in fresh:
                stmts.append((f"DELETE FROM {table} WHERE id = ?", [cid]))
                updates[cid] = None
        return updates

    def run(self, poll_seconds: float = 1.0) -> None:
        """Poll forever (the reference polls every 1 s,
        ``command/consul/sync.rs``); consul errors retry through the
        shared :func:`retry_call` policy (1 s -> 30 s jittered, no retry
        cap — the bridge outlives any consul outage), with waits
        interruptible by :meth:`stop`."""
        while not self._stop.is_set():
            try:
                n_svc, n_chk = retry_call(
                    self.sync_once,
                    backoff=Backoff(min_wait=1.0, max_wait=30.0),
                    retry_on=(urllib.error.URLError, ConnectionError,
                              OSError),
                    sleep=self._stop.wait,
                    abort=self._stop.is_set,
                    on_retry=lambda e, delay, n: logger.warning(
                        "consul poll failed (%s); retry in %.1fs", e, delay
                    ),
                )
            except (urllib.error.URLError, ConnectionError, OSError):
                break  # stop() tripped mid-backoff
            if n_svc or n_chk:
                logger.info("consul sync: %d services, %d checks changed",
                            n_svc, n_chk)
            self._stop.wait(poll_seconds)

    def stop(self) -> None:
        self._stop.set()


def consul_sync_cli(args) -> int:
    from corrosion_tpu.client import CorrosionApiClient

    api = CorrosionApiClient(args.api_addr, args.api_port)
    try:
        api.schema([CONSUL_SCHEMA])
    except Exception as e:  # noqa: BLE001 — tables may already exist
        logger.debug("consul schema apply: %s", e)
    sync = ConsulSync(
        ConsulClient(args.consul_addr),
        execute=lambda stmts, node: api.execute(stmts, node=node),
        node=args.node,
    )
    if args.once:
        n_svc, n_chk = sync.sync_once()
        print(json.dumps({"services": n_svc, "checks": n_chk}))
        return 0
    try:
        sync.run()
    except KeyboardInterrupt:
        sync.stop()
    return 0
