"""Test fixtures: launch a full in-process agent rig per test.

Mirrors ``crates/corro-tests`` (``launch_test_agent`` + ``TEST_SCHEMA``,
``corro-tests/src/lib.rs:13-88``): the reference boots a complete real
agent (QUIC on loopback, tempdir DB, real schema) for every integration
test — no mocks. Here the analog is a small real cluster (16 nodes, 4
writers, lossless network) with the standard test schema applied, plus
optional HTTP/admin listeners.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from corrosion_tpu.agent import Agent
from corrosion_tpu.config import Config
from corrosion_tpu.db import Database

TEST_SCHEMA = """
CREATE TABLE tests (
    id INTEGER PRIMARY KEY,
    text TEXT,
    meta TEXT
);
"""


def cluster_config(**overrides) -> Config:
    """The standard small test cluster (fast first-jit, converges in a
    few rounds). Override any ``sim``/``perf``/``gossip`` field by name."""
    cfg = Config()
    cfg.sim.mode = "scale"
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 8
    cfg.sim.n_cols = 4
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.0
    for key, value in overrides.items():
        for section in (cfg.sim, cfg.perf, cfg.gossip):
            if hasattr(section, key):
                setattr(section, key, value)
                break
        else:
            raise AttributeError(f"no config field named {key!r}")
    return cfg


@contextlib.contextmanager
def launch_test_agent(schema: Optional[str] = TEST_SCHEMA,
                      warm_rounds: int = 10, http: bool = False,
                      admin_path: Optional[str] = None, **overrides):
    """Boot a full agent (+Database, optional listeners) and yield a rig.

    Yields an object with ``agent``, ``db``, and (when requested)
    ``api``/``client``/``admin_path`` attributes. Always shuts down
    cleanly, like the reference's tempdir teardown."""

    class Rig:
        pass

    rig = Rig()
    with Agent(cluster_config(**overrides)) as agent:
        if not agent.wait_rounds(warm_rounds, timeout=180):
            raise RuntimeError("test agent failed to warm up")
        rig.agent = agent
        rig.db = Database(agent)
        if schema:
            rig.db.apply_schema_sql(schema)
        with contextlib.ExitStack() as stack:
            if http:
                from corrosion_tpu.api import ApiServer
                from corrosion_tpu.client import CorrosionApiClient

                rig.api = stack.enter_context(ApiServer(rig.db, port=0))
                rig.client = CorrosionApiClient(rig.api.addr, rig.api.port)
            if admin_path:
                from corrosion_tpu.admin import AdminServer

                stack.enter_context(
                    AdminServer(agent, admin_path, db=rig.db))
                rig.admin_path = admin_path
            yield rig
