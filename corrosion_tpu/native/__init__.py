"""ctypes bindings for the native host components (``native/corro_host.cpp``).

The reference loads its native CRDT engine at runtime
(``crates/corro-types/src/sqlite.rs:121-139``); here the shared library is
built on demand with ``make`` the first time it is needed. If no C++
toolchain is available the callers fall back to the pure-Python oracle
(``sim/oracle.py``) — same semantics, slower.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "libcorro_host.so"
_lock = threading.Lock()
_lib = None


def load(build: bool = True):
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if build:
            # always run make: it is a no-op when fresh and rebuilds a
            # stale .so after corro_host.cpp changes
            try:
                subprocess.run(
                    ["make", "-s"], cwd=_NATIVE_DIR, check=True, capture_output=True
                )
            except (OSError, subprocess.CalledProcessError):
                pass
        if not _LIB_PATH.exists():
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))
        i32, i64, p = ctypes.c_int32, ctypes.c_int64, ctypes.c_void_p
        ip = ctypes.POINTER(ctypes.c_int32)
        lib.corro_lww_new.restype = p
        lib.corro_lww_new.argtypes = [i32]
        lib.corro_lww_free.argtypes = [p]
        lib.corro_lww_merge.restype = i32
        lib.corro_lww_merge.argtypes = [p, i32, i32, i32, i32, i32, i32]
        lib.corro_lww_get.argtypes = [p, i32, ip]
        lib.corro_lww_dump.argtypes = [p, ip, ip, ip, ip, ip]
        lib.corro_book_new.restype = p
        lib.corro_book_new.argtypes = [i32]
        lib.corro_book_free.argtypes = [p]
        lib.corro_book_record.restype = i32
        lib.corro_book_record.argtypes = [p, i32, i32]
        lib.corro_book_head.restype = i32
        lib.corro_book_head.argtypes = [p, i32]
        lib.corro_book_known_max.restype = i32
        lib.corro_book_known_max.argtypes = [p, i32]
        lib.corro_book_needs.restype = i64
        lib.corro_book_needs.argtypes = [p, i32]
        lib.corro_book_n_gaps.restype = i64
        lib.corro_book_n_gaps.argtypes = [p, i32]
        lib.corro_apply_batch.restype = i32
        lib.corro_apply_batch.argtypes = [p, p, ip, i32, ip]
        lib.corro_cluster_new.restype = p
        lib.corro_cluster_new.argtypes = [i32, i32, i32, i32, i32, i32, i64]
        lib.corro_cluster_free.argtypes = [p]
        lib.corro_cluster_write.argtypes = [p, i32, i32, i32, i32]
        lib.corro_cluster_write_tx.argtypes = [p, i32, ip, ip, ip, i32]
        lib.corro_cluster_round.argtypes = [p]
        lib.corro_cluster_kill.argtypes = [p, i32]
        lib.corro_cluster_revive.argtypes = [p, i32]
        lib.corro_cluster_set_partition.argtypes = [p, ip]
        lib.corro_cluster_converged.restype = i32
        lib.corro_cluster_converged.argtypes = [p]
        lib.corro_cluster_settle.restype = i32
        lib.corro_cluster_settle.argtypes = [p, i32]
        lib.corro_cluster_store.argtypes = [p, i32, ip, ip, ip, ip, ip]
        lib.corro_cluster_total_needs.restype = i64
        lib.corro_cluster_total_needs.argtypes = [p]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


class NativeNode:
    """One simulated node backed by the C++ engine: LWW store + bookie.

    Mirrors ``sim/oracle.OracleNode`` exactly — the devcluster parity
    harness uses this for big host clusters where Python dicts are slow.
    """

    def __init__(self, n_cells: int, n_origins: int):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable (no C++ toolchain?)")
        self.n_cells = n_cells
        self.n_origins = n_origins
        self._lww = self._lib.corro_lww_new(n_cells)
        self._book = self._lib.corro_book_new(n_origins)

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None:
            if getattr(self, "_lww", None):
                lib.corro_lww_free(self._lww)
            if getattr(self, "_book", None):
                lib.corro_book_free(self._book)

    def apply(self, changes) -> np.ndarray:
        """Apply [n, 7] int32 rows (cell, ver, val, site, origin, dbv,
        clp); returns per-change freshness flags."""
        arr = np.ascontiguousarray(changes, dtype=np.int32).reshape(-1, 7)
        fresh = np.zeros(arr.shape[0], dtype=np.int32)
        self._lib.corro_apply_batch(
            self._book,
            self._lww,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            arr.shape[0],
            fresh.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return fresh.astype(bool)

    def record(self, origin: int, version: int) -> bool:
        return bool(self._lib.corro_book_record(self._book, origin, version))

    def head(self, origin: int) -> int:
        return self._lib.corro_book_head(self._book, origin)

    def known_max(self, origin: int) -> int:
        return self._lib.corro_book_known_max(self._book, origin)

    def needs(self, origin: int) -> int:
        return self._lib.corro_book_needs(self._book, origin)

    def n_gaps(self, origin: int) -> int:
        return self._lib.corro_book_n_gaps(self._book, origin)

    def store(self):
        """The (ver, val, site, dbv, clp) planes as [n_cells] int32."""
        planes = tuple(
            np.zeros(self.n_cells, dtype=np.int32) for _ in range(5)
        )
        ptrs = [
            pl.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)) for pl in planes
        ]
        self._lib.corro_lww_dump(self._lww, *ptrs)
        return planes


class NativeCluster:
    """Whole-cluster round engine in C++ — the 256+-node devcluster
    oracle (same interface as ``sim/parity.OracleCluster``)."""

    def __init__(self, n_nodes: int, n_origins: int, n_cells: int,
                 fanout: int = 3, rebroadcast_budget: int = 3,
                 sync_peers: int = 2, seed: int = 0):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native library unavailable (no C++ toolchain?)")
        self.n_nodes = n_nodes
        self.n_origins = n_origins
        self.n_cells = n_cells
        self._h = self._lib.corro_cluster_new(
            n_nodes, n_origins, n_cells, fanout, rebroadcast_budget,
            sync_peers, seed,
        )

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and getattr(self, "_h", None):
            lib.corro_cluster_free(self._h)

    def write(self, node: int, cell: int, value: int, clp: int = 0) -> None:
        self._lib.corro_cluster_write(self._h, node, cell, value, clp)

    def write_tx(self, node: int, cells) -> None:
        """Multi-statement transaction: ``cells`` = [(cell, value, clp)]
        commit atomically under one db_version (chunked dissemination)."""
        arr = np.ascontiguousarray(cells, dtype=np.int32).reshape(-1, 3)
        ip = ctypes.POINTER(ctypes.c_int32)
        c = np.ascontiguousarray(arr[:, 0])
        v = np.ascontiguousarray(arr[:, 1])
        l = np.ascontiguousarray(arr[:, 2])  # noqa: E741
        self._lib.corro_cluster_write_tx(
            self._h, node, c.ctypes.data_as(ip), v.ctypes.data_as(ip),
            l.ctypes.data_as(ip), arr.shape[0],
        )

    def round(self) -> None:
        self._lib.corro_cluster_round(self._h)

    # --- fault injection (kill/revive/partition/heal drivers) -----------
    def kill(self, node: int) -> None:
        self._lib.corro_cluster_kill(self._h, node)

    def revive(self, node: int) -> None:
        self._lib.corro_cluster_revive(self._h, node)

    def set_partition(self, groups) -> None:
        g = np.ascontiguousarray(groups, dtype=np.int32)
        if g.shape != (self.n_nodes,):
            raise ValueError(
                f"partition groups shape {g.shape} != ({self.n_nodes},)"
            )
        self._lib.corro_cluster_set_partition(
            self._h, g.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )

    def heal_partition(self) -> None:
        self.set_partition(np.zeros(self.n_nodes, np.int32))

    def apply_faults(self, events) -> None:
        """Apply one round's fault events: ("kill", node),
        ("revive", node), ("partition", groups), ("heal",)."""
        for ev in events:
            kind = ev[0]
            if kind == "kill":
                self.kill(ev[1])
            elif kind == "revive":
                self.revive(ev[1])
            elif kind == "partition":
                self.set_partition(ev[1])
            elif kind == "heal":
                self.heal_partition()
            else:
                raise ValueError(f"unknown fault event {ev!r}")

    def converged(self) -> bool:
        return bool(self._lib.corro_cluster_converged(self._h))

    def total_needs(self) -> int:
        return self._lib.corro_cluster_total_needs(self._h)

    def run(self, script, settle_rounds: int = 256) -> int:
        """Apply a WorkloadScript (writes + fault events) then settle;
        rounds taken or -1. Outstanding faults heal/revive before the
        settle phase so convergence is reachable."""
        from corrosion_tpu.sim.parity import _as_tx

        faults = getattr(script, "faults", None) or []
        for r, batch in enumerate(script.writes):
            if r < len(faults):
                self.apply_faults(faults[r])
            for node, cells in (_as_tx(w) for w in batch):
                self.write_tx(node, cells)
            self.round()
        self.heal_partition()
        for node in range(self.n_nodes):
            self.revive(node)
        settled = self._lib.corro_cluster_settle(self._h, settle_rounds)
        return -1 if settled < 0 else len(script.writes) + settled

    def store_planes(self, node: int = 0):
        planes = tuple(np.zeros(self.n_cells, np.int32) for _ in range(5))
        ptrs = [pl.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
                for pl in planes]
        self._lib.corro_cluster_store(self._h, node, *ptrs)
        return planes
