"""Checkpoint / backup / restore.

In the reference the SQLite file *is* the checkpoint; ``corrosion backup``
produces a portable copy via ``VACUUM INTO`` + site-id ordinal rewrite
(``crates/corrosion/src/main.rs:160-225``) and ``corrosion restore`` swaps
the live DB under file locks (``crates/sqlite3-restore/src/lib.rs``) with
an optional actor re-pivot (``main.rs:227-330``).

Here the durable artifacts are:

- **checkpoint** — the whole cluster: the device-state pytree (saved as
  an ``.npz`` of its leaves, restored against a template built from the
  same config) + the host DB state (schema, value heap, row map) + a
  manifest. ``load_checkpoint`` + ``Agent.restore_state`` resume a live
  agent at the saved round.
- **backup** — one *node's* replica, portable: its store planes and
  bookkeeping rows plus the host DB state. ``restore_backup`` grafts it
  onto a (possibly different) node of a live cluster, optionally
  re-pivoting site ids that named the backed-up node to the new identity
  — the ordinal-rewrite analog.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Tuple

import jax
import numpy as np

FORMAT_VERSION = 1


def _leaves(state) -> list:
    return jax.tree.leaves(state)


def _state_template(mode: str, cfg):
    if mode == "scale":
        from corrosion_tpu.sim.scale_step import ScaleSimState

        return ScaleSimState.create(cfg)
    from corrosion_tpu.sim.step import SimState

    return SimState.create(cfg)


def save_checkpoint(agent, db=None, path: str = "./checkpoint") -> str:
    """Write the full cluster state to ``path`` (a directory).

    Crash-safe ordering: the manifest is removed first and (re)written
    LAST via an atomic rename — a directory without a valid manifest is
    incomplete by definition, so a crash mid-write can never leave a
    side that looks restorable but is not."""
    os.makedirs(path, exist_ok=True)
    manifest_path = os.path.join(path, "manifest.json")
    if os.path.exists(manifest_path):
        os.unlink(manifest_path)
    state = agent.device_state()
    leaves = [np.asarray(x) for x in _leaves(state)]
    np.savez_compressed(
        os.path.join(path, "state.npz"),
        **{f"leaf_{i}": a for i, a in enumerate(leaves)},
    )
    manifest = {
        "format": FORMAT_VERSION,
        "mode": agent.mode,
        "round": agent.round_no,
        "sim_config": dataclasses.asdict(agent.cfg),
        "n_leaves": len(leaves),
        "db": db.state_dict() if db is not None else None,
    }
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, manifest_path)
    return path


def load_checkpoint(path: str) -> Tuple[dict, object]:
    """-> (manifest, device-state pytree). The pytree is rebuilt against
    a template constructed from the saved config, so leaf order/shape
    mismatches fail loudly."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest["format"] != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint format {manifest['format']}")
    if manifest["mode"] == "scale":
        from corrosion_tpu.sim.scale_step import ScaleSimConfig as CfgCls
    else:
        from corrosion_tpu.sim.config import SimConfig as CfgCls
    cfg = CfgCls(**manifest["sim_config"])
    template = _state_template(manifest["mode"], cfg)
    with np.load(os.path.join(path, "state.npz")) as z:
        loaded = [z[f"leaf_{i}"] for i in range(manifest["n_leaves"])]
    tmpl_leaves, treedef = jax.tree.flatten(template)
    if len(tmpl_leaves) != len(loaded):
        raise ValueError(
            f"checkpoint has {len(loaded)} leaves, config expects "
            f"{len(tmpl_leaves)} — config drift"
        )
    for t, l in zip(tmpl_leaves, loaded):
        if tuple(t.shape) != tuple(l.shape):
            raise ValueError(
                f"leaf shape mismatch: checkpoint {l.shape} vs config {t.shape}"
            )
    state = jax.tree.unflatten(treedef, loaded)
    return manifest, state


def restore_checkpoint(agent, path: str, db=None) -> dict:
    """Swap a checkpoint into a live agent (+ its Database host state)."""
    manifest, state = load_checkpoint(path)
    if manifest["mode"] != agent.mode:
        raise ValueError(
            f"checkpoint mode {manifest['mode']!r} != agent mode {agent.mode!r}"
        )
    if not agent.restore_state(state):
        raise TimeoutError("restore did not apply in time")
    if db is not None and manifest.get("db") is not None:
        db.load_state_dict(manifest["db"])
    return manifest


# --- portable single-node backup ----------------------------------------

def backup_node(agent, node: int, db=None, path: str = "./backup.npz") -> str:
    """Portable backup of one node's replica (``corrosion backup``)."""
    snap = agent.snapshot()
    planes = {f"plane_{i}": p[node] for i, p in enumerate(snap["store"])}
    np.savez_compressed(
        path,
        **planes,
        head=snap["head"][node],
        known_max=snap["known_max"][node],
        meta=np.array(
            [FORMAT_VERSION, node, len(snap["store"])], np.int64
        ),
    )
    if db is not None:
        with open(path + ".db.json", "w") as f:
            json.dump(db.state_dict(), f)
    return path


def restore_backup(agent, path: str, node: Optional[int] = None,
                   db=None, repivot: bool = True) -> int:
    """Graft a node backup onto ``node`` of a live cluster.

    With ``repivot`` (the site-id ordinal rewrite analog), site-plane
    entries naming the backed-up node are rewritten to the restored
    node's id, so columns the old identity authored are attributed to the
    new one — including the per-origin head/known_max bookkeeping rows,
    so version attribution stays consistent with the rewritten site plane
    (the reference's restore likewise rewrites the site-id ordinal
    mapping, ``main.rs:227-330``).

    Restoring onto a cluster where ``src_node`` is still a live, distinct
    identity is NOT supported: the grafted cells claim (site=target, dbv)
    pairs drawn from src's version counter, which may collide with or
    outrun versions target already authored. Use it the way the reference
    does — to move an identity, not to clone one."""
    with np.load(path) as z:
        fmt, src_node, n_planes = (int(x) for x in z["meta"])
        if fmt != FORMAT_VERSION:
            raise ValueError(f"unsupported backup format {fmt}")
        planes = [np.array(z[f"plane_{i}"]) for i in range(n_planes)]
        head = np.array(z["head"])
        known_max = np.array(z["known_max"])
    target = src_node if node is None else node
    if repivot and target != src_node:
        site = planes[2]  # (ver, val, site, dbv) plane order
        site[site == src_node] = target
        # move the origin-axis bookkeeping with the identity: versions the
        # backup attributes to origin src_node are now target's
        n_origins = head.shape[0]
        if src_node < n_origins:
            if target < n_origins:
                head[target] = max(head[target], head[src_node])
                known_max[target] = max(known_max[target], known_max[src_node])
            head[src_node] = 0
            known_max[src_node] = 0
    # patch the live state on host, then stage the swap
    state = agent.device_state()
    store = tuple(
        np.asarray(p).copy() for p in state.crdt.store
    )
    for plane, backup_plane in zip(store, planes):
        plane[target] = backup_plane
    h = np.asarray(state.crdt.book.head).copy()
    km = np.asarray(state.crdt.book.known_max).copy()
    h[target] = head
    km[target] = np.maximum(known_max, km[target])
    # the seen window is relative to the head being replaced — clear it
    # (out-of-order dedupe hints only; anti-entropy sync re-derives them)
    seen = np.asarray(state.crdt.book.seen).copy()
    seen[target] = 0
    crdt = state.crdt._replace(
        store=tuple(store),
        book=state.crdt.book._replace(head=h, known_max=km, seen=seen),
    )
    if not agent.restore_state(state._replace(crdt=crdt)):
        raise TimeoutError("backup restore did not apply in time")
    if db is not None and os.path.exists(path + ".db.json"):
        with open(path + ".db.json") as f:
            db.load_state_dict(json.load(f))
    return target
