"""Checkpoint / backup / restore.

In the reference the SQLite file *is* the checkpoint; ``corrosion backup``
produces a portable copy via ``VACUUM INTO`` + site-id ordinal rewrite
(``crates/corrosion/src/main.rs:160-225``) and ``corrosion restore`` swaps
the live DB under file locks (``crates/sqlite3-restore/src/lib.rs``) with
an optional actor re-pivot (``main.rs:227-330``).

Here the durable artifacts are:

- **checkpoint** — the whole cluster: the device-state pytree (saved as
  an ``.npz`` of its leaves, restored against a template built from the
  same config) + the host DB state (schema, value heap, row map) + a
  manifest. ``load_checkpoint`` + ``Agent.restore_state`` resume a live
  agent at the saved round.
- **backup** — one *node's* replica, portable: its store planes and
  bookkeeping rows plus the host DB state. ``restore_backup`` grafts it
  onto a (possibly different) node of a live cluster, optionally
  re-pivoting site ids that named the backed-up node to the new identity
  — the ordinal-rewrite analog.
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import os
from typing import Optional, Tuple

import jax
import numpy as np

# format 2 added per-file SHA-256 content hashes to the manifest
# (``files``) and an optional ``extra`` payload (the segmented soak
# runner records its PRNG key + completed-round counter there). Format 3
# (docs/checkpoints.md) makes the state SHARDED: leaves are stored as
# per-shard slice files (``shard-%05d.npz``) each hashed independently,
# and the manifest records the saving mesh, a per-leaf partition spec
# (``leaves``), and where every slice lives (``slices``) — so each
# device drains/writes only its own slice and restore can re-place the
# slices against a DIFFERENT mesh (elastic restore). Formats 1 and 2
# still load; format-1 checkpoints (no hashes) simply can't be
# integrity-checked. Any checkpoint predating a state-schema change
# (new pytree leaves, e.g. ``CrdtState.sync_defer``) is rejected loudly
# at the leaf-count gate below; recovery then falls back to the
# next-newest candidate or boots fresh with the rejection logged.
FORMAT_VERSION = 3
_SUPPORTED_FORMATS = (1, 2, 3)


class CheckpointIntegrityError(ValueError):
    """A checkpoint directory is incomplete, tampered with, or corrupt."""


#: sim-config keys that select an execution path without changing what
#: the simulation computes (fused == unfused is pinned bit for bit) —
#: excluded from checkpoint config-identity checks so a run may resume
#: under a different execution mode (e.g. a TPU soak's checkpoint
#: restored under ``fused="interpret"`` on CPU), and so manifests
#: written before the key existed keep restoring.
#: ``quiet*`` (ISSUE 19) joins ``fused``: the active-set round is pinned
#: bitwise == dense, and the backstop/shard knobs only steer which
#: rounds take the (result-identical) fixpoint branch and how occupancy
#: is reported — a quiet soak's checkpoint restores under dense and
#: vice versa.
EXECUTION_ONLY_CONFIG_KEYS = (
    "fused", "quiet", "quiet_backstop_interval", "quiet_shards",
)

#: semantic config keys added AFTER checkpoints already existed in the
#: wild, with the default the older code behaved as: a manifest written
#: before the key existed normalizes to this value, so pre-key
#: checkpoints keep restoring under the (identical-avals) default while
#: a NON-default setting still refuses them loudly. ``narrow_int8``
#: (ISSUE 12) changes the ``mem_tx`` aval when on, so unlike ``fused``
#: it cannot be execution-only.
COMPAT_DEFAULT_CONFIG_KEYS = {"narrow_int8": False,
                              "narrow_q_int8": False}


def config_identity(cfg_or_dict) -> dict:
    """The portion of a sim config that checkpoint compatibility is
    judged on: the ``dataclasses.asdict`` dict minus
    :data:`EXECUTION_ONLY_CONFIG_KEYS`, with absent late-added keys
    normalized per :data:`COMPAT_DEFAULT_CONFIG_KEYS`. Accepts a config
    dataclass or an already-serialized manifest ``sim_config`` dict."""
    d = (cfg_or_dict if isinstance(cfg_or_dict, dict)
         else dataclasses.asdict(cfg_or_dict))
    out = {k: v for k, v in d.items()
           if k not in EXECUTION_ONLY_CONFIG_KEYS}
    for k, default in COMPAT_DEFAULT_CONFIG_KEYS.items():
        out.setdefault(k, default)
    return out


def _leaves(state) -> list:
    return jax.tree.leaves(state)


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _verify_files(path: str, manifest: dict) -> None:
    """Recompute every recorded leaf-file hash; mismatch = corruption."""
    for name, want in (manifest.get("files") or {}).items():
        fp = os.path.join(path, name)
        if not os.path.exists(fp):
            raise CheckpointIntegrityError(
                f"checkpoint {path}: leaf file {name} is missing"
            )
        got = _file_sha256(fp)
        if got != want:
            raise CheckpointIntegrityError(
                f"checkpoint {path}: leaf file {name} content hash mismatch "
                f"(manifest {want[:12]}…, on disk {got[:12]}…) — the file "
                f"was truncated or tampered with after the checkpoint "
                f"was committed"
            )


def _serialize_arrays(arrays: dict) -> bytes:
    """Compress named arrays into npz bytes in memory, so the content
    hash is computed over the bytes once instead of re-reading the file
    from disk after the write (the old shape paid a full file re-read
    per checkpoint — a hidden extra IO pass in the soak hot loop)."""
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return buf.getvalue()


def _write_bytes(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)


def _publish_manifest(tmp: str, final: str) -> None:
    """The commit point: a checkpoint exists iff this rename lands.

    A module seam (like ``_write_bytes``) so crash injection — the
    resilience tests and the corrochaos engine
    (``resilience/chaos.py``) — can kill a save exactly between the
    state-file writes and the manifest publish, the mid-segment
    preemption window the crash-consistent ordering exists for."""
    os.replace(tmp, final)


def _shard_filename(ordinal: int) -> str:
    return f"shard-{ordinal:05d}.npz"


def _slice_key(leaf: int, start: int) -> str:
    return f"leaf_{leaf}_{start}"


def _normalized_leaf_records(agent, shards):
    """-> (leaf_records, mesh_meta). Each record is ``(dim, axes, shape,
    dtype, parts)`` with ``parts`` = ((start, owned ndarray), ...) —
    one whole part at start 0 when the leaf is unsharded. ``shards`` is
    a pytree of :class:`~corrosion_tpu.parallel.mesh.HostLeafShards`
    (the per-shard drain); with ``shards=None`` the agent's device
    state drains whole-leaf (the single-device agent path)."""
    if shards is None:
        leaves = [np.asarray(x) for x in _leaves(agent.device_state())]
        return (
            [(None, None, a.shape, a.dtype, ((0, a),)) for a in leaves],
            None,
        )
    from corrosion_tpu.parallel.mesh import drained_mesh_meta

    records = [
        (hs.dim, hs.axes, hs.shape, hs.dtype, hs.parts)
        for hs in _leaves(shards)
    ]
    return records, drained_mesh_meta(shards)


def _slice_groups(leaf_records) -> dict:
    """Group slices into shard files: the k-th window of every sharded
    leaf lands in ``shard-%05d.npz`` number k (one file per saving
    device, matching the mesh device order), unsharded/replicated
    leaves in shard 0. -> {ordinal: [(leaf, start, stop, array), ...]}"""
    groups: dict = {}
    for i, (dim, _axes, _shape, _dtype, parts) in enumerate(leaf_records):
        for k, (start, arr) in enumerate(parts):
            if dim is None:
                ordinal, stop = 0, None
            else:
                ordinal, stop = k, start + arr.shape[dim]
            groups.setdefault(ordinal, []).append((i, start, stop, arr))
    return groups


def _write_state_files(path: str, groups: dict,
                       io_stats: Optional[dict] = None) -> dict:
    """Serialize + hash + write every shard file, in parallel when there
    is more than one (zlib and SHA-256 both release the GIL, so the
    per-shard work genuinely overlaps). -> {filename: sha256}."""
    import time

    t0 = time.perf_counter()

    def one(ordinal: int, entries: list):
        blob = _serialize_arrays({
            _slice_key(leaf, start): arr
            for leaf, start, _stop, arr in entries
        })
        name = _shard_filename(ordinal)
        _write_bytes(os.path.join(path, name), blob)
        return name, hashlib.sha256(blob).hexdigest()

    items = sorted(groups.items())
    if len(items) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(len(items), 8),
            thread_name_prefix="corro-ckpt-shard",
        ) as pool:
            futures = [pool.submit(one, k, entries) for k, entries in items]
            files = dict(f.result() for f in futures)
    else:
        files = dict(one(k, entries) for k, entries in items)
    if io_stats is not None:
        io_stats["serialize_s"] = (
            io_stats.get("serialize_s", 0.0) + time.perf_counter() - t0
        )
        io_stats["shard_files"] = len(items)
    return files


def _state_template(mode: str, cfg):
    if mode == "scale":
        from corrosion_tpu.sim.scale_step import ScaleSimState

        return ScaleSimState.create(cfg)
    from corrosion_tpu.sim.step import SimState

    return SimState.create(cfg)


def save_checkpoint(agent, db=None, path: str = "./checkpoint",
                    extra: Optional[dict] = None, shards=None,
                    io_stats: Optional[dict] = None) -> str:
    """Write the full cluster state to ``path`` (a directory).

    Crash-safe ordering: the manifest is removed first and (re)written
    LAST via an atomic rename — a directory without a valid manifest is
    incomplete by definition, so a crash mid-write can never leave a
    side that looks restorable but is not. Every state file's SHA-256
    is recorded in the manifest, so post-commit corruption (bit rot, a
    truncating copy, a single damaged shard slice) is detected on load
    instead of silently restoring garbage.

    ``shards`` (a pytree of
    :class:`~corrosion_tpu.parallel.mesh.HostLeafShards` from
    ``host_shard_copy``) writes the per-shard v3 layout: one slice file
    per saving device, serialized/hashed in parallel, with the mesh and
    per-leaf partition specs recorded for elastic restore. Without it
    the agent's device state drains whole-leaf into a single shard file
    (the single-device agent path).

    ``extra`` is an arbitrary JSON-able payload stored in the manifest —
    the segmented soak runner records its scan carry (PRNG key data +
    completed rounds) there. ``io_stats`` (optional dict) receives
    ``serialize_s`` / ``shard_files`` for pipeline telemetry."""
    os.makedirs(path, exist_ok=True)
    manifest_path = os.path.join(path, "manifest.json")
    if os.path.exists(manifest_path):
        os.unlink(manifest_path)
    # stale state files from a previous (possibly differently-sharded)
    # occupant of this directory: remove them AFTER the manifest — the
    # side is already invalid, and rotation reuses side dirs
    for name in os.listdir(path):
        if name == "state.npz" or (
                name.startswith("shard-") and name.endswith(".npz")):
            os.unlink(os.path.join(path, name))
    leaf_records, mesh_meta = _normalized_leaf_records(agent, shards)
    groups = _slice_groups(leaf_records)
    files = _write_state_files(path, groups, io_stats)
    manifest = {
        "format": FORMAT_VERSION,
        "mode": agent.mode,
        "round": agent.round_no,
        "sim_config": dataclasses.asdict(agent.cfg),
        "n_leaves": len(leaf_records),
        "mesh": mesh_meta,
        "leaves": [
            {
                "dim": dim,
                "axes": list(axes) if axes else None,
                "shape": [int(s) for s in shape],
                "dtype": str(dtype),
            }
            for dim, axes, shape, dtype, _parts in leaf_records
        ],
        "slices": {
            _shard_filename(ordinal): [
                {"leaf": leaf, "start": int(start),
                 "stop": None if stop is None else int(stop)}
                for leaf, start, stop, _arr in entries
            ]
            for ordinal, entries in sorted(groups.items())
        },
        "files": files,
        "db": db.state_dict() if db is not None else None,
    }
    if extra is not None:
        manifest["extra"] = extra
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    _publish_manifest(tmp, manifest_path)
    return path


def _load_slices_v3(path: str, manifest: dict) -> list:
    """Reassemble the v3 per-shard slice files into full host leaves.

    Every slice's shape/dtype is validated against the manifest record
    and the sharded dim's coverage must tile ``[0, shape[dim])`` exactly
    — a missing, duplicated, or overlapping slice is corruption, not a
    silent partial restore."""
    metas = manifest["leaves"]
    out: list = [None] * manifest["n_leaves"]
    windows: dict = {i: [] for i in range(manifest["n_leaves"])}
    for fname, entries in (manifest.get("slices") or {}).items():
        fp = os.path.join(path, fname)
        if not os.path.exists(fp):
            raise CheckpointIntegrityError(
                f"checkpoint {path}: slice file {fname} is missing"
            )
        with np.load(fp) as z:
            for e in entries:
                i, start, stop = int(e["leaf"]), int(e["start"]), e["stop"]
                meta = metas[i]
                shape, dim = tuple(meta["shape"]), meta["dim"]
                arr = z[_slice_key(i, start)]
                if str(arr.dtype) != meta["dtype"]:
                    raise CheckpointIntegrityError(
                        f"checkpoint {path}: slice {fname}:{i}@{start} "
                        f"dtype {arr.dtype} != manifest {meta['dtype']}"
                    )
                if dim is None:
                    if tuple(arr.shape) != shape:
                        raise CheckpointIntegrityError(
                            f"checkpoint {path}: leaf {i} shape "
                            f"{arr.shape} != manifest {shape}"
                        )
                    out[i] = arr
                    windows[i].append((0, shape[0] if shape else 1))
                    continue
                stop = int(stop)
                want = shape[:dim] + (stop - start,) + shape[dim + 1:]
                if tuple(arr.shape) != want:
                    raise CheckpointIntegrityError(
                        f"checkpoint {path}: slice {fname}:{i}@{start} "
                        f"shape {arr.shape} != manifest window {want}"
                    )
                if out[i] is None:
                    out[i] = np.empty(shape, dtype=arr.dtype)
                sl = (slice(None),) * dim + (slice(start, stop),)
                out[i][sl] = arr
                windows[i].append((start, stop))
    for i, meta in enumerate(metas):
        if out[i] is None:
            raise CheckpointIntegrityError(
                f"checkpoint {path}: no slices recorded for leaf {i}"
            )
        dim = meta["dim"]
        if dim is None:
            if len(windows[i]) != 1:
                raise CheckpointIntegrityError(
                    f"checkpoint {path}: unsharded leaf {i} has "
                    f"{len(windows[i])} slices"
                )
            continue
        seen = sorted(windows[i])
        cursor = 0
        for start, stop in seen:
            if start != cursor:
                raise CheckpointIntegrityError(
                    f"checkpoint {path}: leaf {i} slice coverage has a "
                    f"gap/overlap at index {cursor} (next slice starts "
                    f"at {start})"
                )
            cursor = stop
        if cursor != meta["shape"][dim]:
            raise CheckpointIntegrityError(
                f"checkpoint {path}: leaf {i} slices cover only "
                f"[0, {cursor}) of dim {dim} (size {meta['shape'][dim]})"
            )
    return out


def _place_leaves(loaded: list, manifest: dict, cfg, mesh) -> list:
    """Elastic restore placement: put every reassembled leaf directly at
    its TARGET sharding on the resuming process's mesh — whatever shape
    the saving mesh had (different device count, 1-D↔2-D, or none).
    With no mesh the host arrays are returned as-is (single-device
    callers upload them on first use, exactly the v2 behavior)."""
    if mesh is None:
        return loaded
    import jax.numpy as jnp

    from corrosion_tpu.parallel.mesh import elastic_sharding

    metas = manifest.get("leaves") or [{"dim": None}] * len(loaded)
    # jnp.array first (copy semantics), THEN re-place: a bare
    # device_put zero-copy-adopts 64-byte-aligned numpy buffers on the
    # CPU backend, and restored state can reach a DONATED dispatch
    # (e.g. adopted by an agent whose round loop donates the carry) —
    # donating an adopted buffer frees numpy-owned memory (glibc heap
    # corruption, see parallel.mesh.device_put_shards)
    return [
        jax.device_put(
            jnp.array(arr),
            elastic_sharding(mesh, cfg.n_nodes, arr, meta.get("dim")),
        )
        for arr, meta in zip(loaded, metas)
    ]


def load_checkpoint(path: str, verify: bool = True,
                    mesh=None) -> Tuple[dict, object]:
    """-> (manifest, state pytree). The pytree is rebuilt against a
    template constructed from the saved config, so leaf order/shape
    mismatches fail loudly; state-file content hashes are verified
    against the manifest before anything is deserialized.

    ``mesh`` makes the restore **mesh-shape-agnostic**: the recorded
    slices are reassembled and every leaf is placed directly with its
    target sharding on the CURRENT mesh — resuming an 8-chip soak on 4
    chips, folding a 1-D mesh into 2-D ``(dcn, node)`` (or back), or
    collapsing to a single device all produce bitwise-identical state
    (see docs/checkpoints.md)."""
    manifest_path = os.path.join(path, "manifest.json")
    if not os.path.exists(manifest_path):
        raise CheckpointIntegrityError(
            f"checkpoint {path}: no manifest — directory is incomplete "
            f"(a crash mid-save, or not a checkpoint)"
        )
    with open(manifest_path) as f:
        manifest = json.load(f)
    if manifest["format"] not in _SUPPORTED_FORMATS:
        raise ValueError(f"unsupported checkpoint format {manifest['format']}")
    if verify:
        _verify_files(path, manifest)
    if manifest["mode"] == "scale":
        from corrosion_tpu.sim.scale_step import ScaleSimConfig as CfgCls
    else:
        from corrosion_tpu.sim.config import SimConfig as CfgCls
    cfg = CfgCls(**manifest["sim_config"])
    template = _state_template(manifest["mode"], cfg)
    if manifest["format"] >= 3:
        loaded = _load_slices_v3(path, manifest)
    else:  # v1/v2: one whole-state npz
        with np.load(os.path.join(path, "state.npz")) as z:
            loaded = [z[f"leaf_{i}"] for i in range(manifest["n_leaves"])]
    tmpl_leaves, treedef = jax.tree.flatten(template)
    if len(tmpl_leaves) != len(loaded):
        raise ValueError(
            f"checkpoint has {len(loaded)} leaves, config expects "
            f"{len(tmpl_leaves)} — config drift"
        )
    for t, l in zip(tmpl_leaves, loaded):
        if tuple(t.shape) != tuple(l.shape):
            raise ValueError(
                f"leaf shape mismatch: checkpoint {l.shape} vs config {t.shape}"
            )
        if t.dtype != l.dtype:
            raise ValueError(
                f"leaf dtype mismatch: checkpoint {l.dtype} vs config "
                f"{t.dtype}"
            )
    loaded = _place_leaves(loaded, manifest, cfg, mesh)
    state = jax.tree.unflatten(treedef, loaded)
    return manifest, state


def verify_checkpoint(path: str) -> dict:
    """Full integrity check of a checkpoint directory without touching
    any live agent: manifest present + parseable, format supported, leaf
    files hash-clean, and the state pytree deserializes against the saved
    config. Returns a summary dict; raises (``CheckpointIntegrityError``
    / ``ValueError``) on any defect — the CLI's ``verify-checkpoint``
    maps that to a non-zero exit."""
    manifest, state = load_checkpoint(path, verify=True)
    return {
        "path": path,
        "format": manifest["format"],
        "mode": manifest["mode"],
        "round": manifest["round"],
        "n_leaves": manifest["n_leaves"],
        # sharded (v3) checkpoints: how many per-device slice files the
        # state is split over (1 = v2 whole-state or single-device save)
        "shards": len(manifest["slices"]) if manifest.get("slices") else 1,
        "mesh": manifest.get("mesh"),
        "hashed_files": sorted((manifest.get("files") or {})),
        "extra": manifest.get("extra"),
    }


def restore_checkpoint(agent, path: str, db=None, verify: bool = True) -> dict:
    """Swap a checkpoint into a live agent (+ its Database host state).

    ``verify=False`` skips the hash pass — for callers that just ran
    ``verify_checkpoint``/``latest_valid_checkpoint`` on the same path
    and would otherwise hash and decompress the state twice per
    recovery."""
    manifest, state = load_checkpoint(path, verify=verify)
    if manifest["mode"] != agent.mode:
        raise ValueError(
            f"checkpoint mode {manifest['mode']!r} != agent mode {agent.mode!r}"
        )
    if not agent.restore_state(state):
        raise TimeoutError("restore did not apply in time")
    if db is not None:
        if manifest.get("db") is not None:
            db.load_state_dict(manifest["db"])
        else:
            # the device state rewinds but the host DB cannot: this
            # checkpoint was written without db= (a soak segment, an
            # external save). Rows committed after the checkpoint stay
            # visible host-side even though the cluster no longer holds
            # them — surface the divergence instead of hiding it.
            from corrosion_tpu.utils.tracing import logger

            logger.warning(
                "checkpoint %s carries no host-DB state; the attached "
                "Database was NOT rewound and may serve rows the "
                "restored cluster no longer holds", path,
            )
    return manifest


# --- portable single-node backup ----------------------------------------

def backup_node(agent, node: int, db=None, path: str = "./backup.npz") -> str:
    """Portable backup of one node's replica (``corrosion backup``)."""
    snap = agent.snapshot()
    planes = {f"plane_{i}": p[node] for i, p in enumerate(snap["store"])}
    np.savez_compressed(
        path,
        **planes,
        head=snap["head"][node],
        known_max=snap["known_max"][node],
        meta=np.array(
            [FORMAT_VERSION, node, len(snap["store"])], np.int64
        ),
    )
    if db is not None:
        with open(path + ".db.json", "w") as f:
            json.dump(db.state_dict(), f)
    return path


def restore_backup(agent, path: str, node: Optional[int] = None,
                   db=None, repivot: bool = True) -> int:
    """Graft a node backup onto ``node`` of a live cluster.

    With ``repivot`` (the site-id ordinal rewrite analog), site-plane
    entries naming the backed-up node are rewritten to the restored
    node's id, so columns the old identity authored are attributed to the
    new one — including the per-origin head/known_max bookkeeping rows,
    so version attribution stays consistent with the rewritten site plane
    (the reference's restore likewise rewrites the site-id ordinal
    mapping, ``main.rs:227-330``).

    Restoring onto a cluster where ``src_node`` is still a live, distinct
    identity is NOT supported: the grafted cells claim (site=target, dbv)
    pairs drawn from src's version counter, which may collide with or
    outrun versions target already authored. Use it the way the reference
    does — to move an identity, not to clone one."""
    with np.load(path) as z:
        fmt, src_node, n_planes = (int(x) for x in z["meta"])
        if fmt != FORMAT_VERSION:
            raise ValueError(f"unsupported backup format {fmt}")
        planes = [np.array(z[f"plane_{i}"]) for i in range(n_planes)]
        head = np.array(z["head"])
        known_max = np.array(z["known_max"])
    target = src_node if node is None else node
    if repivot and target != src_node:
        site = planes[2]  # (ver, val, site, dbv) plane order
        site[site == src_node] = target
        # move the origin-axis bookkeeping with the identity: versions the
        # backup attributes to origin src_node are now target's
        n_origins = head.shape[0]
        if src_node < n_origins:
            if target < n_origins:
                head[target] = max(head[target], head[src_node])
                known_max[target] = max(known_max[target], known_max[src_node])
            head[src_node] = 0
            known_max[src_node] = 0
    # patch the live state on host, then stage the swap
    state = agent.device_state()
    store = tuple(
        np.asarray(p).copy() for p in state.crdt.store
    )
    for plane, backup_plane in zip(store, planes):
        plane[target] = backup_plane
    h = np.asarray(state.crdt.book.head).copy()
    km = np.asarray(state.crdt.book.known_max).copy()
    h[target] = head
    km[target] = np.maximum(known_max, km[target])
    # the seen window is relative to the head being replaced — clear it
    # (out-of-order dedupe hints only; anti-entropy sync re-derives them)
    seen = np.asarray(state.crdt.book.seen).copy()
    seen[target] = 0
    crdt = state.crdt._replace(
        store=tuple(store),
        book=state.crdt.book._replace(head=h, known_max=km, seen=seen),
    )
    if not agent.restore_state(state._replace(crdt=crdt)):
        raise TimeoutError("backup restore did not apply in time")
    if db is not None and os.path.exists(path + ".db.json"):
        with open(path + ".db.json") as f:
            db.load_state_dict(json.load(f))
    return target
