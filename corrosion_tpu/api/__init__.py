"""Public API layer: HTTP server + client types.

Maps the reference's layer 7 (``crates/corro-agent/src/api/public/``,
routes registered at ``agent/util.rs:182-294``).
"""

from corrosion_tpu.api.http import ApiServer

__all__ = ["ApiServer"]
