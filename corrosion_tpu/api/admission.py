"""corroguard admission control: per-route-class concurrency + queue
limits for the serving plane (docs/overload.md).

The reference survives swamped nodes by shedding at the edges — bounded
channels drop, HTTP returns 503, subscribers get disconnected — instead
of queueing without bound until latency diverges. This module is the
policy surface for our port's host plane: every HTTP route (except the
control plane — health, readiness, metrics must answer precisely when
the node is drowning) and every PG-wire connection passes through one
:class:`AdmissionController` shared by :class:`~corrosion_tpu.api.http.
ApiServer` and :class:`~corrosion_tpu.pg.PgServer`.

Policy (config ``[serve]``, :class:`~corrosion_tpu.config.ServeConfig`):
each route class admits at most ``max_inflight`` concurrent requests;
up to ``max_queue`` more may wait ``queue_wait`` seconds for a slot;
everything past that is shed with 503 + ``Retry-After``. The hint is
not a constant: it is derived from the LIVE latency histograms
(``corro.http.request.seconds`` / ``corro.pg.query.seconds``) as
p95 × (requests ahead of you), clamped to ``[1, retry_after_cap]`` —
an overloaded node quotes a wait proportional to how overloaded it
actually is. ``max_inflight <= 0`` disables the guard entirely (the
unguarded plane the overload bench drives to the breaking point).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

from corrosion_tpu.config import ServeConfig
from corrosion_tpu.utils.metrics import (
    REGISTRY,
    Registry,
    aggregate_histograms,
    histogram_quantile,
)

#: the route classes admission partitions the plane into. "write" and
#: "read" are one-shot requests; "stream" tickets are held for the whole
#: NDJSON stream; "pg" tickets are held for the whole wire connection.
ROUTE_CLASSES = ("write", "read", "stream", "pg")

#: latency family each class derives its Retry-After from
_LATENCY_SOURCE = {
    "write": "corro.http.request.seconds",
    "read": "corro.http.request.seconds",
    "stream": "corro.http.request.seconds",
    "pg": "corro.pg.query.seconds",
}


def route_class(route: str, method: str) -> Optional[str]:
    """Map a templated route label (``route_label`` form) + method to
    its admission class — ``None`` is the control plane, never gated."""
    if route in ("/v1/health", "/v1/ready", "/metrics"):
        return None
    if route.startswith("/v1/subscriptions") or route.startswith(
            "/v1/updates"):
        return "stream"
    if method == "POST" and route in ("/v1/transactions", "/v1/migrations"):
        return "write"
    return "read"


class AdmissionController:
    """Shared per-route-class admission state.

    ``admit(cls)`` returns True (slot held — pair with ``release(cls)``
    in a finally) or False (shed). A full class queues the caller on a
    condition variable for at most ``queue_wait`` seconds when fewer
    than ``max_queue`` others are already waiting; timing out or finding
    the waiting room full both shed. Counters: ``corro.admission.
    admitted_total`` / ``rejected_total`` / ``queued_total`` plus the
    ``corro.admission.inflight`` and ``corro.admission.queue.depth``
    level gauges, all labelled ``{class}``.
    """

    def __init__(self, cfg: Optional[ServeConfig] = None,
                 registry: Registry = REGISTRY):
        self.cfg = cfg or ServeConfig()
        self.registry = registry
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._inflight = {c: 0 for c in ROUTE_CLASSES}
        self._waiting = {c: 0 for c in ROUTE_CLASSES}
        # retry_after memo: deriving the hint snapshots the registry,
        # and rejects are exactly the path that must stay cheap under
        # overload — recompute at most every 0.25 s per class
        self._ra_memo = {}  # cls -> (monotonic_ts, seconds)

    @property
    def enabled(self) -> bool:
        return self.cfg.max_inflight > 0

    def capacity(self, cls: str) -> int:
        """Concurrency cap for a class. ``stream`` and ``pg`` tickets
        are held for the whole stream / wire connection, so they get
        ``max_streams`` when set (> 0) rather than starving one-shot
        requests out of ``max_inflight``."""
        if cls in ("stream", "pg") and self.cfg.max_streams > 0:
            return self.cfg.max_streams
        return self.cfg.max_inflight

    def admit(self, cls: str) -> bool:
        """Take a slot in ``cls`` (True) or get shed (False)."""
        if not self.enabled:
            return True
        reg = self.registry
        cap = self.capacity(cls)
        deadline = None
        with self._cv:
            queued = False
            while self._inflight[cls] >= cap:
                if not queued:
                    if self._waiting[cls] >= self.cfg.max_queue:
                        self._reject_locked(cls)
                        return False
                    queued = True
                    self._waiting[cls] += 1
                    reg.counter("corro.admission.queued_total", 1.0,
                                {"class": cls})
                    reg.gauge("corro.admission.queue.depth",
                              float(self._waiting[cls]), {"class": cls})
                    deadline = time.monotonic() + self.cfg.queue_wait
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(remaining):
                    # timed out waiting for a slot: leave the queue, shed
                    self._waiting[cls] -= 1
                    reg.gauge("corro.admission.queue.depth",
                              float(self._waiting[cls]), {"class": cls})
                    self._reject_locked(cls)
                    return False
            if queued:
                self._waiting[cls] -= 1
                reg.gauge("corro.admission.queue.depth",
                          float(self._waiting[cls]), {"class": cls})
            self._inflight[cls] += 1
            reg.counter("corro.admission.admitted_total", 1.0,
                        {"class": cls})
            reg.gauge("corro.admission.inflight",
                      float(self._inflight[cls]), {"class": cls})
        return True

    def release(self, cls: str) -> None:
        if not self.enabled:
            return
        with self._cv:
            self._inflight[cls] -= 1
            self.registry.gauge("corro.admission.inflight",
                                float(self._inflight[cls]), {"class": cls})
            self._cv.notify()

    def _reject_locked(self, cls: str) -> None:
        self.registry.counter("corro.admission.rejected_total", 1.0,
                              {"class": cls})

    # --- Retry-After derivation ------------------------------------------
    def retry_after(self, cls: str) -> int:
        """Whole seconds a shed client should wait before retrying:
        live p95 service time × (requests ahead of it — inflight plus
        waiters of its class), clamped to ``[1, retry_after_cap]``. An
        empty histogram (cold plane) quotes the 1 s floor."""
        now = time.monotonic()
        with self._mu:
            memo = self._ra_memo.get(cls)
            ahead = self._inflight.get(cls, 0) + self._waiting.get(cls, 0)
        if memo is not None and now - memo[0] < 0.25:
            p95 = memo[1]
        else:
            agg = aggregate_histograms(self.registry.snapshot(),
                                       _LATENCY_SOURCE[cls])
            p95 = histogram_quantile(agg, 0.95)
            with self._mu:
                self._ra_memo[cls] = (now, p95)
        hint = p95 * max(1, ahead)
        return int(min(self.cfg.retry_after_cap,
                       max(1.0, math.ceil(hint))))
