"""HTTP API server.

The reference's public REST surface (``crates/corro-agent/src/api/public/``,
router at ``agent/util.rs:182-294``), same routes and event shapes:

- ``POST /v1/transactions[?node=K]`` — JSON array of statements (each a
  string, ``[sql, params]`` pair, or ``{"query", "params"}``) executed as
  one transaction at writer node K; returns ``{"results": [...]}``
  (``api_v1_transactions``, ``public/mod.rs:177-256``).
- ``POST /v1/queries[?node=K]`` — one read-only statement; NDJSON stream
  of ``{"columns"}``, ``{"row": [rowid, values]}``, ``{"eoq"}`` events
  (``public/mod.rs:266-538``).
- ``POST /v1/subscriptions[?node=K&from=ID]`` — subscribe to a query;
  NDJSON stream (initial snapshot then ``{"change"}`` events); the
  matcher id is returned in the ``corro-query-id`` header.
  ``GET /v1/subscriptions/{id}[?from=ID]`` re-attaches, resuming from a
  ChangeId (``api/public/pubsub.rs:29-112``).
- ``GET /v1/updates/{table}`` — row-level NotifyEvent stream
  (``api/public/update.rs``).
- ``POST /v1/migrations`` — JSON array of schema SQL strings
  (``execute_schema``, ``public/mod.rs:540-593``).
- ``GET /v1/table_stats``, ``GET /v1/members``, ``GET /v1/sync`` —
  introspection (admin surface exposes the same data over UDS).
- ``GET /v1/obs/memory`` — per-table HBM audit of the live device state
  (``obs/memory.py``; metadata only, docs/observability.md).
- ``GET /metrics`` — Prometheus exposition (the reference serves this on
  the telemetry listener, ``command/agent.rs:114-139``); a running
  soak advances the ``corro.soak.*`` series here live (ISSUE 11).

Statement values ride JSON; blobs are not representable in JSON and use
``{"blob": "<hex>"}`` wrappers on both paths.
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional, Tuple

from corrosion_tpu.api.admission import AdmissionController, route_class
from corrosion_tpu.db.database import SqlError
from corrosion_tpu.db.schema import SchemaError
from corrosion_tpu.pubsub import SubsManager, UpdatesManager
from corrosion_tpu.utils.lifecycle import DrainingConnMixin
from corrosion_tpu.utils.tracing import inject_traceparent, logger, span


class _DrainingHTTPServer(DrainingConnMixin, ThreadingHTTPServer):
    _conn_name = "corro-http-conn"

# fixed route templates (ISSUE 16): request metrics label by TEMPLATE,
# never by raw path — subscription ids and table names in the path (or
# arbitrary 404 probes) would otherwise mint unbounded label cardinality
_FIXED_ROUTES = frozenset({
    "/v1/transactions", "/v1/queries", "/v1/migrations",
    "/v1/subscriptions", "/v1/health", "/v1/ready", "/v1/table_stats",
    "/v1/members", "/v1/sync", "/v1/obs/memory", "/metrics",
})


def route_label(path: str) -> str:
    """Collapse a request path onto its route template."""
    if path in _FIXED_ROUTES:
        return path
    if path.startswith("/v1/subscriptions/"):
        return "/v1/subscriptions/{id}"
    if path.startswith("/v1/updates/"):
        return "/v1/updates/{table}"
    return "unmatched"


def _encode_value(v: Any) -> Any:
    if isinstance(v, bytes):
        return {"blob": v.hex()}
    return v


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict) and set(v) == {"blob"}:
        return bytes.fromhex(v["blob"])
    return v


def _decode_params(params: Any) -> Any:
    if isinstance(params, dict):
        return {k: _decode_value(v) for k, v in params.items()}
    if isinstance(params, list):
        return [_decode_value(v) for v in params]
    return params


def parse_statements(body: Any) -> List[Tuple[str, Any]]:
    """JSON statement forms -> (sql, params) pairs (corro-api-types
    ``Statement``: Simple / WithParams / WithNamedParams)."""
    out: List[Tuple[str, Any]] = []
    for stmt in body:
        if isinstance(stmt, str):
            out.append((stmt, None))
        elif isinstance(stmt, list):
            sql = stmt[0]
            params = _decode_params(stmt[1]) if len(stmt) > 1 else None
            out.append((sql, params))
        elif isinstance(stmt, dict):
            out.append((stmt["query"], _decode_params(stmt.get("params"))))
        else:
            raise SqlError(f"bad statement shape: {type(stmt).__name__}")
    return out


class ApiServer:
    """HTTP listener bound to one Database (+ its Agent)."""

    def __init__(self, db, addr: str = "127.0.0.1", port: int = 0,
                 default_node: int = 0, subs: Optional[SubsManager] = None,
                 updates: Optional[UpdatesManager] = None, serve=None,
                 admission: Optional[AdmissionController] = None):
        self.db = db
        self.agent = db.agent
        self.default_node = default_node
        # corroguard (docs/overload.md): ``serve`` is the [serve] config
        # section (queue bounds + admission limits); ``admission`` lets a
        # PgServer share ONE controller so both listeners shed against
        # the same per-class budgets
        self.serve = serve
        self.admission = admission or AdmissionController(
            serve, registry=db.agent.metrics)
        self.subs = subs or SubsManager(db, serve=serve)
        self.updates = updates or UpdatesManager(db, serve=serve)
        handler = _make_handler(self)
        self.httpd = _DrainingHTTPServer((addr, port), handler)
        self.addr, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        # stop() raises this so streaming handlers (which otherwise
        # poll their queue forever on a quiet subscription) exit within
        # one poll period and the connection drain stays graceful
        self._stopping = threading.Event()

    def start(self) -> "ApiServer":
        from corrosion_tpu.utils.lifecycle import spawn_counted

        # counted + corro- named (ISSUE 8): stop() drains serve_forever
        # and joins, so the shutdown barrier and the sanitizer's leak
        # gate both see an attributable, finishing thread
        self._thread = spawn_counted(
            self.httpd.serve_forever, name="corro-api-http"
        )
        return self

    def stop(self) -> None:
        self._stopping.set()
        self.httpd.shutdown()
        self.httpd.drain_connections()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def _make_handler(server: ApiServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # per-request accounting (reset by _serve; a keep-alive
        # connection reuses one Handler instance across requests)
        _code = 0
        _resp_bytes = 0

        def log_message(self, fmt, *args):  # route to our logger
            logger.debug("http: " + fmt, *args)

        def send_response(self, code, message=None):
            self._code = code
            super().send_response(code, message)

        # --- helpers -----------------------------------------------------
        def _json_body(self) -> Any:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) if length else b""
            return json.loads(raw) if raw else None

        def _reply_json(self, code: int, obj: Any,
                        headers: Optional[dict] = None) -> None:
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(data)
            self._resp_bytes += len(data)

        def _reply_error(self, code: int, msg: str) -> None:
            self._reply_json(code, {"error": msg})

        def _start_ndjson(self, headers: Optional[dict] = None) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()

        def _ndjson_line(self, obj: Any) -> None:
            self._write_frame(json.dumps(obj).encode() + b"\n")

        def _write_frame(self, data: bytes) -> None:
            """One NDJSON line, pre-encoded: the chunked framing around
            the multicast bytes the batched fanout cached (the hot path
            writes frames verbatim instead of re-encoding per
            subscriber — corroguard, docs/overload.md)."""
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()
            self._resp_bytes += len(data)

        def _end_chunks(self) -> None:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()

        def _route(self) -> Tuple[str, dict]:
            parsed = urllib.parse.urlparse(self.path)
            q = {k: v[-1] for k, v in
                 urllib.parse.parse_qs(parsed.query).items()}
            return parsed.path.rstrip("/"), q

        def _node(self, q: dict) -> int:
            return int(q.get("node", server.default_node))

        # --- instrumented dispatch (ISSUE 16) ----------------------------
        def do_POST(self):
            self._serve("POST")

        def do_GET(self):
            self._serve("GET")

        def _serve(self, method: str) -> None:
            """Every route rides one instrumented envelope: a joined
            per-request span (the ``sync.serve`` traceparent pattern
            extended to the whole surface — a client write traces
            through commit into fanout), the per-{route, method, code}
            latency histogram, the in-flight gauge, and request/response
            byte counters. Streaming routes observe their full stream
            lifetime — that IS the request latency for an NDJSON feed."""
            path, q = self._route()
            route = route_label(path)
            metrics = server.agent.metrics
            self._code = 0
            self._resp_bytes = 0
            req_bytes = int(self.headers.get("Content-Length") or 0)
            metrics.gauge_add("corro.http.inflight", 1)
            t0 = time.perf_counter()
            # corroguard admission (docs/overload.md): every route
            # except the control plane takes a per-class slot before
            # dispatch; a shed request still rides the full metrics
            # envelope below (the 503 is a served request — the
            # server-vs-client agreement gates count it)
            cls = route_class(route, method)
            admitted = cls is None or server.admission.admit(cls)
            try:
                if not admitted:
                    self._reject_overloaded(cls, route)
                else:
                    with span(f"http.{method.lower()}.{route}",
                              traceparent=self.headers.get("traceparent"),
                              route=route, method=method):
                        if method == "POST":
                            self._dispatch_post(path, q)
                        else:
                            self._dispatch_get(path, q)
            except (SqlError, SchemaError, ValueError, KeyError) as e:
                self._reply_error(400, str(e))
            except BrokenPipeError:
                pass
            except Exception as e:  # noqa: BLE001
                logger.exception("http handler error")
                try:
                    self._reply_error(500, str(e))
                except Exception:  # noqa: BLE001 — headers may be gone
                    pass
            finally:
                if admitted and cls is not None:
                    server.admission.release(cls)
                dt = time.perf_counter() - t0
                metrics.gauge_add("corro.http.inflight", -1)
                metrics.histogram(
                    "corro.http.request.seconds", dt,
                    {"route": route, "method": method,
                     "code": str(self._code or 0)})
                if req_bytes:
                    metrics.counter(
                        "corro.http.request.bytes", float(req_bytes),
                        {"route": route, "method": method})
                if self._resp_bytes:
                    metrics.counter(
                        "corro.http.response.bytes", float(self._resp_bytes),
                        {"route": route, "method": method})

        def _dispatch_post(self, path: str, q: dict) -> None:
            if path == "/v1/transactions":
                self._transactions(q)
            elif path == "/v1/queries":
                self._queries(q)
            elif path == "/v1/migrations":
                self._migrations()
            elif path == "/v1/subscriptions":
                self._subscribe_new(q)
            else:
                self._reply_error(404, f"no such route: POST {path}")

        def _dispatch_get(self, path: str, q: dict) -> None:
            if path in ("/v1/health", "/v1/ready"):
                self._health()
            elif path == "/v1/table_stats":
                self._reply_json(
                    200, server.db.table_stats(self._node(q)))
            elif path == "/v1/members":
                self._reply_json(200, server.agent.members())
            elif path == "/v1/sync":
                node = self._node(q)
                # serve_sync answers inside its own joined span
                # (sync.rs:33-67 + peer/mod.rs:1414-1416), nested under
                # the request span; the server span id is returned so
                # the caller can link both sides
                with span("sync.serve",
                          traceparent=self.headers.get("traceparent")):
                    state = server.agent.sync_state(node)
                    state["traceparent"] = inject_traceparent()
                self._reply_json(200, state)
            elif path == "/v1/obs/memory":
                # per-table HBM audit of the live state (ISSUE 11):
                # array metadata only, never a device transfer —
                # cheap enough to poll while a 1M-node soak runs
                self._reply_json(200, server.agent.memory_report())
            elif path == "/metrics":
                data = server.agent.metrics.render().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                self._resp_bytes += len(data)
            elif path.startswith("/v1/subscriptions/"):
                self._subscribe_existing(path.rsplit("/", 1)[1], q)
            elif path.startswith("/v1/updates/"):
                self._updates_feed(path.rsplit("/", 1)[1])
            else:
                self._reply_error(404, f"no such route: GET {path}")

        # --- route bodies ------------------------------------------------
        def _reject_overloaded(self, cls: str, route: str) -> None:
            """corroguard shed: 503 + a Retry-After derived from the
            LIVE latency histograms (p95 × requests ahead, clamped),
            riding the same unready accounting the ``/v1/ready``
            machinery established (docs/overload.md)."""
            ra = server.admission.retry_after(cls)
            metrics = server.agent.metrics
            metrics.counter("corro.http.unready_total", 1.0,
                            {"status": "overloaded"})
            metrics.histogram("corro.http.retry_after.seconds", float(ra))
            self._reply_json(
                503,
                {"error": "overloaded", "class": cls, "route": route,
                 "retry_after": ra},
                headers={"Retry-After": str(ra)})

        def _health(self) -> None:
            """``/v1/health`` and ``/v1/ready`` (both route here — the
            two names exist for orchestrator convention; this agent has
            no alive-but-not-ready phase they could distinguish).

            Degrades gracefully instead of lying: while the agent is
            restoring a checkpoint or the watchdog supervisor is backing
            off between dispatch retries, the reply is 503 with a
            ``Retry-After`` hint so load balancers drain politely and
            clients (whose retries ride the shared ``retry_call``
            policy) know when to come back. Once the agent is shut down
            for good the 503 carries no ``Retry-After``: nothing will
            recover — restart instead of waiting."""
            h = server.agent.health()
            ok = h["ready"]
            headers = {}
            if not ok and h["status"] != "down":
                headers["Retry-After"] = str(h.get("retry_after", 1))
            if not ok:
                # readiness shedding is measurable (ISSUE 16): the
                # future admission-control PR needs a baseline of how
                # often — and for how long — the plane turned clients
                # away while restoring / backing off
                metrics = server.agent.metrics
                metrics.counter("corro.http.unready_total", 1.0,
                                {"status": h["status"]})
                if "Retry-After" in headers:
                    metrics.histogram("corro.http.retry_after.seconds",
                                      float(headers["Retry-After"]))
            self._reply_json(200 if ok else 503, h, headers=headers)

        def _transactions(self, q: dict) -> None:
            stmts = parse_statements(self._json_body() or [])
            results = server.db.execute(self._node(q), stmts)
            self._reply_json(200, {"results": [dict(r) for r in results]})

        def _queries(self, q: dict) -> None:
            body = self._json_body()
            stmts = parse_statements([body])
            sql, params = stmts[0]
            cols, rows = server.db.query(self._node(q), sql, params)
            self._start_ndjson()
            self._ndjson_line({"columns": cols})
            for i, row in enumerate(rows):
                self._ndjson_line(
                    {"row": [i + 1, [_encode_value(v) for v in row]]}
                )
            self._ndjson_line({"eoq": {}})
            self._end_chunks()

        def _migrations(self) -> None:
            body = self._json_body() or []
            if isinstance(body, str):
                body = [body]
            changes = []
            for sql in body:
                changes.extend(server.db.apply_schema_sql(sql))
            self._reply_json(200, {"results": [list(c) for c in changes]})

        def _subscribe_new(self, q: dict) -> None:
            body = self._json_body()
            sql, params = parse_statements([body])[0]
            from_id = int(q["from"]) if "from" in q else None
            matcher, _created = server.subs.subscribe(
                self._node(q), sql, params)
            self._stream_matcher(matcher, from_id)

        def _subscribe_existing(self, sub_id: str, q: dict) -> None:
            matcher = server.subs.get(sub_id)
            if matcher is None:
                self._reply_error(404, f"no such subscription: {sub_id}")
                return
            from_id = int(q["from"]) if "from" in q else None
            self._stream_matcher(matcher, from_id)

        def _clamp_stream_socket(self) -> None:
            """Bound the kernel half of the delivery pipeline: the
            per-sub queue only bounds a slow consumer's lag if the
            socket send buffer behind it can't silently absorb the
            backlog (docs/overload.md)."""
            sndbuf = getattr(server.serve, "stream_sndbuf", 0) or 0
            if sndbuf > 0:
                try:
                    self.connection.setsockopt(
                        socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
                except OSError:
                    pass

        def _stream_matcher(self, matcher, from_id: Optional[int]) -> None:
            sub_q = matcher.attach(from_change_id=from_id)
            self._clamp_stream_socket()
            self._start_ndjson({"corro-query-id": matcher.id})
            try:
                while not (server.agent.tripwire.tripped
                           or server._stopping.is_set()):
                    try:
                        kind, payload = sub_q.get(timeout=1.0)
                    except queue.Empty:
                        if sub_q.lagged:
                            # slow consumer disconnected by the fanout:
                            # the stream's last line is an explicit
                            # resync marker — the client must re-snapshot
                            # (docs/overload.md resync contract)
                            self._resync_marker(
                                sub_q.take_resync(), matcher,
                                "slow-consumer")
                            break
                        continue
                    # shed-oldest drops leave a gap in the change-id
                    # sequence: announce it BEFORE the next event so the
                    # client knows the stream skipped ahead
                    dropped = sub_q.take_resync()
                    if dropped:
                        self._resync_marker(dropped, matcher,
                                            "shed-oldest")
                    if kind == "columns":
                        self._ndjson_line({"columns": payload})
                    elif kind == "row":
                        key, row = payload
                        self._ndjson_line(
                            {"row": [_encode_value(key),
                                     [_encode_value(v) for v in row]]}
                        )
                    elif kind == "eoq":
                        self._ndjson_line({"eoq": {"change_id": payload}})
                    elif kind == "change":
                        cid, ckind, key, row = payload
                        # batched fanout: multicast the frame the matcher
                        # encoded once for ALL subscribers; encode only
                        # when the cache already trimmed past this id
                        frame = matcher.wire_frame(cid)
                        if frame is None:
                            frame = json.dumps({"change": [
                                ckind, _encode_value(key),
                                None if row is None
                                else [_encode_value(v) for v in row],
                                cid,
                            ]}).encode() + b"\n"
                        self._write_frame(frame)
                        self._observe_delivery(matcher, key)
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                matcher.detach(sub_q)

        def _resync_marker(self, dropped: int, matcher, reason: str
                           ) -> None:
            """The catch-up resync marker (docs/overload.md): the
            stream shed frames (or is disconnecting a slow consumer) —
            the client must re-snapshot, or re-attach with
            ``?from=<last delivered id>`` to replay the gap from the
            retained change log."""
            self._ndjson_line({"resync": {
                "dropped": int(dropped),
                "change_id": matcher.last_change_id,
                "reason": reason,
            }})

        def _observe_delivery(self, matcher, key) -> None:
            """Write-commit -> NDJSON delivery latency: the change event
            just went out on the wire; diff against the commit stamp the
            Database recorded for its (table, pk). Composite JOIN keys
            observe the first component carrying a stamp (the write that
            triggered the event)."""
            keys = key if isinstance(key, tuple) else (key,)
            now = time.perf_counter()
            for table, pk in zip(matcher.delivery_tables, keys):
                t = server.db.write_stamp(table, pk)
                if t is not None:
                    server.agent.metrics.histogram(
                        "corro.subs.delivery.seconds", max(0.0, now - t))
                    return

        def _updates_feed(self, table: str) -> None:
            feed_q = server.updates.attach(table)
            self._clamp_stream_socket()
            self._start_ndjson()
            try:
                while not (server.agent.tripwire.tripped
                           or server._stopping.is_set()):
                    try:
                        kind, payload = feed_q.get(timeout=1.0)
                    except queue.Empty:
                        if feed_q.lagged:
                            # same resync contract as subscriptions
                            self._ndjson_line({"resync": {
                                "dropped": feed_q.take_resync(),
                                "reason": "slow-consumer"}})
                            break
                        continue
                    dropped = feed_q.take_resync()
                    if dropped:
                        self._ndjson_line({"resync": {
                            "dropped": dropped, "reason": "shed-oldest"}})
                    ckind, pk = payload
                    self._ndjson_line({"notify": [ckind, _encode_value(pk)]})
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                server.updates.detach(table, feed_q)

    return Handler
