"""The fused whole-cluster step: SWIM + writes + broadcast + sync.

This is the simulator's "training step": one call advances every
simulated node through one protocol round — the analog of every
corro-agent loop (``runtime_loop``, ``handle_changes``, ``sync_loop``)
ticking once across the whole cluster. It is pure, jittable, and
scannable (``lax.scan`` over rounds), which is what the benchmark
measures (rounds/sec) and what shards over the device mesh.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.random as jr

from corrosion_tpu.ops.lww import STATE_ALIVE
from corrosion_tpu.ops.versions import needs_count
from corrosion_tpu.sim.broadcast import (
    CrdtState,
    bcast_step,
    local_write,
    local_write_tx,
)
from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.swim import SwimState, swim_metrics, swim_step
from corrosion_tpu.sim.transport import NetModel


class SimState(NamedTuple):
    swim: SwimState
    crdt: CrdtState

    @staticmethod
    def create(cfg: SimConfig, n_seeds: int = 4) -> "SimState":
        return SimState(SwimState.create(cfg, n_seeds), CrdtState.create(cfg))


class RoundInput(NamedTuple):
    """External events for one round (fault + workload injection)."""

    kill: jax.Array  # bool [N]
    revive: jax.Array  # bool [N]
    write_mask: jax.Array  # bool [N] (effective only for nodes < n_origins)
    write_cell: jax.Array  # int32 [N]
    write_val: jax.Array  # int32 [N]
    write_clp: jax.Array  # int32 [N] — causal-length lifetime of the write
    # multi-cell transactions (one per node per round, K = tx_max_cells
    # lanes; chunked delivery + remote atomicity — change.rs:66-178)
    tx_mask: jax.Array  # bool [N]
    tx_len: jax.Array  # int32 [N] — real lanes (1..K)
    tx_cell: jax.Array  # int32 [N, K]
    tx_val: jax.Array  # int32 [N, K]
    tx_clp: jax.Array  # int32 [N, K]

    @staticmethod
    def quiet(cfg: SimConfig) -> "RoundInput":
        n, k = cfg.n_nodes, max(1, cfg.tx_max_cells)
        return RoundInput(
            kill=jnp.zeros(n, bool),
            revive=jnp.zeros(n, bool),
            write_mask=jnp.zeros(n, bool),
            write_cell=jnp.zeros(n, jnp.int32),
            write_val=jnp.zeros(n, jnp.int32),
            write_clp=jnp.zeros(n, jnp.int32),
            tx_mask=jnp.zeros(n, bool),
            tx_len=jnp.ones(n, jnp.int32),
            tx_cell=jnp.zeros((n, k), jnp.int32),
            tx_val=jnp.zeros((n, k), jnp.int32),
            tx_clp=jnp.zeros((n, k), jnp.int32),
        )


def sim_step(cfg: SimConfig, st: SimState, net: NetModel, key, inp: RoundInput):
    """One full protocol round for the whole cluster."""
    from corrosion_tpu.ops.select import sample_k, sample_k_biased  # local: avoid import cycle
    from corrosion_tpu.sim.broadcast import LAST_SYNC_CAP
    from corrosion_tpu.sim.sync import choose_sync_peers, sync_step
    from corrosion_tpu.sim.transport import ring_of, same_region

    n = cfg.n_nodes
    k_swim, k_bcast, k_sync, k_bt, k_sp = jr.split(key, 5)
    swim, swim_info = swim_step(
        cfg, st.swim, net, k_swim, kill=inp.kill, revive=inp.revive
    )
    believed = (swim.view >= 0) & ((swim.view & 3) == STATE_ALIVE)
    cand = believed & ~jnp.eye(n, dtype=bool)

    # tick the round counter — the HLC's physical time axis
    cst = st.crdt._replace(now=st.crdt.now + 1)
    cst = local_write(
        cfg, cst, inp.write_mask, inp.write_cell, inp.write_val,
        inp.write_clp,
    )
    if cfg.tx_max_cells > 1:
        cst = local_write_tx(
            cfg, cst, inp.tx_mask, inp.tx_cell, inp.tx_val, inp.tx_clp,
            inp.tx_len,
        )
    # broadcast fanout: ring0 (same-region) members take strict priority,
    # the rest of the set is random — handle_broadcasts sends local
    # changes to ring0 first, then random members (broadcast/mod.rs:653-713)
    ring0 = same_region(net)
    targets, t_ok = sample_k_biased(
        cand & swim.alive[:, None], ring0.astype(jnp.float32), cfg.bcast_fanout,
        k_bt,
    )
    cst, b_info = bcast_step(cfg, cst, targets, t_ok, swim.alive, net, k_bcast)

    # need-driven sync peer choice from a 2x random sample: most-needed
    # versions first, then longest since last sync, then closest ring
    # (handlers.rs:808-894); last_sync tracks are peer node ids here
    iarr = jnp.arange(n, dtype=jnp.int32)
    p_cnt = cfg.sync_peers
    cand_ids, cand_sok = sample_k(cand, min(2 * p_cnt, n), k_sp)
    staleness = jnp.take_along_axis(cst.last_sync, cand_ids, axis=1)
    rings_c = ring_of(
        net, jnp.broadcast_to(iarr[:, None], cand_ids.shape), cand_ids
    )
    peers, p_ok, _ = choose_sync_peers(
        cfg, cst.book, cand_ids, cand_sok, staleness, rings_c, p_cnt
    )
    sweep = None
    if getattr(cfg, "sync_sweep_every", 0) > 0:
        sweep = (
            cst.now % (max(1, cfg.sync_interval)
                       * cfg.sync_sweep_every) == 0
        )
    cst, s_ok, s_info = sync_step(
        cfg, cst, peers, p_ok, swim.alive, net, k_sync, sweep=sweep
    )
    ls = jnp.minimum(cst.last_sync + 1, LAST_SYNC_CAP)
    flat = jnp.where(s_ok, iarr[:, None] * n + peers, n * n)
    ls = (
        ls.reshape(-1).at[flat.reshape(-1)].set(0, mode="drop").reshape(n, n)
    )
    cst = cst._replace(last_sync=ls)

    info = {**swim_info, **b_info, **s_info}
    return SimState(swim, cst), info


def run_rounds_carry(cfg: SimConfig, st: SimState, net: NetModel, key,
                     inputs: RoundInput):
    """``lax.scan`` over stacked per-round inputs, returning the FULL
    scan carry ``((state, key), infos)``.

    This is the segment entry point: because the per-round key is split
    off the carried key inside the scan body, feeding one segment's
    carry-out into the next segment's carry-in reproduces the
    straight-through scan bit for bit — the segmented soak runner
    (``resilience/segments.py``) rides on exactly this property.
    """

    def body(carry, inp):
        st, key = carry
        key, sub = jr.split(key)
        st, info = sim_step(cfg, st, net, sub, inp)
        return (st, key), info

    return jax.lax.scan(body, (st, key), inputs)


def run_rounds(cfg: SimConfig, st: SimState, net: NetModel, key, inputs: RoundInput):
    """``lax.scan`` over stacked per-round inputs (leading axis = rounds).

    The whole simulation compiles to one XLA program — the form the
    benchmark runs and the mesh shards.
    """
    (st, _key), infos = run_rounds_carry(cfg, st, net, key, inputs)
    return st, infos


def crdt_metrics(cfg: SimConfig, st: SimState):
    """The reference's convergence predicate, vectorized: equal LWW
    stores, equal heads, and no outstanding needs across all alive nodes
    (``check_bookkeeping.py``: fails if any node still needs versions or
    heads mismatch)."""
    alive = st.swim.alive
    ref = jnp.argmax(alive)  # some alive node as the comparison anchor
    same_store = jnp.stack(
        [jnp.all(p == p[ref], axis=1) for p in st.crdt.store]
    ).all(axis=0)
    book = st.crdt.book
    # heads compare only on slots tracking the SAME actor (round 4:
    # hash-slotted origin table; identity claims make this the plain
    # equality check whenever all writers are < n_origins)
    aligned = book.org_id == book.org_id[ref]
    same_head = jnp.all(
        jnp.where(aligned, book.head == book.head[ref], True), axis=1
    )
    needs = needs_count(st.crdt.book)
    no_needs = jnp.all(needs <= 0, axis=1)
    ok = (~alive) | (same_store & same_head & no_needs)
    swim_m = {f"swim_{k}": v for k, v in swim_metrics(st.swim).items()}
    return {
        "converged": jnp.all(ok),
        "n_diverged": jnp.sum(~ok),
        "total_needs": jnp.sum(jnp.where(alive[:, None], jnp.maximum(needs, 0), 0)),
        **swim_m,
    }
