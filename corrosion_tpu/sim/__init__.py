"""The TPU cluster simulator: Corrosion's distributed protocols (SWIM
membership, CRDT changeset broadcast, anti-entropy sync) as fused, jittable
message-passing steps over struct-of-arrays node state."""
