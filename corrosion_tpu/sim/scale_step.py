"""The fused whole-cluster round at scale: bounded-table SWIM + CRDT.

This is the 100k-node counterpart of ``sim/step.py``. The full-view round
routes changeset broadcast through an explicit fanout + mailbox sort
(``sim/broadcast.py``), which costs O(N*Q*F log) per round — fine at
small N, fatal at 100k. Here dissemination is re-designed the way epidemic
broadcast systems actually ride at scale (plumtree/scuttlebutt style):
**changesets piggyback on the membership channels**. Every SWIM packet
(probe / ack / announce / announce-reply — each per-receiver unique, see
``sim/scale.py``) carries up to ``pig_changes`` queued changesets from the
sender's broadcast queue; receiving stays a dense gather + the usual
dedupe/apply (``record_versions`` + ``apply_changes_to_store``). The
reference's equivalents: broadcast fanout with re-send budgets
(``crates/corro-agent/src/broadcast/mod.rs:410-812``) and rebroadcast of
fresh changes (``agent/handlers.rs:768-779``) — same budgets, same
dedupe, different carrier.

Anti-entropy sync is unchanged from the full sim (``sim/sync.py`` is
already O(N*P*C) dense); peers are sampled from the bounded member table
instead of the full view.

The per-origin version bookkeeping (``Book``) is [N, O]: at scale the
writer set is a bounded pool of ``n_origins`` nodes — the array analog of
"any node may write, but per-actor bookkeeping is per *observed* actor";
a dense [N, N] head matrix would be the same 40 GB wall the member table
avoids (SURVEY §7 hard-part (e)).
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.random as jr

from corrosion_tpu.ops.dense import scatter_cols_add, scatter_cols_set, select_cols
from corrosion_tpu.ops.lww import STATE_ALIVE
from corrosion_tpu.ops.select import sample_k
from corrosion_tpu.ops.slots import budget_mask
from corrosion_tpu.ops.versions import needs_count
from corrosion_tpu.sim.broadcast import (
    CHANGE_WIRE_BYTES,
    LAST_SYNC_CAP,
    NO_Q,
    CrdtState,
    ingest_changes,
    local_write,
    local_write_tx,
)
from corrosion_tpu.sim.scale import (
    ScaleSwimState,
    _swim_back,
    _swim_front,
    scale_config,
    scale_swim_metrics,
    scale_swim_step,
    swim_front_disturbed,
)
from corrosion_tpu.sim.transport import (
    NetModel,
    card_at,
    link_card,
    ring_of_c,
)


@dataclasses.dataclass(frozen=True)
class ScaleSimConfig:
    """Static shapes for the scale round (SWIM knobs mirror ScaleConfig)."""

    n_nodes: int
    # --- SWIM (see ScaleConfig) -----------------------------------------
    m_slots: int = 64
    n_seeds: int = 4
    n_indirect: int = 3
    suspicion_rounds: int = 6
    max_transmissions: int = 10
    announce_interval: int = 16
    down_purge_rounds: int = 64
    pig_members: int = 0  # bounded piggyback (see ScaleConfig.pig_members)
    # --- CRDT store ------------------------------------------------------
    # bookkeeping slots per node; with any_writer (the flagship default)
    # this bounds TRACKED actors, not writers — see config.SimConfig
    n_origins: int = 16
    # unbounded writer set (reference semantics): any node may write;
    # per-actor bookkeeping rides the hash-slotted origin table
    any_writer: bool = True
    org_keep_rounds: int = 16
    n_rows: int = 16
    n_cols: int = 4
    buf_slots: int = 32
    # multi-cell transactions: 1 keeps the 100k hot path free of the
    # partial buffer (single-cell versions complete on arrival); raise it
    # to run chunked-changeset workloads at scale (change.rs:66-178)
    tx_max_cells: int = 1
    partial_slots: int = 8
    # --- dissemination ---------------------------------------------------
    bcast_queue: int = 32
    bcast_max_transmissions: int = 4
    # budget-following re-broadcast (round 5, default OFF): the wire
    # payload carries each changeset's REMAINING transmission budget,
    # and receivers re-enqueue even bookkeeping-less (unowned) fresh
    # messages at ``incoming - 1`` — circulation terminates by budget
    # depth instead of relying on seen-dedupe, which restores epidemic
    # spread for actors displaced from their hash slot by the monotone
    # claim rule (collision fairness). Forces the XLA ingest path (the
    # fused kernel predates the wire lane).
    bcast_wire_budget: bool = False
    pig_changes: int = 4  # changesets per SWIM packet
    # per-node per-round send budget in wire bytes (10 MiB/s analog);
    # bounds how many queued changesets may ride this round's packets
    bcast_budget_bytes: int = 10 * 1024 * 1024
    # --- anti-entropy sync -----------------------------------------------
    sync_interval: int = 8
    sync_peers: int = 2
    # peers actually PULLED from per cohort round: the reference scores
    # clamp(members/100, 3, 10) candidates but requests each version
    # range from ONE peer (parallel_sync dedupes ranges across servers,
    # peer/mod.rs:1186-1317) — pulling whole stores from all 10 is the
    # sync phase's dominant HBM cost at 100k (5 planes x P gathers)
    sync_pull_peers: int = 3
    sync_chunk: int = 32
    # server-side load adaptation (see SimConfig.serve_cap)
    serve_cap: int = 3
    sync_min_chunk: int = 4
    # anti-starvation bound on the shed (see SimConfig.sync_defer_cap)
    sync_defer_cap: int = 8
    # every k-th cohort/sync period, lane 0 merges its peer's FULL
    # store (ignores grants/ownership; LWW join is idempotent) — the
    # convergence backstop when bookkeeping slots are contended
    # (round 4 unbounded writers); 0 disables
    sync_sweep_every: int = 4
    # cohort scheduling: run the (dense, whole-cluster) sync phase once
    # every sync_interval rounds with every node participating, instead
    # of a 1/interval per-node draw every round — same average sync rate,
    # but the heavy phase compiles behind a lax.cond and costs nothing on
    # the other rounds (the reference's per-node jittered timers are a
    # wall-clock spread the round model abstracts anyway)
    sync_cohort: bool = True
    # dtype narrowing (PERF.md cut #4): small-range planes (mem_timer,
    # mem_tx, q_cell, q_seq, q_nseq, q_tx, last_sync — mirrored in
    # corrolint's analysis/dtypes.py::NARROW_LEAVES registry, whose
    # dtype-widen rule flags any silent widening at these boundaries)
    # live as int16 in HBM; compute widens freely (XLA fuses the
    # converts) and the round
    # step re-narrows once on carry-out — the scan carry (the HBM
    # working set between rounds) halves for those planes. Default ON
    # (round 4): narrow == wide is pinned bit-for-bit, the CPU A/B
    # favors it slightly, and the TPU traffic model halves those
    # planes' HBM bytes; BENCH_NARROW=0 measures the wide arm
    narrow_dtypes: bool = True
    # int8 tier for the mem_tx budget plane (ISSUE 12 — the shrink
    # corrobudget's dtype-bound analysis proves safe; see
    # ScaleConfig.narrow_int8 and docs/memory-budget.md). Default OFF
    # pending a real-TPU width probe; BENCH_NARROW8=1 measures it
    narrow_int8: bool = False
    # int8 tier for the broadcast queue's counter planes q_tx/q_seq/
    # q_nseq (ISSUE 19): q_tx is bounded by bcast_max_transmissions and
    # q_seq/q_nseq by tx_max_cells, all tiny. q_cell stays int16 (cell
    # ids range over the grid) and last_sync stays int16 (cap 4095).
    # Default OFF like narrow_int8, pending a real-TPU width probe
    narrow_q_int8: bool = False
    # --- fused megakernel path (ops/megakernel.py, docs/fused.md) --------
    # the production execution knob, fed from ``config.perf.fused``:
    #   "auto"      — pallas kernels on non-CPU backends when the eager
    #                 probes pass (hoist them with
    #                 ``megakernel.prime_fused`` before trace time);
    #   "on"        — pin the fused path (interpret-mode on CPU);
    #   "off"       — pin the XLA path;
    #   "interpret" — fused kernels in pallas interpret mode on ANY
    #                 backend: the tier-1 testing mode (fused==unfused
    #                 parity runs on CPU).
    # Execution only: fused == unfused bit for bit, so checkpoints
    # written under one mode resume under another
    # (checkpoint.config_identity excludes this key).
    fused: str = "auto"
    # --- quiescence-aware active-set rounds (docs/fused.md, PERF.md) -----
    # corroquiet execution knob, fed from ``config.perf.quiet``:
    #   "auto" — host-resolved: resilience/segments picks the quiet step
    #            per segment when the segment's inputs are all-quiet (the
    #            device step itself runs dense under "auto", so direct
    #            callers see the historical program);
    #   "on"   — the scan body is ``scale_sim_step_quiet``: rounds whose
    #            carry + inputs are provably quiescent take a fixpoint
    #            branch that skips the SWIM back half, the piggyback
    #            layer and the sync phase (bitwise == the dense round);
    #   "off"  — always the dense step.
    # Execution only: quiet == dense bit for bit, so checkpoints written
    # under one mode resume under another (checkpoint.config_identity
    # excludes all three quiet keys).
    quiet: str = "auto"
    # dense-round backstop while quiet: rounds where (now % interval)==0
    # never take the fixpoint branch, so anti-entropy and the probe layer
    # still sweep every node. 0 = sync_interval (the sync-cohort rounds
    # already forced dense by the schedule predicate).
    quiet_backstop_interval: int = 0
    # observability granularity of the per-shard occupancy series
    # (``corro.quiet.shards.*``): the node axis folds into this many
    # groups for reporting. Execution unaffected — the fixpoint gate is
    # one cluster-wide scalar (a jit-sharded program replicates scalar
    # branch predicates, so per-group divergence cannot exist in one
    # SPMD program; see parallel/mesh.py).
    quiet_shards: int = 1

    @property
    def n_cells(self) -> int:
        return self.n_rows * self.n_cols

    @property
    def sync_tracks(self) -> int:
        """Columns of the per-node last-sync table: the bounded sim tracks
        last-sync-round per member-table *slot*."""
        return self.m_slots

    def validate(self) -> "ScaleSimConfig":
        # real errors, not bare asserts (stripped under ``python -O``)
        if self.n_origins > self.n_nodes or self.m_slots <= 0:
            raise ValueError(
                f"need n_origins <= n_nodes and m_slots > 0, got "
                f"{self.n_origins}/{self.n_nodes}/{self.m_slots}"
            )
        if not 1 <= self.tx_max_cells <= 30:
            raise ValueError(
                f"tx_max_cells {self.tx_max_cells} not in 1..30 "
                f"(seq bitmask lives in an int32)"
            )
        # shares the sender-election int32 packing (see ScaleConfig.validate)
        if self.n_nodes > 1 << 30:
            raise ValueError(
                f"n_nodes {self.n_nodes} > 2^30: sender-election packs "
                f"priority + node id in one int32 word"
            )
        if not 0 <= self.pig_members <= self.m_slots:
            raise ValueError(
                f"pig_members {self.pig_members} must be 0..m_slots "
                f"({self.m_slots}) (top_k over the slot axis)"
            )
        if self.narrow_dtypes:
            from corrosion_tpu.sim.broadcast import LAST_SYNC_CAP

            if max(self.n_cells, self.tx_max_cells + 1,
                   self.bcast_max_transmissions + 1,
                   self.max_transmissions, self.suspicion_rounds,
                   self.down_purge_rounds, LAST_SYNC_CAP) >= (1 << 15):
                raise ValueError(
                    "narrow_dtypes stores these planes as int16; a "
                    "plane bound exceeds int16 range"
                )
        if self.narrow_int8 and not self.narrow_dtypes:
            raise ValueError(
                "narrow_int8 is a tier of narrow_dtypes; enable both"
            )
        if self.narrow_int8 and self.max_transmissions >= (1 << 7):
            raise ValueError(
                "narrow_int8 stores mem_tx as int8; max_transmissions "
                f"{self.max_transmissions} exceeds int8 range"
            )
        if self.narrow_q_int8:
            if not self.narrow_dtypes:
                raise ValueError(
                    "narrow_q_int8 is a tier of narrow_dtypes; "
                    "enable both"
                )
            if max(self.bcast_max_transmissions,
                   self.tx_max_cells) >= (1 << 7):
                raise ValueError(
                    "narrow_q_int8 stores q_tx/q_seq/q_nseq as int8; "
                    f"bcast_max_transmissions "
                    f"{self.bcast_max_transmissions} or tx_max_cells "
                    f"{self.tx_max_cells} exceeds int8 range"
                )
        from corrosion_tpu.sim.config import FUSED_MODES, QUIET_MODES

        if self.fused not in FUSED_MODES:
            raise ValueError(
                f"fused {self.fused!r} not one of {FUSED_MODES} "
                f"(docs/fused.md)"
            )
        if self.quiet not in QUIET_MODES:
            raise ValueError(
                f"quiet {self.quiet!r} not one of {QUIET_MODES} "
                f"(docs/fused.md)"
            )
        if self.quiet == "on" and not self.sync_cohort:
            raise ValueError(
                "quiet='on' requires sync_cohort: without the cohort "
                "schedule the sync phase runs (and ages scoring state) "
                "every round, so no round is ever a fixpoint"
            )
        if self.quiet_backstop_interval < 0:
            raise ValueError(
                f"quiet_backstop_interval {self.quiet_backstop_interval} "
                f"must be >= 0 (0 = sync_interval)"
            )
        if self.quiet_shards < 1 or self.n_nodes % self.quiet_shards:
            raise ValueError(
                f"quiet_shards {self.quiet_shards} must be >= 1 and "
                f"divide n_nodes ({self.n_nodes})"
            )
        return self

    @property
    def timer_dtype(self):
        """Dtype of the narrowed planes (see ``ScaleConfig.timer_dtype``)."""
        return jnp.int16 if self.narrow_dtypes else jnp.int32

    @property
    def tx_dtype(self):
        """HBM dtype of ``mem_tx`` (see ``ScaleConfig.tx_dtype``)."""
        return jnp.int8 if self.narrow_int8 else self.timer_dtype

    @property
    def q_dtype(self):
        """HBM dtype of the q_tx/q_seq/q_nseq counter planes (ISSUE 19
        int8 tier; mirrored by ``analysis/shapes.py::ConfigVal``)."""
        return jnp.int8 if self.narrow_q_int8 else self.timer_dtype


def scale_sim_config(n_nodes: int, **overrides) -> ScaleSimConfig:
    """Cluster-size-adaptive defaults.

    The SWIM portion is derived from ``scale_config`` (single source of
    truth for the membership tuning); only the CRDT-layer knobs are set
    here."""
    swim = scale_config(n_nodes)
    log_n = max(1, math.ceil(math.log2(max(2, n_nodes))))
    defaults = dict(
        m_slots=swim.m_slots,
        n_seeds=swim.n_seeds,
        n_indirect=swim.n_indirect,
        suspicion_rounds=swim.suspicion_rounds,
        max_transmissions=swim.max_transmissions,
        announce_interval=swim.announce_interval,
        down_purge_rounds=swim.down_purge_rounds,
        bcast_max_transmissions=max(3, log_n // 2),
        # clamp(members/100, 3, 10) — the reference's cluster-size-adaptive
        # sync fanout (handlers.rs:838); static N stands in for the live
        # member count (a bounded table cannot observe the true size)
        sync_peers=max(3, min(10, n_nodes // 100)),
    )
    defaults.update(overrides)
    return ScaleSimConfig(n_nodes=n_nodes, **defaults).validate()


class ScaleSimState(NamedTuple):
    swim: ScaleSwimState
    crdt: CrdtState

    @staticmethod
    def create(cfg: ScaleSimConfig) -> "ScaleSimState":
        return ScaleSimState(ScaleSwimState.create(cfg), CrdtState.create(cfg))


class ScaleRoundInput(NamedTuple):
    """External events for one round (same shape as the full sim's)."""

    kill: jax.Array  # bool [N]
    revive: jax.Array  # bool [N]
    write_mask: jax.Array  # bool [N]
    write_cell: jax.Array  # int32 [N]
    write_val: jax.Array  # int32 [N]
    write_clp: jax.Array  # int32 [N] — causal-length lifetime of the write
    # multi-cell transactions (K = tx_max_cells lanes; [N, 1] dummies when
    # the scale path runs single-cell versions only)
    tx_mask: jax.Array  # bool [N]
    tx_len: jax.Array  # int32 [N]
    tx_cell: jax.Array  # int32 [N, K]
    tx_val: jax.Array  # int32 [N, K]
    tx_clp: jax.Array  # int32 [N, K]

    @staticmethod
    def quiet(cfg: ScaleSimConfig) -> "ScaleRoundInput":
        n, k = cfg.n_nodes, max(1, cfg.tx_max_cells)
        return ScaleRoundInput(
            kill=jnp.zeros(n, bool),
            revive=jnp.zeros(n, bool),
            write_mask=jnp.zeros(n, bool),
            write_cell=jnp.zeros(n, jnp.int32),
            write_val=jnp.zeros(n, jnp.int32),
            write_clp=jnp.zeros(n, jnp.int32),
            tx_mask=jnp.zeros(n, bool),
            tx_len=jnp.ones(n, jnp.int32),
            tx_cell=jnp.zeros((n, k), jnp.int32),
            tx_val=jnp.zeros((n, k), jnp.int32),
            tx_clp=jnp.zeros((n, k), jnp.int32),
        )


def make_write_inputs(cfg: ScaleSimConfig, key, rounds: int, write_mask):
    """Stacked per-round :class:`ScaleRoundInput` with conflict-heavy
    random writes for the nodes in ``write_mask`` (bool [rounds, N]).
    Routes through K-cell chunked transactions (the partial-buffer
    path, ``change.rs:66-178`` + ``util.rs:1061-1194``) when
    ``cfg.tx_max_cells > 1`` — the ONE construction shared by bench.py,
    ab_bench, and convergence_bench so the arms can't drift."""
    k_cell, k_val, k_len = jr.split(key, 3)
    n = cfg.n_nodes
    quiet = ScaleRoundInput.quiet(cfg)
    inputs = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), quiet
    )
    if cfg.tx_max_cells > 1:
        k_lanes = cfg.tx_max_cells
        return inputs._replace(
            tx_mask=write_mask,
            tx_len=jr.randint(k_len, (rounds, n), 1, k_lanes + 1,
                              dtype=jnp.int32),
            tx_cell=jr.randint(k_cell, (rounds, n, k_lanes), 0,
                               cfg.n_cells, dtype=jnp.int32),
            tx_val=jr.randint(k_val, (rounds, n, k_lanes), 0, 1 << 20,
                              dtype=jnp.int32),
        )
    return inputs._replace(
        write_mask=write_mask,
        write_cell=jr.randint(k_cell, (rounds, n), 0, cfg.n_cells,
                              dtype=jnp.int32),
        write_val=jr.randint(k_val, (rounds, n), 0, 1 << 20,
                             dtype=jnp.int32),
    )


def piggyback_bcast_step(cfg: ScaleSimConfig, cst: CrdtState, channels, key,
                         carried=None, emitted=None):
    """Disseminate queued changesets over the SWIM packet channels.

    ``channels``: list of ``(src, valid)`` pairs — per-receiver-unique
    senders from the membership round. Each delivered packet carries the
    sender's ``pig_changes`` highest-priority live queue slots; the
    receiver dedupes via the Book, applies fresh cells, and re-enqueues
    fresh changes with a decremented budget (``handlers.rs:768-779``).

    ``carried`` int32 [N]: DELIVERED packets per sender this round
    (computed elementwise + two [N] scatters in the SWIM step). The
    budget multiplicity must be delivery-coupled: burning budget on
    attempts lets an unlucky writer exhaust its changeset with zero
    deliveries, and the version then never disseminates.

    ``emitted``: optional ``(payload, sel_slots, sel_ok)`` produced by
    the local-write ingest kernel (which already holds the queue planes
    in VMEM) — when given, the whole selection below is skipped.
    """
    n, q, r = cfg.n_nodes, cfg.bcast_queue, cfg.pig_changes
    iarr = jnp.arange(n, dtype=jnp.int32)

    if carried is None:  # legacy callers: recompute the delivered count
        carried = jnp.zeros(n, jnp.int32)
        for src, valid in channels:
            carried = carried.at[jnp.clip(src, 0)].add(
                valid.astype(jnp.int32), mode="drop"
            )

    if emitted is not None:
        payload, sel_slots, sel_ok = emitted
    else:
        live_slot = (cst.q_origin != NO_Q) & (cst.q_tx > 0)  # [N, Q]
        # per-round byte budget (10 MiB/s governor analog): each selected
        # slot costs CHANGE_WIRE_BYTES per delivered packet; least-sent
        # changesets get the budget first, the rest wait
        allowed = jnp.maximum(
            cfg.bcast_budget_bytes
            // (CHANGE_WIRE_BYTES * jnp.maximum(carried, 1)),
            1,
        ).astype(jnp.int32)
        live_slot = budget_mask(live_slot, cst.q_tx, allowed)
        sel_slots, sel_ok = sample_k(live_slot, r, key)  # [N, R]

        # --- sender-side payload, packed once ----------------------------
        # every channel carries the SAME selected slots of its sender, so
        # the field selection happens once per sender (not once per
        # receiver): pack the 10 payload lanes plus an ok lane into one
        # [N, 11*R] plane; each channel is ONE fast row gather of that
        # small plane (barriered — a fused row gather scalarizes on this
        # backend, see PERF.md)
        fields = [
            cst.q_origin, cst.q_dbv, cst.q_cell, cst.q_ver, cst.q_val,
            cst.q_site, cst.q_clp, cst.q_seq, cst.q_nseq, cst.q_ts,
        ]
        if cfg.bcast_wire_budget:
            # wire-budget lane: the changeset's REMAINING transmission
            # budget rides the packet so receivers can re-enqueue at
            # incoming-1 (budget-following re-broadcast)
            fields.append(cst.q_tx.astype(jnp.int32))
        payload = jnp.concatenate(
            [select_cols(f, sel_slots) for f in fields]
            + [sel_ok.astype(jnp.int32)],
            axis=1,
        )  # [N, (n_fields+1)*R]

    # --- gather each channel's payload; [N, n_channels*R] messages ------
    # an emitted (kernel-packed) payload is always 10 lanes + ok; the
    # use_fused_ingest gate forces the XLA path under the flag — keep
    # that invariant local
    if emitted is not None and cfg.bcast_wire_budget:
        raise ValueError(
            "fused-ingest (emitted) payloads carry no wire-budget lane; "
            "bcast_wire_budget requires the XLA path"
        )
    n_fields = 11 if cfg.bcast_wire_budget else 10
    parts, valids = [], []
    for src, valid in channels:
        src = jnp.clip(src, 0)
        got = jax.lax.optimization_barrier(payload[src])
        parts.append([got[:, i * r:(i + 1) * r] for i in range(n_fields)])
        valids.append(
            valid[:, None]
            & (got[:, n_fields * r:(n_fields + 1) * r] != 0)
        )
    lanes = [
        jnp.concatenate([p[i] for p in parts], axis=1)
        for i in range(n_fields)
    ]
    (m_origin, m_dbv, m_cell, m_ver, m_val, m_site, m_clp, m_seq, m_nseq,
     m_ts) = lanes[:10]
    m_tx = lanes[10] if cfg.bcast_wire_budget else None
    live = jnp.concatenate(valids, axis=1)

    # --- sender budget decrement: one per delivered packet ---------------
    # plane-dtype accumulation: keeps q_tx at its (possibly narrowed)
    # dtype so the fused ingest kernel lowered next round matches the
    # dtype set the width probe validated
    dec = scatter_cols_add(
        jnp.zeros((n, q), cst.q_tx.dtype), sel_slots,
        jnp.broadcast_to(carried[:, None], sel_slots.shape), sel_ok,
    )
    q_tx = jnp.maximum(cst.q_tx - dec, 0)
    exhausted = (cst.q_origin != NO_Q) & (q_tx <= 0)
    cst = cst._replace(
        q_tx=q_tx, q_origin=jnp.where(exhausted, NO_Q, cst.q_origin)
    )

    # --- receiver ingest: dedupe, apply, re-broadcast --------------------
    return ingest_changes(
        cfg, cst, live, m_origin, m_dbv, m_cell, m_ver, m_val, m_site, m_clp,
        m_seq, m_nseq, m_ts, m_tx=m_tx,
    )


def _post_swim(cfg, st, net, swim, swim_info, channels, carried,
               k_pig, k_sp, k_sync, inp):
    """CRDT half of the round — everything after the SWIM step: local
    writes, piggyback broadcast, staleness aging and the sync phase.
    Shared verbatim by the dense step and the quiet step's active branch
    (pure code motion out of the historical ``scale_sim_step`` body)."""
    from corrosion_tpu.sim.sync import choose_sync_peers, sync_step

    n, m = cfg.n_nodes, cfg.m_slots

    # tick the round counter — the HLC's physical time axis
    cst = st.crdt._replace(now=st.crdt.now + 1)
    from corrosion_tpu.ops import megakernel

    emitted = None
    if (cfg.tx_max_cells <= 1 and cfg.pig_changes > 0
            and megakernel.use_fused_ingest(cfg, msgs=1, emit=True)):
        # the local-write ingest kernel also emits this round's
        # piggyback payload selection from the queue planes it already
        # holds in VMEM — the XLA selection phase below is skipped.
        # ``rand`` is the same draw sample_k would make from k_pig, so
        # fused and unfused selections are bit-identical.
        rand = jr.uniform(k_pig, (n, cfg.bcast_queue))
        cst, emitted = megakernel.local_write_fused(
            cfg, cst, inp.write_mask, inp.write_cell, inp.write_val,
            inp.write_clp, rand=rand, carried=carried,
        )
    else:
        cst = local_write(
            cfg, cst, inp.write_mask, inp.write_cell, inp.write_val,
            inp.write_clp,
        )
        if cfg.tx_max_cells > 1:
            cst = local_write_tx(
                cfg, cst, inp.tx_mask, inp.tx_cell, inp.tx_val,
                inp.tx_clp, inp.tx_len,
            )
    cst, b_info = piggyback_bcast_step(
        cfg, cst, channels, k_pig, carried, emitted=emitted
    )

    # need-driven sync peer choice from a 2x sample of believed-alive
    # member-table entries: most-needed versions first, then longest since
    # last sync, then closest RTT ring (handlers.rs:808-894); last_sync
    # tracks are member-table slots here
    iarr = jnp.arange(n, dtype=jnp.int32)
    bel_alive = (
        (swim.mem_id >= 0)
        & (swim.mem_id != iarr[:, None])
        & (swim.mem_view >= 0)
        & ((swim.mem_view & 3) == STATE_ALIVE)
    )
    p_cnt = min(cfg.sync_peers, max(1, cfg.sync_pull_peers))
    # staleness ages every round, synced tracks reset inside the branch
    cst = cst._replace(
        last_sync=jnp.minimum(cst.last_sync + 1, LAST_SYNC_CAP)
    )

    def run_sync(cst):
        # the SCORING pool stays at the reference's fanout (2x oversample
        # of sync_peers candidates); only the top-p_cnt get pulled from
        cand_slots, cand_sok = sample_k(
            bel_alive, min(2 * cfg.sync_peers, m), k_sp
        )
        cand_ids = select_cols(swim.mem_id, cand_slots)
        staleness = select_cols(cst.last_sync, cand_slots)
        card = link_card(net, swim.alive)
        rings_c = ring_of_c(
            net, card[:, None, :], card_at(card, jnp.clip(cand_ids, 0))
        )
        peers, p_ok, c_idx = choose_sync_peers(
            cfg, cst.book, cand_ids, cand_sok, staleness, rings_c, p_cnt
        )
        sweep = None
        if cfg.sync_sweep_every > 0:
            sweep = (
                cst.now % (max(1, cfg.sync_interval)
                           * cfg.sync_sweep_every) == 0
            )
            # the sweep lane pairs UNIFORMLY over the whole id space:
            # need-driven scoring herds every needy node onto the same
            # (often unservable) peer where serve-shedding can starve
            # the backstop, and even a random MEMBER-TABLE draw mixes
            # only along the frozen partial-view digraph, which can
            # strand a minority org assignment unreachably. Anti-entropy
            # may dial any known member (at this scale the reference
            # effectively knows everyone); uniform pairing gives the
            # lattice join global mixing. Dead/partitioned peers fail
            # the link check inside sync_step like any other pair.
            r_peer = jr.randint(
                jr.fold_in(k_sp, 1), (n,), 0, n, dtype=jnp.int32
            )
            r_valid = r_peer != iarr
            peers = peers.at[:, 0].set(
                jnp.where(sweep, r_peer, peers[:, 0])
            )
            p_ok = p_ok.at[:, 0].set(
                jnp.where(sweep, r_valid, p_ok[:, 0])
            )
        cst, s_ok, s_info = sync_step(
            cfg, cst, peers, p_ok, swim.alive, net, k_sync,
            go_all=cfg.sync_cohort, sweep=sweep,
        )
        if sweep is not None:
            # lane 0 synced the RANDOM sweep peer on sweep rounds, not
            # the scored candidate synced_slots maps back to — don't
            # reset the displaced candidate's staleness
            s_ok = s_ok.at[:, 0].set(s_ok[:, 0] & ~sweep)
        synced_slots = select_cols(cand_slots, c_idx)
        # zeros in the plane's own dtype: both lax.cond branches must
        # carry last_sync at the same (possibly narrowed) dtype
        ls = scatter_cols_set(
            cst.last_sync, synced_slots,
            jnp.zeros(synced_slots.shape, cst.last_sync.dtype), s_ok,
        )
        return cst._replace(last_sync=ls), s_info

    if cfg.sync_cohort:
        def skip_sync(cst):
            zero = jnp.int32(0)
            return cst, {
                "syncs": zero, "cells_pulled": zero,
                "versions_granted": zero, "serve_rejects": zero,
            }

        cst, s_info = jax.lax.cond(
            cst.now % max(1, cfg.sync_interval) == 0, run_sync, skip_sync, cst
        )
    else:
        cst, s_info = run_sync(cst)

    st_out = _narrow_carry(cfg, ScaleSimState(swim, cst))
    info = {**swim_info, **b_info, **s_info, **activity_info(cfg, st_out)}
    return st_out, info


def scale_sim_step(
    cfg: ScaleSimConfig,
    st: ScaleSimState,
    net: NetModel,
    key,
    inp: ScaleRoundInput,
):
    """One full protocol round at scale. Returns (state, info)."""
    k_swim, k_pig, k_sp, k_sync = jr.split(key, 4)
    swim, swim_info, channels, carried = scale_swim_step(
        cfg, st.swim, net, k_swim, kill=inp.kill, revive=inp.revive
    )
    return _post_swim(cfg, st, net, swim, swim_info, channels, carried,
                      k_pig, k_sp, k_sync, inp)


def _quiet_busy(cfg: ScaleSimConfig, st: ScaleSimState):
    """bool [N]: alive nodes that still owe the cluster work — the
    carry-occupancy half of the quiet-round predicate.

    Strictly stronger than ``activity_masks`` on alive rows, by design:

    - membership pendings count REGARDLESS of timer residue (the masks'
      ``probes`` bit requires a running timer, but a Suspect/Down entry
      with a stalled timer still mutates state the next time news about
      it arrives, and a Down entry keeps purge eligibility);
    - a nonzero membership transmission budget (``mem_tx``) counts: a
      sendable entry would be piggybacked, decrementing budgets and
      merging into receiver tables.

    Dead rows are EXCLUDED on purpose — their queue/partials/table
    residue is provably inert (every mutating path in the round is
    gated on the row being alive or on a delivered packet from an alive
    sender), and counting it would pin a post-churn cluster dense
    forever. The quiet≡dense parity battery (tests/test_quiet.py) is
    the oracle for that proof."""
    from corrosion_tpu.ops.lww import STATE_DOWN, STATE_SUSPECT
    from corrosion_tpu.ops.partials import NO_SLOT

    view = st.swim.mem_view
    pending = (
        (st.swim.mem_id >= 0)
        & (view >= 0)
        & (((view & 3) == STATE_SUSPECT) | ((view & 3) == STATE_DOWN))
    )
    row_busy = (
        jnp.any(pending, axis=1)
        | jnp.any(st.swim.mem_tx > 0, axis=1)
        | jnp.any(st.crdt.q_origin != NO_Q, axis=1)
        | jnp.any(st.crdt.partials.origin != NO_SLOT, axis=1)
        | jnp.any(needs_count(st.crdt.book) > 0, axis=1)
    )
    return st.swim.alive & row_busy


def _quiet_info(cfg: ScaleSimConfig, busy, quiet_ok, settled, schedule_ok):
    """The ``quiet_*`` round-info keys (``corro.quiet.*`` series) —
    computed OUTSIDE the fixpoint cond so both branches share them."""
    shards = max(1, int(getattr(cfg, "quiet_shards", 1)))
    shard_busy = jnp.any(busy.reshape(shards, -1), axis=1)
    return {
        "quiet_round": quiet_ok.astype(jnp.int32),
        "quiet_shards_quiet": jnp.sum(~shard_busy).astype(jnp.int32),
        "quiet_shards_skipped": jnp.where(
            quiet_ok, jnp.int32(shards), jnp.int32(0)
        ),
        "quiet_backstop": (settled & ~schedule_ok).astype(jnp.int32),
        "quiet_nodes_active": jnp.sum(busy).astype(jnp.int32),
    }


def scale_sim_step_quiet(
    cfg: ScaleSimConfig,
    st: ScaleSimState,
    net: NetModel,
    key,
    inp: ScaleRoundInput,
):
    """Quiescence-aware variant of :func:`scale_sim_step` — the
    active-set round (``cfg.quiet == "on"``; corroquiet tentpole).

    Always runs the cheap SWIM front half (churn, probe/announce legs,
    elections — the round's RNG draws and delivered-packet channels),
    then decides on device whether this round can change ANY state:

    - ``carry quiet``  — no alive node owes work (:func:`_quiet_busy`);
    - ``input quiet``  — this round injects no kills/revives/writes/txs;
    - ``undisturbed``  — the delivered SWIM traffic would not touch any
      membership table (:func:`sim.scale.swim_front_disturbed`);
    - ``schedule ok``  — neither a sync-cohort round nor a
      ``quiet_backstop_interval`` backstop round.

    When all four hold the round is a proven fixpoint and one
    ``lax.cond`` takes the cheap branch: carry the state through with
    only the round counter tick + staleness aging (exactly what the
    dense round computes on such a round — bit for bit, pinned by
    tests/test_quiet.py and the check.sh quiet-parity stage). Any doubt
    takes the dense branch, so correctness never leans on the predicate
    being tight — only the speedup does."""
    k_swim, k_pig, k_sp, k_sync = jr.split(key, 4)
    front = _swim_front(
        cfg, st.swim, net, k_swim, kill=inp.kill, revive=inp.revive
    )

    busy = _quiet_busy(cfg, st)
    carry_quiet = ~jnp.any(busy)
    input_quiet = ~(
        jnp.any(inp.kill) | jnp.any(inp.revive)
        | jnp.any(inp.write_mask) | jnp.any(inp.tx_mask)
    )
    # the dense round gates sync on (now % interval == 0) AFTER the tick
    now1 = st.crdt.now + 1
    si = max(1, cfg.sync_interval)
    bs = max(1, cfg.quiet_backstop_interval or cfg.sync_interval)
    schedule_ok = (now1 % si != 0) & (now1 % bs != 0)
    settled = carry_quiet & input_quiet & ~swim_front_disturbed(cfg, front)
    quiet_ok = settled & schedule_ok

    def active(_):
        swim, swim_info = _swim_back(cfg, st.swim, front)
        return _post_swim(
            cfg, st, net, swim, swim_info, list(front.channels),
            front.carried, k_pig, k_sp, k_sync, inp,
        )

    def fixpoint(_):
        # what the dense round computes on a proven-quiet round: the
        # counter tick and the last_sync aging — nothing else moves
        crdt = st.crdt._replace(
            now=st.crdt.now + 1,
            last_sync=jnp.minimum(st.crdt.last_sync + 1, LAST_SYNC_CAP),
        )
        st_out = _narrow_carry(cfg, ScaleSimState(st.swim, crdt))
        zero = jnp.int32(0)
        info = {
            # swim_info: no refutations; acked/failed mirror the front
            "acked": jnp.sum(front.acked),
            "failed_probes": jnp.sum(front.failed),
            "refutes": zero,
            # b_info: nothing delivered; queued counts (dead-row) residue
            "delivered": zero,
            "fresh": zero,
            "tx_completed": zero,
            "clock_drift_rejects": zero,
            "queued": jnp.sum(st.crdt.q_origin != NO_Q),
            # s_info: the schedule predicate proves this is a skip round
            "syncs": zero,
            "cells_pulled": zero,
            "versions_granted": zero,
            "serve_rejects": zero,
            **activity_info(cfg, st_out),
        }
        return st_out, info

    st_out, info = jax.lax.cond(quiet_ok, fixpoint, active, None)
    info = {**info, **_quiet_info(cfg, busy, quiet_ok, settled, schedule_ok)}
    return st_out, info


def activity_masks(cfg: ScaleSimConfig, st: ScaleSimState) -> dict:
    """Per-node activity masks, computed on device from the round's
    carry-out state (ISSUE 11 / ROADMAP quiescence item).

    These are EXACTLY the occupancy bits a future active-set round
    variant would gate on to cheap-path inactive shards: a node is
    "active" on a channel when it still owes the cluster work —

    - ``bcast``: any live broadcast-queue slot (changesets awaiting
      further transmissions);
    - ``partials``: any buffered incomplete multi-cell version;
    - ``sync``: any outstanding version need (heard-of-but-unseen,
      ``ops.versions.needs_count``) that anti-entropy must pull;
    - ``probes``: any RUNNING SWIM suspicion / down-purge timer
      (membership churn in flight; steady-state probing of a healthy
      quiet cluster keeps all timers at zero). A timer only runs while
      its entry is still Suspect or Down — the membership update
      neither ticks nor clears ``mem_timer`` once an entry is refuted
      back to Alive, so the raw plane legitimately carries stale
      nonzero residue after recovered churn (the chaos quiescence
      oracle found exactly this); counting residue as activity would
      keep healed shards hot forever.

    The quiet-trace oracle rides on this: zero traffic (no writes, no
    kills) ⇒ every mask all-False ⇒ every ``active_*`` info count is
    zero. Each mask is one cheap reduce over an existing state plane —
    no new HBM tables, no extra gathers."""
    from corrosion_tpu.ops.lww import STATE_DOWN, STATE_SUSPECT
    from corrosion_tpu.ops.partials import NO_SLOT

    view = st.swim.mem_view
    pending = (
        (st.swim.mem_id >= 0)
        & (view >= 0)
        & (((view & 3) == STATE_SUSPECT) | ((view & 3) == STATE_DOWN))
    )
    return {
        "bcast": jnp.any(st.crdt.q_origin != NO_Q, axis=1),
        "partials": jnp.any(st.crdt.partials.origin != NO_SLOT, axis=1),
        "sync": jnp.any(needs_count(st.crdt.book) > 0, axis=1),
        "probes": jnp.any(pending & (st.swim.mem_timer > 0), axis=1),
    }


def activity_info(cfg: ScaleSimConfig, st: ScaleSimState) -> dict:
    """Fold the activity masks into round-info counts (``active_*``
    keys, mapped onto ``corro.activity.*.nodes`` gauges by
    ``utils.metrics._INFO_MAP``). Under a mesh the masks shard with the
    node axis and the sums reduce across shards like every other info
    value."""
    return {
        f"active_{k}": jnp.sum(v.astype(jnp.int32))
        for k, v in activity_masks(cfg, st).items()
    }


def _narrow_carry(cfg: ScaleSimConfig, st: ScaleSimState) -> ScaleSimState:
    """Re-narrow the int16 HBM planes on round carry-out.

    Mid-step compute promotes them to int32 wherever convenient (XLA
    fuses the converts); one cast here keeps the scan carry — the HBM
    working set between rounds — at the narrow dtype, which is where
    the traffic saving lives (PERF.md cut #4)."""
    if not cfg.narrow_dtypes:
        return st
    dt = cfg.timer_dtype
    swim = st.swim._replace(
        mem_timer=st.swim.mem_timer.astype(dt),
        # mem_tx has its own (possibly int8) HBM tier — ISSUE 12 shrink
        mem_tx=st.swim.mem_tx.astype(cfg.tx_dtype),
    )
    # the counter planes have their own (possibly int8) HBM tier —
    # ISSUE 19 shrink; q_cell/last_sync hold grid ids / the 4095 cap
    # and stay at the int16 tier
    qdt = cfg.q_dtype
    crdt = st.crdt._replace(
        q_cell=st.crdt.q_cell.astype(dt),
        q_seq=st.crdt.q_seq.astype(qdt),
        q_nseq=st.crdt.q_nseq.astype(qdt),
        q_tx=st.crdt.q_tx.astype(qdt),
        last_sync=st.crdt.last_sync.astype(dt),
    )
    return ScaleSimState(swim, crdt)


def scale_run_rounds_carry(cfg: ScaleSimConfig, st, net: NetModel, key,
                           inputs):
    """Scan returning the FULL carry ``((state, key), infos)`` — the
    segment entry point (see ``sim/step.run_rounds_carry``): chaining
    segment carries reproduces the straight-through scan bit for bit.

    ``cfg.quiet == "on"`` swaps the scan body for the active-set round
    (:func:`scale_sim_step_quiet` — quiet == dense bitwise); "auto" runs
    dense here (the host plane resolves "auto" per segment,
    ``resilience/segments.py``)."""
    step = (scale_sim_step_quiet
            if getattr(cfg, "quiet", "off") == "on" else scale_sim_step)

    def body(carry, inp):
        st, key = carry
        key, sub = jr.split(key)
        st, info = step(cfg, st, net, sub, inp)
        return (st, key), info

    return jax.lax.scan(body, (st, key), inputs)


def scale_run_rounds(cfg: ScaleSimConfig, st, net: NetModel, key, inputs):
    """``lax.scan`` over stacked per-round inputs — one XLA program."""
    (st, _key), infos = scale_run_rounds_carry(cfg, st, net, key, inputs)
    return st, infos


def scale_crdt_metrics(cfg: ScaleSimConfig, st: ScaleSimState):
    """Convergence predicate at scale (same as ``crdt_metrics``).

    With the unbounded writer set, bookkeeping convergence is
    per-tracked-actor: a node's head must equal the reference node's
    wherever both track the SAME actor in a slot (hash-colliding actor
    sets may legitimately leave different nodes tracking different
    actors; store equality is still required everywhere)."""
    alive = st.swim.alive
    ref = jnp.argmax(alive)
    same_store = jnp.stack(
        [jnp.all(p == p[ref], axis=1) for p in st.crdt.store]
    ).all(axis=0)
    book = st.crdt.book
    aligned = book.org_id == book.org_id[ref]
    same_head = jnp.all(
        jnp.where(aligned, book.head == book.head[ref], True), axis=1
    )
    needs = needs_count(book)
    no_needs = jnp.all(needs <= 0, axis=1)
    ok = (~alive) | (same_store & same_head & no_needs)
    swim_m = {f"swim_{k}": v for k, v in scale_swim_metrics(st.swim).items()}
    # observability for the slots the head comparison skips (ADVICE r4):
    # misaligned slots still must have needs==0 (no_needs covers every
    # slot), but a persistently low alignment fraction would mean books
    # silently tracking different actors — surface it in the metrics
    alive_slots = jnp.sum(alive.astype(jnp.float32)) * aligned.shape[1]
    org_aligned_frac = jnp.sum(
        (aligned & alive[:, None]).astype(jnp.float32)
    ) / jnp.maximum(alive_slots, 1.0)
    store_ok = (~alive) | same_store
    return {
        "converged": jnp.all(ok),
        # the user-visible guarantee alone: every alive replica holds
        # identical data. In the collision regime (active writers >>
        # origin slots) bookkeeping churns indefinitely — slot re-claims
        # reset heads, needs re-open, sync re-fetches already-applied
        # versions — while stores stay converged via the sweep; this
        # metric separates the two (scripts/collision_probe.py)
        "store_converged": jnp.all(store_ok),
        "n_store_diverged": jnp.sum(~store_ok),
        "n_diverged": jnp.sum(~ok),
        "total_needs": jnp.sum(jnp.where(alive[:, None], jnp.maximum(needs, 0), 0)),
        "org_aligned_frac": org_aligned_frac,
        **swim_m,
    }
