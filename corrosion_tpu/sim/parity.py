"""State-parity harness: host oracle cluster vs the TPU simulator.

The build plan's step 7 (SURVEY §7): drive a real (CPU, pure-Python)
cluster and the TPU sim with *identical workload scripts* and compare
final state — the analog of running corro-devcluster next to the
simulator and applying the Antithesis ``check_bookkeeping.py`` predicate
("no needs, equal heads") plus full LWW-store equality.

Determinism contract (SURVEY hard part (d) — RNG models differ, so
parity is defined on RNG-independent facts):

- **single-writer-per-cell** workloads: a cell's ``col_version`` only
  ever advances through its one writer's own writes, so the converged
  store is a pure function of the write script — the oracle and the sim
  must match **bitwise** on all four planes (ver, val, site, dbv).
- **multi-writer** workloads: ``col_version`` bumps from the writer's
  *merged* clock (cr-sqlite semantics, ``local_write``), which depends
  on delivery timing; parity is then **agreement + validity**: every
  node converged to the same store, the winning value for each cell was
  actually written to that cell, and the convergence predicate holds on
  both systems.

The oracle cluster mirrors the sim's protocol semantics exactly
(one-cell writes with ``ver = merged_ver + 1``, per-origin ``db_version``
counters, fanout + rebroadcast budgets, pull-based anti-entropy) in plain
Python over :class:`OracleNode` — deliberately obvious, nothing shared
with the array code.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import numpy as np

from corrosion_tpu.sim.oracle import OracleNode

# (cell, ver, val, site, dbv, clp); origin==site
Change = Tuple[int, int, int, int, int, int]


def _write4(w):
    """Normalize a script write to (node, cell, value, clp). Scripts may
    omit clp (plain LWW workloads — one immortal lifetime, clp=0)."""
    return (*w, 0) if len(w) == 3 else tuple(w)


def _as_tx(w):
    """Normalize a script write to transaction form:
    ``(node, [(cell, value, clp), ...])``. Scripts may record plain
    single-cell writes ``(node, cell, value[, clp])`` or multi-statement
    transactions ``(node, [(cell, value[, clp]), ...])``."""
    if isinstance(w[1], (list, tuple)) and w[1] and isinstance(
        w[1][0], (list, tuple)
    ):
        return w[0], [(*c, 0) if len(c) == 2 else tuple(c) for c in w[1]]
    node, cell, val, clp = _write4(w)
    return node, [(cell, val, clp)]


@dataclass
class WorkloadScript:
    """Per-round write lists, shareable between oracle and sim.

    ``writes[r]`` = list of (node, cell, value[, clp]) committed in round
    r — ``clp`` is the causal-length lifetime (delete/resurrect
    workloads; defaults to 0). One write per node per round (the sim's
    RoundInput shape)."""

    n_nodes: int
    n_origins: int
    n_cells: int
    writes: List[List[Tuple]] = field(default_factory=list)
    # faults[r] = events applied before round r's writes: ("kill", node),
    # ("revive", node), ("partition", [group per node]), ("heal",) —
    # the Antithesis driver surface (kill/revive/partition/heal)
    faults: List[List[Tuple]] = field(default_factory=list)

    @staticmethod
    def random_single_writer(n_nodes: int, n_origins: int, n_cells: int,
                             rounds: int, seed: int = 0,
                             write_prob: float = 0.5) -> "WorkloadScript":
        """Each cell is owned by one writer (cell % n_origins) — the
        bitwise-parity regime."""
        rng = random.Random(seed)
        ws = WorkloadScript(n_nodes, n_origins, n_cells)
        for _ in range(rounds):
            batch = []
            for w in range(n_origins):
                if rng.random() < write_prob:
                    owned = [c for c in range(n_cells) if c % n_origins == w]
                    if owned:
                        batch.append((w, rng.choice(owned),
                                      rng.randrange(1, 1 << 20)))
            ws.writes.append(batch)
        return ws

    @staticmethod
    def random_conflicting(n_nodes: int, n_origins: int, n_cells: int,
                           rounds: int, seed: int = 0,
                           write_prob: float = 0.5,
                           hot_cells: int = 2) -> "WorkloadScript":
        """All writers hammer a few hot cells — the LWW-conflict regime."""
        rng = random.Random(seed)
        ws = WorkloadScript(n_nodes, n_origins, n_cells)
        for _ in range(rounds):
            batch = []
            for w in range(n_origins):
                if rng.random() < write_prob:
                    batch.append((w, rng.randrange(hot_cells),
                                  rng.randrange(1, 1 << 20)))
            ws.writes.append(batch)
        return ws

    @staticmethod
    def random_delete_resurrect(n_nodes: int, n_origins: int, n_rows: int,
                                n_cols: int, rounds: int, seed: int = 0,
                                op_prob: float = 0.6) -> "WorkloadScript":
        """Row-lifecycle workload: inserts, updates, deletes, resurrects —
        the causal-length regime (``doc/crdts.md`` ``cl``). Cell layout:
        ``row*n_cols`` is the CL register, value cells follow. Deletes
        race in-flight updates and resurrects race stale lifetimes
        through the network — agreement+validity parity regime."""
        rng = random.Random(seed)
        ws = WorkloadScript(n_nodes, n_origins, n_rows * n_cols)
        cl = [0] * n_rows
        for _ in range(rounds):
            batch = []
            for w in rng.sample(range(n_origins), n_origins):
                if rng.random() >= op_prob:
                    continue
                row = rng.randrange(n_rows)
                live = cl[row] % 2 == 1
                if not live or rng.random() < 0.3:
                    # insert/resurrect (dead row) or delete (live row):
                    # bump the causal length register
                    cl[row] += 1
                    batch.append((w, row * n_cols, cl[row], cl[row]))
                else:
                    # update a value column within the current lifetime
                    col = rng.randrange(1, n_cols)
                    batch.append((w, row * n_cols + col,
                                  rng.randrange(1, 1 << 20), cl[row]))
            ws.writes.append(batch)
        return ws

    @staticmethod
    def random_transactions(n_nodes: int, n_origins: int, n_cells: int,
                            rounds: int, tx_cells: int = 4, seed: int = 0,
                            write_prob: float = 0.5) -> "WorkloadScript":
        """Multi-statement transactions over single-writer-owned cells —
        the chunked-changeset regime (``change.rs:66-178``): each commit
        writes ``tx_cells`` distinct owned cells under one db_version;
        remote nodes must apply them atomically. Single-writer per cell
        keeps the bitwise-parity determinism contract."""
        rng = random.Random(seed)
        ws = WorkloadScript(n_nodes, n_origins, n_cells)
        for _ in range(rounds):
            batch = []
            for w in range(n_origins):
                if rng.random() < write_prob:
                    owned = [c for c in range(n_cells) if c % n_origins == w]
                    k = min(tx_cells, len(owned))
                    if k:
                        cells = rng.sample(owned, k)
                        batch.append((w, [(c, rng.randrange(1, 1 << 20))
                                          for c in cells]))
            ws.writes.append(batch)
        return ws

    @staticmethod
    def random_full_mix(n_nodes: int, n_origins: int, n_cells: int,
                        rounds: int, seed: int = 0, write_prob: float = 0.5,
                        hot_cells: int = 4, kill_prob: float = 0.08,
                        revive_prob: float = 0.3,
                        partition_window: Tuple[int, int] = None) -> "WorkloadScript":
        """BASELINE's full-mix correctness config: multi-writer hot cells
        + kill/revive churn + a partition window (split into two halves,
        healed later). Writes only fire at alive, reachable... any alive
        origin (partitioned writers keep writing — divergence repairs on
        heal). The agreement+validity parity regime."""
        rng = random.Random(seed)
        ws = WorkloadScript(n_nodes, n_origins, n_cells)
        alive = [True] * n_nodes
        if partition_window is None:
            partition_window = (rounds // 3, 2 * rounds // 3)
        p_start, p_end = partition_window
        for r in range(rounds):
            events: List[Tuple] = []
            # churn: kill a random alive non-seed node / revive a dead one
            dead = [i for i in range(n_nodes) if not alive[i]]
            if dead and rng.random() < revive_prob:
                node = rng.choice(dead)
                alive[node] = True
                events.append(("revive", node))
            candidates = [i for i in range(4, n_nodes) if alive[i]]
            if candidates and rng.random() < kill_prob:
                node = rng.choice(candidates)
                alive[node] = False
                events.append(("kill", node))
            if r == p_start:
                half = [1 if i >= n_nodes // 2 else 0 for i in range(n_nodes)]
                events.append(("partition", half))
            elif r == p_end:
                events.append(("heal",))
            ws.faults.append(events)
            batch = []
            for w in range(n_origins):
                if alive[w] and rng.random() < write_prob:
                    batch.append((w, rng.randrange(hot_cells),
                                  rng.randrange(1, 1 << 20)))
            ws.writes.append(batch)
        return ws

    @property
    def max_tx_cells(self) -> int:
        return max(
            (len(cells) for batch in self.writes
             for _, cells in (_as_tx(w) for w in batch)),
            default=1,
        )

    def written_values(self) -> Dict[int, set]:
        """cell -> set of all values ever written to it (validity check)."""
        out: Dict[int, set] = {}
        for batch in self.writes:
            for _node, cells in (_as_tx(w) for w in batch):
                for cell, val, _clp in cells:
                    out.setdefault(cell, set()).add(val)
        return out


class OracleCluster:
    """N pure-Python nodes speaking the sim's protocol semantics."""

    def __init__(self, n_nodes: int, n_origins: int, n_cells: int,
                 fanout: int = 3, rebroadcast_budget: int = 3,
                 sync_peers: int = 2, seed: int = 0):
        self.n_nodes = n_nodes
        self.n_origins = n_origins
        self.n_cells = n_cells
        self.fanout = fanout
        self.sync_peers = sync_peers
        self.budget = rebroadcast_budget
        self.rng = random.Random(seed)
        self.nodes = [OracleNode(n_origins) for _ in range(n_nodes)]
        self.next_dbv = [1] * n_nodes
        # per-node *complete* version payloads for serving sync:
        # (origin, dbv) -> tuple of (Change, seq, nseq) — a node can only
        # serve versions it holds whole (its store never contains torn
        # versions, so neither can what it serves)
        self.payloads: List[Dict[Tuple[int, int], tuple]] = [
            {} for _ in range(n_nodes)
        ]
        # chunks of not-yet-complete versions, promoted to payloads at
        # completion: (origin, dbv) -> {seq: (Change, seq, nseq)}
        self.payload_chunks: List[Dict[Tuple[int, int], dict]] = [
            {} for _ in range(n_nodes)
        ]
        # per-node broadcast queue: (change, seq, nseq, remaining tx)
        self.queues: List[List[tuple]] = [[] for _ in range(n_nodes)]

    # --- write path ------------------------------------------------------
    def write(self, node: int, cell: int, value: int, clp: int = 0) -> None:
        self.write_tx(node, [(cell, value, clp)])

    def write_tx(self, node: int, cells) -> None:
        """Commit a multi-statement transaction: all cells share one
        db_version, stamped seq 0..n-1 (``ChunkedChanges``,
        ``change.rs:66-178``); applied atomically to the writer's own
        store. ``cells`` = [(cell, value, clp), ...], distinct cells."""
        if node >= self.n_origins:
            raise ValueError(
                f"node {node} is not a writer (n_origins="
                f"{self.n_origins})"
            )
        me = self.nodes[node]
        dbv = self.next_dbv[node]
        self.next_dbv[node] += 1
        nseq = len(cells)
        chunks = []
        for seq, (cell, value, clp) in enumerate(cells):
            cur = me.store.get(cell)
            ver = (cur[0] if cur else 0) + 1  # bump the merged clock
            chunks.append(((cell, ver, value, node, dbv, clp), seq, nseq))
        me.record(node, dbv)
        for (cell, ver, value, site, dbv_, clp), seq, _n in chunks:
            me.merge_cell(cell, ver, value, site, dbv_, clp)
            self.queues[node].append(
                ((cell, ver, value, site, dbv_, clp), seq, nseq, self.budget)
            )
        self.payloads[node][(node, dbv)] = tuple(chunks)

    # --- dissemination round ---------------------------------------------
    def round(self) -> None:
        # broadcast flush: every queued change goes to a random fanout set
        deliveries: List[tuple] = []
        for src in range(self.n_nodes):
            newq = []
            for ch, seq, nseq, tx in self.queues[src]:
                targets = self.rng.sample(
                    [t for t in range(self.n_nodes) if t != src],
                    min(self.fanout, self.n_nodes - 1),
                )
                deliveries.extend((t, ch, seq, nseq) for t in targets)
                if tx - 1 > 0:
                    newq.append((ch, seq, nseq, tx - 1))
            self.queues[src] = newq
        for dst, ch, seq, nseq in deliveries:
            self._ingest(dst, ch, seq, nseq)
        # anti-entropy: each node pulls its missing versions from peers
        for node in range(self.n_nodes):
            peers = self.rng.sample(
                [p for p in range(self.n_nodes) if p != node],
                min(self.sync_peers, self.n_nodes - 1),
            )
            for peer in peers:
                self._sync_pull(node, peer)

    def _ingest(self, dst: int, ch: Change, seq: int = 0, nseq: int = 1) -> None:
        cell, ver, val, site, dbv, clp = ch
        fresh = self.nodes[dst].apply_chunk(
            (cell, ver, val, site, site, dbv, clp), seq, nseq
        )
        if fresh:
            chunks = self.payload_chunks[dst].setdefault((site, dbv), {})
            chunks[seq] = (ch, seq, nseq)
            if dbv in self.nodes[dst].seen.get(site, set()):
                # version now whole -> servable via sync
                self.payloads[dst][(site, dbv)] = tuple(chunks.values())
                del self.payload_chunks[dst][(site, dbv)]
            self.queues[dst].append((ch, seq, nseq, max(1, self.budget - 1)))

    def _sync_pull(self, node: int, peer: int) -> None:
        """compute_available_needs + serve: pull every version the peer
        can grant whole that we lack (``sync.rs:127``) — the bi channel
        transfers a version's full seq range atomically."""
        mine, theirs = self.nodes[node], self.nodes[peer]
        for origin in range(self.n_origins):
            their_seen = theirs.seen.get(origin, set())
            my_seen = mine.seen.get(origin, set())
            for dbv in sorted(their_seen - my_seen):
                chunks = self.payloads[peer].get((origin, dbv))
                if chunks is not None:
                    for ch, seq, nseq in chunks:
                        self._ingest(node, ch, seq, nseq)

    # --- harness ---------------------------------------------------------
    def run(self, script: WorkloadScript, settle_rounds: int = 64) -> int:
        """Apply the script, then settle until converged. Returns rounds
        taken (-1 if it never converged — a harness failure)."""
        from corrosion_tpu.sim.oracle import converged

        for batch in script.writes:
            for node, cells in (_as_tx(w) for w in batch):
                self.write_tx(node, cells)
            self.round()
        for r in range(settle_rounds):
            if not any(self.queues) and converged(self.nodes):
                return len(script.writes) + r
            self.round()
        return len(script.writes) + settle_rounds if converged(self.nodes) else -1

    def store_planes(self) -> Tuple[np.ndarray, ...]:
        """Node-0's converged store as dense (ver, val, site, dbv, clp)
        planes (after ``run`` all nodes are identical)."""
        planes = [np.zeros(self.n_cells, np.int32) for _ in range(5)]
        for cell, (ver, val, site, dbv, clp) in self.nodes[0].store.items():
            planes[0][cell], planes[1][cell] = ver, val
            planes[2][cell], planes[3][cell] = site, dbv
            planes[4][cell] = clp
        return tuple(planes)


# --- sim-side runner ------------------------------------------------------

def run_sim_script(script: WorkloadScript, seed: int = 0,
                   settle_rounds: int = 512, drop_prob: float = 0.0,
                   sync_interval: int = 4, quiet: str = "auto"):
    """Run the scale sim under the same script until converged.

    ``quiet`` selects the round variant (ISSUE 19): "on" routes every
    round through ``scale_sim_step_quiet`` — the battery runs the same
    script under "on" and "off" and requires identical planes/alive/
    rounds-taken (the masked==dense oracle at harness level).

    Returns (store planes [N, n_cells] x4, alive mask, rounds-taken or -1).
    """
    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_crdt_metrics,
        scale_sim_config,
        scale_sim_step,
        scale_sim_step_quiet,
    )
    from corrosion_tpu.sim.transport import NetModel

    n_rows = max(1, (script.n_cells + 3) // 4)
    tx_k = script.max_tx_cells
    cfg = scale_sim_config(
        script.n_nodes, n_origins=script.n_origins,
        n_rows=n_rows, n_cols=(script.n_cells + n_rows - 1) // n_rows,
        sync_interval=sync_interval, tx_max_cells=tx_k, quiet=quiet,
    )
    # the configured grid must cover the script's cell space
    if cfg.n_cells < script.n_cells:
        raise ValueError(
            f"config grid has {cfg.n_cells} cells < script's "
            f"{script.n_cells}"
        )
    st = ScaleSimState.create(cfg)
    net = NetModel.create(script.n_nodes, drop_prob=drop_prob)
    step_fn = scale_sim_step_quiet if cfg.quiet == "on" else scale_sim_step
    step = jax.jit(lambda s, nt, k, i: step_fn(cfg, s, nt, k, i))
    key = jr.key(seed)
    quiet = ScaleRoundInput.quiet(cfg)

    def round_input(batch):
        n = script.n_nodes
        wm = np.zeros(n, bool)
        wc = np.zeros(n, np.int32)
        wv = np.zeros(n, np.int32)
        wl = np.zeros(n, np.int32)
        tm = np.zeros(n, bool)
        tl = np.ones(n, np.int32)
        tc = np.zeros((n, tx_k), np.int32)
        tv = np.zeros((n, tx_k), np.int32)
        tp = np.zeros((n, tx_k), np.int32)
        seen_nodes = set()
        for node, cells in (_as_tx(w) for w in batch):
            # the sim's RoundInput holds ONE write per node per round; a
            # second same-node write would silently overwrite the lanes
            # and diverge from the oracle's apply-all-in-order semantics
            if node in seen_nodes:
                raise ValueError(
                    f"script batch has two writes for node {node}; the "
                    f"sim round carries one write per node per round"
                )
            seen_nodes.add(node)
            if len(cells) == 1:
                cell, val, clp = cells[0]
                wm[node], wc[node], wv[node], wl[node] = True, cell, val, clp
            else:
                tm[node], tl[node] = True, len(cells)
                for i, (cell, val, clp) in enumerate(cells):
                    tc[node, i], tv[node, i], tp[node, i] = cell, val, clp
        return quiet._replace(
            write_mask=jnp.asarray(wm), write_cell=jnp.asarray(wc),
            write_val=jnp.asarray(wv), write_clp=jnp.asarray(wl),
            tx_mask=jnp.asarray(tm), tx_len=jnp.asarray(tl),
            tx_cell=jnp.asarray(tc), tx_val=jnp.asarray(tv),
            tx_clp=jnp.asarray(tp),
        )

    def apply_faults(inp, net, events):
        """Fold one round's fault events into the RoundInput + NetModel."""
        kill = np.zeros(script.n_nodes, bool)
        revive = np.zeros(script.n_nodes, bool)
        for ev in events:
            if ev[0] == "kill":
                kill[ev[1]] = True
            elif ev[0] == "revive":
                revive[ev[1]] = True
            elif ev[0] == "partition":
                net = net._replace(partition=jnp.asarray(ev[1], jnp.int32))
            elif ev[0] == "heal":
                net = net._replace(
                    partition=jnp.zeros(script.n_nodes, jnp.int32)
                )
            else:
                raise ValueError(f"unknown fault event {ev!r}")
        if kill.any() or revive.any():
            inp = inp._replace(kill=jnp.asarray(kill),
                               revive=jnp.asarray(revive))
        return inp, net

    for r, batch in enumerate(script.writes):
        inp = round_input(batch)
        if r < len(script.faults):
            inp, net = apply_faults(inp, net, script.faults[r])
        key, sub = jr.split(key)
        st, _ = step(st, net, sub, inp)
    # settle with every node revived and partitions healed (the harness's
    # final repair phase — dead nodes rejoin and catch up via sync)
    if script.faults:
        net = net._replace(partition=jnp.zeros(script.n_nodes, jnp.int32))
        revive_all = quiet._replace(
            revive=jnp.asarray(~np.asarray(st.swim.alive))
        )
        key, sub = jr.split(key)
        st, _ = step(st, net, sub, revive_all)
    taken = -1
    for r in range(settle_rounds + 1):  # +1: check AFTER the last step too
        m = scale_crdt_metrics(cfg, st)
        if bool(m["converged"]):
            taken = len(script.writes) + r
            break
        if r == settle_rounds:
            break
        key, sub = jr.split(key)
        st, _ = step(st, net, sub, quiet)
    planes = tuple(np.asarray(p)[:, :script.n_cells] for p in st.crdt.store)
    return planes, np.asarray(st.swim.alive), taken


# --- comparison -----------------------------------------------------------

def check_bitwise_parity(oracle: OracleCluster, sim_planes, alive) -> List[str]:
    """Single-writer regime: every alive sim node's store must equal the
    oracle's converged store, plane by plane. Returns mismatch messages."""
    problems = []
    o_planes = oracle.store_planes()
    names = ("col_version", "value", "site", "db_version", "cl_lifetime")
    for name, op, sp in zip(names, o_planes, sim_planes):
        for node in np.nonzero(alive)[0]:
            if not np.array_equal(sp[node], op):
                bad = np.nonzero(sp[node] != op)[0]
                problems.append(
                    f"{name} plane: sim node {node} differs from oracle at "
                    f"cells {bad.tolist()[:8]} "
                    f"(sim={sp[node][bad[:8]].tolist()} "
                    f"oracle={op[bad[:8]].tolist()})"
                )
                break  # one node per plane is enough signal
    return problems


def check_agreement_validity(script: WorkloadScript, sim_planes,
                             alive) -> List[str]:
    """Multi-writer regime: all alive nodes identical + every winning
    value was actually written to its cell."""
    problems = []
    alive_idx = np.nonzero(alive)[0]
    ref = alive_idx[0]
    for name, plane in zip(("ver", "val", "site", "dbv", "clp"), sim_planes):
        same = np.all(plane[alive_idx] == plane[ref], axis=0)
        if not same.all():
            problems.append(
                f"agreement violated on {name} at cells "
                f"{np.nonzero(~same)[0].tolist()[:8]}"
            )
    written = script.written_values()
    val_plane = sim_planes[1][ref]
    ver_plane = sim_planes[0][ref]
    for cell in range(script.n_cells):
        if ver_plane[cell] <= 0:
            continue
        if cell not in written:
            problems.append(
                f"validity violated: cell {cell} has version "
                f"{int(ver_plane[cell])} but the script never wrote it"
            )
        elif int(val_plane[cell]) not in written[cell]:
            problems.append(
                f"validity violated: cell {cell} holds "
                f"{int(val_plane[cell])}, never written there"
            )
    return problems
