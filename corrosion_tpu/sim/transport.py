"""The simulated transport — the boundary the TPU backend plugs in behind.

The reference funnels every byte through ``Transport`` over QUIC/Quinn
(``crates/corro-agent/src/transport.rs:79,106,141``) with three channel
classes: datagrams (SWIM), uni streams (changeset broadcast), bi streams
(anti-entropy sync) — see SURVEY §2.3 "Distributed comm backend". Here the
same three semantics become pure delivery predicates over arrays:

- ``datagram_ok`` / ``uni_ok``: fire-and-forget; lost on partition, node
  death, or random drop (UDP-ish datagrams; uni streams in practice abort
  when the peer goes away mid-flight).
- ``bi_ok``: reliable request/response; fails only on partition or dead
  peer (QUIC bi streams retransmit — random loss is invisible above them).

Partitions are modeled as a group id per node (``NetModel.partition``):
messages deliver only within a group. Healing = assigning everyone the
same group. This keeps partition state O(N) and the step fully jittable
(masked adjacency, no Python branching — build-plan hard-part (c)).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.random as jr


class NetModel(NamedTuple):
    """Dynamic network conditions (traced, changeable every round)."""

    partition: jax.Array  # int32 [N] — partition group per node
    drop_prob: jax.Array  # float32 scalar — per-message loss probability

    @staticmethod
    def create(n_nodes: int, drop_prob: float = 0.0) -> "NetModel":
        return NetModel(
            partition=jnp.zeros(n_nodes, jnp.int32),
            drop_prob=jnp.float32(drop_prob),
        )


def _link_ok(net: NetModel, alive, src, dst):
    """Both endpoints up and in the same partition group."""
    return (
        alive[src]
        & alive[dst]
        & (net.partition[src] == net.partition[dst])
    )


def datagram_ok(net: NetModel, key, alive, src, dst):
    """SWIM datagram delivery (lossy). ``src``/``dst`` int32, same shape."""
    drop = jr.uniform(key, src.shape) < net.drop_prob
    return _link_ok(net, alive, src, dst) & ~drop


# Changeset broadcast uni streams share datagram loss semantics in the sim.
uni_ok = datagram_ok


def bi_ok(net: NetModel, key, alive, src, dst):
    """Sync bi-stream availability.

    QUIC bi streams retransmit, so per-packet loss is largely invisible —
    but the stream still rides the same network: model the whole exchange
    as failing iff the connect or the response leg is lost (two draws).
    Under heavy loss syncs abort (the reference's slow-peer 5 s abort,
    ``api/peer/mod.rs:364-368``); under a blackout nothing flows.
    """
    k1, k2 = jr.split(key)
    drop = (jr.uniform(k1, src.shape) < net.drop_prob) | (
        jr.uniform(k2, src.shape) < net.drop_prob
    )
    return _link_ok(net, alive, src, dst) & ~drop
