"""The simulated transport — the boundary the TPU backend plugs in behind.

The reference funnels every byte through ``Transport`` over QUIC/Quinn
(``crates/corro-agent/src/transport.rs:79,106,141``) with three channel
classes: datagrams (SWIM), uni streams (changeset broadcast), bi streams
(anti-entropy sync) — see SURVEY §2.3 "Distributed comm backend". Here the
same three semantics become pure delivery predicates over arrays:

- ``datagram_ok`` / ``uni_ok``: fire-and-forget; lost on partition, node
  death, or random drop (UDP-ish datagrams; uni streams in practice abort
  when the peer goes away mid-flight).
- ``bi_ok``: reliable request/response; fails only on partition or dead
  peer (QUIC bi streams retransmit — random loss is invisible above them).

Partitions are modeled as a group id per node (``NetModel.partition``):
messages deliver only within a group. Healing = assigning everyone the
same group. This keeps partition state O(N) and the step fully jittable
(masked adjacency, no Python branching — build-plan hard-part (c)).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.random as jr


N_RINGS = 6  # the reference buckets RTT into 6 rings (members.rs:38)

# representative one-way latencies per ring for the members dump
# (0-6 ms ... 200-300 ms buckets, members.rs:130-178)
RING_RTT_MS = (3.0, 15.0, 45.0, 80.0, 150.0, 250.0)


class NetModel(NamedTuple):
    """Dynamic network conditions (traced, changeable every round).

    ``region`` models geography: the RTT between two nodes is a function
    of their circular region distance, bucketed into the reference's six
    RTT rings (``members.rs:38,130-178``, fed by QUIC RTT samples at
    ``transport.rs:220``). Ring 0 = same region (LAN-close) — the set the
    broadcast layer prefers for local changes."""

    partition: jax.Array  # int32 [N] — partition group per node
    drop_prob: jax.Array  # float32 scalar — per-message loss probability
    region: jax.Array  # int32 [N] — geographic region id
    cluster_id: jax.Array  # int32 [N] — ClusterId stamped on payloads;
    # mismatched traffic drops (uni payloads ``uni.rs:75-77``, sync
    # rejection ``peer/mod.rs:1425-1436``); settable live via admin

    @staticmethod
    def create(n_nodes: int, drop_prob: float = 0.0,
               n_regions: int = 1) -> "NetModel":
        return NetModel(
            partition=jnp.zeros(n_nodes, jnp.int32),
            drop_prob=jnp.float32(drop_prob),
            region=(jnp.arange(n_nodes, dtype=jnp.int32) % max(1, n_regions)),
            cluster_id=jnp.zeros(n_nodes, jnp.int32),
        )

def ring_of(net: NetModel, src, dst):
    """RTT ring between node ids (int32 arrays, same shape): circular
    region distance clipped to the six reference buckets."""
    ra, rb = net.region[src], net.region[dst]
    d = jnp.abs(ra - rb)
    n = jnp.maximum(jnp.max(net.region) + 1, 1)
    circ = jnp.minimum(d, n - d)
    return jnp.minimum(circ, N_RINGS - 1).astype(jnp.int32)


def same_region(net: NetModel):
    """[N, N] ring-0 adjacency (full-view sims only)."""
    # corrolint: disable=densify -- full-view broadcast fanout only (sim/step.py); the scale path pairs via cards and never calls this
    return net.region[:, None] == net.region[None, :]


def _link_ok(net: NetModel, alive, src, dst):
    """Both endpoints up, same partition group, same cluster id (a
    payload stamped with a foreign ClusterId is dropped at the receiver,
    ``uni.rs:75-77`` / ``peer/mod.rs:1425-1436``)."""
    return (
        alive[src]
        & alive[dst]
        & (net.partition[src] == net.partition[dst])
        & (net.cluster_id[src] == net.cluster_id[dst])
    )


def datagram_ok(net: NetModel, key, alive, src, dst):
    """SWIM datagram delivery (lossy). ``src``/``dst`` int32, same shape."""
    drop = jr.uniform(key, src.shape) < net.drop_prob
    return _link_ok(net, alive, src, dst) & ~drop


# Changeset broadcast uni streams share datagram loss semantics in the sim.
uni_ok = datagram_ok


# --- node cards: batched per-node fields for the 100k path ---------------
# On the target TPU backend a 1-D gather ``alive[idx]`` lowers to the
# per-ELEMENT index class (~9 ns/element, PERF.md) while multi-column row
# gathers run at full HBM bandwidth. The scale path therefore packs every
# per-node scalar the round needs remotely (liveness, partition group,
# cluster id, region, incarnation, HLC, ...) into one [N, C] int32 "node
# card"; ONE barriered row gather per distinct peer-index array replaces
# the ~6 element gathers each transport predicate would otherwise issue.
# Semantics are identical to the predicate forms above (same fields, same
# comparisons) — this is purely a lowering-shape change.

CARD_ALIVE, CARD_PART, CARD_CLUSTER, CARD_REGION = 0, 1, 2, 3
CARD_EXTRA = 4  # first caller-defined column


def link_card(net: NetModel, alive, extra=()):
    """Build the [N, 4+len(extra)] node card (columns CARD_*)."""
    cols = [alive.astype(jnp.int32), net.partition, net.cluster_id,
            net.region]
    cols += [e.astype(jnp.int32) for e in extra]
    return jnp.stack(cols, axis=1)


def card_at(card, idx):
    """Row-gather card rows for an arbitrary-shape index array.

    Barriered — an unbarriered row gather gets fused into its elementwise
    consumers and scalarized by this backend (PERF.md)."""
    flat = jnp.clip(idx.reshape(-1), 0)
    got = jax.lax.optimization_barrier(card[flat])
    return got.reshape(idx.shape + (card.shape[1],))


def _link_ok_c(a, b):
    return (
        (a[..., CARD_ALIVE] != 0)
        & (b[..., CARD_ALIVE] != 0)
        & (a[..., CARD_PART] == b[..., CARD_PART])
        & (a[..., CARD_CLUSTER] == b[..., CARD_CLUSTER])
    )


def datagram_ok_c(net: NetModel, key, src_card, dst_card):
    """Card form of :func:`datagram_ok` (src/dst pre-gathered rows,
    broadcastable against each other)."""
    shape = jnp.broadcast_shapes(src_card.shape[:-1], dst_card.shape[:-1])
    drop = jr.uniform(key, shape) < net.drop_prob
    return _link_ok_c(src_card, dst_card) & ~drop


def bi_ok_c(net: NetModel, key, src_card, dst_card):
    """Card form of :func:`bi_ok` (two loss draws, same link predicate)."""
    k1, k2 = jr.split(key)
    shape = jnp.broadcast_shapes(src_card.shape[:-1], dst_card.shape[:-1])
    drop = (jr.uniform(k1, shape) < net.drop_prob) | (
        jr.uniform(k2, shape) < net.drop_prob
    )
    return _link_ok_c(src_card, dst_card) & ~drop


def ring_of_c(net: NetModel, a_card, b_card):
    """Card form of :func:`ring_of` — region columns already gathered."""
    ra, rb = a_card[..., CARD_REGION], b_card[..., CARD_REGION]
    d = jnp.abs(ra - rb)
    n = jnp.maximum(jnp.max(net.region) + 1, 1)
    circ = jnp.minimum(d, n - d)
    return jnp.minimum(circ, N_RINGS - 1).astype(jnp.int32)


def bi_ok(net: NetModel, key, alive, src, dst):
    """Sync bi-stream availability.

    QUIC bi streams retransmit, so per-packet loss is largely invisible —
    but the stream still rides the same network: model the whole exchange
    as failing iff the connect or the response leg is lost (two draws).
    Under heavy loss syncs abort (the reference's slow-peer 5 s abort,
    ``api/peer/mod.rs:364-368``); under a blackout nothing flows.
    """
    k1, k2 = jr.split(key)
    drop = (jr.uniform(k1, src.shape) < net.drop_prob) | (
        jr.uniform(k2, src.shape) < net.drop_prob
    )
    return _link_ok(net, alive, src, dst) & ~drop
