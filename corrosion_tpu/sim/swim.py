"""SWIM membership as one fused, jittable message-passing round.

The reference runs foca (SWIM) as an event loop: probe a member each
period, wait for ack, fall back to ``num_indirect_probes`` helpers,
suspect on silence, declare down when the suspicion timer lapses, refute
by bumping our incarnation when we hear ourselves suspected, and
piggyback a bounded batch of freshest membership updates on every packet
(``runtime_loop``, ``crates/corro-agent/src/broadcast/mod.rs:122-376``;
identity renew/rejoin ``crates/corro-types/src/actor.rs:184-210``).

Array re-design: all N nodes execute one probe period simultaneously.

- A node's *view* of every other node is one packed int32
  (``incarnation * 4 + state``; -1 = unknown), so "apply a received
  membership update" is ``scatter-max`` — foca's precedence rules
  (higher incarnation wins; same incarnation Down > Suspect > Alive)
  collapse into integer ordering (see ``ops/lww.py``).
- Probe targets / indirect helpers / piggyback subjects are chosen by
  masked random scores + ``argmax``/``top_k`` (distributionally matching
  foca's shuffled round-robin; parity is distributional by design —
  SURVEY §7 hard-part (d)).
- Suspicion timers are countdown planes; expiry is an elementwise
  rewrite to Down.
- Per-(viewer, subject) remaining-transmission budgets (``tx_left``)
  vectorize foca's update queue: any cell whose view changed this round
  gets a fresh budget and is eligible for piggybacking until it drains.

One call = one probe period for the whole cluster; wall-clock per round
is the benchmark metric (BASELINE config 2: N-node join + churn).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from corrosion_tpu.ops.lww import STATE_ALIVE, STATE_DOWN, STATE_SUSPECT, pack_inc_state
from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.transport import NetModel, datagram_ok

UNKNOWN = np.int32(-1)  # np scalar: safe to close over in pallas kernels


class SwimState(NamedTuple):
    alive: jax.Array  # bool  [N] — ground-truth process liveness
    incarnation: jax.Array  # int32 [N] — own incarnation counter
    view: jax.Array  # int32 [N, N] — packed (inc, state); -1 unknown
    suspect_timer: jax.Array  # int32 [N, N] — rounds until suspect -> down
    tx_left: jax.Array  # int32 [N, N] — piggyback budget per belief

    @staticmethod
    def create(cfg: SimConfig, n_seeds: int = 4) -> "SwimState":
        """Fresh cluster: everyone up; each node knows itself and the
        first ``n_seeds`` nodes (the bootstrap list — the reference
        resolves a configured seed set at startup,
        ``crates/corro-agent/src/agent/bootstrap.rs:14-150``)."""
        n = cfg.n_nodes
        view = jnp.full((n, n), UNKNOWN, jnp.int32)
        seed_key = pack_inc_state(jnp.int32(0), jnp.int32(STATE_ALIVE))
        view = view.at[:, : max(1, n_seeds)].set(seed_key)
        view = view.at[jnp.arange(n), jnp.arange(n)].set(seed_key)
        return SwimState(
            alive=jnp.ones(n, bool),
            incarnation=jnp.zeros(n, jnp.int32),
            view=view,
            suspect_timer=jnp.zeros((n, n), jnp.int32),
            tx_left=jnp.full((n, n), cfg.max_transmissions, jnp.int32),
        )


def bootstrap_members(st: SwimState, member_ids, incarnations=None) -> "SwimState":
    """Seed every node's view with a persisted member list — the boot
    path that replays ``__corro_members`` into foca (``initialise_foca``'s
    ApplyMany, ``crates/corro-agent/src/agent/util.rs:69-130``): restart
    with yesterday's membership instead of just the static seed set."""
    import numpy as np

    n = st.view.shape[0]
    ids_np = np.asarray(member_ids, np.int32)
    incs_np = (np.asarray(incarnations, np.int32)
               if incarnations is not None
               else np.zeros(ids_np.shape, np.int32))
    in_range = (ids_np >= 0) & (ids_np < n)  # foreign ids dropped, never
    ids_np, incs_np = ids_np[in_range], incs_np[in_range]  # clipped onto
    if ids_np.size == 0:  # a real node's view
        return st
    keys = pack_inc_state(jnp.asarray(incs_np), jnp.int32(STATE_ALIVE))
    view = st.view.at[:, jnp.asarray(ids_np)].max(keys[None, :])
    return st._replace(view=view)


def swim_step(
    cfg: SimConfig,
    st: SwimState,
    net: NetModel,
    key: jax.Array,
    kill=None,
    revive=None,
):
    """One SWIM probe period for all nodes. Returns (state, info)."""
    n = cfg.n_nodes
    iarr = jnp.arange(n, dtype=jnp.int32)
    k_tgt, k_p1, k_p2, k_help, k_ind, k_pri, k_announce = jr.split(key, 7)

    # --- churn (external fault injection, BASELINE config 2/5) ----------
    kill = jnp.zeros(n, bool) if kill is None else kill
    revive = jnp.zeros(n, bool) if revive is None else revive
    alive = (st.alive & ~kill) | revive
    # rejoin = identity renew: bump incarnation so the old Down loses
    # (actor.rs:199-210 `renew()` + auto-rejoin)
    inc = st.incarnation + revive.astype(jnp.int32)

    old_view = st.view
    self_key = pack_inc_state(inc, jnp.int32(STATE_ALIVE))
    view = old_view.at[iarr, iarr].max(jnp.where(alive, self_key, UNKNOWN))

    # --- probe target: one believed-alive member, uniformly ------------
    believed_alive = (view >= 0) & ((view & 3) == STATE_ALIVE)
    believed_alive = believed_alive & ~jnp.eye(n, dtype=bool)
    t_scores = jnp.where(believed_alive, jr.uniform(k_tgt, (n, n)), -1.0)
    tgt = jnp.argmax(t_scores, axis=1).astype(jnp.int32)
    has_tgt = alive & jnp.any(believed_alive, axis=1)

    # --- direct probe + ack (datagram channel, lossy) -------------------
    leg_out = datagram_ok(net, k_p1, alive, iarr, tgt)  # probe reaches tgt
    leg_back = datagram_ok(net, k_p2, alive, tgt, iarr)  # ack reaches us
    probe_ok = has_tgt & leg_out & leg_back

    # --- indirect probes through n_indirect helpers ---------------------
    h_scores = jnp.where(
        believed_alive & (iarr[None, :] != tgt[:, None]),
        jr.uniform(k_help, (n, n)),
        -1.0,
    )
    h_val, helpers = jax.lax.top_k(h_scores, max(1, cfg.n_indirect))
    h_valid = h_val >= 0
    k1, k2, k3, k4 = jr.split(k_ind, 4)
    src = jnp.broadcast_to(iarr[:, None], helpers.shape)
    tgt_b = jnp.broadcast_to(tgt[:, None], helpers.shape)
    ind_leg = (
        datagram_ok(net, k1, alive, src, helpers)
        & datagram_ok(net, k2, alive, helpers, tgt_b)
        & datagram_ok(net, k3, alive, tgt_b, helpers)
        & datagram_ok(net, k4, alive, helpers, src)
    )
    ind_ok = jnp.any(h_valid & ind_leg, axis=1) & has_tgt
    acked = probe_ok | ind_ok
    failed = has_tgt & ~acked

    # --- suspicion start: probe failed => suspect at the known inc ------
    cur_tgt = view[iarr, tgt]
    suspect_key = (cur_tgt >> 2) * 4 + STATE_SUSPECT
    view = view.at[iarr, tgt].max(jnp.where(failed, suspect_key, UNKNOWN))
    # the suspicion also travels toward the target itself (gossip fanout
    # reaches the subject quickly in practice; foca's refutation depends
    # on it) — if it lands, the target's self-cell merge triggers the
    # incarnation bump below
    k_notify = jr.fold_in(k_p1, 1)
    notify_ok = failed & datagram_ok(net, k_notify, alive, iarr, tgt)
    view = view.at[tgt, tgt].max(jnp.where(notify_ok, suspect_key, UNKNOWN))

    # --- periodic announce (spawn_swim_announcer analog) ----------------
    # Each round a node announces with prob 1/announce_interval to a
    # uniformly random *ever-known* member — NOT just believed-alive ones.
    # This is the partition-heal / rejoin path: the reference announces to
    # DB-known members on a jittered timer
    # (``agent/handlers.rs:193-244``, ``ANNOUNCE_INTERVAL`` agent/mod.rs:33).
    k_ann, k_annt, k_ann1, k_ann2 = jr.split(k_announce, 4)
    announcing = alive & (
        jr.uniform(k_ann, (n,)) < 1.0 / max(1, cfg.announce_interval)
    )
    known = (view >= 0) & ~jnp.eye(n, dtype=bool)
    a_scores = jnp.where(known, jr.uniform(k_annt, (n, n)), -1.0)
    ann_tgt = jnp.argmax(a_scores, axis=1).astype(jnp.int32)
    announcing = announcing & jnp.any(known, axis=1)
    ann_out = announcing & datagram_ok(net, k_ann1, alive, iarr, ann_tgt)
    ann_back = ann_out & datagram_ok(net, k_ann2, alive, ann_tgt, iarr)

    # the announce asserts the sender is alive at its current incarnation
    view = view.at[ann_tgt, iarr].max(jnp.where(ann_out, self_key, UNKNOWN))
    # down-notice: if the receiver believed the sender suspect/down, it
    # tells the sender, whose self-cell merge triggers refutation below
    # (the reference's "declared down -> renew + rejoin", actor.rs:199-210)
    bel = old_view[ann_tgt, iarr]
    notice = ann_back & (bel >= 0) & ((bel & 3) != STATE_ALIVE)
    view = view.at[iarr, iarr].max(jnp.where(notice, bel, UNKNOWN))

    # --- piggyback gossip on probe + ack + announce packets -------------
    # each sender picks up to `piggyback` subjects with budget left
    pri = jnp.where(st.tx_left > 0, jr.uniform(k_pri, (n, n)), -1.0)
    sel_val, subj = jax.lax.top_k(pri, cfg.piggyback)  # [N, U]
    sel_ok = sel_val >= 0
    payload = view[iarr[:, None], subj]  # [N, U]

    def edges(sender_rows, receiver, ok):
        return (
            jnp.broadcast_to(receiver[:, None], subj.shape),
            subj[sender_rows],
            payload[sender_rows],
            ok[:, None] & sel_ok[sender_rows],
        )

    # probe i->tgt (iff leg_out), ack tgt->i (iff probe_ok),
    # announce i->ann_tgt (iff ann_out), announce-reply ann_tgt->i
    parts = [
        edges(iarr, tgt, has_tgt & leg_out),
        (jnp.broadcast_to(iarr[:, None], subj.shape), subj[tgt], payload[tgt], probe_ok[:, None] & sel_ok[tgt]),
        edges(iarr, ann_tgt, ann_out),
        (jnp.broadcast_to(iarr[:, None], subj.shape), subj[ann_tgt], payload[ann_tgt], ann_back[:, None] & sel_ok[ann_tgt]),
    ]
    recv = jnp.concatenate([p[0] for p in parts])
    subjects = jnp.concatenate([p[1] for p in parts])
    keys_m = jnp.concatenate([p[2] for p in parts])
    valid_m = jnp.concatenate([p[3] for p in parts])

    # every delivered packet also asserts its sender is alive at the
    # sender's current incarnation (receiving data from a peer IS
    # liveness evidence; this is what re-knits views after rejoin when
    # the dedicated rumor budget has already drained)
    sender_assert = [
        (tgt, iarr, self_key, has_tgt & leg_out),  # probe: tgt hears i
        (iarr, tgt, self_key[tgt], probe_ok),  # ack: i hears tgt
        (ann_tgt, iarr, self_key, ann_out),
        (iarr, ann_tgt, self_key[ann_tgt], ann_back),
    ]
    recv = jnp.concatenate([recv.reshape(-1)] + [r for r, *_ in sender_assert])
    subjects = jnp.concatenate(
        [subjects.reshape(-1)] + [s for _, s, *_ in sender_assert]
    )
    keys_m = jnp.concatenate([keys_m.reshape(-1)] + [k for *_, k, _ in sender_assert])
    valid_m = jnp.concatenate([valid_m.reshape(-1)] + [v for *_, v in sender_assert])

    flat_cell = jnp.where(valid_m, recv * n + subjects, n * n).reshape(-1)
    view = (
        view.reshape(-1)
        .at[flat_cell]
        .max(keys_m.reshape(-1), mode="drop")
        .reshape(n, n)
    )

    # --- decrement piggyback budgets for attempted sends ----------------
    sends = (
        has_tgt.astype(jnp.int32)
        + announcing.astype(jnp.int32)
        + jnp.zeros(n, jnp.int32).at[tgt].add((leg_out & alive[tgt]).astype(jnp.int32))
        + jnp.zeros(n, jnp.int32).at[ann_tgt].add(ann_back.astype(jnp.int32))
    )
    dec_cell = jnp.where(sel_ok, iarr[:, None] * n + subj, n * n).reshape(-1)
    tx_left = (
        st.tx_left.reshape(-1)
        .at[dec_cell]
        .add(-jnp.broadcast_to(sends[:, None], subj.shape).reshape(-1), mode="drop")
        .reshape(n, n)
    )
    tx_left = jnp.maximum(tx_left, 0)

    # --- suspicion timers: arm on fresh suspicion, tick, expire to Down -
    changed = view != old_view
    is_suspect = (view >= 0) & ((view & 3) == STATE_SUSPECT)
    newly = changed & is_suspect
    timer = jnp.where(newly, cfg.suspicion_rounds, st.suspect_timer)
    ticking = is_suspect & ~newly & alive[:, None]
    timer = jnp.where(ticking, timer - 1, timer)
    expired = is_suspect & (timer <= 0) & alive[:, None]
    view = jnp.where(expired, (view >> 2) * 4 + STATE_DOWN, view)

    # --- refutation: I hear I'm suspected/down => bump my incarnation ---
    selfv = view[iarr, iarr]
    refute = alive & (selfv >= 0) & ((selfv & 3) != STATE_ALIVE)
    inc = jnp.where(refute, (selfv >> 2) + 1, inc)
    view = view.at[iarr, iarr].set(
        jnp.where(alive, pack_inc_state(inc, jnp.int32(STATE_ALIVE)), selfv)
    )

    # --- fresh news gets a fresh dissemination budget -------------------
    changed = view != old_view
    tx_left = jnp.where(changed, cfg.max_transmissions, tx_left)

    st2 = SwimState(alive, inc, view, timer, tx_left)
    info = {
        "acked": jnp.sum(acked),
        "failed_probes": jnp.sum(failed),
        "refutes": jnp.sum(refute),
    }
    return st2, info


def swim_metrics(st: SwimState):
    """Convergence metrics — the assertion of the reference's stress tests
    (``configurable_stress_test``, ``crates/corro-agent/src/agent/tests.rs``)
    transplanted to membership: every alive node's view matches ground
    truth (alive subjects seen Alive; dead subjects seen Down or never
    known)."""
    state = st.view & 3
    known = st.view >= 0
    subj_alive = st.alive[None, :]
    ok = jnp.where(
        subj_alive,
        known & (state == STATE_ALIVE),
        ~known | (state == STATE_DOWN),
    )
    viewer = st.alive[:, None]
    correct = jnp.sum(ok & viewer)
    total = jnp.maximum(jnp.sum(viewer) * st.alive.shape[0], 1)
    accuracy = correct / total
    return {
        "accuracy": accuracy,
        "converged": correct == jnp.sum(viewer) * st.alive.shape[0],
        "n_alive": jnp.sum(st.alive),
    }
