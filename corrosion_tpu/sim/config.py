"""Static simulator configuration.

Mirrors the knobs the reference centralizes in ``GossipConfig`` /
``PerfConfig`` (``crates/corro-types/src/config.rs:200-257``) and the
cluster-size-adaptive foca config (``make_foca_config``,
``crates/corro-agent/src/broadcast/mod.rs:951-960``), re-expressed in
simulator units: one *round* is one fused message-passing step, roughly a
SWIM probe period.

Everything here is static (hashable) so the config can be a jit
static-arg; per-run dynamic knobs (drop probability, partitions) live in
``NetModel`` (``transport.py``) as traced arrays instead.
"""

from __future__ import annotations

import dataclasses
import math

#: legal values of the ``fused`` execution knob (docs/fused.md) — the
#: ONE canonical tuple; the sim configs validate against it and
#: ``ops.megakernel``/the CLI re-export it (this module is import-light,
#: so the CLI parser can use it without pulling in jax)
FUSED_MODES = ("auto", "on", "off", "interpret")

#: legal values of the ``quiet`` execution knob (corroquiet active-set
#: rounds, docs/fused.md): "auto" lets the host plane
#: (resilience/segments) pick the quiet step for all-quiet segments,
#: "on" pins the active-set scan body, "off" pins the dense step.
#: Same import-light contract as FUSED_MODES (the CLI parser uses it).
QUIET_MODES = ("auto", "on", "off")


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Shapes and protocol constants for the simulated cluster."""

    n_nodes: int
    # --- SWIM membership (foca analog) -----------------------------------
    n_indirect: int = 3  # foca num_indirect_probes (new_wan keeps 3)
    suspicion_rounds: int = 6  # probe periods before suspect -> down
    piggyback: int = 8  # membership updates per message (1178 B packet analog)
    max_transmissions: int = 10  # per-update re-send budget before it goes quiet
    announce_interval: int = 16  # mean rounds between announces (ANNOUNCE_INTERVAL)
    # --- CRDT store ------------------------------------------------------
    # bookkeeping SLOTS per node (round 4): with any_writer, n_origins
    # bounds how many distinct actors a node can TRACK, not who may
    # write (hash-slotted origin table, ops/versions.py Book); without
    # it, the legacy fixed pool — only nodes 0..n_origins-1 write
    n_origins: int = 4
    # ANY node may write (the reference's semantics — BookedVersions is
    # per observed actor, agent.rs:1270-1604); off = legacy fixed pool
    any_writer: bool = False
    # slot-eviction idle threshold: a tracked actor with no fresh
    # activity for this many rounds can lose its slot to a colliding
    # foreign writer (sync rebuilds the evicted bookkeeping)
    org_keep_rounds: int = 16
    n_rows: int = 16  # LWW rows per table
    n_cols: int = 4  # LWW columns per row
    buf_slots: int = 64  # out-of-order version buffer per node
    # --- multi-cell transactions (ChunkedChanges analog) ------------------
    # max cells per write transaction == max seqs per version (chunked
    # delivery + receiver-side buffering, change.rs:66-178); 1 = single-
    # cell versions only, which skips the partial buffer entirely
    tx_max_cells: int = 8
    partial_slots: int = 16  # incomplete-version buffer slots per node
    # --- broadcast dissemination (handle_broadcasts analog) --------------
    bcast_fanout: int = 5  # random member fanout per flush
    bcast_queue: int = 64  # pending-broadcast slots per node
    bcast_max_transmissions: int = 3  # re-send budget per changeset
    recv_slots: int = 96  # max applied messages per node per round
    # per-node per-round send budget in wire bytes — the 10 MiB/s governor
    # analog at one round ~= one second (broadcast/mod.rs:460-463); lower
    # it to simulate overload shaping
    bcast_budget_bytes: int = 10 * 1024 * 1024
    # --- anti-entropy sync (parallel_sync analog) -------------------------
    sync_interval: int = 8  # rounds between sync attempts per node
    sync_peers: int = 2  # peers per sync round (clamp(members/100, 3, 10) analog)
    sync_chunk: int = 32  # max versions pulled per (peer, origin) per round
    # server-side load adaptation (agent.rs:143 serve permits = 3,
    # rejection peer/mod.rs:1462-1479, adaptive chunk peer/mod.rs:364-368):
    # clients of an overloaded server are shed down to ~4x the permit
    # count and the survivors' grants shrink toward sync_min_chunk
    serve_cap: int = 3
    sync_min_chunk: int = 4
    # anti-starvation bound on the shed: after this many consecutive
    # shed rounds a requesting client is admitted unconditionally, so
    # degradation stays budget-shaped without ever starving a client
    sync_defer_cap: int = 8
    # every k-th cohort/sync period, lane 0 merges its peer's FULL
    # store (ignores grants/ownership; LWW join is idempotent) — the
    # convergence backstop when bookkeeping slots are contended, which
    # requires any_writer; DEFAULT OFF here so the legacy fixed-pool
    # convergence tests keep exercising the granted-range sync path
    # undiluted (a sweep would mask range-grant regressions)
    sync_sweep_every: int = 0
    # --- fused megakernel path (execution knob, config.perf.fused) -------
    # "auto": pallas kernels on non-CPU backends when the eager probes
    # pass; "on": pin the fused path (interpret-mode on CPU); "off":
    # pin the XLA path; "interpret": fused kernels in pallas interpret
    # mode on ANY backend (the tier-1 parity/testing mode). Execution
    # only — fused == unfused bit for bit (docs/fused.md), so this key
    # is excluded from checkpoint config identity
    # (checkpoint.config_identity)
    fused: str = "auto"

    @property
    def n_cells(self) -> int:
        return self.n_rows * self.n_cols

    @property
    def sync_tracks(self) -> int:
        """Columns of the per-node last-sync table: the full-view sim
        tracks last-sync-round per peer *node id*."""
        return self.n_nodes

    def validate(self) -> "SimConfig":
        # real errors, not bare asserts: ``python -O`` strips asserts
        # and a silently-invalid config would crash far from here
        if self.n_origins > self.n_nodes:
            raise ValueError(
                f"n_origins {self.n_origins} > n_nodes {self.n_nodes}"
            )
        if self.piggyback < 1 or self.n_indirect < 0:
            raise ValueError(
                f"need piggyback >= 1 and n_indirect >= 0, got "
                f"{self.piggyback}/{self.n_indirect}"
            )
        if not 1 <= self.tx_max_cells <= 30:
            raise ValueError(
                f"tx_max_cells {self.tx_max_cells} not in 1..30 "
                f"(seq bitmask lives in an int32)"
            )
        if self.fused not in FUSED_MODES:
            raise ValueError(
                f"fused {self.fused!r} not one of {FUSED_MODES} "
                f"(docs/fused.md)"
            )
        return self


def wan_config(n_nodes: int, **overrides) -> SimConfig:
    """Cluster-size-adaptive defaults, following the shape of the
    reference's ``make_foca_config`` (``broadcast/mod.rs:951-960``): WAN
    tuning, 3 indirect probes, dissemination budget growing with log N so
    rumors survive long enough to cover the cluster."""
    log_n = max(1, math.ceil(math.log2(max(2, n_nodes))))
    defaults = dict(
        n_indirect=3,
        max_transmissions=log_n + 4,
        suspicion_rounds=max(4, log_n),
        piggyback=8,
        bcast_fanout=max(3, min(10, n_nodes // 100 + 3)),
        # clamp(members/100, 3, 10) — the reference's cluster-size-adaptive
        # sync fanout (handlers.rs:838); static N stands in for live count
        sync_peers=max(3, min(10, n_nodes // 100)),
    )
    defaults.update(overrides)
    return SimConfig(n_nodes=n_nodes, **defaults).validate()
