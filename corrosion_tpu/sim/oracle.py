"""Pure-Python host oracle for the simulator — the small-N ground truth.

Mirrors the semantics the array kernels must reproduce, in plain dicts and
sets: LWW cell merge (``doc/crdts.md:14-16,237``), per-origin version
bookkeeping (seen-set / contiguous head — ``BookedVersions``, reference
``crates/corro-types/src/agent.rs:1270-1604``), and the convergence
predicate ("no needs, equal heads", as the reference's Antithesis
``check_bookkeeping.py`` driver checks).

Deliberately slow and obvious; property tests drive both this and the
jitted kernels with the same random traffic and demand identical states.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Set, Tuple

# (cell, ver, val, site, origin, dbv, clp) — clp is the causal-length
# row lifetime the cell was written under (cr-sqlite `cl`)
Change = Tuple[int, int, int, int, int, int, int]


def lww_wins(a: Tuple[int, int, int, int], b: Tuple[int, int, int, int]) -> bool:
    """Does clock ``a`` = (cl_lifetime, col_version, value, site_id) beat
    ``b``? A later causal-length lifetime beats anything from an earlier
    one (cr-sqlite "greater causal length wins", ``doc/crdts.md:24-40``);
    within a lifetime the plain LWW rule applies.

    Ties keep the incumbent ``a`` (identical change)."""
    return a >= b  # Python tuple comparison IS the lexicographic rule


@dataclass
class OracleNode:
    """One simulated node: LWW store + per-origin version bookkeeping."""

    n_origins: int
    # cell -> (col_version, value, site, origin_db_version, cl_lifetime)
    store: Dict[int, Tuple[int, int, int, int, int]] = field(default_factory=dict)
    seen: Dict[int, Set[int]] = field(default_factory=dict)  # origin -> versions
    known_max: Dict[int, int] = field(default_factory=dict)
    # (origin, dbv) -> {seq: (cell, ver, val, site, clp)} — buffered cells
    # of incomplete chunked versions (the __corro_buffered_changes analog,
    # reference crates/corro-agent/src/agent/util.rs:1061-1194); applied
    # atomically once seqs 0..nseq-1 are all present
    partial: Dict[Tuple[int, int], Dict[int, Tuple[int, int, int, int, int]]] = (
        field(default_factory=dict)
    )

    def head(self, origin: int) -> int:
        s = self.seen.get(origin, set())
        h = 0
        while (h + 1) in s:
            h += 1
        return h

    def merge_cell(self, cell: int, ver: int, val: int, site: int, dbv: int,
                   clp: int = 0):
        cur = self.store.get(cell)
        if cur is None or not lww_wins(
            (cur[4], cur[0], cur[1], cur[2]), (clp, ver, val, site)
        ):
            self.store[cell] = (ver, val, site, dbv, clp)

    def record(self, origin: int, version: int) -> bool:
        """Record an origin-version; returns True when fresh (unseen)."""
        s = self.seen.setdefault(origin, set())
        self.known_max[origin] = max(self.known_max.get(origin, 0), version)
        if version in s:
            return False
        s.add(version)
        return True

    def apply(self, change: Change) -> bool:
        cell, ver, val, site, origin, dbv, clp = change
        fresh = self.record(origin, dbv)
        if fresh:
            self.merge_cell(cell, ver, val, site, dbv, clp)
        return fresh

    def apply_chunk(self, change: Change, seq: int, nseq: int) -> bool:
        """Ingest one cell of a chunked version. ``nseq == 1`` is the
        complete-changeset fast path; otherwise the cell buffers until
        the whole seq range 0..nseq-1 is present, then the version
        applies atomically and records as seen
        (``process_incomplete_version`` ->
        ``process_fully_buffered_changes``, ``util.rs:1061-1194,546-696``).
        Returns True when this cell was fresh (re-broadcast it)."""
        if nseq <= 1:
            return self.apply(change)
        cell, ver, val, site, origin, dbv, clp = change
        self.known_max[origin] = max(self.known_max.get(origin, 0), dbv)
        if dbv in self.seen.get(origin, set()):
            return False  # whole version already seen
        buf = self.partial.setdefault((origin, dbv), {})
        if seq in buf:
            return False  # duplicate chunk
        buf[seq] = (cell, ver, val, site, clp)
        if len(buf) == nseq:  # seq range closed -> atomic apply
            self.seen.setdefault(origin, set()).add(dbv)
            for c, v, vl, st, cl in buf.values():
                self.merge_cell(c, v, vl, st, dbv, cl)
            del self.partial[(origin, dbv)]
        return True

    def needs(self, origin: int) -> int:
        s = self.seen.get(origin, set())
        km = self.known_max.get(origin, 0)
        return sum(1 for v in range(1, km + 1) if v not in s)


def converged(nodes) -> bool:
    """The reference's convergence check: no needs + equal heads
    (``check_bookkeeping.py``), plus (stronger) identical LWW stores."""
    first = nodes[0]
    for n in nodes[1:]:
        if n.store != first.store:
            return False
        for o in range(first.n_origins):
            if n.head(o) != first.head(o) or n.needs(o) or first.needs(o):
                return False
    return True
