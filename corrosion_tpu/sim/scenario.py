"""Workload scenarios — the BASELINE.json benchmark configs as input
streams, plus the corrochaos scale-sim fault compiler.

Each full-sim scenario builds a stacked ``RoundInput`` (leading axis =
rounds) plus a ``NetModel``, mirroring the reference's test drivers:
single-writer inserts (config 1/3), membership churn (config 2),
conflict-heavy multi-writer LWW (config 4), and the full mix with
partitions (config 5) — the same shapes as ``configurable_stress_test``
(``crates/corro-agent/src/agent/tests.rs:286-600``) and the Antithesis
workload scripts.

The **fault compiler** at the bottom is the scale-sim half of the
corrochaos engine (``resilience/chaos.py``, docs/chaos.md): a
:class:`FaultPhase` is a declarative window of the scenario — a
constant network shape plus seeded workload/churn/clock-skew knobs —
and :func:`compile_scale_phase` lowers it into the traced fault inputs
the segmented soak pipeline actually consumes (a stacked
``ScaleRoundInput``, the phase's ``NetModel``, and a host-injected HLC
skew vector). Compilation is a pure function of ``(cfg, phase, key,
dead)``: same seed, same trace — the whole determinism contract of the
chaos engine rides on it.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.step import RoundInput
from corrosion_tpu.sim.transport import NetModel


def quiet(cfg: SimConfig, rounds: int) -> RoundInput:
    """Membership-only (BASELINE config 2 without churn)."""
    z = RoundInput.quiet(cfg)
    return jax.tree.map(lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), z)


def churn(cfg: SimConfig, rounds: int, key, rate: float = 0.01) -> RoundInput:
    """Random failure churn: each round a node dies or rejoins with
    prob ``rate`` (BASELINE config 2)."""
    n = cfg.n_nodes
    k1, k2 = jr.split(key)
    kill = jr.uniform(k1, (rounds, n)) < rate
    revive = jr.uniform(k2, (rounds, n)) < rate
    base = quiet(cfg, rounds)
    return base._replace(kill=kill, revive=revive & ~kill)


def single_writer(cfg: SimConfig, rounds: int, key, writes_per_round: int = 1):
    """One writer streams inserts (BASELINE config 3: fanout latency)."""
    n = cfg.n_nodes
    k1, k2 = jr.split(key)
    base = quiet(cfg, rounds)
    w = jnp.zeros((rounds, n), bool).at[:, 0].set(True)
    cell = jnp.zeros((rounds, n), jnp.int32).at[:, 0].set(
        jr.randint(k1, (rounds,), 0, cfg.n_cells)
    )
    val = jnp.zeros((rounds, n), jnp.int32).at[:, 0].set(
        jr.randint(k2, (rounds,), 0, 1 << 20)
    )
    return base._replace(write_mask=w, write_cell=cell, write_val=val)


def conflict_heavy(
    cfg: SimConfig, rounds: int, key, write_prob: float = 0.5, hot_cells: int = 2
):
    """All origins hammer a few hot cells concurrently — the LWW
    conflict workload (BASELINE config 4)."""
    n = cfg.n_nodes
    k1, k2, k3 = jr.split(key, 3)
    base = quiet(cfg, rounds)
    w = (jr.uniform(k1, (rounds, n)) < write_prob) & (
        jnp.arange(n)[None, :] < cfg.n_origins
    )
    cell = jr.randint(k2, (rounds, n), 0, max(1, hot_cells)).astype(jnp.int32)
    val = jr.randint(k3, (rounds, n), 0, 1 << 20).astype(jnp.int32)
    return base._replace(write_mask=w, write_cell=cell, write_val=val)


def full_mix(
    cfg: SimConfig,
    rounds: int,
    key,
    churn_rate: float = 0.005,
    write_prob: float = 0.3,
    partition_rounds: tuple = (),
):
    """Churn + multi-writer + (optional) partition/heal windows
    (BASELINE config 5). Returns (inputs, net_for_partition_phase)."""
    k1, k2 = jr.split(key)
    inp = conflict_heavy(cfg, rounds, k1, write_prob=write_prob, hot_cells=cfg.n_cells)
    ch = churn(cfg, rounds, k2, rate=churn_rate)
    return inp._replace(kill=ch.kill, revive=ch.revive)


def partitioned_net(cfg: SimConfig, groups: int = 2, drop_prob: float = 0.0) -> NetModel:
    return NetModel.create(cfg.n_nodes, drop_prob=drop_prob)._replace(
        partition=(jnp.arange(cfg.n_nodes) % groups).astype(jnp.int32),
    )


# --- corrochaos: the scale-sim fault compiler (docs/chaos.md) ------------


@dataclasses.dataclass(frozen=True)
class FaultPhase:
    """One declarative window of a chaos scenario (scenario-as-data).

    Device-plane faults (kills, revives, writes) land in the compiled
    ``ScaleRoundInput`` stack; network faults (partition, loss) shape
    the phase's constant ``NetModel``; ``clock_skew_*`` compiles to a
    host-injected HLC bump the engine applies at phase entry — the
    knob the HLC max-drift gate (``broadcast.hlc_fold``,
    ``HLC_MAX_DRIFT_ROUNDS``) is swept against. Kills and revives both
    fire on the phase's FIRST round and are disjoint by construction:
    revives cover only nodes dead at entry, kills draw from alive
    non-seed nodes (seeds anchor bootstrap, like the reference's
    Antithesis driver sparing its bootstrap set)."""

    rounds: int
    write_frac: float = 0.0  # conflict-heavy writer fraction per round
    kill_frac: float = 0.0  # one-shot kill draw at phase entry
    revive_killed: bool = False  # revive every dead node at phase entry
    partition_groups: int = 1  # >1: net split into id%groups islands
    drop_prob: float = 0.0  # datagram loss for the phase
    clock_skew_rounds: int = 0  # HLC skew injected at phase entry...
    clock_skew_frac: float = 0.0  # ...on this fraction of nodes

    def validate(self) -> "FaultPhase":
        if self.rounds <= 0:
            raise ValueError(f"phase rounds must be positive, got {self.rounds}")
        for name in ("write_frac", "kill_frac", "clock_skew_frac"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} {v} not in [0, 1]")
        if self.partition_groups < 1:
            raise ValueError(
                f"partition_groups must be >= 1, got {self.partition_groups}"
            )
        if self.clock_skew_rounds < 0 or not 0.0 <= self.drop_prob <= 1.0:
            raise ValueError(
                f"bad clock_skew_rounds/drop_prob "
                f"{self.clock_skew_rounds}/{self.drop_prob}"
            )
        return self


def compile_scale_phase(cfg, phase: FaultPhase, key, dead=None):
    """Lower one :class:`FaultPhase` into traced fault inputs.

    -> ``(inputs, net, skew, dead_out)`` where ``inputs`` is a stacked
    ``ScaleRoundInput`` (leading axis = ``phase.rounds``), ``net`` the
    phase's constant ``NetModel``, ``skew`` an int32 numpy [N] of
    pre-shifted HLC units (``rounds << HLC_ROUND_BITS``; all-zero when
    the phase skews no clocks) the engine adds to ``crdt.hlc`` at phase
    entry, and ``dead_out`` the bool numpy [N] dead-set after this
    phase's entry events (thread it into the next phase so revives stay
    exact inverses of prior kills).

    Pure in ``(cfg, phase, key, dead)`` — the chaos determinism
    contract. Writes are masked to nodes alive after the entry events,
    so a scripted workload never writes from a corpse."""
    from corrosion_tpu.sim.broadcast import HLC_ROUND_BITS
    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        make_write_inputs,
    )

    phase.validate()
    n, rounds = cfg.n_nodes, phase.rounds
    k_kill, k_write, k_mask, k_skew = jr.split(key, 4)
    dead = (np.zeros(n, bool) if dead is None
            else np.array(dead, dtype=bool, copy=True))
    if dead.shape != (n,):
        raise ValueError(f"dead mask shape {dead.shape} != ({n},)")

    revive_mask = dead if phase.revive_killed else np.zeros(n, bool)
    killable = ~dead & (np.arange(n) >= cfg.n_seeds)
    kill_mask = (
        (np.asarray(jr.uniform(k_kill, (n,))) < phase.kill_frac) & killable
        if phase.kill_frac > 0.0 else np.zeros(n, bool)
    )
    dead_out = (dead & ~revive_mask) | kill_mask

    if phase.write_frac > 0.0:
        wm = (np.asarray(jr.uniform(k_mask, (rounds, n))) < phase.write_frac)
        wm &= ~dead_out[None, :]
        inputs = make_write_inputs(cfg, k_write, rounds, jnp.asarray(wm))
    else:
        inputs = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (rounds,) + a.shape),
            ScaleRoundInput.quiet(cfg),
        )
    z = np.zeros((rounds, n), bool)
    inputs = inputs._replace(
        kill=jnp.asarray(np.where(np.arange(rounds)[:, None] == 0,
                                  kill_mask[None, :], z)),
        revive=jnp.asarray(np.where(np.arange(rounds)[:, None] == 0,
                                    revive_mask[None, :], z)),
    )

    net = NetModel.create(n, drop_prob=phase.drop_prob)
    if phase.partition_groups > 1:
        net = net._replace(
            partition=(jnp.arange(n) % phase.partition_groups).astype(
                jnp.int32
            )
        )

    skew = np.zeros(n, np.int32)
    if phase.clock_skew_rounds > 0 and phase.clock_skew_frac > 0.0:
        sel = np.asarray(jr.uniform(k_skew, (n,))) < phase.clock_skew_frac
        skew = np.where(
            sel, np.int32(phase.clock_skew_rounds << HLC_ROUND_BITS), 0
        ).astype(np.int32)
    return inputs, net, skew, dead_out
