"""Workload scenarios — the BASELINE.json benchmark configs as input
streams.

Each scenario builds a stacked ``RoundInput`` (leading axis = rounds)
plus a ``NetModel``, mirroring the reference's test drivers: single-writer
inserts (config 1/3), membership churn (config 2), conflict-heavy
multi-writer LWW (config 4), and the full mix with partitions (config 5)
— the same shapes as ``configurable_stress_test``
(``crates/corro-agent/src/agent/tests.rs:286-600``) and the Antithesis
workload scripts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr

from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.step import RoundInput
from corrosion_tpu.sim.transport import NetModel


def quiet(cfg: SimConfig, rounds: int) -> RoundInput:
    """Membership-only (BASELINE config 2 without churn)."""
    z = RoundInput.quiet(cfg)
    return jax.tree.map(lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), z)


def churn(cfg: SimConfig, rounds: int, key, rate: float = 0.01) -> RoundInput:
    """Random failure churn: each round a node dies or rejoins with
    prob ``rate`` (BASELINE config 2)."""
    n = cfg.n_nodes
    k1, k2 = jr.split(key)
    kill = jr.uniform(k1, (rounds, n)) < rate
    revive = jr.uniform(k2, (rounds, n)) < rate
    base = quiet(cfg, rounds)
    return base._replace(kill=kill, revive=revive & ~kill)


def single_writer(cfg: SimConfig, rounds: int, key, writes_per_round: int = 1):
    """One writer streams inserts (BASELINE config 3: fanout latency)."""
    n = cfg.n_nodes
    k1, k2 = jr.split(key)
    base = quiet(cfg, rounds)
    w = jnp.zeros((rounds, n), bool).at[:, 0].set(True)
    cell = jnp.zeros((rounds, n), jnp.int32).at[:, 0].set(
        jr.randint(k1, (rounds,), 0, cfg.n_cells)
    )
    val = jnp.zeros((rounds, n), jnp.int32).at[:, 0].set(
        jr.randint(k2, (rounds,), 0, 1 << 20)
    )
    return base._replace(write_mask=w, write_cell=cell, write_val=val)


def conflict_heavy(
    cfg: SimConfig, rounds: int, key, write_prob: float = 0.5, hot_cells: int = 2
):
    """All origins hammer a few hot cells concurrently — the LWW
    conflict workload (BASELINE config 4)."""
    n = cfg.n_nodes
    k1, k2, k3 = jr.split(key, 3)
    base = quiet(cfg, rounds)
    w = (jr.uniform(k1, (rounds, n)) < write_prob) & (
        jnp.arange(n)[None, :] < cfg.n_origins
    )
    cell = jr.randint(k2, (rounds, n), 0, max(1, hot_cells)).astype(jnp.int32)
    val = jr.randint(k3, (rounds, n), 0, 1 << 20).astype(jnp.int32)
    return base._replace(write_mask=w, write_cell=cell, write_val=val)


def full_mix(
    cfg: SimConfig,
    rounds: int,
    key,
    churn_rate: float = 0.005,
    write_prob: float = 0.3,
    partition_rounds: tuple = (),
):
    """Churn + multi-writer + (optional) partition/heal windows
    (BASELINE config 5). Returns (inputs, net_for_partition_phase)."""
    k1, k2 = jr.split(key)
    inp = conflict_heavy(cfg, rounds, k1, write_prob=write_prob, hot_cells=cfg.n_cells)
    ch = churn(cfg, rounds, k2, rate=churn_rate)
    return inp._replace(kill=ch.kill, revive=ch.revive)


def partitioned_net(cfg: SimConfig, groups: int = 2, drop_prob: float = 0.0) -> NetModel:
    return NetModel.create(cfg.n_nodes, drop_prob=drop_prob)._replace(
        partition=(jnp.arange(cfg.n_nodes) % groups).astype(jnp.int32),
    )
