"""CRDT changeset broadcast + apply as one fused round.

Reference pipeline (SURVEY §3.2/§3.3): a local write commits, its
changeset rows are chunked and pushed onto the broadcast queue
(``make_broadcastable_changes`` ->
``crates/corro-types/src/broadcast.rs:506-574``); ``handle_broadcasts``
flushes the queue to ring0 + a random sample of members, re-sending each
changeset up to ``max_transmissions`` times
(``crates/corro-agent/src/broadcast/mod.rs:410-812``); receivers dedupe
against the seen-cache/bookie, apply in batched transactions, and
*re-broadcast* fresh changes with a decremented budget
(``agent/handlers.rs:548-786``).

Array re-design: every node carries a fixed-width outgoing queue of
pending changesets (free slot = origin -1). One round =

1. writers commit new cells (``local_write``),
2. every node with queued changes picks ``bcast_fanout`` believed-alive
   targets and fires its sendable slots over the lossy uni channel,
3. the flat message soup is packed into per-receiver mailboxes
   (bounded; overflow = the reference's queue-cap drop, repaired by sync),
4. receivers dedupe via ``Book`` (fresh = unseen origin-version), apply
   fresh cells to the LWW store in one ``apply_changes_to_store``, and
   enqueue fresh changes for re-broadcast with budget-1.

Ordering is irrelevant to correctness (LWW join is commutative), which is
what lets a whole round apply as one scatter — the reference needs
newest-first wire order only as a latency optimization
(``test_broadcast_order``, ``broadcast/mod.rs:1104-1202``).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import jax.random as jr

from corrosion_tpu.ops.dense import apply_changes, lookup_cols
from corrosion_tpu.ops.partials import (
    Partials,
    complete_mask,
    free_slots,
    ingest_partials,
)
from corrosion_tpu.ops.slots import (
    alloc_slots_evict,
    budget_mask,
    mailbox_pack,
    scatter_rows,
)
from corrosion_tpu.ops.versions import (
    Book,
    bump_known_max,
    record_versions,
    seen_versions,
)
from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.transport import NetModel, uni_ok

NO_Q = np.int32(-1)  # np scalar: safe to close over in pallas kernels
LAST_SYNC_CAP = 4095  # staleness saturates (never-synced == very stale)

# --- hybrid logical clock, in sim units ---------------------------------
# The reference stamps every local write with its uhlc HLC
# (``crsql_set_ts``, ``public/mod.rs:88-100``), folds every received ts
# (``handlers.rs:689-701``) and sync clock message
# (``peer/mod.rs:1439-1458``), and drops stamps >300 ms ahead
# (``setup.rs:96-101``). Here physical time is the round counter: a stamp
# is ``round << HLC_ROUND_BITS | logical``; drift rejection compares the
# stamp's round part against the receiver's current round.
HLC_ROUND_BITS = 10
HLC_MAX_DRIFT_ROUNDS = 2  # the 300 ms analog, in rounds


def hlc_tick(hlc, now, active):
    """Issue per-node stamps: strictly monotonic, >= round<<bits
    (uhlc ``new_timestamp``). Returns (stamp [N], hlc')."""
    stamp = jnp.maximum(hlc + 1, now << HLC_ROUND_BITS)
    return stamp, jnp.where(active, stamp, hlc)


def hlc_fold(hlc, now, m_ts, live):
    """Fold received stamps into each node's clock, rejecting stamps too
    far ahead of local physical time. Returns (hlc', ok [N, M], rejects).
    Rejected stamps' changes are dropped, as the reference drops them
    (``handlers.rs:696-701``)."""
    phys = m_ts >> HLC_ROUND_BITS
    ok = live & (phys <= now + HLC_MAX_DRIFT_ROUNDS)
    folded = jnp.max(jnp.where(ok, m_ts, 0), axis=1)
    return jnp.maximum(hlc, folded), ok, jnp.sum(live & ~ok)

# wire-size estimate of one changeset cell: 9 int32 fields (incl. the
# seq/nseq chunking stamps) + length-delimited framing overhead — the
# bytes-per-changeset unit of the send budget (the reference meters
# serialized ChangeV1 bytes through its governor, broadcast/mod.rs:460-463)
CHANGE_WIRE_BYTES = 64


class CrdtState(NamedTuple):
    """LWW store + bookkeeping + broadcast queues for all N nodes."""

    # (ver, val, site, dbv, clp) planes — clp is the causal-length row
    # lifetime the cell was written under (cr-sqlite `cl`, doc/crdts.md)
    store: Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]
    book: Book
    next_dbv: jax.Array  # int32 [N] — origin's next db_version (1-based)
    q_origin: jax.Array  # int32 [N, Q] — -1 = free slot
    q_dbv: jax.Array  # int32 [N, Q]
    q_cell: jax.Array  # int32 [N, Q]
    q_ver: jax.Array  # int32 [N, Q]
    q_val: jax.Array  # int32 [N, Q]
    q_site: jax.Array  # int32 [N, Q]
    q_clp: jax.Array  # int32 [N, Q] — causal-length lifetime of the cell
    q_seq: jax.Array  # int32 [N, Q] — cell's seq within its version
    q_nseq: jax.Array  # int32 [N, Q] — total seqs in the version
    q_ts: jax.Array  # int32 [N, Q] — HLC stamp of the change's write
    q_tx: jax.Array  # int32 [N, Q] — remaining transmissions
    partials: Partials  # buffered incomplete multi-cell versions
    hlc: jax.Array  # int32 [N] — per-node hybrid logical clock (uhlc)
    now: jax.Array  # int32 [] — round counter (the HLC's physical time)
    last_sync: jax.Array  # int32 [N, S] — rounds since last sync per track
    # (S = peer node id for the full-view sim, member-table slot at scale;
    #  drives the "then by last-sync time" ordering of handlers.rs:808-863)
    sync_defer: jax.Array  # int32 [N] — consecutive rounds this node's
    # sync requests were ALL shed by overloaded servers; at
    # cfg.sync_defer_cap the next request is force-admitted (the shed's
    # anti-starvation bound)

    @staticmethod
    def create(cfg: SimConfig) -> "CrdtState":
        # budget-bearing boundary (corrobudget, docs/memory-budget.md):
        # every plane built here is priced symbolically by
        # analysis/shapes.py and gated at N=1M by the mem-budget rule —
        # the store and queue planes below are the two largest O(N·M)
        # line items of the flagship budget
        n, q, c = cfg.n_nodes, cfg.bcast_queue, cfg.n_cells
        z = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
        # narrowed planes (PERF.md cut #4): small-range bookkeeping lives
        # as int16 in HBM when the config asks; compute widens freely and
        # the scale round-step re-narrows on carry-out
        ndt = (jnp.int16 if getattr(cfg, "narrow_dtypes", False)
               else jnp.int32)
        # the q counter planes' own tier (ISSUE 19): int8 under
        # narrow_q_int8, else the narrow int16 default
        qdt = (jnp.int8 if getattr(cfg, "narrow_q_int8", False) else ndt)
        return CrdtState(
            store=(z(n, c), z(n, c), z(n, c), z(n, c), z(n, c)),
            book=Book.create(n, cfg.n_origins, cfg.buf_slots),
            next_dbv=jnp.ones(n, jnp.int32),
            q_origin=jnp.full((n, q), NO_Q, jnp.int32),
            q_dbv=z(n, q),
            q_cell=jnp.zeros((n, q), ndt),
            q_ver=z(n, q),
            q_val=z(n, q),
            q_site=z(n, q),
            q_clp=z(n, q),
            q_seq=jnp.zeros((n, q), qdt),
            q_nseq=jnp.ones((n, q), qdt),
            q_ts=z(n, q),
            q_tx=jnp.zeros((n, q), qdt),
            hlc=z(n),
            now=jnp.int32(0),
            partials=Partials.create(
                n, cfg.partial_slots if cfg.tx_max_cells > 1 else 1,
                max(1, cfg.tx_max_cells),
            ),
            last_sync=jnp.full((n, cfg.sync_tracks), LAST_SYNC_CAP, ndt),
            sync_defer=z(n),
        )


def _enqueue(cst: CrdtState, want, origin, dbv, cell, ver, val, site, clp,
             seq, nseq, ts, tx):
    """Place per-node batches of changes into queue slots; on overflow the
    most-sent queued changeset is evicted to admit the new one
    (drop-oldest-most-sent, ``broadcast/mod.rs:410-812``)."""
    free = cst.q_origin == NO_Q
    slot, placed = alloc_slots_evict(free, cst.q_tx, want)
    return cst._replace(
        q_origin=scatter_rows(cst.q_origin, slot, placed, origin),
        q_dbv=scatter_rows(cst.q_dbv, slot, placed, dbv),
        q_cell=scatter_rows(cst.q_cell, slot, placed, cell),
        q_ver=scatter_rows(cst.q_ver, slot, placed, ver),
        q_val=scatter_rows(cst.q_val, slot, placed, val),
        q_site=scatter_rows(cst.q_site, slot, placed, site),
        q_clp=scatter_rows(cst.q_clp, slot, placed, clp),
        q_seq=scatter_rows(cst.q_seq, slot, placed, seq),
        q_nseq=scatter_rows(cst.q_nseq, slot, placed, nseq),
        q_ts=scatter_rows(cst.q_ts, slot, placed, ts),
        q_tx=scatter_rows(cst.q_tx, slot, placed, tx),
    )


def local_write(cfg: SimConfig, cst: CrdtState, write_mask, cell, val, clp=None):
    """Commit one-cell write transactions at the writer nodes.

    ``write_mask`` bool [N] (only indices < n_origins may be set),
    ``cell``/``val`` int32 [N]; ``clp`` int32 [N] is the causal-length
    row lifetime the write belongs to (the DB layer stamps it from the
    row's ``cl``; raw sim workloads default to 0 — one immortal
    lifetime, the pre-delete semantics). Mirrors ``POST /v1/transactions``
    (SURVEY §3.2): assign db_version, bump the cell's col_version from
    the *current* clock (cr-sqlite increments the clock row it sees,
    merged or local), apply locally, queue the changeset for broadcast.
    """
    n = cfg.n_nodes
    if cfg.tx_max_cells <= 1:
        from corrosion_tpu.ops import megakernel

        if megakernel.use_fused_ingest(cfg, msgs=1):
            return megakernel.local_write_fused(
                cfg, cst, write_mask, cell, val, clp
            )
    iarr = jnp.arange(n, dtype=jnp.int32)
    # any_writer (round 4): every node commits; bookkeeping rides the
    # hash-slotted origin table. Legacy: fixed pool of n_origins writers
    if getattr(cfg, "any_writer", False):
        w = write_mask
    else:
        w = write_mask & (iarr < cfg.n_origins)
    if clp is None:
        clp = jnp.zeros(n, jnp.int32)

    dbv = cst.next_dbv
    cur_ver = lookup_cols(cst.store[0], cell[:, None])[:, 0]
    ver = cur_ver + 1
    site = iarr
    # stamp the write with the node's HLC (crsql_set_ts analog)
    ts, hlc = hlc_tick(cst.hlc, cst.now, w)
    cst = cst._replace(hlc=hlc)

    # apply to own store
    store = apply_changes(
        cst.store, cell[:, None], ver[:, None], val[:, None], site[:, None],
        dbv[:, None], clp[:, None], w[:, None],
    )

    # record own version in own bookkeeping (a writer has trivially seen
    # its own db_versions; its head over itself == next_dbv - 1)
    book, _, _ = record_versions(
        cst.book, site[:, None], dbv[:, None], w[:, None],
        now=cst.now, keep_rounds=getattr(cfg, "org_keep_rounds", 16),
    )

    cst = cst._replace(
        store=store, book=book, next_dbv=jnp.where(w, dbv + 1, cst.next_dbv)
    )
    return _enqueue(
        cst,
        w[:, None],
        site[:, None],
        dbv[:, None],
        cell[:, None],
        ver[:, None],
        val[:, None],
        site[:, None],
        clp[:, None],
        jnp.zeros((n, 1), jnp.int32),
        jnp.ones((n, 1), jnp.int32),
        ts[:, None],
        jnp.full((n, 1), cfg.bcast_max_transmissions, jnp.int32),
    )


def local_write_tx(cfg: SimConfig, cst: CrdtState, tx_mask, tx_cell, tx_val,
                   tx_clp, tx_len):
    """Commit multi-cell write transactions at the writer nodes.

    ``tx_mask`` bool [N]; ``tx_cell``/``tx_val``/``tx_clp`` int32 [N, K]
    (K = ``cfg.tx_max_cells``); ``tx_len`` int32 [N] — how many lanes are
    real (1..K). A transaction's cells must be distinct. All cells share
    one ``db_version`` and are stamped ``seq`` 0..len-1 — the array
    ``ChunkedChanges`` (``crates/corro-types/src/change.rs:66-178``): the
    writer applies them atomically to its own store and queues each cell
    as a chunk; remote nodes buffer the chunks and apply only once the
    whole seq range is present (multi-statement ``POST
    /v1/transactions`` atomicity, ``public/mod.rs:177-256``).
    """
    n, k = cfg.n_nodes, tx_cell.shape[1]
    if k > max(1, cfg.tx_max_cells):
        raise ValueError(
            f"tx_cell has {k} lanes > tx_max_cells "
            f"{max(1, cfg.tx_max_cells)}"
        )
    iarr = jnp.arange(n, dtype=jnp.int32)
    if getattr(cfg, "any_writer", False):
        w = tx_mask
    else:
        w = tx_mask & (iarr < cfg.n_origins)
    lane = jnp.arange(k, dtype=jnp.int32)[None, :]
    lane_ok = w[:, None] & (lane < tx_len[:, None])  # [N, K]

    dbv = cst.next_dbv
    cur_ver = lookup_cols(cst.store[0], tx_cell)
    ver = cur_ver + 1
    site = jnp.broadcast_to(iarr[:, None], (n, k))
    # one HLC stamp per transaction (the whole tx commits at one ts)
    ts, hlc = hlc_tick(cst.hlc, cst.now, w)
    cst = cst._replace(hlc=hlc)

    store = apply_changes(
        cst.store, tx_cell, ver, tx_val, site,
        jnp.broadcast_to(dbv[:, None], (n, k)), tx_clp, lane_ok,
    )

    book, _, _ = record_versions(
        cst.book, iarr[:, None], dbv[:, None], w[:, None],
        now=cst.now, keep_rounds=getattr(cfg, "org_keep_rounds", 16),
    )
    cst = cst._replace(
        store=store, book=book, next_dbv=jnp.where(w, dbv + 1, cst.next_dbv)
    )
    return _enqueue(
        cst,
        lane_ok,
        site,
        jnp.broadcast_to(dbv[:, None], (n, k)),
        tx_cell,
        ver,
        tx_val,
        site,
        tx_clp,
        jnp.broadcast_to(lane, (n, k)),
        jnp.broadcast_to(tx_len[:, None], (n, k)),
        jnp.broadcast_to(ts[:, None], (n, k)),
        jnp.full((n, k), cfg.bcast_max_transmissions, jnp.int32),
    )


def ingest_changes(cfg, cst: CrdtState, live, m_origin, m_dbv, m_cell, m_ver,
                   m_val, m_site, m_clp, m_seq=None, m_nseq=None, m_ts=None,
                   m_tx=None):
    """Receiver ingest shared by every dissemination carrier: dedupe via
    the Book, apply fresh cells to the LWW store, re-enqueue fresh changes
    for re-broadcast with a decremented budget (``handlers.rs:548-786``,
    rebroadcast ``handlers.rs:768-779``).

    Single-cell versions (``nseq == 1`` — the complete-changeset fast
    path, ``process_complete_version``, ``util.rs:1197``) apply on
    arrival. Cells of chunked versions (``nseq > 1``) park in the partial
    buffer and apply atomically once the whole seq range is present
    (``process_incomplete_version`` -> ``process_fully_buffered_changes``,
    ``util.rs:1061-1194,546-696``) — remote readers never observe a torn
    transaction.

    Message fields are [N, M] per-receiver batches; ``live`` masks real
    messages. Returns ``(cst, info)``.
    """
    n = cfg.n_nodes
    iarr = jnp.arange(n, dtype=jnp.int32)
    if m_seq is None:
        m_seq = jnp.zeros_like(m_origin)
    if m_nseq is None:
        m_nseq = jnp.ones_like(m_origin)
    if m_ts is None:
        m_ts = jnp.zeros_like(m_origin)

    if cfg.tx_max_cells <= 1:
        from corrosion_tpu.ops import megakernel

        if megakernel.use_fused_ingest(cfg, msgs=m_origin.shape[1]):
            # single-cell configs take the whole phase as one pallas
            # kernel per node block (ops/megakernel.py) — identical
            # semantics, differentially tested
            return megakernel.ingest_changes_fused(
                cfg, cst, live, m_origin, m_dbv, m_cell, m_ver, m_val,
                m_site, m_clp, m_ts,
            )
    rebudget = jnp.full(
        m_origin.shape, max(1, cfg.bcast_max_transmissions - 1), jnp.int32
    )
    wire_budget = (
        m_tx is not None and getattr(cfg, "bcast_wire_budget", False)
    )
    if wire_budget:
        # budget-following re-broadcast (round 5): an unowned fresh
        # message re-enqueues at the INCOMING budget minus one —
        # circulation terminates by budget depth, not seen-dedupe, so
        # actors displaced from their hash slot by the monotone claim
        # rule still spread epidemically. Owned/recorded messages keep
        # the classic fresh budget (dedupe bounds them).
        wire_next = jnp.clip(
            m_tx.astype(jnp.int32) - 1, 0,
            max(1, cfg.bcast_max_transmissions - 1),
        )

    # fold received HLC stamps into each node's clock; stamps too far
    # ahead of local time get their changes dropped (handlers.rs:689-701)
    hlc, ts_ok, drift_rejects = hlc_fold(cst.hlc, cst.now, m_ts, live)
    cst = cst._replace(hlc=hlc)
    live = ts_ok  # live & within max drift; rejected changes drop

    # --- complete (single-cell) versions: record + apply on arrival -----
    single = live & (m_nseq <= 1)
    book, fresh1, rec1 = record_versions(
        cst.book, m_origin, m_dbv, single,
        now=cst.now, keep_rounds=getattr(cfg, "org_keep_rounds", 16),
    )

    store = apply_changes(
        cst.store, m_cell, m_ver, m_val, m_site, m_dbv, m_clp, fresh1
    )
    cst = cst._replace(store=store, book=book)

    fresh = fresh1
    enq = rec1
    wire_extra = None
    if wire_budget:
        from corrosion_tpu.ops.versions import org_slot

        _, owned1 = org_slot(book, m_origin)
        wire_extra = fresh1 & ~owned1 & (wire_next > 0)
        enq = rec1 | wire_extra
        # ONLY the unowned-fresh messages ride the wire budget; owned/
        # recorded ones (incl. chunked fragments below) keep the classic
        # fresh budget — seen-dedupe bounds those
        rebudget = jnp.where(wire_extra, wire_next, rebudget)
    completed = jnp.int32(0)
    if cfg.tx_max_cells > 1:
        # --- chunked versions: buffer, complete, then apply atomically --
        multi = live & (m_nseq > 1)
        seen = seen_versions(cst.book, m_origin, m_dbv, multi)
        book = bump_known_max(cst.book, m_origin, m_dbv, multi)
        par, fresh_m = ingest_partials(
            cst.partials, multi & ~seen, m_origin, m_dbv, m_seq, m_nseq,
            m_cell, m_ver, m_val, m_site, m_clp,
        )
        full = complete_mask(par)  # [N, P]
        p, k = par.cell.shape[1], par.cell.shape[2]
        lane = jnp.arange(k, dtype=jnp.int32)[None, None, :]
        lane_ok = full[:, :, None] & (lane < par.nseq[:, :, None])
        pk = p * k
        store = apply_changes(
            cst.store,
            par.cell.reshape(n, pk),
            par.ver.reshape(n, pk),
            par.val.reshape(n, pk),
            par.site.reshape(n, pk),
            jnp.broadcast_to(par.dbv[:, :, None], (n, p, k)).reshape(n, pk),
            par.clp.reshape(n, pk),
            lane_ok.reshape(n, pk),
        )
        book, _, _ = record_versions(
            book, par.origin, par.dbv, full,
            now=cst.now, keep_rounds=getattr(cfg, "org_keep_rounds", 16),
        )
        par = free_slots(par, full)
        cst = cst._replace(store=store, book=book, partials=par)
        fresh = fresh1 | fresh_m
        # fragments of chunked versions re-broadcast only from nodes
        # whose slot tracks the fragment's actor — an unowned fragment
        # re-buffers and re-reports fresh on every arrival (the freed
        # partial slot forgets it), so re-enqueueing it would circulate
        # forever, the same loop the single-cell path gates via ``rec``
        from corrosion_tpu.ops.versions import org_slot

        _, owned_m = org_slot(book, m_origin)
        enq = rec1 | (fresh_m & owned_m)
        if wire_extra is not None:
            # keep the wire-budget re-broadcast for displaced actors'
            # single-cell messages in chunked configs too
            enq = enq | wire_extra
        completed = jnp.sum(full)

    # re-broadcast only RECORDED changes (+ buffered fresh chunks):
    # unrecorded fresh messages re-report fresh on every arrival, so
    # re-enqueueing them with a fresh budget would circulate forever
    # between nodes with mismatched slot ownership (see
    # versions.record_versions)
    cst = _enqueue(
        cst,
        enq,
        m_origin,
        m_dbv,
        m_cell,
        m_ver,
        m_val,
        m_site,
        m_clp,
        m_seq,
        m_nseq,
        m_ts,
        rebudget,
    )
    info = {
        "delivered": jnp.sum(live),
        "fresh": jnp.sum(fresh),
        "tx_completed": completed,
        "clock_drift_rejects": drift_rejects,
        "queued": jnp.sum(cst.q_origin != NO_Q),
    }
    return cst, info


def bcast_step(
    cfg: SimConfig,
    cst: CrdtState,
    targets,  # int32 [N, F] fanout target ids (chosen from the SWIM view)
    t_ok,  # bool [N, F] target validity
    alive,  # bool [N] ground truth
    net: NetModel,
    key: jax.Array,
):
    """One broadcast flush + ingest round. Returns (state, info).

    Target choice is the caller's (full-view sim samples the [N, N]
    believed-alive matrix; the scale sim samples its bounded member
    table) — mirroring how ``handle_broadcasts`` asks the ``Members``
    registry for its fanout set (``broadcast/mod.rs:653-713``).
    """
    n, q, f = cfg.n_nodes, cfg.bcast_queue, cfg.bcast_fanout
    iarr = jnp.arange(n, dtype=jnp.int32)
    k_drop = key
    if targets.shape != (n, f):
        raise ValueError(
            f"targets shape {targets.shape} != ({n}, {f}) "
            f"(n_nodes, bcast_fanout)"
        )

    # --- sendable slots: anything queued with budget left ---------------
    live_slot = (cst.q_origin != NO_Q) & (cst.q_tx > 0)  # [N, Q]

    # per-round send budget (10 MiB/s governor analog): each slot flush
    # costs CHANGE_WIRE_BYTES * fanout; when over budget, the least-sent
    # changesets go first and the rest wait (rate shaping, not drop)
    allowed = max(1, cfg.bcast_budget_bytes // (CHANGE_WIRE_BYTES * max(1, f)))
    live_slot = budget_mask(live_slot, cst.q_tx, allowed)

    # messages: sender x slot x target
    src = jnp.broadcast_to(iarr[:, None, None], (n, q, f))
    dst = jnp.broadcast_to(targets[:, None, :], (n, q, f))
    m_ok = (
        live_slot[:, :, None]
        & t_ok[:, None, :]
        & uni_ok(net, k_drop, alive, src, dst)
    )

    flat = lambda a: jnp.broadcast_to(a[:, :, None], (n, q, f)).reshape(-1)  # noqa: E731
    live, (m_origin, m_dbv, m_cell, m_ver, m_val, m_site, m_clp, m_seq,
           m_nseq, m_ts) = mailbox_pack(
        dst.reshape(-1),
        m_ok.reshape(-1),
        n_rows=n,
        capacity=cfg.recv_slots,
        fields=(
            flat(cst.q_origin),
            flat(cst.q_dbv),
            flat(cst.q_cell),
            flat(cst.q_ver),
            flat(cst.q_val),
            flat(cst.q_site),
            flat(cst.q_clp),
            flat(cst.q_seq),
            flat(cst.q_nseq),
            flat(cst.q_ts),
        ),
    )

    # --- sender-side budget decrement, free exhausted slots -------------
    # one "transmission" = one flush to the fanout set; decrement on the
    # attempt (the sender cannot observe datagram loss)
    # plane-dtype arithmetic (same idiom as piggyback_bcast_step): the
    # decrement must not widen q_tx — under narrow_dtypes the plane is
    # int16 and an int32 result would double its HBM traffic and change
    # the carry aval
    attempted = (live_slot & jnp.any(t_ok, axis=1)[:, None]).astype(
        cst.q_tx.dtype)
    q_tx = jnp.where(live_slot, cst.q_tx - attempted, cst.q_tx)
    exhausted = (cst.q_origin != NO_Q) & (q_tx <= 0)
    cst = cst._replace(
        q_tx=jnp.maximum(q_tx, 0),
        q_origin=jnp.where(exhausted, NO_Q, cst.q_origin),
    )

    # --- receiver ingest: dedupe, apply, re-broadcast -------------------
    cst, info = ingest_changes(
        cfg, cst, live, m_origin, m_dbv, m_cell, m_ver, m_val, m_site, m_clp,
        m_seq, m_nseq, m_ts,
    )
    return cst, {**info, "sent": jnp.sum(m_ok)}
