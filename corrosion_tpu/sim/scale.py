"""Large-cluster SWIM with bounded O(N*M) member tables — the 100k-node path.

The full-view simulator (``sim/swim.py``) keeps every node's belief about
every other node: an [N, N] plane. That is the faithful small-N model, but
at the north-star scale (100k nodes, BASELINE.md) an [N, N] int32 plane is
40 GB — far beyond HBM, and one round would touch all of it, capping
throughput near 100 rounds/s. The reference has the same wall in spirit:
foca bounds its *updates backlog* and packet size (<=1178 B,
``crates/corro-agent/src/broadcast/mod.rs:951-960``) so per-node work stays
bounded no matter the cluster size; a member list is cheap on a CPU heap
but a dense plane is not cheap on a TPU.

Scale re-design (SURVEY §7 step 1: "membership table [N, M_slots]"): each
node tracks at most M members in a **globally hash-slotted table** — the
entry for subject ``s`` may only ever live in slot ``h(s) = s mod M``.
The payoff is that slot indices agree across all nodes, so a gossip packet
is simply the sender's *aligned row*: receiving a packet is a gather of
the sender's row plus one elementwise insert-or-merge — no scatters over
the big planes, no sorts; the whole round is dense [N, M] arithmetic plus
O(N) bookkeeping. The cost is that each node tracks at most one subject
per hash class (a random-eviction partial view, in the HyParView spirit);
membership knowledge becomes probabilistic but SWIM's detection and
refutation semantics are preserved exactly per-entry.

Channels per round (each per-receiver unique, so merges stay dense):

1. probe     prober -> target   (one prober chosen per target per round;
                                 surplus probers' packets drop — the
                                 datagram channel is lossy anyway)
2. ack       target -> prober
3. announce  announcer -> ever-known member (heal/rejoin path, like the
             reference's DB-known announces, ``agent/handlers.rs:193-244``)
4. announce-reply (carries the down-notice that triggers refutation)

Piggyback = the sender's row masked by per-entry remaining-transmission
budgets (``mem_tx``), the array analog of foca's bounded updates backlog;
fresh news refills the budget, so rumors spread epidemically then quiesce.

Suspicion timers, Down conversion, incarnation refutation and the
down-purge (48 h analog, ``broadcast/mod.rs:953``) all run as elementwise
updates on the [N, M] planes.
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import jax.random as jr

from corrosion_tpu.ops.lww import (
    STATE_ALIVE,
    STATE_DOWN,
    STATE_SUSPECT,
    pack_inc_state,
)
from corrosion_tpu.ops.dense import (
    lookup_cols,
    scatter_cols_add,
    scatter_cols_max,
    scatter_cols_set,
    select_cols,
)
from corrosion_tpu.ops.select import sample_k, sample_k_biased, sample_one
from corrosion_tpu.sim.transport import (
    CARD_EXTRA,
    NetModel,
    card_at,
    datagram_ok_c,
    link_card,
)

FREE = -1  # plain int: referenced inside the pallas swim kernel, where a
# module-level device array would be a captured constant


@dataclasses.dataclass(frozen=True)
class ScaleConfig:
    """Static shapes/constants for the bounded-table simulator."""

    n_nodes: int
    m_slots: int = 64  # member-table slots per node (hash classes)
    n_seeds: int = 4  # bootstrap: everyone initially knows nodes 0..n_seeds-1
    n_indirect: int = 3  # foca num_indirect_probes
    suspicion_rounds: int = 6
    max_transmissions: int = 10
    announce_interval: int = 16
    down_purge_rounds: int = 64  # rounds a Down entry lingers (48 h analog)
    # bounded piggyback: member-update entries per SWIM packet (foca's
    # <=1178 B packet bound, broadcast/mod.rs:951-960). 0 = carry the
    # full aligned member row (cheap merge, 3x[N,M] channel gathers);
    # k > 0 = carry the k freshest sendable entries ([N,2k] gathers —
    # ~4x less channel HBM traffic at M=64, k=16; the merge becomes
    # per-entry hash-class scatters, VMEM-cheap under the pallas kernel)
    pig_members: int = 0
    # dtype narrowing (PERF.md cut #4): store small-range planes
    # (mem_timer, mem_tx — and the CRDT queue/staleness planes at the
    # scale-sim level) as int16 in HBM; compute widens them and the
    # round-step narrows once on carry-out, halving those planes' HBM
    # read+write traffic
    narrow_dtypes: bool = False
    # int8 tier (ISSUE 12, the corrobudget-identified shrink): the
    # piggyback budget plane ``mem_tx`` is the one [N, M] table whose
    # value range the analyzer can PROVE < 2^7 under flagship defaults
    # (max_transmissions = log2(N)+4 ≈ 24 at 1M; mem_timer is refused —
    # down_purge_rounds = 8·log2(N) = 160 overflows int8). Requires
    # narrow_dtypes (it is a deeper tier of the same knob); halves
    # mem_tx's HBM footprint again (docs/memory-budget.md). Default OFF
    # until a real-TPU width probe validates the int8 lowering — the
    # same staging int16 went through in rounds 3→4.
    narrow_int8: bool = False
    # fused megakernel path: auto/on/off/interpret (see docs/fused.md
    # and ScaleSimConfig.fused — execution knob, never changes results)
    fused: str = "auto"

    def validate(self) -> "ScaleConfig":
        # real errors, not bare asserts (stripped under ``python -O``)
        if self.m_slots <= 0 or self.n_seeds < 1:
            raise ValueError(
                f"need m_slots > 0 and n_seeds >= 1, got "
                f"{self.m_slots}/{self.n_seeds}"
            )
        # sender-election packs an adaptive-width random priority above
        # the node id in one int32 (_one_sender_per_receiver /
        # _election_pri_bits — 12 bits through 2^19 ids, 11 at the 1M
        # flagship point); past 2^30 ids no priority bit is left
        if self.n_nodes > 1 << 30:
            raise ValueError(
                f"n_nodes {self.n_nodes} > 2^30: sender-election packs "
                f"priority + node id in one int32 word"
            )
        if not 0 <= self.pig_members <= self.m_slots:
            raise ValueError(
                f"pig_members {self.pig_members} must be 0..m_slots "
                f"({self.m_slots}) (top_k over the slot axis)"
            )
        if self.narrow_dtypes and max(
                self.max_transmissions, self.suspicion_rounds,
                self.down_purge_rounds) >= (1 << 15):
            raise ValueError(
                "narrow_dtypes stores timers/budgets as int16; a "
                "timer/budget bound exceeds int16 range"
            )
        if self.narrow_int8 and not self.narrow_dtypes:
            raise ValueError(
                "narrow_int8 is a tier of narrow_dtypes; enable both"
            )
        if self.narrow_int8 and self.max_transmissions >= (1 << 7):
            raise ValueError(
                "narrow_int8 stores mem_tx as int8; max_transmissions "
                f"{self.max_transmissions} exceeds int8 range"
            )
        from corrosion_tpu.sim.config import FUSED_MODES

        if self.fused not in FUSED_MODES:
            raise ValueError(
                f"fused {self.fused!r} not one of {FUSED_MODES} "
                f"(docs/fused.md)"
            )
        return self

    @property
    def timer_dtype(self):
        return jnp.int16 if self.narrow_dtypes else jnp.int32

    @property
    def tx_dtype(self):
        """HBM dtype of the ``mem_tx`` budget plane (the ISSUE-12 int8
        shrink; mirrored by ``analysis/shapes.py::ConfigVal.tx_dtype``
        so the static inventory prices the same plane set)."""
        return jnp.int8 if self.narrow_int8 else self.timer_dtype


def scale_config(n_nodes: int, **overrides) -> ScaleConfig:
    """Cluster-size-adaptive defaults (``make_foca_config`` shape,
    ``broadcast/mod.rs:951-960``): dissemination budget grows with log N."""
    log_n = max(1, math.ceil(math.log2(max(2, n_nodes))))
    defaults = dict(
        m_slots=min(64, max(8, n_nodes // 2)),
        max_transmissions=log_n + 4,
        suspicion_rounds=max(4, log_n),
        down_purge_rounds=8 * max(4, log_n),
    )
    defaults.update(overrides)
    return ScaleConfig(n_nodes=n_nodes, **defaults).validate()


class ScaleSwimState(NamedTuple):
    alive: jax.Array  # bool  [N] — ground-truth process liveness
    inc: jax.Array  # int32 [N] — own incarnation
    mem_id: jax.Array  # int32 [N, M] — subject id per slot, -1 free
    mem_view: jax.Array  # int32 [N, M] — packed (inc, state), -1 on free
    mem_timer: jax.Array  # int32 [N, M] — suspicion / down-purge countdown
    mem_tx: jax.Array  # int32 [N, M] — piggyback budget per entry

    @staticmethod
    def create(cfg: ScaleConfig) -> "ScaleSwimState":
        n, m = cfg.n_nodes, cfg.m_slots
        iarr = jnp.arange(n, dtype=jnp.int32)
        mem_id = jnp.full((n, m), FREE, jnp.int32)
        mem_view = jnp.full((n, m), FREE, jnp.int32)
        alive_key = pack_inc_state(jnp.int32(0), jnp.int32(STATE_ALIVE))
        for s in range(min(cfg.n_seeds, n)):
            mem_id = mem_id.at[:, s % m].set(s)
            mem_view = mem_view.at[:, s % m].set(alive_key)
        # self entry (always wins its hash class)
        mem_id = mem_id.at[iarr, iarr % m].set(iarr)
        mem_view = mem_view.at[iarr, iarr % m].set(alive_key)
        # budget-bearing boundary (corrobudget, docs/memory-budget.md):
        # every plane built here is priced by the static inventory
        # (analysis/shapes.py) and gated at N=1M by the mem-budget
        # rule — a new [N, M] table or a widened dtype fails lint
        # until HBM_BUDGET is re-priced with it
        return ScaleSwimState(
            alive=jnp.ones(n, bool),
            inc=jnp.zeros(n, jnp.int32),
            mem_id=mem_id,
            mem_view=mem_view,
            mem_timer=jnp.zeros((n, m), cfg.timer_dtype),
            mem_tx=jnp.full((n, m), cfg.max_transmissions,
                            cfg.tx_dtype),
        )


def bootstrap_members(st: ScaleSwimState, member_ids,
                      incarnations=None) -> "ScaleSwimState":
    """Seed every node's bounded member table with a persisted member
    list (the ``__corro_members`` replay at boot, ``util.rs:69-130``).
    Entries land in their hash class; collisions keep the later id (the
    table's random-eviction partial-view semantics)."""
    import numpy as np

    n, m = st.mem_id.shape
    ids = np.asarray(member_ids, np.int32)
    incs = (np.asarray(incarnations, np.int32) if incarnations is not None
            else np.zeros(ids.shape, np.int32))
    in_range = (ids >= 0) & (ids < n)
    ids, incs = ids[in_range], incs[in_range]
    # dedupe hash-colliding slots host-side (last id wins) so the two
    # scatters below never see duplicate indices — XLA leaves duplicate-
    # index .set order-undefined, which could tear (id, view) pairs
    by_slot = {int(i) % m: (int(i), int(inc)) for i, inc in zip(ids, incs)}
    if not by_slot:
        return st
    slots_np = np.fromiter(by_slot.keys(), np.int32)
    ids = np.asarray([v[0] for v in by_slot.values()], np.int32)
    incs = np.asarray([v[1] for v in by_slot.values()], np.int32)
    mem_id, mem_view = st.mem_id, st.mem_view
    keys = pack_inc_state(jnp.asarray(incs), jnp.int32(STATE_ALIVE))
    slots = jnp.asarray(slots_np)
    mem_id = mem_id.at[:, slots].set(jnp.asarray(ids)[None, :])
    mem_view = mem_view.at[:, slots].set(keys[None, :])
    # self entry always wins its hash class back
    iarr = jnp.arange(n, dtype=jnp.int32)
    self_key = pack_inc_state(st.inc, jnp.int32(STATE_ALIVE))
    mem_id = mem_id.at[iarr, iarr % m].set(iarr)
    mem_view = mem_view.at[iarr, iarr % m].set(self_key)
    return st._replace(mem_id=mem_id, mem_view=mem_view)


def _election_pri_bits(n: int) -> int:
    """Random-priority width of the sender election: 12 bits while the
    id width leaves room (every n <= 2^19 — bit-for-bit identical to
    the historical fixed-12-bit packing), narrowing as the id grows so
    priority + id always fit one non-negative int32. The flagship 1M
    point (20 id bits) gets 11 priority bits; the packing runs out of
    room past 2^30 ids (the validate() wall)."""
    bits = max(1, n - 1).bit_length()
    pri_bits = min(12, 31 - bits)
    if pri_bits < 1:
        raise ValueError(
            f"sender election has no priority bit left above {bits} id "
            f"bits (n_nodes {n} > 2^30)"
        )
    return pri_bits


def _one_sender_per_receiver(n, src_valid, tgt, key):
    """Pick one sender per receiver from competing (sender -> tgt) edges.

    Packs a random priority above the sender id so a single O(N) scatter-max
    resolves contention; surplus senders' packets drop (the datagram
    channel is lossy anyway). Returns (sender_of [N], has_sender [N])."""
    bits = max(1, n - 1).bit_length()
    pri = jr.randint(key, (n,), 0, 1 << _election_pri_bits(n),
                     dtype=jnp.int32)
    packed = jnp.where(
        src_valid, (pri << bits) | jnp.arange(n, dtype=jnp.int32), -1
    )
    best = jnp.full(n, -1, jnp.int32).at[tgt].max(packed, mode="drop")
    return best & ((1 << bits) - 1), best >= 0


def swim_tables_update(
    consts,
    mem_id, mem_view, old_id, old_view, mem_timer, mem_tx,
    alive, inc, node_id, self_slot, sus_heard, sends,
    probe_slot, suspect_key, probe_failed,
    ch_in_id, ch_in_view, ch_in_sendable, ch_valid, ch_snd, ch_snd_inc,
):
    """The row-local back half of a SWIM round: suspect-mark, the four
    packet merges + sender-alive assertions, send-budget decrement,
    suspicion/down timers, purge, refutation, self refresh, budget
    refill. Shared verbatim by the XLA path and the pallas swim kernel
    (``ops/megakernel.swim_tables_fused``) so the two can never drift.

    ``ch_*`` carry the four delivered-packet channels with their sender
    rows already gathered (cross-node row gathers stay outside):
    ``ch_in_id``/``ch_in_view``/``ch_in_sendable`` are length-4 lists of
    [N, M] planes; ``ch_valid``/``ch_snd``/``ch_snd_inc`` length-4 lists
    of [N] vectors; ``node_id`` is each row's global node id. Returns ``(mem_id, mem_view, timer, mem_tx, inc,
    refute)``.

    ``consts`` may carry a 5th element ``pig_k``: when > 0 the channels
    are BOUNDED packets — ``ch_in_id``/``ch_in_view`` are [N, pig_k]
    *packed entry lists* (foca's <=1178 B packet bound,
    ``broadcast/mod.rs:951-960``) instead of aligned member rows; each
    entry routes to its hash class ``id % m`` via dense column scatters.
    The caller then owns the mem_tx transmit decrement (only selected
    entries were sent); the refill-on-change stays here.
    """
    (m, suspicion_rounds, down_purge_rounds, max_transmissions) = consts[:4]
    pig_k = consts[4] if len(consts) > 4 else 0
    # node_id carries each row's GLOBAL id: inside the pallas kernel a
    # block sees only its slice, so an arange here would be block-local
    # and corrupt every self-entry write beyond the first block
    iarr = node_id

    # --- failed probe: suspect the probed entry --------------------------
    mem_view = scatter_cols_max(
        mem_view, probe_slot[:, None], suspect_key[:, None],
        probe_failed[:, None],
    )

    # --- four packet merges + sender-alive assertions --------------------
    sendable = mem_tx > 0
    if pig_k > 0:
        # bounded packets: k (id, view) entries per packet, each applied
        # at its hash class; sequential application keeps same-class
        # collisions within one packet well-defined
        for in_id, in_view, _in_send, valid in zip(
            ch_in_id, ch_in_view, ch_in_sendable, ch_valid
        ):
            for j in range(pig_k):
                idj = in_id[:, j]
                vwj = in_view[:, j]
                okj = valid & (idj >= 0)
                slotj = (idj % m)[:, None]
                curid = lookup_cols(mem_id, slotj)[:, 0]
                curvw = lookup_cols(mem_view, slotj, fill=-1)[:, 0]
                same = okj & (curid == idj)
                ins = okj & (curid < 0)
                take = (
                    okj
                    & (curid >= 0)
                    & (curid != idj)
                    & ((curvw & 3) == STATE_DOWN)
                    & ((vwj & 3) == STATE_ALIVE)
                )
                new_vw = jnp.where(same, jnp.maximum(curvw, vwj), vwj)
                wmask = (same | ins | take)[:, None]
                mem_view = scatter_cols_set(
                    mem_view, slotj, new_vw[:, None], wmask
                )
                mem_id = scatter_cols_set(
                    mem_id, slotj, idj[:, None], (ins | take)[:, None]
                )
    else:
        for in_id, in_view, in_sendable, valid in zip(
            ch_in_id, ch_in_view, ch_in_sendable, ch_valid
        ):
            ok = valid[:, None] & (in_id >= 0) & in_sendable
            same = ok & (mem_id == in_id)
            ins = ok & (mem_id < 0)
            take = (
                ok
                & (mem_id >= 0)
                & (mem_id != in_id)
                & ((mem_view & 3) == STATE_DOWN)
                & ((in_view & 3) == STATE_ALIVE)
            )
            mem_view = jnp.where(
                same, jnp.maximum(mem_view, in_view), mem_view
            )
            mem_view = jnp.where(ins | take, in_view, mem_view)
            mem_id = jnp.where(ins | take, in_id, mem_id)

    for snd, valid, s_inc in zip(ch_snd, ch_valid, ch_snd_inc):
        s_key = pack_inc_state(s_inc, jnp.int32(STATE_ALIVE))
        slot = (snd % m)[:, None]
        cur_id = lookup_cols(mem_id, slot)[:, 0]
        same1 = cur_id == snd
        free1 = cur_id < 0
        mem_view = scatter_cols_max(
            mem_view, slot, s_key[:, None], (valid & (same1 | free1))[:, None]
        )
        mem_id = scatter_cols_set(
            mem_id, slot, snd[:, None], (valid & free1)[:, None]
        )

    # --- budget decrement for attempted sends ---------------------------
    # (bounded-packet mode decrements only the SELECTED entries, at the
    # caller, before this function runs)
    if pig_k == 0:
        mem_tx = jnp.maximum(
            jnp.where(sendable, mem_tx - sends[:, None], mem_tx), 0
        )

    # --- suspicion timers / down conversion / purge ----------------------
    occupied = mem_id >= 0
    changed = (mem_view != old_view) | (mem_id != old_id)
    is_suspect = occupied & (mem_view >= 0) & ((mem_view & 3) == STATE_SUSPECT)
    newly = changed & is_suspect
    timer = jnp.where(newly, suspicion_rounds, mem_timer)
    ticking = is_suspect & ~newly & alive[:, None]
    timer = jnp.where(ticking, timer - 1, timer)
    expired = is_suspect & (timer <= 0) & alive[:, None]
    mem_view = jnp.where(expired, (mem_view >> 2) * 4 + STATE_DOWN, mem_view)

    is_down = occupied & (mem_view >= 0) & ((mem_view & 3) == STATE_DOWN)
    newly_down = expired | (changed & is_down)
    timer = jnp.where(is_down & newly_down, down_purge_rounds, timer)
    timer = jnp.where(is_down & ~newly_down & alive[:, None], timer - 1, timer)
    purge = is_down & (timer <= 0) & alive[:, None]
    mem_id = jnp.where(purge, FREE, mem_id)
    mem_view = jnp.where(purge, FREE, mem_view)

    # --- refutation ------------------------------------------------------
    id_at_self = lookup_cols(mem_id, self_slot[:, None])[:, 0]
    view_at_self = lookup_cols(mem_view, self_slot[:, None], fill=-1)[:, 0]
    self_gossip = jnp.where(id_at_self == iarr, view_at_self, -1)
    heard = jnp.maximum(sus_heard, self_gossip)
    refute = alive & (heard >= inc * 4 + STATE_SUSPECT)
    inc = jnp.where(refute, (heard >> 2) + 1, inc)
    self_key = pack_inc_state(inc, jnp.int32(STATE_ALIVE))
    self_mask = self_slot[:, None] == jnp.arange(m, dtype=jnp.int32)[None, :]
    own = self_mask & alive[:, None]
    mem_view = jnp.where(own, self_key[:, None], mem_view)
    mem_id = jnp.where(own, iarr[:, None], mem_id)

    # --- fresh news refills the dissemination budget ---------------------
    changed = (mem_view != old_view) | (mem_id != old_id)
    mem_tx = jnp.where(changed, max_transmissions, mem_tx)
    return mem_id, mem_view, timer, mem_tx, inc, refute


class _SwimFront(NamedTuple):
    """First half of the SWIM round: everything up to (and excluding) the
    cross-node row gathers — churn + self refresh, probe/announce legs,
    per-receiver elections, the delivered-packet channel list with sender
    incarnations, and the delivered-packet counts.

    ``scale_swim_step`` always runs front + back (pure code motion, bitwise
    the historical single-function round). The quiet round variant
    (``sim/scale_step.scale_sim_step_quiet``) runs the front
    unconditionally — its outputs decide whether this round's traffic could
    change any membership table (:func:`swim_front_disturbed`) — and gates
    the expensive back half behind one ``lax.cond``."""

    alive: jax.Array        # bool  [N] post-churn liveness
    inc: jax.Array          # int32 [N] post-churn incarnations
    mem_id: jax.Array       # int32 [N, M] self-refreshed member ids
    mem_view: jax.Array     # int32 [N, M] self-refreshed member views
    self_slot: jax.Array    # int32 [N] own hash slot (i mod M)
    sus_heard: jax.Array    # int32 [N] probe-notify suspicion only (the
                            # announce-reply down-notice lands in the back)
    sends: jax.Array        # int32 [N] attempted membership transmissions
    probe_slot: jax.Array   # int32 [N] probed table slot
    suspect_key: jax.Array  # int32 [N] suspect mark for a failed probe
    failed: jax.Array       # bool  [N] failed probes
    acked: jax.Array        # bool  [N] acked probes
    ann_tgt: jax.Array      # int32 [N] announce target
    ann_back: jax.Array     # bool  [N] announce reply delivered
    channels: tuple         # 4 x (sender, valid) delivered-packet pairs
    ch_snd_inc: tuple       # 4 x int32 [N] sender incarnations (off cards)
    carried: jax.Array      # int32 [N] delivered packets per sender
    k_upd: jax.Array        # PRNG key for the bounded-piggyback selection


def _swim_front(
    cfg: ScaleConfig,
    st: ScaleSwimState,
    net: NetModel,
    key: jax.Array,
    kill=None,
    revive=None,
) -> _SwimFront:
    """Front half of the SWIM probe period (see :class:`_SwimFront`)."""
    n, m = cfg.n_nodes, cfg.m_slots
    iarr = jnp.arange(n, dtype=jnp.int32)
    (k_tgt, k_p1, k_p2, k_help, k_ind, k_ann, k_annt, k_ann1, k_ann2,
     k_cp, k_ca, k_upd) = jr.split(key, 12)

    # --- churn ----------------------------------------------------------
    kill = jnp.zeros(n, bool) if kill is None else kill
    revive = jnp.zeros(n, bool) if revive is None else revive
    alive = (st.alive & ~kill) | revive
    inc = st.inc + revive.astype(jnp.int32)  # rejoin = renew (actor.rs:199-210)

    old_id, old_view = st.mem_id, st.mem_view
    mem_id, mem_view = old_id, old_view

    # refresh self entry: an alive node always occupies its own hash
    # slot; slot = i mod m is a static pattern, so the update is a pure
    # elementwise mask — no per-element scatter (see ops/dense.py)
    self_slot = iarr % m
    self_mask = self_slot[:, None] == jnp.arange(m, dtype=jnp.int32)[None, :]
    own = self_mask & alive[:, None]
    self_key = pack_inc_state(inc, jnp.int32(STATE_ALIVE))
    mem_id = jnp.where(own, iarr[:, None], mem_id)
    mem_view = jnp.where(own, self_key[:, None], mem_view)

    occupied = mem_id >= 0
    not_self = mem_id != iarr[:, None]
    bel_alive = occupied & not_self & (mem_view >= 0) & ((mem_view & 3) == STATE_ALIVE)

    # node card: every per-node scalar the round reads remotely, so each
    # peer-index array costs ONE fast row gather instead of several
    # per-element gathers (see transport.py "node cards")
    card = link_card(net, alive, extra=(inc,))
    CARD_INC = CARD_EXTRA

    # --- probe target: one believed-alive table entry -------------------
    probe_slot, has_slot = sample_one(bel_alive, k_tgt)
    tgt = jnp.clip(select_cols(mem_id, probe_slot[:, None])[:, 0], 0)
    has_tgt = alive & has_slot
    tgt_card = card_at(card, tgt)  # [N, C]

    leg_out = has_tgt & datagram_ok_c(net, k_p1, card, tgt_card)
    leg_back = datagram_ok_c(net, k_p2, tgt_card, card)
    probe_ok = leg_out & leg_back

    # --- indirect probes through helper entries -------------------------
    h_mask = bel_alive & (mem_id != tgt[:, None])
    h_slots, h_valid = sample_k(h_mask, max(1, cfg.n_indirect), k_help)
    helpers = jnp.clip(select_cols(mem_id, h_slots), 0)
    k1, k2, k3, k4 = jr.split(k_ind, 4)
    helper_card = card_at(card, helpers)  # [N, H, C]
    self_b = card[:, None, :]
    tgt_b = tgt_card[:, None, :]
    ind_leg = (
        datagram_ok_c(net, k1, self_b, helper_card)
        & datagram_ok_c(net, k2, helper_card, tgt_b)
        & datagram_ok_c(net, k3, tgt_b, helper_card)
        & datagram_ok_c(net, k4, helper_card, self_b)
    )
    ind_ok = jnp.any(h_valid & ind_leg, axis=1) & has_tgt
    acked = probe_ok | ind_ok
    failed = has_tgt & ~acked

    # --- failed probe: suspect the entry, notify the subject -------------
    # (the suspect mark itself lands inside swim_tables_update)
    cur = select_cols(mem_view, probe_slot[:, None])[:, 0]
    suspect_key = (cur >> 2) * 4 + STATE_SUSPECT
    notify_ok = failed & datagram_ok_c(
        net, jr.fold_in(k_p1, 1), card, tgt_card
    )
    sus_heard = (
        jnp.full(n, -1, jnp.int32)
        .at[tgt]
        .max(jnp.where(notify_ok, suspect_key, -1), mode="drop")
    )

    # --- announce to a random ever-known member (heal/rejoin path) ------
    announcing = alive & (
        jr.uniform(k_ann, (n,)) < 1.0 / max(1, cfg.announce_interval)
    )
    known = occupied & not_self
    ann_slot, has_known = sample_one(known, k_annt)
    ann_tgt = jnp.clip(select_cols(mem_id, ann_slot[:, None])[:, 0], 0)
    # bootstrap fallback: a node whose table holds nobody but itself
    # (long-dead, fully purged by the cluster, its own view reset by the
    # state-loss rejoin) announces to a random static seed instead — the
    # restart-time bootstrap-host re-contact. Without it a forgotten
    # node can never rejoin: it has no announce target, no probe target,
    # and nobody probes it, so its queued changesets wedge undrained
    # (the chaos quiescence oracle caught exactly this on
    # rejoin-refutation).
    seed_tgt = jr.randint(
        jr.fold_in(k_annt, 1), (n,), 0, min(cfg.n_seeds, n),
        dtype=jnp.int32,
    )
    lonely = alive & ~has_known & (seed_tgt != iarr)
    ann_tgt = jnp.where(lonely, seed_tgt, ann_tgt)
    has_known = has_known | lonely
    ann_card = card_at(card, ann_tgt)
    announcing = announcing & has_known
    ann_out = announcing & datagram_ok_c(net, k_ann1, card, ann_card)
    ann_back = ann_out & datagram_ok_c(net, k_ann2, ann_card, card)

    # --- choose one prober / announcer per receiver ----------------------
    prober_of, has_prober = _one_sender_per_receiver(n, leg_out, tgt, k_cp)
    announcer_of, has_announcer = _one_sender_per_receiver(
        n, ann_out, ann_tgt, k_ca
    )

    sends = (
        has_tgt.astype(jnp.int32)  # probe we sent
        + announcing.astype(jnp.int32)  # announce we sent
        + has_prober.astype(jnp.int32)  # ack we sent back to our prober
        + has_announcer.astype(jnp.int32)  # reply we sent to our announcer
    )
    # (``sends`` is the SWIM-layer mem_tx decrement — attempted
    # membership-update transmissions.)
    # the one channel list: consumed here for the table update AND
    # returned for the piggyback layer (scale_step.py) — a single source
    # so membership packets and the changesets riding them cannot drift
    channels = [
        (jnp.clip(prober_of, 0), has_prober),
        (tgt, probe_ok),
        (jnp.clip(announcer_of, 0), has_announcer),
        (ann_tgt, ann_back),
    ]
    # sender incarnations ride the cards (one row gather per channel for
    # the two senders whose cards aren't already gathered)
    ch_cards = [
        card_at(card, channels[0][0]),
        tgt_card,
        card_at(card, channels[2][0]),
        ann_card,
    ]
    ch_snd_inc = tuple(c[:, CARD_INC] for c in ch_cards)

    # delivered-packet count per sender — the piggyback layer's budget
    # multiplicity. It must be delivery-coupled (a changeset's budget
    # only burns when a packet actually carried it) or an unlucky writer
    # can exhaust its budget with zero deliveries and its version never
    # disseminates. Probe/announce deliveries are election wins (one
    # fast card gather each); ack/reply deliveries need one [N]
    # scatter-add each (a receiver-side count).
    elect = jnp.stack(
        [jnp.clip(prober_of, 0), jnp.clip(announcer_of, 0)], axis=1
    )
    g_tgt = card_at(elect, tgt)  # [N, 2]
    g_ann = card_at(elect, ann_tgt)
    probe_delivered = leg_out & (g_tgt[:, 0] == iarr)
    ann_delivered = ann_out & (g_ann[:, 1] == iarr)
    ack_count = (
        jnp.zeros(n, jnp.int32).at[tgt].add(
            probe_ok.astype(jnp.int32), mode="drop")
    )
    reply_count = (
        jnp.zeros(n, jnp.int32).at[ann_tgt].add(
            ann_back.astype(jnp.int32), mode="drop")
    )
    carried = (
        probe_delivered.astype(jnp.int32)
        + ann_delivered.astype(jnp.int32)
        + ack_count
        + reply_count
    )
    return _SwimFront(
        alive=alive, inc=inc, mem_id=mem_id, mem_view=mem_view,
        self_slot=self_slot, sus_heard=sus_heard, sends=sends,
        probe_slot=probe_slot, suspect_key=suspect_key, failed=failed,
        acked=acked, ann_tgt=ann_tgt, ann_back=ann_back,
        channels=tuple(channels), ch_snd_inc=ch_snd_inc,
        carried=carried, k_upd=k_upd,
    )


def _swim_back(cfg: ScaleConfig, st: ScaleSwimState, front: _SwimFront):
    """Back half of the SWIM probe period: the cross-node row gathers
    (down-notice, piggybacked member entries) plus the row-local table
    transforms (``swim_tables_update`` / the fused kernel). Pure code
    motion out of the historical ``scale_swim_step`` body — running
    front + back is bit-for-bit the original round."""
    n, m = cfg.n_nodes, cfg.m_slots
    iarr = jnp.arange(n, dtype=jnp.int32)
    old_id, old_view = st.mem_id, st.mem_view

    # down-notice: the announce receiver's (possibly stale) belief about
    # the announcer rides the reply; a non-alive belief at >= our
    # incarnation triggers refutation inside the table update
    # peer's view row = fast row gather; the self column picks densely
    peer_view_rows = jax.lax.optimization_barrier(old_view[front.ann_tgt])
    peer_id_rows = jax.lax.optimization_barrier(old_id[front.ann_tgt])
    bel = select_cols(peer_view_rows, front.self_slot[:, None])[:, 0]
    bel_is_me = (
        select_cols(peer_id_rows, front.self_slot[:, None])[:, 0] == iarr
    )
    notice = jnp.where(front.ann_back & bel_is_me, bel, -1)
    sus_heard = jnp.maximum(front.sus_heard, notice)

    # --- row-local back half: merges, assertions, timers, refutation ----
    # sender rows gathered here (barriered — see PERF.md on fused-gather
    # scalarization); the table transforms run either as plain XLA or as
    # one pallas kernel per node block (ops/megakernel.py)
    sendable = st.mem_tx > 0
    sends = front.sends
    channels = list(front.channels)
    ch_in_id, ch_in_view, ch_in_send, ch_valid, ch_snd = [], [], [], [], []
    ch_snd_inc = list(front.ch_snd_inc)
    pig_k = int(getattr(cfg, "pig_members", 0) or 0)
    mem_tx_in = st.mem_tx
    if pig_k > 0:
        # bounded packets: every packet a node sends this round carries
        # its pig_k freshest sendable entries (highest remaining budget
        # first, random tiebreak — foca flushes its least-sent updates
        # first); one [N, 2k] gather per channel replaces three [N, M]
        # row gathers
        occ_sendable = sendable & (old_id >= 0)
        upd_slots, upd_ok = sample_k_biased(
            occ_sendable, st.mem_tx.astype(jnp.float32), pig_k, front.k_upd
        )
        upd_id = jnp.where(
            upd_ok, select_cols(old_id, upd_slots), jnp.int32(FREE)
        )
        upd_view = select_cols(old_view, upd_slots)
        pig_pack = jnp.concatenate([upd_id, upd_view], axis=1)  # [N, 2k]
        ones_k = jnp.ones((n, pig_k), bool)
        for src, valid in channels:
            got = jax.lax.optimization_barrier(pig_pack[src])
            ch_in_id.append(got[:, :pig_k])
            ch_in_view.append(got[:, pig_k:])
            ch_in_send.append(ones_k)  # selection already applied it
            ch_valid.append(valid)
            ch_snd.append(src)
        # transmit-budget decrement for the SELECTED entries only (the
        # table-update function skips its full-row decrement in this
        # mode); refill-on-change still happens inside it
        # accumulate in the plane's own dtype: the fused swim kernel is
        # probed at the plane dtypes, so a promotion here would lower a
        # DIFFERENT (unprobed) kernel under narrow_dtypes
        dec = scatter_cols_add(
            jnp.zeros((n, m), st.mem_tx.dtype), upd_slots,
            jnp.broadcast_to(sends[:, None], upd_slots.shape), upd_ok,
        )
        mem_tx_in = jnp.maximum(st.mem_tx - dec, 0)
    else:
        for src, valid in channels:
            ch_in_id.append(jax.lax.optimization_barrier(old_id[src]))
            ch_in_view.append(jax.lax.optimization_barrier(old_view[src]))
            ch_in_send.append(jax.lax.optimization_barrier(sendable[src]))
            ch_valid.append(valid)
            ch_snd.append(src)

    consts = (
        m, int(cfg.suspicion_rounds), int(cfg.down_purge_rounds),
        int(cfg.max_transmissions), pig_k,
    )
    args = (
        front.mem_id, front.mem_view, old_id, old_view, st.mem_timer,
        mem_tx_in, front.alive, front.inc, iarr, front.self_slot,
        sus_heard, sends, front.probe_slot, front.suspect_key, front.failed,
        ch_in_id, ch_in_view, ch_in_send, ch_valid, ch_snd, ch_snd_inc,
    )
    from corrosion_tpu.ops import megakernel

    if megakernel.use_fused_swim(
            cfg.n_nodes, cfg.m_slots, pig_k,
            narrow=bool(getattr(cfg, "narrow_dtypes", False)),
            tx8=bool(getattr(cfg, "narrow_int8", False)),
            mode=megakernel.fused_mode(cfg)):
        mem_id, mem_view, timer, mem_tx, inc, refute = (
            megakernel.swim_tables_fused(
                consts, *args,
                interpret=megakernel.fused_interpret(cfg),
            )
        )
    else:
        mem_id, mem_view, timer, mem_tx, inc, refute = swim_tables_update(
            consts, *args
        )

    st2 = ScaleSwimState(
        front.alive, front.inc, mem_id, mem_view, timer, mem_tx
    )
    info = {
        "acked": jnp.sum(front.acked),
        "failed_probes": jnp.sum(front.failed),
        "refutes": jnp.sum(refute),
    }
    return st2, info


def swim_front_disturbed(cfg: ScaleConfig, front: _SwimFront):
    """Would this round's delivered SWIM traffic change any membership
    table? Scalar bool, computed from the front half alone.

    Re-checks the back half's only input-driven mutation surfaces against
    the front's (self-refreshed) planes: a failed probe plants a suspect
    mark (``swim_tables_update`` suspect scatter), and a delivered
    packet's sender-alive assertion inserts the sender into a free hash
    slot or raises a stale incarnation (the two assertion scatters). The
    merge sections need no term here: their masks require a sendable
    (mem_tx > 0) entry at an alive sender, which the quiet predicate's
    carry-occupancy bits (``scale_step._quiet_busy``) already exclude.

    False ⇒ — given the carry-occupancy and input-quiet predicates of
    ``scale_sim_step_quiet`` — the back half is a bitwise no-op on every
    plane; any True sends the round down the dense branch."""
    m = cfg.m_slots
    disturbed = jnp.any(front.failed)
    for (src, valid), s_inc in zip(front.channels, front.ch_snd_inc):
        s_key = pack_inc_state(s_inc, jnp.int32(STATE_ALIVE))
        slot = (src % m)[:, None]
        cur_id = lookup_cols(front.mem_id, slot)[:, 0]
        cur_view = lookup_cols(front.mem_view, slot, fill=-1)[:, 0]
        would = valid & (
            (cur_id < 0) | ((cur_id == src) & (s_key > cur_view))
        )
        disturbed = disturbed | jnp.any(would)
    return disturbed


def scale_swim_step(
    cfg: ScaleConfig,
    st: ScaleSwimState,
    net: NetModel,
    key: jax.Array,
    kill=None,
    revive=None,
):
    """One SWIM probe period for the whole cluster, O(N*M) work."""
    front = _swim_front(cfg, st, net, key, kill=kill, revive=revive)
    st2, info = _swim_back(cfg, st, front)
    # channels: the four delivered-packet (sender, valid) pairs built by
    # the front — higher layers piggyback changesets on exactly these
    # packets; ``carried`` is each sender's delivered-packet count, the
    # piggyback layer's budget multiplicity (one transmission per
    # delivered packet, like the reference's max_transmissions counter).
    return st2, info, list(front.channels), front.carried


def scale_swim_metrics(st: ScaleSwimState):
    """Belief accuracy over occupied entries of alive viewers: alive
    subjects believed Alive, dead subjects believed Down (or purged —
    purged entries simply stop counting). The bounded-view analog of the
    reference's stress-test convergence assertion."""
    n = st.alive.shape[0]
    occ = (st.mem_id >= 0) & (st.mem_view >= 0)
    not_self = st.mem_id != jnp.arange(n, dtype=jnp.int32)[:, None]
    subj = jnp.clip(st.mem_id, 0)
    subj_alive = st.alive[subj]
    state = st.mem_view & 3
    entry_ok = jnp.where(subj_alive, state == STATE_ALIVE, state == STATE_DOWN)
    counted = occ & not_self & st.alive[:, None]
    correct = jnp.sum(entry_ok & counted)
    total = jnp.maximum(jnp.sum(counted), 1)
    return {
        "accuracy": correct / total,
        "mean_tracked": jnp.sum(counted) / jnp.maximum(jnp.sum(st.alive), 1),
        "n_alive": jnp.sum(st.alive),
    }
