"""Anti-entropy sync as a batched pairwise exchange round.

Reference (SURVEY §3.4): every 1-15 s a node picks a few peers
(``sync_loop``, ``crates/corro-agent/src/agent/util.rs:352-398``; choice
``handlers.rs:793-894``), exchanges ``SyncStateV1`` (per-actor heads +
needs), computes the diff (``compute_available_needs``,
``crates/corro-types/src/sync.rs:127``), requests missing version ranges
in chunks, and the server streams the matching ``crsql_changes`` rows
back (``parallel_sync``/``serve_sync``,
``crates/corro-agent/src/api/peer/mod.rs:1001,1405``).

Array re-design: a syncing node i and peer p exchange head vectors; the
need is the interval ``(head_i[o], min(head_p[o], head_i[o]+chunk)]`` per
origin o — interval subtraction collapses to a clamp because heads are
contiguous prefixes (out-of-order residue lives in the bounded buffer and
is subsumed by the head jump). The "stream" is an elementwise masked LWW
merge of p's store cells whose ``(site, db_version)`` fall in the granted
range — cr-sqlite keeps only current clock rows, so version ranges whose
writes were overwritten transfer as nothing, exactly the reference's
empty/cleared-version handling (``util.rs:1048-1058``). The head then
jumps to the granted top, because the reliable bi channel transferred the
whole range atomically.

Chunking (``sync_chunk``) bounds per-round transfer like the reference's
10-version request chunks; a node converges over several sync rounds —
that cadence is what BASELINE config 4 measures.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.random as jr

from corrosion_tpu.ops.dense import lookup_cols
from corrosion_tpu.ops.lww import INT32_MIN, lex_max
from corrosion_tpu.ops.partials import drop_stale_partials
from corrosion_tpu.ops.versions import advance_heads, needs_count, raise_heads
from corrosion_tpu.sim.broadcast import LAST_SYNC_CAP, CrdtState, hlc_fold
from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.transport import (
    CARD_EXTRA,
    N_RINGS,
    NetModel,
    bi_ok_c,
    card_at,
    link_card,
)


def choose_sync_peers(cfg, book, cand_ids, cand_ok, staleness, rings, k):
    """Need-driven sync peer choice (``handlers.rs:808-894``): from a
    2x-oversampled candidate set, order by (1) most versions we still need
    from that peer-as-origin, (2) longest time since we last synced with
    it, (3) closest RTT ring — and take the top ``k``.

    ``cand_ids``/``staleness``/``rings`` int32 [N, 2k]; ``cand_ok`` bool.
    Returns ``(peers [N, k], ok [N, k], cand_idx [N, k])`` where
    ``cand_idx`` indexes back into the candidate axis (for last-sync
    bookkeeping updates at the caller).

    The three criteria pack into one int32 score — 12 bits of need above
    12 bits of staleness (:data:`LAST_SYNC_CAP`) above 3 bits of ring
    closeness — so the ordering is exactly lexicographic (no float
    mantissa truncation).
    """
    n_org = cfg.n_origins
    needs = jnp.maximum(needs_count(book), 0)  # [N, O]
    # a candidate's need-as-origin lives at its hash slot, and only
    # counts while the slot actually tracks that actor (round 4:
    # unbounded writer set, ops/versions.py Book)
    slot = jnp.where(cand_ids >= 0, cand_ids % n_org, 0)
    owned = (cand_ids >= 0) & (lookup_cols(book.org_id, slot) == cand_ids)
    need = jnp.where(owned, lookup_cols(needs, slot), 0)
    score = (
        (jnp.minimum(need, 4095) << 15)
        + (jnp.minimum(staleness, LAST_SYNC_CAP) << 3)
        + (N_RINGS - 1 - jnp.clip(rings, 0, N_RINGS - 1))
    ).astype(jnp.int32)
    score = jnp.where(cand_ok, score, jnp.int32(-1))
    val, idx = jax.lax.top_k(score, k)
    peers = lookup_cols(cand_ids, idx.astype(jnp.int32))
    return jnp.clip(peers, 0), val >= 0, idx.astype(jnp.int32)


def sync_step(
    cfg: SimConfig,
    cst: CrdtState,
    peers,  # int32 [N, P] chosen sync peers (caller-sampled, see bcast_step)
    p_ok,  # bool [N, P] peer validity
    alive,  # bool [N]
    net: NetModel,
    key: jax.Array,
    go_all: bool = False,
    sweep=None,
):
    """One sync round: a random subset of nodes each pulls from the
    caller-chosen ``peers`` lanes (the scale path scores ``sync_peers``
    candidates and passes the top ``sync_pull_peers``; ``go_all``: every
    alive node syncs — the cohort-scheduled caller already rate-limited
    the rounds). Returns (state, ok, info) where ``ok`` [N, P] marks
    pairs that actually exchanged (drives last-sync bookkeeping).

    ``sweep`` (traced bool or None): a FULL-STORE sweep round — lane 0
    merges its peer's entire store elementwise, ignoring range grants
    and slot ownership. The LWW join is idempotent/commutative, so this
    is always safe; it is the convergence backstop for actors whose
    hash slot is held by a *different continuously-active* actor
    (bounded bookkeeping cannot range-track them, and gossip budgets
    are finite). Callers schedule it every ``sync_sweep_every``-th
    cohort round — amortized, one extra granted-lane's worth of
    traffic."""
    n, n_org = cfg.n_nodes, cfg.n_origins
    p_cnt = peers.shape[1]
    iarr = jnp.arange(n, dtype=jnp.int32)
    k_go, k_bi = jr.split(key)
    if peers.shape[0] != n or p_ok.shape != peers.shape:
        raise ValueError(
            f"peers {peers.shape} / p_ok {p_ok.shape} must both be "
            f"({n}, P)"
        )

    if go_all:
        syncing = alive
    else:
        syncing = alive & (
            jr.uniform(k_go, (n,)) < 1.0 / max(1, cfg.sync_interval)
        )
    # node card: link fields + HLC, one row gather for all of them
    # (see transport.py "node cards")
    card = link_card(net, alive, extra=(cst.hlc,))
    CARD_HLC = CARD_EXTRA
    peer_card = card_at(card, peers)  # [N, P, C]
    ok = syncing[:, None] & p_ok & bi_ok_c(
        net, k_bi, card[:, None, :], peer_card
    )

    # --- server-side load adaptation ------------------------------------
    # The reference caps concurrent sync serves at 3 (``agent.rs:143``;
    # rejection ``peer/mod.rs:1462-1479``) and adapts its stream chunk
    # 8 KiB -> 1 KiB for slow/loaded peers (``peer/mod.rs:364-368``).
    # Dense analog: count this round's serve requests per server; clients
    # of a server loaded past ~4x its permits are shed (they retry a later
    # cohort round — budget-shaped degradation that sync then repairs),
    # and the survivors' version grants shrink toward ``sync_min_chunk``
    # so a server's expected granted work stays ~serve_cap * sync_chunk.
    serve_cap = max(1, cfg.serve_cap)
    load = (
        jnp.zeros(n + 1, jnp.int32)
        .at[jnp.where(ok, peers, n).reshape(-1)]
        .add(1, mode="drop")[:n]
    )
    loadp = card_at(load[:, None], peers)[..., 0]  # [N, P]
    k_adm = jr.fold_in(k_bi, 7)
    admit_p = jnp.where(
        loadp > 4 * serve_cap,
        (4.0 * serve_cap) / jnp.maximum(loadp, 1).astype(jnp.float32),
        1.0,
    )
    # anti-starvation force-admit: the shed coin flips are independent
    # per round, so an unlucky client could lose every one of them for
    # arbitrarily long. cst.sync_defer counts consecutive fully-shed
    # rounds per client; at cfg.sync_defer_cap the next request is
    # admitted unconditionally — a requesting client is served at least
    # once every cap+1 rounds, deterministically, while the expected
    # granted work stays budget-shaped.
    defer_cap = max(1, getattr(cfg, "sync_defer_cap", 8))
    force = (cst.sync_defer >= defer_cap)[:, None]
    admitted = ok & ((jr.uniform(k_adm, ok.shape) < admit_p) | force)
    rejects = jnp.sum(ok & ~admitted)
    admitted_any = jnp.any(admitted, axis=1)
    shed_all = jnp.any(ok, axis=1) & ~admitted_any
    cst = cst._replace(sync_defer=jnp.where(
        admitted_any,
        0,
        jnp.where(shed_all,
                  jnp.minimum(cst.sync_defer + 1, defer_cap),
                  cst.sync_defer),
    ))
    ok = admitted
    chunk_eff = jnp.clip(
        (cfg.sync_chunk * serve_cap)
        // jnp.maximum(loadp, serve_cap),
        min(cfg.sync_min_chunk, cfg.sync_chunk),
        cfg.sync_chunk,
    )  # [N, P]

    head_p = jax.lax.optimization_barrier(cst.book.head[peers])  # [N, P, O]
    # slot-aligned org agreement (round 4): a peer's slot grants to me
    # when we track the SAME actor there. Anti-entropy must also be the
    # backstop for actors I never heard gossip from (budgets are finite),
    # so an idle/free slot of mine CLAIMS the actor my top-scored peer
    # (lane 0 — one lane, so claims are deterministic) tracks there:
    # bookkeeping resets to zero and the granted range rebuilds it, the
    # same repair path as an ingest-side eviction.
    org_p = jax.lax.optimization_barrier(
        cst.book.org_id[peers]
    )  # [N, P, O]
    now = cst.now
    keep = getattr(cfg, "org_keep_rounds", 16)
    evictable = (cst.book.org_id < 0) | (
        cst.book.org_last + keep < now
    )  # [N, O]
    claim_plain = (
        ok[:, 0, None]
        & evictable
        # monotone lattice rule, SAME as the sweep claim (round 5): the
        # tracked actor id per slot is non-decreasing. Without the
        # ordering, quiescence flip-flops forever — after
        # org_keep_rounds idle rounds every slot is evictable, and two
        # nodes tracking different slot-colliding actors keep swapping
        # assignments (each claim resets head/known_max, re-opening
        # needs that sync then re-drains: measured as total_needs
        # oscillating at 200-380k through 512 quiet rounds at 4096
        # nodes, scripts/collision_probe.py). Displaced smaller-id
        # actors lose BOOKKEEPING only; their data still rides the
        # sweep's full-store merge.
        & (org_p[:, 0, :] > cst.book.org_id)
        # never trade real (idle) bookkeeping for a peer slot with
        # nothing to grant — an empty claim resets dedupe state for
        # zero data
        & (head_p[:, 0, :] > 0)
    )  # [N, O]
    if sweep is not None:
        # sweep rounds: idle slots take a deterministic LATTICE JOIN
        # with the peer's entry — the larger actor id wins the class
        # (same rule on every node ⇒ org assignments converge
        # epidemically during quiescence), and the adopted head rides
        # the full-head grant below, backed by the full-store merge.
        # Without this, a cluster whose distinct active actors exceed
        # the slot table can never align its books: every node tracks
        # whichever actors it heard last, and needs stay positive
        # forever even though stores are long equal.
        claim_sweep = (
            ok[:, 0, None]
            & evictable
            & (org_p[:, 0, :] > cst.book.org_id)
        )
        claim0 = jnp.where(sweep, claim_sweep, claim_plain)
    else:
        claim0 = claim_plain
    org_id2 = jnp.where(claim0, org_p[:, 0, :], cst.book.org_id)
    head_i = jnp.where(claim0, 0, cst.book.head)  # [N, O]
    book0 = cst.book._replace(
        head=head_i,
        known_max=jnp.where(claim0, 0, cst.book.known_max),
        seen=jnp.where(
            claim0[:, :, None], jnp.zeros((), jnp.uint32), cst.book.seen
        ),
        org_id=org_id2,
        org_last=jnp.where(claim0, jnp.int32(now), cst.book.org_last),
    )
    match = (
        ok[:, :, None]
        & (org_p == org_id2[:, None, :])
        & (org_id2[:, None, :] >= 0)
    )
    granted = jnp.minimum(head_p, head_i[:, None, :] + chunk_eff[:, :, None])
    granted = jnp.where(match, granted, 0)  # [N, P, O]
    if sweep is not None:
        # a sweep round's lane-0 FULL-store merge reflects every effect
        # of every version the peer has seen, so adopting the peer's
        # whole head for org-matched slots is safe (a re-delivery of a
        # version <= that head is either already reflected or loses the
        # LWW compare) — and it is what un-wedges bookkeeping after
        # evictions: versions whose changesets expired from every queue
        # can never close head gaps by re-delivery, only by this
        # head adoption (the reference's SyncStateV1 head exchange)
        g0 = jnp.where(
            match[:, 0, :] & sweep, head_p[:, 0, :], granted[:, 0, :]
        )
        granted = granted.at[:, 0, :].set(g0)

    # --- transfer: masked elementwise merge per peer --------------------
    store = tuple(p.astype(jnp.int32) for p in cst.store)
    pulled = jnp.int32(0)
    for j in range(p_cnt):
        pj = peers[:, j]  # [N]

        def merge_lane(store, pj=pj, j=j):
            # row gathers are fast on TPU; the per-cell head lookups
            # below loop over the small origin axis instead of
            # element-gathering (ops/dense.py)
            p_ver, p_val, p_site, p_dbv, p_clp = (
                jax.lax.optimization_barrier(
                    tuple(pl[pj] for pl in cst.store)
                )
            )  # [N, C]
            # range check per cell, at the site's hash slot (which must
            # track that exact actor): head_i[slot] < dbv <= granted
            slot_c = jnp.where(p_site >= 0, p_site % n_org, 0)
            owned_c = (p_site >= 0) & (
                lookup_cols(org_id2, slot_c) == p_site
            )
            lo = lookup_cols(head_i, slot_c)
            hi = lookup_cols(granted[:, j, :], slot_c)
            sel = (
                ok[:, j : j + 1]
                & owned_c
                & (p_dbv > lo)
                & (p_dbv <= hi)
                & (p_ver > 0)
            )
            if sweep is not None and j == 0:
                # full-store sweep: every live peer cell merges
                sel = sel | (sweep & ok[:, 0:1] & (p_ver > 0))
            # merge key (clp, ver, val, site) — causal-length lifetime
            # dominates, then the LWW clock (ops/lww.py merge_store)
            b = (
                jnp.where(sel, p_clp, INT32_MIN),
                jnp.where(sel, p_ver, INT32_MIN),
                jnp.where(sel, p_val, INT32_MIN),
                jnp.where(sel, p_site, INT32_MIN),
            )
            m_clp, m_ver, m_val, m_site, m_dbv = lex_max(
                (store[4], store[0], store[1], store[2]), b,
                (store[3], p_dbv),
            )
            merged = (m_ver, m_val, m_site, m_dbv, m_clp)
            new_store = tuple(
                jnp.where(sel, m, s) for m, s in zip(merged, store)
            )
            return new_store, jnp.sum(sel, dtype=jnp.int32)

        # steady state grants nothing: skip the lane's 5 store gathers +
        # merge entirely when no node was granted anything from it (the
        # reference's sync_loop similarly no-ops when needs are empty)
        any_grant = jnp.any(granted[:, j, :] > head_i)
        if sweep is not None and j == 0:
            any_grant = any_grant | sweep
        store, cnt = jax.lax.cond(
            any_grant, merge_lane,
            lambda s: (s, jnp.int32(0)),
            store,
        )
        pulled = pulled + cnt

    # --- head jump + known_max exchange ---------------------------------
    # the head jump goes through raise_heads: the seen window is
    # head-relative and must be rebased alongside the jump
    new_head = jnp.maximum(head_i, jnp.max(granted, axis=1))
    # NO known_max exchange here (round 4): km is hearsay, and a
    # max-exchange ratchets it through the population faster than the
    # sweep's collapse can drain it — with bounded books, versions whose
    # bookkeeping was evicted everywhere would then show as needs
    # forever. km stays what this node actually observed (message dbvs
    # on owned slots + its own writes + the sweep frontier); grants
    # never used peer km anyway (they clamp against head_p), and sync
    # peer scoring still ranks by the locally-known need.
    book = raise_heads(book0, new_head)
    book = advance_heads(book)
    if sweep is not None:
        # sweep collapses hearsay: after adopting the peer's full head
        # (backed by the full-store merge), known_max above it is
        # unverifiable rumor — in the over-capacity regime the books
        # that actually saw those versions were evicted, so no head can
        # ever reach the rumored max and needs would stay positive
        # forever. Collapse to the verifiable frontier (the advanced
        # head); real circulating changesets re-teach km if the
        # versions still exist anywhere.
        km_collapse = sweep & ok[:, 0, None] & match[:, 0, :]
        book = book._replace(
            known_max=jnp.where(km_collapse, book.head, book.known_max)
        )
    # versions that arrived whole through sync obsolete their buffered
    # fragments (the buffered-meta GC analog, util.rs:430-490)
    if cst.partials.origin.shape[1] > 1 or cst.partials.cell.shape[2] > 1:
        cst = cst._replace(
            partials=drop_stale_partials(cst.partials, book)
        )

    # sync handshake exchanges HLC clocks; BOTH sides fold, with the same
    # max-drift rejection as change ingest (peer/mod.rs:1439-1458)
    hlc, _, _ = hlc_fold(cst.hlc, cst.now, peer_card[..., CARD_HLC], ok)
    # server side: peer p folds the client's clock (scatter-max)
    from corrosion_tpu.sim.broadcast import HLC_MAX_DRIFT_ROUNDS, HLC_ROUND_BITS
    client_ts = jnp.broadcast_to(cst.hlc[:, None], peers.shape)
    within = ok & ((client_ts >> HLC_ROUND_BITS) <= cst.now + HLC_MAX_DRIFT_ROUNDS)
    flat = jnp.where(within, peers, n)
    hlc = (
        jnp.concatenate([hlc, jnp.zeros(1, jnp.int32)])
        .at[flat.reshape(-1)]
        .max(client_ts.reshape(-1), mode="drop")[:n]
    )
    cst = cst._replace(hlc=hlc)

    info = {
        "syncs": jnp.sum(ok),
        "cells_pulled": pulled,
        "versions_granted": jnp.sum(
            jnp.maximum(jnp.max(granted, axis=1) - head_i, 0)
        ),
        "serve_rejects": rejects,
    }
    return cst._replace(store=store, book=book), ok, info
