"""HTTP API client.

Mirrors ``crates/corro-client``: ``CorrosionApiClient`` with
``/v1/transactions`` execution, streaming ``/v1/queries`` (NDJSON), and
``SubscriptionStream`` with resume-from-ChangeId
(``corro-client/src/lib.rs:32``, ``sub.rs``).
"""

from __future__ import annotations

import http.client
import json
import urllib.parse
from typing import Any, Iterator, List, Optional, Sequence, Tuple

from corrosion_tpu.utils.backoff import Backoff, retry_call


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str,
                 retry_after: Optional[float] = None):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        # the server's Retry-After hint (seconds, parsed off a 503) —
        # ``retry_call`` honors it over its own jittered schedule,
        # capped at the policy's max_wait (corroguard, docs/overload.md)
        self.retry_after = retry_after


class ApiUnavailable(ApiError):
    """503 from the serving plane: the agent is restoring/backing off
    (``/v1/ready`` machinery) or corroguard admission shed the request.
    Carries the Retry-After hint; a client built with ``retry_503 > 0``
    retries these through the shared ``retry_call`` policy."""


def _parse_retry_after(resp) -> Optional[float]:
    raw = resp.headers.get("Retry-After")
    if raw is None:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def _raise_for_status(resp, status: int, msg: str) -> None:
    if status == 503:
        raise ApiUnavailable(status, msg, _parse_retry_after(resp))
    raise ApiError(status, msg)


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict) and set(v) == {"blob"}:
        return bytes.fromhex(v["blob"])
    return v


def _encode_params(params: Any) -> Any:
    def enc(v):
        return {"blob": v.hex()} if isinstance(v, (bytes, bytearray)) else v

    if isinstance(params, dict):
        return {k: enc(v) for k, v in params.items()}
    if isinstance(params, (list, tuple)):
        return [enc(v) for v in params]
    return params


class _NdjsonStream:
    """Iterate parsed NDJSON events off an open HTTP response."""

    def __init__(self, conn: http.client.HTTPConnection,
                 resp: http.client.HTTPResponse):
        self._conn = conn
        self.resp = resp

    def __iter__(self) -> Iterator[dict]:
        try:
            for raw in self.resp:
                raw = raw.strip()
                if raw:
                    yield json.loads(raw)
        finally:
            self.close()

    def close(self) -> None:
        try:
            self._conn.close()
        except Exception:  # noqa: BLE001
            pass


class SubscriptionStream(_NdjsonStream):
    """A live subscription: tracks the matcher id + last seen ChangeId so
    the caller can reconnect with ``client.resubscribe(stream)``."""

    def __init__(self, conn, resp, sub_id: str,
                 last_change_id: Optional[int] = None):
        super().__init__(conn, resp)
        self.id = sub_id
        self.last_change_id = last_change_id
        # resync markers seen (corroguard shed — the stream has gaps
        # and the consumer should re-snapshot, docs/overload.md)
        self.resyncs = 0
        self.dropped = 0

    def __iter__(self) -> Iterator[dict]:
        for event in super().__iter__():
            if "change" in event:
                self.last_change_id = event["change"][3]
            elif "eoq" in event and isinstance(event["eoq"], dict):
                cid = event["eoq"].get("change_id")
                if cid is not None:
                    self.last_change_id = cid
            elif "resync" in event:
                self.resyncs += 1
                self.dropped += int(event["resync"].get("dropped", 0))
            yield event


class CorrosionApiClient:
    """Client for one agent's HTTP API."""

    def __init__(self, addr: str = "127.0.0.1", port: int = 8787,
                 timeout: float = 30.0, connect_retries: int = 2,
                 retry_503: int = 0, retry_503_max_wait: float = 2.0):
        self.addr = addr
        self.port = port
        self.timeout = timeout
        # connection-refused retries ride the shared retry_call policy:
        # a CLI racing agent boot (or an agent restarting under its
        # supervisor) answers after a brief jittered wait instead of
        # failing the one-shot command. Refused means nothing was sent,
        # so retrying is safe for writes too.
        self.connect_retries = connect_retries
        # corroguard closed-loop mode (docs/overload.md): retry_503 > 0
        # also retries 503s, sleeping the server's Retry-After hint
        # (capped at retry_503_max_wait) instead of the jittered
        # schedule. A 503 was a complete (rejected) exchange — nothing
        # committed — so retrying writes is safe too.
        self.retry_503 = retry_503
        self.retry_503_max_wait = retry_503_max_wait

    def _retry_connect(self, attempt):
        retry_on: tuple = (ConnectionRefusedError,)
        max_wait = 0.5
        retries = self.connect_retries
        if self.retry_503 > 0:
            retry_on = (ConnectionRefusedError, ApiUnavailable)
            max_wait = self.retry_503_max_wait
            retries = max(self.connect_retries, self.retry_503)
        return retry_call(
            attempt,
            backoff=Backoff(min_wait=0.05, max_wait=max_wait,
                            max_retries=retries),
            retry_on=retry_on,
        )

    # --- plumbing --------------------------------------------------------
    _UNSET = object()  # sentinel: None must mean "no timeout" (endless streams)

    def _connect(self, timeout=_UNSET) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(
            self.addr, self.port,
            timeout=self.timeout if timeout is self._UNSET else timeout,
        )

    def _request_json(self, method: str, path: str, body: Any = None) -> Any:
        payload = None if body is None else json.dumps(body)
        headers = {"Content-Type": "application/json"}
        # cross-process trace propagation (the reference injects
        # SyncTraceContextV1 into sync handshakes, sync.rs:33-67 +
        # peer/mod.rs:1017-1020); any active client span rides the
        # standard W3C header
        from corrosion_tpu.utils.tracing import inject_traceparent

        tp = inject_traceparent()
        if tp:
            headers["traceparent"] = tp

        def attempt():
            conn = self._connect()
            try:
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                obj = json.loads(data) if data else None
                if resp.status >= 400:
                    msg = obj.get("error", data.decode()) if isinstance(
                        obj, dict) else data.decode()
                    _raise_for_status(resp, resp.status, msg)
                return obj
            finally:
                conn.close()

        return self._retry_connect(attempt)

    def _request_stream(self, method: str, path: str, body: Any = None,
                        stream_timeout=_UNSET):
        payload = None if body is None else json.dumps(body)
        headers = {"Content-Type": "application/json"}
        # streams join the same trace as one-shot requests: the server
        # wraps every route in a joined per-request span (ISSUE 16)
        from corrosion_tpu.utils.tracing import inject_traceparent

        tp = inject_traceparent()
        if tp:
            headers["traceparent"] = tp

        def attempt():
            conn = self._connect(timeout=stream_timeout)
            try:
                conn.request(method, path, body=payload, headers=headers)
                resp = conn.getresponse()
            except BaseException:
                conn.close()
                raise
            if resp.status >= 400:
                data = resp.read()
                conn.close()
                try:
                    msg = json.loads(data).get("error", data.decode())
                except Exception:  # noqa: BLE001
                    msg = data.decode()
                _raise_for_status(resp, resp.status, msg)
            return conn, resp

        return self._retry_connect(attempt)

    @staticmethod
    def _stmts(statements: Sequence) -> list:
        out = []
        for s in statements:
            if isinstance(s, str):
                out.append(s)
            elif isinstance(s, (list, tuple)):
                sql = s[0]
                params = _encode_params(s[1]) if len(s) > 1 else None
                out.append([sql, params] if params is not None else sql)
            else:
                raise TypeError(f"bad statement: {s!r}")
        return out

    # --- API surface -----------------------------------------------------
    def execute(self, statements: Sequence, node: int = 0) -> List[dict]:
        """``POST /v1/transactions``."""
        obj = self._request_json(
            "POST", f"/v1/transactions?node={node}", self._stmts(statements)
        )
        return obj["results"]

    def query(self, sql: str, params: Any = None, node: int = 0
              ) -> Tuple[List[str], List[List[Any]]]:
        """``POST /v1/queries`` — returns (columns, rows), fully drained."""
        cols: List[str] = []
        rows: List[List[Any]] = []
        for event in self.query_stream(sql, params, node):
            if "columns" in event:
                cols = event["columns"]
            elif "row" in event:
                rows.append([_decode_value(v) for v in event["row"][1]])
            elif "error" in event:
                raise ApiError(500, event["error"])
        return cols, rows

    def query_stream(self, sql: str, params: Any = None, node: int = 0
                     ) -> _NdjsonStream:
        body = [sql, _encode_params(params)] if params is not None else sql
        conn, resp = self._request_stream(
            "POST", f"/v1/queries?node={node}", body)
        return _NdjsonStream(conn, resp)

    def subscribe(self, sql: str, params: Any = None, node: int = 0,
                  from_change_id: Optional[int] = None,
                  stream_timeout: Optional[float] = None
                  ) -> SubscriptionStream:
        """``POST /v1/subscriptions`` — an endless NDJSON event stream.

        ``stream_timeout`` bounds each socket read (None = wait
        forever): harness/test subscribers use it so a stalled stream
        surfaces as ``TimeoutError`` instead of a hung thread."""
        body = [sql, _encode_params(params)] if params is not None else sql
        path = f"/v1/subscriptions?node={node}"
        if from_change_id is not None:
            path += f"&from={from_change_id}"
        conn, resp = self._request_stream("POST", path, body,
                                          stream_timeout=stream_timeout)
        sub_id = resp.headers.get("corro-query-id", "")
        return SubscriptionStream(conn, resp, sub_id, from_change_id)

    def resubscribe(self, stream: SubscriptionStream) -> SubscriptionStream:
        """``GET /v1/subscriptions/{id}?from=`` — resume after disconnect."""
        path = f"/v1/subscriptions/{stream.id}"
        if stream.last_change_id is not None:
            path += f"?from={stream.last_change_id}"
        conn, resp = self._request_stream("GET", path, stream_timeout=None)
        return SubscriptionStream(conn, resp, stream.id,
                                  stream.last_change_id)

    def updates(self, table: str) -> _NdjsonStream:
        """``GET /v1/updates/{table}``."""
        conn, resp = self._request_stream(
            "GET", f"/v1/updates/{urllib.parse.quote(table)}",
            stream_timeout=None)
        return _NdjsonStream(conn, resp)

    def schema(self, schema_sql: Sequence[str]) -> List[list]:
        """``POST /v1/migrations``."""
        obj = self._request_json("POST", "/v1/migrations", list(schema_sql))
        return obj["results"]

    def table_stats(self, node: int = 0) -> dict:
        return self._request_json("GET", f"/v1/table_stats?node={node}")

    def members(self) -> list:
        return self._request_json("GET", "/v1/members")

    def sync_state(self, node: int = 0) -> dict:
        return self._request_json("GET", f"/v1/sync?node={node}")

    def metrics(self) -> str:
        conn = self._connect()
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            return resp.read().decode()
        finally:
            conn.close()
