"""Inline always/sometimes assertions — the Antithesis SDK analog.

The reference instruments its hot paths with ``antithesis_sdk`` macros:
``assert_always`` invariants (e.g. "deleted non-contiguous seq ranges!"
``util.rs:1160-1165``, "bookie lock held too long" ``setup.rs:226-231``)
and ``assert_sometimes`` liveness probes (e.g. "Corrosion syncs with
other nodes" ``handlers.rs:837``), which the Antithesis hypervisor
aggregates across fault-injected runs (SURVEY §4).

Here the registry aggregates in-process: ``always`` violations log +
count (and optionally raise under ``CORRO_TPU_STRICT_ASSERTS=1``, the
test-mode equivalent of failing the Antithesis run); ``sometimes`` probes
record whether each liveness property was ever observed, and
``liveness_report`` lists the ones that never fired — the signal
Antithesis calls an unreachable ``assert_sometimes``.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

from corrosion_tpu.utils.tracing import logger


class AssertionRegistry:
    def __init__(self):
        self._always: Dict[str, list] = {}  # name -> [passes, failures]
        self._sometimes: Dict[str, list] = {}  # name -> [checks, hits]
        self._mu = threading.Lock()

    @property
    def strict(self) -> bool:
        return os.environ.get("CORRO_TPU_STRICT_ASSERTS", "") == "1"

    def always(self, condition: bool, name: str, details: str = "") -> bool:
        """Invariant: must hold on every evaluation."""
        with self._mu:
            rec = self._always.setdefault(name, [0, 0])
            rec[0 if condition else 1] += 1
        if not condition:
            logger.error("assert_always violated: %s%s", name,
                         f" ({details})" if details else "")
            if self.strict:
                raise AssertionError(f"assert_always violated: {name} {details}")
        return bool(condition)

    def sometimes(self, condition: bool, name: str) -> bool:
        """Liveness probe: should hold at least once across a run."""
        with self._mu:
            rec = self._sometimes.setdefault(name, [0, 0])
            rec[0] += 1
            if condition:
                rec[1] += 1
        return bool(condition)

    def unreachable(self, name: str, details: str = "") -> None:
        """A state that must never be reached (``assert_unreachable``,
        ``agent.rs:664-667``)."""
        self.always(False, f"unreachable: {name}", details)

    # --- reporting --------------------------------------------------------
    def violations(self) -> Dict[str, int]:
        with self._mu:
            return {k: v[1] for k, v in self._always.items() if v[1]}

    def liveness_report(self) -> Dict[str, dict]:
        """Per-probe evaluation/hit counts; ``never_hit`` marks probes
        that were checked but never observed true."""
        with self._mu:
            return {
                k: {"checks": v[0], "hits": v[1], "never_hit": v[1] == 0}
                for k, v in self._sometimes.items()
            }

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "always": {k: {"passes": v[0], "failures": v[1]}
                           for k, v in self._always.items()},
                "sometimes": {k: {"checks": v[0], "hits": v[1]}
                              for k, v in self._sometimes.items()},
            }


REGISTRY = AssertionRegistry()


def assert_always(condition: bool, name: str, details: str = "") -> bool:
    return REGISTRY.always(condition, name, details)


def assert_sometimes(condition: bool, name: str) -> bool:
    return REGISTRY.sometimes(condition, name)


def assert_unreachable(name: str, details: str = "") -> None:
    REGISTRY.unreachable(name, details)
