"""Jittered exponential backoff iterator.

Mirrors the reference's ``backoff`` crate (``crates/backoff/src/lib.rs:7-50``):
an iterator of sleep durations that grows exponentially from ``min`` to
``max`` with multiplicative ``factor``, each step jittered by a random
fraction so a fleet of nodes does not thunder-herd. Used by the sync loop
(1 s -> 15 s, ``agent/util.rs:352-398``) and bootstrap announcements
(5 s -> 120 s, ``agent/bootstrap.rs``).
"""

from __future__ import annotations

import random
from typing import Iterator, Optional


class Backoff:
    """``iter(Backoff(...))`` yields jittered, exponentially growing delays.

    The iterator is infinite unless ``max_retries`` is set; after the cap
    it keeps yielding ``max_wait`` (like the reference's saturating
    iterator).
    """

    def __init__(
        self,
        min_wait: float = 1.0,
        max_wait: float = 15.0,
        factor: float = 2.0,
        jitter: float = 0.5,
        max_retries: Optional[int] = None,
        rng: Optional[random.Random] = None,
    ):
        assert min_wait > 0 and max_wait >= min_wait and factor >= 1.0
        assert 0.0 <= jitter <= 1.0
        self.min_wait = min_wait
        self.max_wait = max_wait
        self.factor = factor
        self.jitter = jitter
        self.max_retries = max_retries
        self._rng = rng or random.Random()

    def __iter__(self) -> Iterator[float]:
        base = self.min_wait
        n = 0
        while True:
            if self.max_retries is not None and n >= self.max_retries:
                return
            # jitter scales the delay in [1-j, 1+j], clamped to [min, max]
            scale = 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
            yield max(self.min_wait, min(self.max_wait, base * scale))
            base = min(self.max_wait, base * self.factor)
            n += 1

    def iter_no_jitter(self) -> Iterator[float]:
        base = self.min_wait
        n = 0
        while True:
            if self.max_retries is not None and n >= self.max_retries:
                return
            yield base
            base = min(self.max_wait, base * self.factor)
            n += 1
