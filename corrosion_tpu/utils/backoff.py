"""Jittered exponential backoff iterator.

Mirrors the reference's ``backoff`` crate (``crates/backoff/src/lib.rs:7-50``):
an iterator of sleep durations that grows exponentially from ``min`` to
``max`` with multiplicative ``factor``, each step jittered by a random
fraction so a fleet of nodes does not thunder-herd. Used by the sync loop
(1 s -> 15 s, ``agent/util.rs:352-398``) and bootstrap announcements
(5 s -> 120 s, ``agent/bootstrap.rs``).
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type


class Backoff:
    """``iter(Backoff(...))`` yields jittered, exponentially growing delays.

    The iterator is infinite unless ``max_retries`` is set; after the cap
    it keeps yielding ``max_wait`` (like the reference's saturating
    iterator).
    """

    def __init__(
        self,
        min_wait: float = 1.0,
        max_wait: float = 15.0,
        factor: float = 2.0,
        jitter: float = 0.5,
        max_retries: Optional[int] = None,
        rng: Optional[random.Random] = None,
    ):
        if not (min_wait > 0 and max_wait >= min_wait and factor >= 1.0):
            raise ValueError(
                f"need 0 < min_wait <= max_wait and factor >= 1.0, got "
                f"min={min_wait} max={max_wait} factor={factor}"
            )
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.min_wait = min_wait
        self.max_wait = max_wait
        self.factor = factor
        self.jitter = jitter
        self.max_retries = max_retries
        self._rng = rng or random.Random()

    def __iter__(self) -> Iterator[float]:
        base = self.min_wait
        n = 0
        while True:
            if self.max_retries is not None and n >= self.max_retries:
                return
            # jitter scales the delay in [1-j, 1+j], clamped to [min, max]
            scale = 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
            yield max(self.min_wait, min(self.max_wait, base * scale))
            base = min(self.max_wait, base * self.factor)
            n += 1

    def iter_no_jitter(self) -> Iterator[float]:
        base = self.min_wait
        n = 0
        while True:
            if self.max_retries is not None and n >= self.max_retries:
                return
            yield base
            base = min(self.max_wait, base * self.factor)
            n += 1


def retry_call(
    fn: Callable,
    *args,
    backoff: Optional[Backoff] = None,
    retry_on: Tuple[Type[BaseException], ...] = (
        ConnectionError, TimeoutError, OSError,
    ),
    sleep: Callable[[float], object] = time.sleep,
    abort: Optional[Callable[[], bool]] = None,
    on_retry: Optional[Callable[[BaseException, float, int], None]] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)`` until it succeeds, sleeping through
    one shared jittered policy between attempts — the ONE retry engine
    every poll/reconnect loop in the codebase rides (the reference's
    ``backoff`` crate is likewise the single policy behind sync retries
    and bootstrap announcements).

    - ``backoff``: delay source; default ``Backoff(max_retries=5)``. A
      ``Backoff`` without ``max_retries`` retries forever (pair it with
      ``abort``).
    - ``retry_on``: exception types that trigger a retry; anything else
      propagates immediately.
    - ``sleep``: delay function — pass an ``Event.wait`` to make waits
      interruptible by shutdown.
    - ``abort``: checked after each failure; when it returns True the
      last exception propagates instead of sleeping (shutdown must not
      sit out a 30 s delay).
    - ``on_retry(exc, delay, attempt)``: observation hook (logging,
      supervisor state).

    When the delay iterator is exhausted the last exception propagates —
    callers keep their natural ``except`` types.

    Server hints (corroguard, docs/overload.md): an exception carrying a
    numeric ``retry_after`` attribute (the parsed ``Retry-After`` of a
    503) OVERRIDES the jittered delay for that attempt — the server
    knows how overloaded it is better than the client's schedule does —
    capped at the policy's ``max_wait`` so a hostile or confused hint
    cannot park the client."""
    bo = backoff if backoff is not None else Backoff(max_retries=5)
    delays = iter(bo)
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if abort is not None and abort():
                raise
            delay = next(delays, None)
            if delay is None:
                raise
            hint = getattr(e, "retry_after", None)
            if hint is not None:
                delay = min(float(hint), bo.max_wait)
            attempt += 1
            if on_retry is not None:
                on_retry(e, delay, attempt)
            sleep(delay)
            if abort is not None and abort():
                # an interruptible sleep (Event.wait) returns early on
                # shutdown — don't launch one more full attempt after
                # the caller already tripped
                raise
