"""Lifecycle utilities: graceful shutdown, counted spawns, backoff.

The reference's layer-9 crates (SURVEY §1): ``tripwire`` — a shutdown
future tripped by SIGTERM/SIGINT or programmatically
(``crates/tripwire/src/tripwire.rs:21``); ``spawn`` — ``spawn_counted``
tracks pending tasks so shutdown can wait for all of them
(``crates/spawn/src/lib.rs:14-28``); ``backoff`` — a jittered exponential
backoff iterator (``crates/backoff/src/lib.rs:7-50``). Threads play the
role of tokio tasks in the host agent.
"""

from __future__ import annotations

import random
import signal
import socket
import threading
import time
import weakref
from typing import Iterator, Optional


class Tripwire:
    """Shutdown signal: ``tripped`` flips once; waiters unblock."""

    def __init__(self):
        self._event = threading.Event()

    def trip(self):
        self._event.set()

    @property
    def tripped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def hook_signals(self):
        """SIGTERM/SIGINT -> trip (main thread only)."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self.trip())
        return self


_pending = 0
_pending_mu = threading.Lock()
_pending_zero = threading.Condition(_pending_mu)


def spawn_counted(target, *args, name: Optional[str] = None, **kwargs) -> threading.Thread:
    """Spawn a thread counted toward ``wait_for_all_pending``."""
    global _pending
    with _pending_mu:
        _pending += 1

    def run():
        global _pending
        try:
            target(*args, **kwargs)
        finally:
            with _pending_mu:
                _pending -= 1
                if _pending == 0:
                    _pending_zero.notify_all()

    t = threading.Thread(target=run, daemon=True, name=name)
    t.start()
    return t


def pending_count() -> int:
    with _pending_mu:
        return _pending


def wait_for_all_pending(timeout: Optional[float] = None) -> bool:
    """Block until every counted spawn finished (shutdown barrier)."""
    with _pending_mu:
        return _pending_zero.wait_for(lambda: _pending == 0, timeout)


class DrainingConnMixin:
    """``socketserver.ThreadingMixIn`` companion for the serving-plane
    listeners: per-connection threads are corro- named, counted, and
    drained by the owning listener's ``stop()``.

    stdlib ``ThreadingMixIn`` with ``daemon_threads`` never tracks its
    handler threads, so ``server_close()`` joins nothing and a handler
    parked on a quiet socket (an NDJSON stream whose client went away,
    a PG connection that never sent Terminate) outlives the listener —
    exactly the leak the corrosan gate flags. Here the threads stay
    daemonic (a stuck peer cannot wedge interpreter exit) but
    ``drain_connections()`` makes shutdown deterministic: a grace join
    for handlers that exit on their own, then a socket shutdown to
    unblock any still parked in ``recv``, then a final join.
    """

    _conn_name = "corro-conn"

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._conns_mu = threading.Lock()
        self._conn_threads: "weakref.WeakSet[threading.Thread]" = (
            weakref.WeakSet())
        self._conn_socks: "weakref.WeakSet[socket.socket]" = (
            weakref.WeakSet())

    def process_request(self, request, client_address):
        global _pending
        with _pending_mu:
            _pending += 1

        def run():
            global _pending
            try:
                self.process_request_thread(request, client_address)
            finally:
                with _pending_mu:
                    _pending -= 1
                    if _pending == 0:
                        _pending_zero.notify_all()

        t = threading.Thread(target=run, daemon=True, name=self._conn_name)
        with self._conns_mu:
            self._conn_threads.add(t)
            self._conn_socks.add(request)
        t.start()

    def drain_connections(self, grace: float = 2.0,
                          timeout: float = 10.0) -> bool:
        """Join handler threads; force-close sockets of any that
        outlive ``grace``. True iff everything exited in time."""
        deadline = time.monotonic() + timeout
        grace_end = time.monotonic() + grace
        with self._conns_mu:
            threads = list(self._conn_threads)
        for t in threads:
            t.join(timeout=max(0.0, grace_end - time.monotonic()))
        if any(t.is_alive() for t in threads):
            with self._conns_mu:
                socks = list(self._conn_socks)
            for s in socks:
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass  # already closed by its handler
            for t in threads:
                if t.is_alive():
                    t.join(timeout=max(0.1, deadline - time.monotonic()))
        return not any(t.is_alive() for t in threads)


def backoff(
    base: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 60.0,
    jitter: float = 0.25,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Jittered exponential backoff delays (``backoff`` crate analog).

    Thin generator facade over :class:`corrosion_tpu.utils.backoff.Backoff`
    for call sites that just want delays."""
    from corrosion_tpu.utils.backoff import Backoff

    yield from Backoff(min_wait=base, max_wait=max_delay, factor=factor,
                       jitter=jitter, rng=rng)
