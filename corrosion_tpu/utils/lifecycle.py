"""Lifecycle utilities: graceful shutdown, counted spawns, backoff.

The reference's layer-9 crates (SURVEY §1): ``tripwire`` — a shutdown
future tripped by SIGTERM/SIGINT or programmatically
(``crates/tripwire/src/tripwire.rs:21``); ``spawn`` — ``spawn_counted``
tracks pending tasks so shutdown can wait for all of them
(``crates/spawn/src/lib.rs:14-28``); ``backoff`` — a jittered exponential
backoff iterator (``crates/backoff/src/lib.rs:7-50``). Threads play the
role of tokio tasks in the host agent.
"""

from __future__ import annotations

import random
import signal
import threading
from typing import Iterator, Optional


class Tripwire:
    """Shutdown signal: ``tripped`` flips once; waiters unblock."""

    def __init__(self):
        self._event = threading.Event()

    def trip(self):
        self._event.set()

    @property
    def tripped(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)

    def hook_signals(self):
        """SIGTERM/SIGINT -> trip (main thread only)."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, lambda *_: self.trip())
        return self


_pending = 0
_pending_mu = threading.Lock()
_pending_zero = threading.Condition(_pending_mu)


def spawn_counted(target, *args, name: Optional[str] = None, **kwargs) -> threading.Thread:
    """Spawn a thread counted toward ``wait_for_all_pending``."""
    global _pending
    with _pending_mu:
        _pending += 1

    def run():
        global _pending
        try:
            target(*args, **kwargs)
        finally:
            with _pending_mu:
                _pending -= 1
                if _pending == 0:
                    _pending_zero.notify_all()

    t = threading.Thread(target=run, daemon=True, name=name)
    t.start()
    return t


def pending_count() -> int:
    with _pending_mu:
        return _pending


def wait_for_all_pending(timeout: Optional[float] = None) -> bool:
    """Block until every counted spawn finished (shutdown barrier)."""
    with _pending_mu:
        return _pending_zero.wait_for(lambda: _pending == 0, timeout)


def backoff(
    base: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 60.0,
    jitter: float = 0.25,
    rng: Optional[random.Random] = None,
) -> Iterator[float]:
    """Jittered exponential backoff delays (``backoff`` crate analog).

    Thin generator facade over :class:`corrosion_tpu.utils.backoff.Backoff`
    for call sites that just want delays."""
    from corrosion_tpu.utils.backoff import Backoff

    yield from Backoff(min_wait=base, max_wait=max_delay, factor=factor,
                       jitter=jitter, rng=rng)
