"""Lock registry + watchdog — the race-detection analog.

The reference has no TSan/loom; its guard is a **LockRegistry** that
labels every Bookie/Booked RwLock acquisition with label/kind/state/start
time plus a watchdog that warns (and Antithesis-asserts) on locks held
longer than 10 s / 60 s (``crates/corro-types/src/agent.rs:839-1063``,
``setup.rs:183-241``). Same design here for the host agent's locks: a
registry of instrumented locks, a snapshot of who holds/waits what (the
admin socket's ``lock dump`` uses it), and a watchdog thread.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class LockEvent:
    label: str
    kind: str  # "acquire" | "held"
    started: float = field(default_factory=time.monotonic)


class TrackedLock:
    """An RLock whose acquisitions are visible to the registry."""

    def __init__(self, registry: "LockRegistry", label: str):
        self._lock = threading.RLock()
        self._registry = registry
        self._label = label

    def __enter__(self):
        tid = threading.get_ident()
        ev = LockEvent(self._label, "acquire")
        self._registry._note(tid, ev)
        self._lock.acquire()
        ev.kind = "held"
        ev.started = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        self._registry._clear(threading.get_ident(), self._label)
        return False


class LockRegistry:
    """Registry + watchdog over every TrackedLock it creates."""

    def __init__(self, warn_seconds: float = 10.0, logger=None):
        self.warn_seconds = warn_seconds
        self.logger = logger
        self._mu = threading.Lock()
        self._events: Dict[tuple, LockEvent] = {}
        self.slow_count = 0

    def lock(self, label: str) -> TrackedLock:
        return TrackedLock(self, label)

    def _note(self, tid: int, ev: LockEvent):
        with self._mu:
            self._events[(tid, ev.label)] = ev

    def _clear(self, tid: int, label: str):
        with self._mu:
            self._events.pop((tid, label), None)

    def snapshot(self) -> List[dict]:
        """Current registry state, longest-held first (admin lock dump)."""
        now = time.monotonic()
        with self._mu:
            rows = [
                {
                    "label": ev.label,
                    "kind": ev.kind,
                    "held_seconds": round(now - ev.started, 3),
                    "thread": tid,
                }
                for (tid, _), ev in self._events.items()
            ]
        rows.sort(key=lambda r: -r["held_seconds"])
        return rows

    def check(self) -> List[dict]:
        """One watchdog pass: warn on locks held/waited too long
        (the reference's 10 s warn, ``setup.rs:183-241``)."""
        slow = [r for r in self.snapshot() if r["held_seconds"] > self.warn_seconds]
        for r in slow:
            self.slow_count += 1
            if self.logger is not None:
                self.logger.warning(
                    "lock %s %s for %.1fs by thread %d",
                    r["label"], r["kind"], r["held_seconds"], r["thread"],
                )
        return slow

    def start_watchdog(self, interval: float = 1.0, stop: Optional[threading.Event] = None):
        stop = stop or threading.Event()

        def loop():
            while not stop.wait(interval):
                self.check()

        t = threading.Thread(target=loop, daemon=True,
                             name="corro-lock-watchdog")
        t.start()
        return stop
