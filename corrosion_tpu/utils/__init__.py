"""Host-side utilities: metrics, tracing, lock registry, lifecycle."""
