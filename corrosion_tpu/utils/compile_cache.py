"""Persistent XLA compilation cache for bench/profile runs.

Whole-program compiles of the 100k-node round cost ~195 s on the TPU
tunnel (PERF.md); the tunnel itself is flaky enough that bench attempts
get retried. The persistent cache makes every retry after the first pay
dispatch cost only, so a tunnel that recovers minutes into the capture
window still produces a full TPU record (the round-2 post-mortem:
both probes timed out and the bench never re-tried TPU at all).
"""

from __future__ import annotations

import os


def default_cache_dir() -> str:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, ".jax_cache")


def enable_compile_cache(path: str | None = None) -> str:
    """Idempotently point JAX's persistent compilation cache at ``path``
    (default: ``<repo>/.jax_cache``). Call before the first jit."""
    import jax

    path = path or os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
        default_cache_dir()
    # one subdir per (platform, jaxlib): CPU AOT entries written by a
    # DIFFERENT jaxlib/LLVM (the tunnel terminal's env) carry target
    # features the local host rejects ("+prefer-no-scatter ... could
    # lead to SIGILL") and poison local runs; TPU/CPU entries never
    # cross-hit anyway
    import jaxlib

    path = os.path.join(
        path,
        (os.environ.get("JAX_PLATFORMS") or "auto")
        + "-" + getattr(jaxlib, "__version__", "unknown"),
    )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache everything that took meaningful compile time; the default
    # (1 s? backend-dependent) can skip mid-sized programs
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return path
