"""Tracing: spans, slow-span warnings, W3C trace-context propagation.

The reference runs ``tracing`` everywhere with an optional OpenTelemetry
OTLP pipeline (``crates/corrosion/src/main.rs:57-150``) and propagates
trace context **across nodes inside the sync protocol** —
``SyncTraceContextV1 {traceparent, tracestate}`` implements the otel
Injector/Extractor (``crates/corro-types/src/sync.rs:33-67``), injected by
the sync client (``api/peer/mod.rs:1017-1020``) and extracted by the
server (``peer/mod.rs:1414-1416``).

Here: a dependency-free span implementation logging through ``logging``,
a W3C ``traceparent`` codec for the same cross-agent propagation (the
host sync harness passes it peer to peer), and a dynamic level filter
reloadable at runtime through the admin socket (the reference's
``LogCommand``, ``corro-admin/src/lib.rs:129-132``).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import secrets
import time
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger("corrosion_tpu")

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "corro_span", default=None
)


@dataclass
class SpanContext:
    """W3C trace-context ids (``SyncTraceContextV1`` analog)."""

    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @staticmethod
    def from_traceparent(tp: Optional[str]) -> Optional["SpanContext"]:
        if not tp:
            return None
        parts = tp.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return SpanContext(trace_id=parts[1], span_id=parts[2])


def current_span() -> Optional[SpanContext]:
    return _current_span.get()


def inject_traceparent() -> Optional[str]:
    """For the sync client: current context -> wire field."""
    ctx = current_span()
    return ctx.to_traceparent() if ctx else None


@contextlib.contextmanager
def span(name: str, traceparent: Optional[str] = None, warn_seconds: float = 1.0,
         **attrs):
    """A timed span; nests under the current one or under an extracted
    remote parent (the sync server path)."""
    parent = SpanContext.from_traceparent(traceparent) or current_span()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
    )
    token = _current_span.set(ctx)
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        dt = time.perf_counter() - t0
        _current_span.reset(token)
        level = logging.WARNING if dt > warn_seconds else logging.DEBUG
        logger.log(
            level,
            "span %s took %.3fs trace=%s span=%s %s",
            name, dt, ctx.trace_id[:8], ctx.span_id,
            " ".join(f"{k}={v}" for k, v in attrs.items()),
        )


def set_level(level: str):
    """Dynamic log filter reload (admin ``LogCommand`` analog)."""
    logger.setLevel(getattr(logging, level.upper()))
