"""Tracing: spans, slow-span warnings, W3C trace-context propagation.

The reference runs ``tracing`` everywhere with an optional OpenTelemetry
OTLP pipeline (``crates/corrosion/src/main.rs:57-150``) and propagates
trace context **across nodes inside the sync protocol** —
``SyncTraceContextV1 {traceparent, tracestate}`` implements the otel
Injector/Extractor (``crates/corro-types/src/sync.rs:33-67``), injected by
the sync client (``api/peer/mod.rs:1017-1020``) and extracted by the
server (``peer/mod.rs:1414-1416``).

Here: a dependency-free span implementation logging through ``logging``,
a W3C ``traceparent`` codec for the same cross-agent propagation (the
host sync harness passes it peer to peer), an **OTLP/JSON file
exporter** (the OTLP pipeline analog in a zero-egress environment:
spans serialize in the OpenTelemetry OTLP-JSON ``resourceSpans`` shape,
one export batch per line, consumable by any OTLP tooling), and a
dynamic level filter reloadable at runtime through the admin socket
(the reference's ``LogCommand``, ``corro-admin/src/lib.rs:129-132``).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import secrets
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

logger = logging.getLogger("corrosion_tpu")

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "corro_span", default=None
)


@dataclass
class SpanContext:
    """W3C trace-context ids (``SyncTraceContextV1`` analog)."""

    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    parent_span_id: str = ""  # 16 hex chars, "" at the trace root

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @staticmethod
    def from_traceparent(tp: Optional[str]) -> Optional["SpanContext"]:
        if not tp:
            return None
        parts = tp.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        try:  # ids must be hex or they would poison strict OTLP consumers
            int(parts[1], 16), int(parts[2], 16)
        except ValueError:
            return None
        return SpanContext(trace_id=parts[1], span_id=parts[2])


# --- OTLP/JSON file exporter ---------------------------------------------

class OtlpFileExporter:
    """Buffers finished spans and appends OTLP-JSON export batches
    (``resourceSpans`` shape) to a file — the agent's OpenTelemetry
    pipeline (``corrosion/src/main.rs:57-150``) pointed at a file
    instead of a collector socket."""

    def __init__(self, path: str, service_name: str = "corrosion-tpu",
                 flush_every: int = 64):
        self.path = path
        self.service_name = service_name
        self.flush_every = flush_every
        self._mu = threading.Lock()
        self._buf: List[dict] = []

    def export(self, span_record: dict) -> None:
        with self._mu:
            self._buf.append(span_record)
            ready = len(self._buf) >= self.flush_every
        if ready:
            self.flush()

    MAX_BUFFERED = 4096  # retained spans across failed flushes

    def flush(self) -> None:
        # detach the pending batch under the lock, write it OUTSIDE —
        # exporting threads must never stall behind a slow disk
        # (lock-discipline: no IO under self._mu). Concurrent flushes
        # may interleave batches in the file; span order within a batch
        # is preserved, which is all OTLP consumers assume.
        with self._mu:
            pending, self._buf = self._buf, []
        if not pending:
            return
        batch = {
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "corrosion_tpu"},
                    "spans": pending,
                }],
            }]
        }
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(batch) + "\n")
        except OSError:
            # keep the batch for the next flush attempt (bounded so a
            # permanently broken path cannot grow without limit)
            logger.exception("OTLP file export failed; retaining batch")
            with self._mu:
                self._buf = (pending + self._buf)[-self.MAX_BUFFERED:]


_exporter: Optional[OtlpFileExporter] = None


def configure_otlp_file(path: Optional[str], service_name: str = "corrosion-tpu"):
    """Install (or, with ``None``, remove) the OTLP file exporter."""
    global _exporter
    if _exporter is not None:
        _exporter.flush()
    _exporter = OtlpFileExporter(path, service_name) if path else None
    return _exporter


def flush_otlp() -> None:
    if _exporter is not None:
        _exporter.flush()


def current_span() -> Optional[SpanContext]:
    return _current_span.get()


def inject_traceparent() -> Optional[str]:
    """For the sync client: current context -> wire field."""
    ctx = current_span()
    return ctx.to_traceparent() if ctx else None


@contextlib.contextmanager
def span(name: str, traceparent: Optional[str] = None, warn_seconds: float = 1.0,
         **attrs):
    """A timed span; nests under the current one or under an extracted
    remote parent (the sync server path)."""
    parent = SpanContext.from_traceparent(traceparent) or current_span()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
        parent_span_id=parent.span_id if parent else "",
    )
    token = _current_span.set(ctx)
    start_ns = time.time_ns()
    t0 = time.perf_counter()
    try:
        yield ctx
    finally:
        dt = time.perf_counter() - t0
        _current_span.reset(token)
        level = logging.WARNING if dt > warn_seconds else logging.DEBUG
        logger.log(
            level,
            "span %s took %.3fs trace=%s span=%s %s",
            name, dt, ctx.trace_id[:8], ctx.span_id,
            " ".join(f"{k}={v}" for k, v in attrs.items()),
        )
        if _exporter is not None:
            _exporter.export({
                "traceId": ctx.trace_id,
                "spanId": ctx.span_id,
                **({"parentSpanId": ctx.parent_span_id}
                   if ctx.parent_span_id else {}),
                "name": name,
                "kind": 1,  # SPAN_KIND_INTERNAL
                "startTimeUnixNano": str(start_ns),
                "endTimeUnixNano": str(start_ns + int(dt * 1e9)),
                "attributes": [
                    {"key": k, "value": {"stringValue": str(v)}}
                    for k, v in attrs.items()
                ],
            })


def set_level(level: str):
    """Dynamic log filter reload (admin ``LogCommand`` analog)."""
    logger.setLevel(getattr(logging, level.upper()))
