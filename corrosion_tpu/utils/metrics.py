"""Metrics registry with Prometheus text exposition.

The reference records ~100 series through the ``metrics`` crate facade and
exposes them via a Prometheus HTTP exporter with curated buckets
(``crates/corrosion/src/command/agent.rs:114-139``; series documented in
``doc/telemetry/prometheus.md``). Here the same facade: counters, gauges,
and histograms keyed by name + sorted labels, a global registry, and a
text-format renderer; the host agent serves it at ``/metrics``.

The simulator's round ``info`` dicts map onto ``corro.*`` names via
``record_round_info`` — the analog of the metrics calls sprinkled through
the reference's loops (gossip ``broadcast/mod.rs:296-312``, changes-queue
``handlers.rs:636-638``, sync ``api/peer/mod.rs:975-987``).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Optional, Tuple

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


def _key(name: str, labels: Optional[dict]) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return name, tuple(sorted((labels or {}).items()))


class Registry:
    """Thread-safe metrics store (one per agent; a global default)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict = {}
        self._gauges: Dict = {}
        self._histograms: Dict = {}

    def counter(self, name: str, value: float = 1.0, labels: Optional[dict] = None):
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def gauge(self, name: str, value: float, labels: Optional[dict] = None):
        with self._lock:
            self._gauges[_key(name, labels)] = float(value)

    def histogram(
        self,
        name: str,
        value: float,
        labels: Optional[dict] = None,
        buckets: Tuple[float, ...] = _DEFAULT_BUCKETS,
    ):
        k = _key(name, labels)
        with self._lock:
            h = self._histograms.get(k)
            if h is None:
                h = {"buckets": buckets, "counts": [0] * (len(buckets) + 1),
                     "sum": 0.0, "count": 0}
                self._histograms[k] = h
            h["counts"][bisect.bisect_left(h["buckets"], value)] += 1
            h["sum"] += value
            h["count"] += 1

    def get_counter(self, name: str, labels: Optional[dict] = None) -> float:
        return self._counters.get(_key(name, labels), 0.0)

    def get_gauge(self, name: str, labels: Optional[dict] = None) -> Optional[float]:
        return self._gauges.get(_key(name, labels))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: dict(v, counts=list(v["counts"]))
                    for k, v in self._histograms.items()
                },
            }

    # --- Prometheus text format v0.0.4 ----------------------------------
    def render(self) -> str:
        def fmt_labels(lab, extra=()):
            items = list(lab) + list(extra)
            if not items:
                return ""
            inner = ",".join(f'{k}="{v}"' for k, v in items)
            return "{" + inner + "}"

        out = []
        snap = self.snapshot()
        for (name, lab), v in sorted(snap["counters"].items()):
            pname = name.replace(".", "_")
            out.append(f"# TYPE {pname} counter")
            out.append(f"{pname}{fmt_labels(lab)} {v}")
        for (name, lab), v in sorted(snap["gauges"].items()):
            pname = name.replace(".", "_")
            out.append(f"# TYPE {pname} gauge")
            out.append(f"{pname}{fmt_labels(lab)} {v}")
        for (name, lab), h in sorted(snap["histograms"].items()):
            pname = name.replace(".", "_")
            out.append(f"# TYPE {pname} histogram")
            acc = 0
            for b, c in zip(h["buckets"], h["counts"]):
                acc += c
                out.append(f"{pname}_bucket{fmt_labels(lab, [('le', b)])} {acc}")
            out.append(f"{pname}_bucket{fmt_labels(lab, [('le', '+Inf')])} {h['count']}")
            out.append(f"{pname}_sum{fmt_labels(lab)} {h['sum']}")
            out.append(f"{pname}_count{fmt_labels(lab)} {h['count']}")
        return "\n".join(out) + "\n"


REGISTRY = Registry()

# round-info key -> corro.* series (reference names where one exists)
_INFO_MAP = {
    "acked": ("corro.gossip.probe.acked", "counter"),
    "failed_probes": ("corro.gossip.probe.failed", "counter"),
    "refutes": ("corro.gossip.refutes", "counter"),
    "sent": ("corro.broadcast.sent", "counter"),
    "delivered": ("corro.broadcast.recv.count", "counter"),
    "fresh": ("corro.broadcast.processed.count", "counter"),
    "queued": ("corro.broadcast.pending.count", "gauge"),
    "syncs": ("corro.sync.client.count", "counter"),
    "cells_pulled": ("corro.sync.changes.recv", "counter"),
    "versions_granted": ("corro.sync.chunk.sent.versions", "counter"),
}


def record_round_info(info: dict, registry: Registry = REGISTRY):
    """Map one round's info dict onto the corro.* series."""
    for k, v in info.items():
        mapped = _INFO_MAP.get(k)
        if mapped is None:
            continue
        name, kind = mapped
        v = float(v)
        if kind == "counter":
            registry.counter(name, v)
        else:
            registry.gauge(name, v)


class RoundTimer:
    """Slow-turn watchdog: the reference warns when a runtime-loop turn
    exceeds 1 s (``broadcast/mod.rs:319-323``) and profiles statements
    slower than 1 s (``sqlite.rs:51-61``). Use as a context manager around
    host-side round dispatch."""

    def __init__(self, name: str, warn_seconds: float = 1.0,
                 registry: Registry = REGISTRY, logger=None):
        self.name = name
        self.warn_seconds = warn_seconds
        self.registry = registry
        self.logger = logger

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self.registry.histogram(f"corro.{self.name}.seconds", dt)
        if dt > self.warn_seconds:
            self.registry.counter(f"corro.{self.name}.slow", 1)
            if self.logger is not None:
                self.logger.warning(
                    "%s turn took %.3fs (> %.1fs)", self.name, dt, self.warn_seconds
                )
        return False


def start_prometheus_listener(registry: Registry, addr: str = "127.0.0.1",
                              port: int = 9090):
    """Standalone Prometheus exposition listener (the reference serves
    metrics on a dedicated telemetry address, ``command/agent.rs:114-139``).
    Returns the HTTPServer; call ``.shutdown()`` to stop."""
    import http.server

    from corrosion_tpu.utils.lifecycle import spawn_counted

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            data = registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer((addr, port), Handler)
    httpd.daemon_threads = True
    # counted + corro- named: .shutdown() drains serve_forever, so the
    # lifecycle barrier sees it finish, and leak reports name the owner
    spawn_counted(httpd.serve_forever, name="corro-prometheus")
    return httpd
