"""Metrics registry with Prometheus text exposition.

The reference records ~100 series through the ``metrics`` crate facade and
exposes them via a Prometheus HTTP exporter with curated buckets
(``crates/corrosion/src/command/agent.rs:114-139``; series documented in
``doc/telemetry/prometheus.md``). Here the same facade: counters, gauges,
and histograms keyed by name + sorted labels, a global registry, and a
text-format renderer; the host agent serves it at ``/metrics``.

The simulator's round ``info`` dicts map onto ``corro.*`` names via
``record_round_info`` — the analog of the metrics calls sprinkled through
the reference's loops (gossip ``broadcast/mod.rs:296-312``, changes-queue
``handlers.rs:636-638``, sync ``api/peer/mod.rs:975-987``).
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# Latency-appropriate default ladder (ISSUE 16): log-spaced 1/2.5/5 per
# decade from 100 µs to 10 s. The serving plane observes sub-millisecond
# host operations (a PG catalog probe, a cached read) next to multi-
# second streams — the old 1 ms floor folded everything fast into one
# bucket and made the quantile estimator blind below it.
_DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)
# public alias for callers that override per histogram and want the
# standard ladder as a base
LATENCY_BUCKETS = _DEFAULT_BUCKETS


def _key(name: str, labels: Optional[dict]) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return name, tuple(sorted((labels or {}).items()))


def _escape_label_value(v) -> str:
    """Exposition-format label escaping: a raw ``"``, ``\\`` or newline
    in a label value corrupts the whole scrape (the parser sees a torn
    line), so they must be escaped exactly per the text format v0.0.4
    spec: ``\\`` -> ``\\\\``, ``"`` -> ``\\"``, LF -> ``\\n``."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_le(b) -> str:
    """Canonical ``le`` bucket-bound rendering: raw ``str(float)`` emits
    forms like ``1.0`` where the canonical exposition (and every
    upstream client library) writes ``1``. Shortest ROUND-TRIP form
    (``repr``), not ``%g`` — ``%g``'s 6-significant-digit truncation
    could collide two distinct bounds into duplicate ``le`` labels."""
    s = repr(float(b))
    return s[:-2] if s.endswith(".0") else s


class Registry:
    """Thread-safe metrics store (one per agent; a global default)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict = {}
        self._gauges: Dict = {}
        self._histograms: Dict = {}
        # bucket ladder per histogram NAME (not per label set): every
        # label combination of one metric family must share one `le`
        # ladder or the exposition is unqueryable (and strict parsers
        # reject the family) — see histogram() below
        self._hist_buckets: Dict[str, Tuple[float, ...]] = {}

    def counter(self, name: str, value: float = 1.0, labels: Optional[dict] = None):
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def gauge(self, name: str, value: float, labels: Optional[dict] = None):
        with self._lock:
            self._gauges[_key(name, labels)] = float(value)

    def gauge_add(self, name: str, delta: float, labels: Optional[dict] = None):
        """Additive gauge update (in-flight request counts and other
        up/down levels; Prometheus gauges support both set and add)."""
        k = _key(name, labels)
        with self._lock:
            self._gauges[k] = self._gauges.get(k, 0.0) + float(delta)

    def histogram(
        self,
        name: str,
        value: float,
        labels: Optional[dict] = None,
        buckets: Tuple[float, ...] = _DEFAULT_BUCKETS,
    ):
        """Observe ``value``. ``buckets`` overrides the default ladder —
        but the FIRST observation of a name fixes the ladder for every
        label set of that family: per-{route,method,code} histograms
        (ISSUE 16) create label sets lazily, and mixing ladders within
        one family would render inconsistent ``le`` label sets for the
        same metric (the latent exposition gap the render-roundtrip test
        pins)."""
        k = _key(name, labels)
        with self._lock:
            eff = self._hist_buckets.setdefault(name, tuple(buckets))
            h = self._histograms.get(k)
            if h is None:
                h = {"buckets": eff, "counts": [0] * (len(eff) + 1),
                     "sum": 0.0, "count": 0}
                self._histograms[k] = h
            h["counts"][bisect.bisect_left(h["buckets"], value)] += 1
            h["sum"] += value
            h["count"] += 1

    def get_counter(self, name: str, labels: Optional[dict] = None) -> float:
        return self._counters.get(_key(name, labels), 0.0)

    def get_gauge(self, name: str, labels: Optional[dict] = None) -> Optional[float]:
        return self._gauges.get(_key(name, labels))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: dict(v, counts=list(v["counts"]))
                    for k, v in self._histograms.items()
                },
            }

    # --- Prometheus text format v0.0.4 ----------------------------------
    def render(self) -> str:
        def fmt_labels(lab, extra=()):
            items = list(lab) + list(extra)
            if not items:
                return ""
            inner = ",".join(
                f'{k}="{_escape_label_value(v)}"' for k, v in items
            )
            return "{" + inner + "}"

        out = []
        # ONE TYPE line per metric name: labeled samples of the same
        # metric (e.g. the per-table corro.mem.table.bytes gauges) share
        # it — a repeated TYPE line makes strict expfmt parsers reject
        # the whole scrape
        typed: set = set()

        def type_line(pname: str, kind: str) -> None:
            if pname not in typed:
                typed.add(pname)
                out.append(f"# TYPE {pname} {kind}")

        snap = self.snapshot()
        for (name, lab), v in sorted(snap["counters"].items()):
            pname = name.replace(".", "_")
            type_line(pname, "counter")
            out.append(f"{pname}{fmt_labels(lab)} {v}")
        for (name, lab), v in sorted(snap["gauges"].items()):
            pname = name.replace(".", "_")
            type_line(pname, "gauge")
            out.append(f"{pname}{fmt_labels(lab)} {v}")
        for (name, lab), h in sorted(snap["histograms"].items()):
            pname = name.replace(".", "_")
            type_line(pname, "histogram")
            acc = 0
            for b, c in zip(h["buckets"], h["counts"]):
                acc += c
                out.append(
                    f"{pname}_bucket"
                    f"{fmt_labels(lab, [('le', _fmt_le(b))])} {acc}"
                )
            out.append(f"{pname}_bucket{fmt_labels(lab, [('le', '+Inf')])} {h['count']}")
            out.append(f"{pname}_sum{fmt_labels(lab)} {h['sum']}")
            out.append(f"{pname}_count{fmt_labels(lab)} {h['count']}")
        return "\n".join(out) + "\n"


REGISTRY = Registry()


# --- snapshot-side quantile estimation (ISSUE 16) ------------------------
def histogram_quantile(h: dict, q: float) -> float:
    """Estimate the ``q``-quantile (0 < q <= 1) of one snapshot
    histogram dict (``{"buckets", "counts", "count", ...}``) by linear
    interpolation inside the owning bucket — the same model PromQL's
    ``histogram_quantile`` applies server-side. Values in the overflow
    bucket clamp to the top bound (the ladder cannot see past it).
    Returns 0.0 for an empty histogram."""
    count = h.get("count", 0)
    if count <= 0:
        return 0.0
    target = q * count
    acc = 0.0
    lo = 0.0
    for b, c in zip(h["buckets"], h["counts"]):
        if c and acc + c >= target:
            return lo + (float(b) - lo) * (target - acc) / c
        acc += c
        lo = float(b)
    # remaining mass sits in the overflow bucket: clamp to the top bound
    return float(h["buckets"][-1]) if h["buckets"] else lo


def aggregate_histograms(snap: dict, name: str) -> dict:
    """Fold every label set of one histogram family in a snapshot (or a
    ``parse_exposition`` result) into a single histogram dict. All label
    sets of a family share one bucket ladder by construction
    (:meth:`Registry.histogram` pins the ladder on first observation),
    so the per-bucket counts sum directly. Returns an empty histogram
    when the family has no samples — ``histogram_quantile`` of the
    result is then 0.0. The admission controller derives live
    Retry-After hints through this (docs/overload.md)."""
    agg = {"buckets": (), "counts": [], "sum": 0.0, "count": 0}
    for (n, _lab), h in snap.get("histograms", {}).items():
        if n != name:
            continue
        if not agg["buckets"]:
            agg["buckets"] = tuple(h["buckets"])
            agg["counts"] = [0] * len(h["counts"])
        for i, c in enumerate(h["counts"]):
            agg["counts"][i] += c
        agg["sum"] += h["sum"]
        agg["count"] += h["count"]
    return agg


def quantiles_from_histogram(
    h: dict, qs: Sequence[float] = (0.5, 0.95, 0.99)
) -> Dict[str, float]:
    """``{"p50": ..., "p95": ..., "p99": ...}`` from one snapshot
    histogram — the server-side half of the load-harness report."""
    out = {}
    for q in qs:
        out[f"p{int(round(q * 100))}"] = histogram_quantile(h, q)
    return out


# --- Prometheus text-format parsing --------------------------------------
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(v: str) -> str:
    return (
        v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def parse_exposition(text: str) -> dict:
    """Parse Prometheus text format v0.0.4 (as :meth:`Registry.render`
    emits it) back into a snapshot-shaped dict.

    Names come back in exposition form (dots already folded to
    underscores — the fold is lossy, so the original dotted name is not
    recoverable); histogram cumulative ``_bucket`` samples are
    de-accumulated back into per-bucket counts. The load harness scrapes
    ``/metrics`` through this to compare server-side request counts with
    its own client-side tallies, and the render-roundtrip test pins
    ``parse_exposition(reg.render())`` == ``reg.snapshot()`` (modulo the
    name fold)."""
    kinds: Dict[str, str] = {}
    counters: Dict = {}
    gauges: Dict = {}
    hist_raw: Dict = {}  # (name, labels) -> {"le": [...], "sum":, "count":}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name = m.group("name")
        raw_labels = m.group("labels") or ""
        labels = [
            (k, _unescape_label_value(v))
            for k, v in _LABEL_RE.findall(raw_labels)
        ]
        value = float(m.group("value"))
        base = None
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and kinds.get(
                    name[: -len(suffix)]) == "histogram":
                base = name[: -len(suffix)]
                break
        if base is not None:
            plain = tuple(kv for kv in labels if kv[0] != "le")
            h = hist_raw.setdefault(
                (base, plain), {"le": [], "sum": 0.0, "count": 0})
            if name.endswith("_bucket"):
                le = dict(labels).get("le", "+Inf")
                bound = float("inf") if le == "+Inf" else float(le)
                h["le"].append((bound, value))
            elif name.endswith("_sum"):
                h["sum"] = value
            else:
                h["count"] = int(value)
        elif kinds.get(name) == "gauge":
            gauges[(name, tuple(labels))] = value
        else:
            counters[(name, tuple(labels))] = value
    histograms: Dict = {}
    for key, h in hist_raw.items():
        les = sorted(h["le"])
        buckets = tuple(b for b, _ in les if b != float("inf"))
        counts: List[int] = []
        prev = 0.0
        for _, cum in les:
            counts.append(int(cum - prev))
            prev = cum
        if len(counts) == len(buckets):  # no +Inf sample seen
            counts.append(int(h["count"] - prev))
        histograms[key] = {"buckets": buckets, "counts": counts,
                           "sum": h["sum"], "count": h["count"]}
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}

# round-info key -> corro.* series (reference names where one exists).
# MUST cover every key ``sim_step``/``scale_sim_step`` emit — an
# unmapped key silently vanishes from /metrics; the drift guard
# (tests/test_obs.py::test_info_map_covers_every_emitted_key) diffs
# this table against the live info dicts so a new sim counter cannot
# disappear unnoticed.
_INFO_MAP = {
    "acked": ("corro.gossip.probe.acked", "counter"),
    "failed_probes": ("corro.gossip.probe.failed", "counter"),
    "refutes": ("corro.gossip.refutes", "counter"),
    "sent": ("corro.broadcast.sent", "counter"),
    "delivered": ("corro.broadcast.recv.count", "counter"),
    "fresh": ("corro.broadcast.processed.count", "counter"),
    "queued": ("corro.broadcast.pending.count", "gauge"),
    "tx_completed": ("corro.broadcast.tx.completed", "counter"),
    "clock_drift_rejects": ("corro.broadcast.drift.rejects", "counter"),
    "syncs": ("corro.sync.client.count", "counter"),
    "cells_pulled": ("corro.sync.changes.recv", "counter"),
    "versions_granted": ("corro.sync.chunk.sent.versions", "counter"),
    "serve_rejects": ("corro.sync.server.rejects", "counter"),
    # per-shard activity occupancy (ISSUE 11): node counts of the
    # device-computed masks the active-set round variant will gate on
    # (sim/scale_step.activity_masks) — gauges, they are occupancy
    # levels, not monotone totals
    "active_bcast": ("corro.activity.bcast.nodes", "gauge"),
    "active_partials": ("corro.activity.partials.nodes", "gauge"),
    "active_sync": ("corro.activity.sync.nodes", "gauge"),
    "active_probes": ("corro.activity.swim.nodes", "gauge"),
    # corroquiet active-set rounds (ISSUE 19): emitted by the quiet
    # step only (``scale_sim_step_quiet``); a dense round emits none of
    # these, and the segmented runner zero-fills mixed soaks
    "quiet_round": ("corro.quiet.rounds.cheap", "counter"),
    "quiet_backstop": ("corro.quiet.backstop.fires", "counter"),
    "quiet_shards_skipped": ("corro.quiet.shards.skipped", "counter"),
    "quiet_shards_quiet": ("corro.quiet.shards.quiet", "gauge"),
    "quiet_nodes_active": ("corro.quiet.nodes.active", "gauge"),
}


def info_series() -> dict:
    """The info-key -> (series, kind) table (read-only copy) — the obs
    metrics bridge folds per-segment info sums/lasts through it."""
    return dict(_INFO_MAP)


def record_round_info(info: dict, registry: Registry = REGISTRY):
    """Map one round's info dict onto the corro.* series."""
    for k, v in info.items():
        mapped = _INFO_MAP.get(k)
        if mapped is None:
            continue
        name, kind = mapped
        v = float(v)
        if kind == "counter":
            registry.counter(name, v)
        else:
            registry.gauge(name, v)


class RoundTimer:
    """Slow-turn watchdog: the reference warns when a runtime-loop turn
    exceeds 1 s (``broadcast/mod.rs:319-323``) and profiles statements
    slower than 1 s (``sqlite.rs:51-61``). Use as a context manager around
    host-side round dispatch."""

    def __init__(self, name: str, warn_seconds: float = 1.0,
                 registry: Registry = REGISTRY, logger=None):
        self.name = name
        self.warn_seconds = warn_seconds
        self.registry = registry
        self.logger = logger

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self.registry.histogram(f"corro.{self.name}.seconds", dt)
        if dt > self.warn_seconds:
            self.registry.counter(f"corro.{self.name}.slow", 1)
            if self.logger is not None:
                self.logger.warning(
                    "%s turn took %.3fs (> %.1fs)", self.name, dt, self.warn_seconds
                )
        return False


def start_prometheus_listener(registry: Registry, addr: str = "127.0.0.1",
                              port: int = 9090):
    """Standalone Prometheus exposition listener (the reference serves
    metrics on a dedicated telemetry address, ``command/agent.rs:114-139``).

    ``port=0`` binds an ephemeral port; the actually-bound port is on
    the returned server as ``bound_port`` (tests and the obs soak
    observer scrape it without racing for a fixed port). Returns the
    HTTPServer; ``.shutdown()`` stops the loop, JOINS the counted
    ``corro-prometheus`` thread (so the leak gate sees it exit), and
    closes the listening socket."""
    import http.server

    from corrosion_tpu.utils.lifecycle import spawn_counted

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            data = registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer((addr, port), Handler)
    httpd.daemon_threads = True
    httpd.bound_port = httpd.server_address[1]
    # counted + corro- named: .shutdown() drains serve_forever, so the
    # lifecycle barrier sees it finish, and leak reports name the owner
    thread = spawn_counted(httpd.serve_forever, name="corro-prometheus")
    orig_shutdown = httpd.shutdown

    def _shutdown():
        orig_shutdown()
        thread.join(timeout=10)
        httpd.server_close()

    httpd.shutdown = _shutdown
    return httpd
