"""Hybrid logical clock (HLC).

Mirrors the reference's use of ``uhlc`` (``agent/setup.rs:96-101``): a
clock whose timestamps combine wall time with a logical counter so they
are totally ordered, monotonic, and close to physical time. The agent
stamps every local write (``crsql_set_ts``, ``public/mod.rs:88-100``) and
folds in every remote timestamp it sees — from changes
(``handlers.rs:689-701``) and sync handshakes (``peer/mod.rs:1439-1458``)
— rejecting remote clocks that are too far ahead (max drift 300 ms,
``setup.rs:100``).

Timestamp encoding follows uhlc/NTP64: the physical part in the high bits
at micro-ish resolution, a logical counter in the low 16 bits.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, NamedTuple

LOGICAL_BITS = 16
LOGICAL_MASK = (1 << LOGICAL_BITS) - 1
DEFAULT_MAX_DELTA_MS = 300.0  # setup.rs:100


class Timestamp(NamedTuple):
    """(time, id): totally ordered, id breaks ties (uhlc semantics)."""

    ntp: int  # physical micros << 16 | logical counter
    actor: int

    @property
    def physical_us(self) -> int:
        return self.ntp >> LOGICAL_BITS

    @property
    def logical(self) -> int:
        return self.ntp & LOGICAL_MASK

    def __str__(self) -> str:
        return f"{self.physical_us}.{self.logical}@{self.actor}"


class ClockDriftError(Exception):
    """Remote timestamp exceeds the configured max drift."""


class HLClock:
    """Thread-safe hybrid logical clock for one actor."""

    def __init__(
        self,
        actor: int,
        max_delta_ms: float = DEFAULT_MAX_DELTA_MS,
        now_us: Callable[[], int] = lambda: time.time_ns() // 1000,
    ):
        self.actor = actor
        self.max_delta_us = int(max_delta_ms * 1000)
        self._now_us = now_us
        self._last = 0  # last issued ntp value
        self._mu = threading.Lock()

    def new_timestamp(self) -> Timestamp:
        """Issue a strictly monotonic local timestamp."""
        with self._mu:
            phys = self._now_us() << LOGICAL_BITS
            self._last = max(self._last + 1, phys)
            return Timestamp(self._last, self.actor)

    def peek(self) -> Timestamp:
        with self._mu:
            return Timestamp(self._last, self.actor)

    def update_with_timestamp(self, ts: Timestamp) -> None:
        """Fold in a remote timestamp; raise if it is too far ahead.

        Matches uhlc ``update_with_timestamp``: the local clock jumps
        forward to stay >= every observed remote stamp, but refuses stamps
        more than ``max_delta`` ahead of physical time (the reference logs
        and drops those, ``handlers.rs:696-701``)."""
        now_phys = self._now_us()
        if ts.physical_us > now_phys + self.max_delta_us:
            raise ClockDriftError(
                f"remote ts {ts} is {(ts.physical_us - now_phys) / 1000:.1f} ms "
                f"ahead (max {self.max_delta_us / 1000:.0f} ms)"
            )
        with self._mu:
            self._last = max(self._last, ts.ntp)
