"""Flight-recorder observability plane for the soak pipeline (ISSUE 11).

The reference agent records ~100 Prometheus series and streams OTLP
traces continuously (``doc/telemetry/prometheus.md``,
``command/agent.rs:114-139``); our flagship execution path — the
sharded/donated/fused segmented soak — was a black box while running:
per-round ``infos`` and pipeline ``stats`` only surfaced in
``SoakResult`` after the run ended. This package is the live telemetry
plane threaded through ``resilience.segments.run_segmented`` /
``Agent.soak``:

- :mod:`~corrosion_tpu.obs.flight` — the **FlightRecorder** (crash-safe
  line-atomic NDJSON segment records + :func:`replay_flight_record`)
  and the **SoakObserver** that bundles recorder + metrics bridge +
  optional standalone Prometheus listener per run;
- :mod:`~corrosion_tpu.obs.bridge` — the **live metrics bridge**
  draining each segment's infos into a ``utils.metrics.Registry``
  (reusing the ``record_round_info`` mapping) plus the ``corro.soak.*``
  series, so ``/metrics`` shows a soak advancing in real time;
- :mod:`~corrosion_tpu.obs.memory` — per-table nbytes audit of
  ``ScaleSimState``/``SimState`` (O(N·M) vs O(N) classification),
  memory gauges, and the bench ``hbm_bytes`` field — the measurement
  substrate of the 1M-node memory-budget audit;
- :mod:`~corrosion_tpu.obs.spans` — pipeline spans (+ optional
  ``jax.profiler`` annotations) around segment dispatch, shard drain,
  and checkpoint serialize.

Activity-occupancy telemetry (the quiescence oracle's masks) lives
device-side in :func:`corrosion_tpu.sim.scale_step.activity_masks`; the
``active_*`` info keys it emits flow through this plane like every
other round counter.

Config surface: ``[obs] flight_path / prometheus_port / jax_profile``
(``config.ObsConfig``), threaded config → ``run_segmented`` → ``Agent``
→ CLI ``soak --flight`` → bench. Series catalog + NDJSON schema:
``docs/observability.md``.
"""

from corrosion_tpu.obs.bridge import MetricsBridge
from corrosion_tpu.obs.flight import (
    FLIGHT_SCHEMA_VERSION,
    FlightRecorder,
    SoakObserver,
    make_observer,
    replay_flight_record,
)
from corrosion_tpu.obs.memory import (
    memory_report,
    publish_memory_gauges,
    state_bytes,
)
from corrosion_tpu.obs.spans import pipeline_span

__all__ = [
    "FLIGHT_SCHEMA_VERSION",
    "FlightRecorder",
    "MetricsBridge",
    "SoakObserver",
    "make_observer",
    "memory_report",
    "pipeline_span",
    "publish_memory_gauges",
    "replay_flight_record",
    "state_bytes",
]
