"""Pipeline spans: host tracing + optional device-profiler annotation.

The soak pipeline's interesting overlap — checkpoint serialize/IO
riding the background writer while the next segment's scan runs — is
invisible in a plain log. Wrapping the three phases (segment dispatch,
shard drain, serialize) in spans makes it visible twice over: the OTLP
file export (``utils.tracing.configure_otlp_file``) shows the
wall-clock overlap to any OTLP viewer, and — when ``[obs]
jax_profile`` asks — a ``jax.profiler.TraceAnnotation`` labels the same
region in a device profile so XLA tracer timelines line up with the
host-side story.
"""

from __future__ import annotations

import contextlib

from corrosion_tpu.utils import tracing


@contextlib.contextmanager
def pipeline_span(name: str, jax_profile: bool = False, **attrs):
    """A :func:`corrosion_tpu.utils.tracing.span` that, with
    ``jax_profile=True``, also annotates the region for ``jax.profiler``
    traces. The annotation import is deferred so the common
    (profile-off) path never touches the profiler machinery."""
    with tracing.span(name, **attrs) as ctx:
        if jax_profile:
            import jax.profiler

            with jax.profiler.TraceAnnotation(name):
                yield ctx
        else:
            yield ctx
