"""FlightRecorder: crash-safe NDJSON telemetry + the per-run observer.

A multi-hour soak that dies mid-run used to leave NOTHING — infos and
stats lived in the ``SoakResult`` that never materialized. The flight
recorder is the black box: one JSON object per line, appended to an
``O_APPEND`` fd with a single ``write`` per record (line-atomic — a
crash can tear at most the final line, and :func:`replay_flight_record`
skips an unparseable tail), so whatever survives the crash is a
complete, parseable prefix of the run.

Record kinds (schema ``FLIGHT_SCHEMA_VERSION``, catalog in
``docs/observability.md``):

- ``header`` — one per run: mode, shapes, workload span, donation /
  async-checkpoint / fused provenance, config-identity digest, HBM
  footprint of the starting carry;
- ``segment`` — one per completed segment: absolute round window,
  wall seconds, rounds/s, per-segment info sums + last-round levels,
  the CUMULATIVE pipeline stats snapshot (stall/io/serialize/drain
  bytes, donated segments), and the carry's HBM bytes;
- ``end`` — the run's final stats (writer totals included), completed
  rounds, aborted flag, newest checkpoint.

Appends are staged under a lock and drained by a counted
``corro-obs-flight`` thread (never blocking the hot loop on disk);
:meth:`FlightRecorder.close` drains and joins, so corrosan's leak gate
owns the thread's lifetime.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import List, Optional

import numpy as np

from corrosion_tpu.utils.tracing import logger

FLIGHT_SCHEMA_VERSION = 1

#: keys of ``SoakResult.stats`` that accumulate (sums/counts) — the
#: bridge deltas these per segment; max-tracked and constant keys are
#: snapshotted whole instead
_STATS_SUM_KEYS = (
    "segments", "donated_segments", "carry_reuploads", "ckpt_stall_s",
    "ckpt_io_s", "ckpt_written", "ckpt_overlapped_segments",
    "ckpt_drain_bytes", "ckpt_serialize_s", "quiet_segments",
)


def serve_snapshot(registry) -> dict:
    """The serving plane's shed story as one flat JSON-safe dict: every
    ``corro.admission.*`` counter/gauge plus ``corro.subs.shed_total``
    from ``registry`` (label sets flattened into the key,
    ``name{k=v,...}``). Segment/end flight records embed this so an
    NDJSON replay of an overloaded soak shows WHEN admission started
    rejecting and how much the subscription plane shed — not just that
    the run got slow (docs/observability.md, "Serving plane")."""
    if registry is None:
        return {}
    snap = registry.snapshot()
    out = {}
    for section in ("counters", "gauges"):
        for (name, labels), value in snap.get(section, {}).items():
            # match the admission family structurally (prefix split, not
            # a series-name literal) so the docs-sync catalog gate only
            # sees real series names in this module
            if not (name.split(".")[:2] == ["corro", "admission"]
                    or name == "corro.subs.shed_total"):
                continue
            key = name
            if labels:
                key += "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"
            out[key] = value
    return out


def config_digest(cfg) -> str:
    """Stable digest of the checkpoint-identity view of a sim config —
    lets a replay assert which run a flight record belongs to without
    embedding the whole config in every header."""
    from corrosion_tpu.checkpoint import config_identity

    blob = json.dumps(config_identity(cfg), sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class FlightRecorder:
    """Append-only, line-atomic NDJSON recorder.

    Thread-safe: ``record`` stages the encoded line under ``_mu`` and
    wakes the flush thread; all file IO happens on the flush thread,
    outside the lock (lock-discipline: no IO under ``_mu``). IO errors
    degrade to dropping records with a logged exception — telemetry
    must never kill the soak it observes."""

    def __init__(self, path: str):
        self.path = path
        self._mu = threading.Lock()
        self._buf: List[str] = []
        self._closed = False
        self._wake = threading.Event()
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        from corrosion_tpu.utils.lifecycle import spawn_counted

        self._thread = spawn_counted(self._run, name="corro-obs-flight")

    def record(self, kind: str, **fields) -> None:
        rec = {"kind": kind, "ts": round(time.time(), 3), **fields}
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        with self._mu:
            if self._closed:
                return
            self._buf.append(line)
        self._wake.set()

    def _drain(self):
        with self._mu:
            # clear-before-detach under the lock: a producer's set()
            # either lands before the clear (its record is in this
            # batch) or after (the next wait wakes immediately)
            self._wake.clear()
            batch, self._buf = self._buf, []
            closed = self._closed
        return batch, closed

    def _run(self) -> None:
        fd = None
        try:
            while True:
                self._wake.wait(timeout=0.2)
                batch, closed = self._drain()
                if batch:
                    try:
                        if fd is None:
                            fd = os.open(
                                self.path,
                                os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                                0o644,
                            )
                        for line in batch:
                            # ONE write per record: the line is the
                            # atomicity unit a crash can observe
                            os.write(fd, line.encode())
                    except OSError:
                        logger.exception(
                            "flight-record append to %s failed; dropped "
                            "%d record(s)", self.path, len(batch),
                        )
                if closed and not batch:
                    return
        finally:
            if fd is not None:
                os.close(fd)

    def close(self) -> None:
        """Drain pending records and join the flush thread."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        self._thread.join(timeout=10)


def replay_flight_record(path: str) -> dict:
    """Parse a flight-record NDJSON file into a run summary.

    Torn/garbage lines (the crash tail) are counted in
    ``skipped_lines`` and skipped — everything before them replays.
    ``stats`` is the newest cumulative pipeline-stats snapshot (the
    ``end`` record's when the run closed cleanly, else the last
    segment's): on the segments both saw, it matches the live run's
    ``SoakResult.stats`` field for field."""
    headers: List[dict] = []
    segments: List[dict] = []
    end: Optional[dict] = None
    skipped = 0
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                skipped += 1
                continue
            kind = rec.get("kind")
            if kind == "header":
                headers.append(rec)
            elif kind == "segment":
                segments.append(rec)
            elif kind == "end":
                end = rec
    rounds = sum(int(s.get("rounds", 0)) for s in segments)
    seconds = sum(float(s.get("seconds", 0.0)) for s in segments)
    info_sum: dict = {}
    for s in segments:
        for k, v in (s.get("info_sum") or {}).items():
            info_sum[k] = info_sum.get(k, 0.0) + float(v)
    stats = dict((end or {}).get("stats")
                 or (segments[-1].get("stats") if segments else {}) or {})
    # newest admission/shed snapshot (cumulative, like stats): the
    # end record's when present, else the last segment that carried one
    serve: dict = {}
    if end is not None and "serve" in end:
        serve = dict(end.get("serve") or {})
    else:
        for s in reversed(segments):
            if "serve" in s:
                serve = dict(s.get("serve") or {})
                break
    completed = (
        int(end["completed_rounds"]) if end is not None
        else int(segments[-1]["hi"]) if segments
        else int(headers[-1]["start_round"]) if headers
        else 0
    )
    return {
        "schema": max((int(h.get("schema", 0)) for h in headers),
                      default=0),
        "runs": len(headers),
        "header": headers[-1] if headers else None,
        "segments": len(segments),
        "completed_rounds": completed,
        "rounds": rounds,
        "seconds": round(seconds, 6),
        "rounds_per_s": round(rounds / seconds, 3) if seconds > 0 else 0.0,
        "info_sum": info_sum,
        "stats": stats,
        "serve": serve,
        "hbm_bytes": (int(segments[-1].get("hbm_bytes", 0)) if segments
                      else int(headers[-1].get("hbm_bytes", 0))
                      if headers else 0),
        "ended": end is not None,
        "aborted": bool(end.get("aborted")) if end is not None else None,
        "crashed": bool(end.get("crashed")) if end is not None else None,
        "checkpoint": (end or {}).get("checkpoint"),
        "skipped_lines": skipped,
    }


def _json_safe_stats(stats: dict) -> dict:
    return {k: v for k, v in stats.items()
            if isinstance(v, (bool, int, float, str)) or v is None}


class SoakObserver:
    """One soak run's telemetry plane: flight recorder + metrics bridge
    + optional standalone Prometheus listener + span/profiler config.

    ``run_segmented`` drives the ``open_run``/``on_segment``/``end_run``
    hooks; the OWNER (Agent.soak, the CLI, the bench, a test) creates
    and :meth:`close`\\ s the observer — one observer may span a run and
    its resume (each appends its own header)."""

    def __init__(self, flight: Optional[FlightRecorder] = None,
                 registry=None, listener=None, jax_profile: bool = False,
                 serve_registry=None):
        self.flight = flight
        self.registry = registry
        self.listener = listener  # start_prometheus_listener's server
        self.jax_profile = bool(jax_profile)
        # the serving plane's registry (the agent's / the overload
        # guard's): when set, segment + end records carry its
        # admission/shed snapshot (:func:`serve_snapshot`)
        self.serve_registry = serve_registry
        from corrosion_tpu.obs.bridge import MetricsBridge

        self.bridge = (MetricsBridge(registry)
                       if registry is not None else None)
        self._prev_stats: dict = {}
        self._seg_t0 = 0.0

    # --- run_segmented hooks --------------------------------------------
    def open_run(self, *, cfg, mode: str, total_rounds: int,
                 start_round: int, segment_rounds: int, stats: dict,
                 state) -> None:
        from corrosion_tpu.obs.memory import (
            memory_report,
            publish_memory_gauges,
            state_bytes,
        )

        self._prev_stats = dict(stats)
        self._seg_t0 = time.perf_counter()
        hbm = state_bytes(state)
        if self.registry is not None:
            publish_memory_gauges(
                memory_report(state, getattr(cfg, "n_nodes", None)),
                self.registry,
            )
        if self.flight is not None:
            self.flight.record(
                "header",
                schema=FLIGHT_SCHEMA_VERSION,
                mode=mode,
                n_nodes=int(getattr(cfg, "n_nodes", 0)),
                start_round=int(start_round),
                total_rounds=int(total_rounds),
                segment_rounds=int(segment_rounds),
                donate=bool(stats.get("donate")),
                async_checkpoint=bool(stats.get("async_checkpoint")),
                fused_mode=stats.get("fused_mode"),
                pallas_fused=bool(stats.get("pallas_fused")),
                quiet_mode=stats.get("quiet_mode"),
                config_digest=config_digest(cfg),
                hbm_bytes=hbm,
            )

    def on_segment(self, *, seg_index: int, lo: int, hi: int, infos,
                   stats: dict, state) -> None:
        from corrosion_tpu.obs.memory import state_bytes

        now = time.perf_counter()
        seconds = now - self._seg_t0
        self._seg_t0 = now
        rounds = hi - lo
        info_sum = {k: float(np.asarray(v).sum())
                    for k, v in (infos or {}).items()}
        info_last = {k: float(np.asarray(v)[-1])
                     for k, v in (infos or {}).items()}
        delta = {
            k: stats.get(k, 0) - self._prev_stats.get(k, 0)
            for k in _STATS_SUM_KEYS
        }
        self._prev_stats = dict(stats)
        if self.bridge is not None:
            self.bridge.on_segment(
                completed_rounds=hi, rounds=rounds, seconds=seconds,
                info_sum=info_sum, info_last=info_last, stats_delta=delta,
            )
        if self.flight is not None:
            extra = {}
            if self.serve_registry is not None:
                extra["serve"] = serve_snapshot(self.serve_registry)
            self.flight.record(
                "segment",
                seg=int(seg_index),
                lo=int(lo),
                hi=int(hi),
                rounds=int(rounds),
                seconds=round(seconds, 6),
                rounds_per_s=(round(rounds / seconds, 3)
                              if seconds > 0 else 0.0),
                donated=bool(delta.get("donated_segments", 0) > 0),
                info_sum=info_sum,
                info_last=info_last,
                stats=_json_safe_stats(stats),
                hbm_bytes=state_bytes(state),
                **extra,
            )

    def end_run(self, *, stats: dict, completed_rounds: int,
                aborted: bool, crashed: bool = False,
                checkpoint: Optional[str] = None) -> None:
        if self.bridge is not None:
            self.bridge.on_end(completed_rounds=completed_rounds,
                               aborted=aborted)
        if self.flight is not None:
            extra = {}
            if self.serve_registry is not None:
                extra["serve"] = serve_snapshot(self.serve_registry)
            self.flight.record(
                "end",
                completed_rounds=int(completed_rounds),
                aborted=bool(aborted),
                crashed=bool(crashed),
                checkpoint=checkpoint,
                stats=_json_safe_stats(stats),
                **extra,
            )

    # --- lifecycle ------------------------------------------------------
    def close(self) -> None:
        if self.flight is not None:
            self.flight.close()
        if self.listener is not None:
            self.listener.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def make_observer(obs_cfg, registry=None,
                  serve_registry=None) -> Optional[SoakObserver]:
    """Build a :class:`SoakObserver` from a ``config.ObsConfig`` — the
    config → pipeline seam. Returns None when the section asks for
    nothing (no flight path, listener disabled, profiling off), so
    callers thread ``obs=make_observer(cfg.obs, ...)`` unconditionally.

    ``registry=None`` with a listener (or flight path) enabled uses a
    fresh private registry; pass the agent's to surface the soak on its
    ``/metrics`` route too."""
    flight_path = getattr(obs_cfg, "flight_path", "") or ""
    prom_port = int(getattr(obs_cfg, "prometheus_port", -1))
    jax_profile = bool(getattr(obs_cfg, "jax_profile", False))
    if not flight_path and prom_port < 0 and not jax_profile:
        return None
    from corrosion_tpu.utils.metrics import (
        Registry,
        start_prometheus_listener,
    )

    if registry is None:
        registry = Registry()
    # recorder BEFORE listener: a recorder-init failure (unwritable
    # flight path) must not strand an already-bound listener socket and
    # its corro-prometheus thread with no handle to shut them down
    flight = FlightRecorder(flight_path) if flight_path else None
    listener = None
    if prom_port >= 0:
        try:
            listener = start_prometheus_listener(registry, port=prom_port)
        except BaseException:
            if flight is not None:
                flight.close()
            raise
    return SoakObserver(flight=flight, registry=registry,
                        listener=listener, jax_profile=jax_profile,
                        serve_registry=serve_registry)
