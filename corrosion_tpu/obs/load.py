"""corroload: the seeded concurrent-client load harness (ISSUE 16).

The reference serves whole fleets over its HTTP API, subscriptions and
PG-wire server; this repo's serving plane had only ever seen single
test clients. ``run_load`` drives it the way a fleet would — N open-loop
writers (``POST /v1/transactions``), M NDJSON subscribers measuring
write-commit -> delivery lag client-side, and K PG-wire readers speaking
the v3 simple-query protocol — against an in-process devcluster rig
(Agent + Database + ApiServer + PgServer), and reports client-side
p50/p95/p99 per op class, sustained QPS, and error/503 counts as a
``BENCH_SERVE`` record.

Determinism: the op streams come from :func:`plan_ops`, a pure function
of the seed — the record carries the plan digest that pins them. Wall
times obviously vary run to run; WHAT was issued does not.

The record's ``agreement`` section is the harness's own oracle: the
server-side ``corro.http.request.seconds`` / ``corro.pg.query.seconds``
histograms (scraped off ``/metrics`` and parsed back through
``utils.metrics.parse_exposition``) must count exactly the requests the
clients tallied. A lost or double-counted request fails the record.

CLI: ``corrosion-tpu load`` (``--output-json`` -> the check.sh serve
stage artifact). Under ``CORROSAN=1`` the CLI wraps the whole run in a
sanitized window — every fanout/metrics path race- and leak-gated.
"""

from __future__ import annotations

import hashlib
import json
import random
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

BENCH_SERVE_SCHEMA = 1
BENCH_SERVE_OVERLOAD_SCHEMA = 1

LOAD_SCHEMA = (
    "CREATE TABLE load_kv (k TEXT PRIMARY KEY, v INTEGER, who TEXT);"
)
_STOP_KEY = "__stop__"


# --- seeded op planning (pure) -------------------------------------------
def plan_ops(seed: int, writers: int, write_ops: int, pg_readers: int,
             pg_ops: int, keys: int) -> dict:
    """The deterministic op plan: per-writer and per-reader key-index
    streams, derived only from ``seed`` (``random.Random`` — a stable
    algorithm across CPython versions). Returns
    ``{"writers": [[idx,...],...], "pg": [[idx,...],...], "digest"}``."""
    plan: Dict[str, Any] = {
        "writers": [
            [random.Random(seed * 7919 + w).randrange(keys)
             for _ in range(write_ops)]
            for w in range(writers)
        ],
        "pg": [
            [random.Random(seed * 104729 + 31 * r).randrange(keys)
             for _ in range(pg_ops)]
            for r in range(pg_readers)
        ],
    }
    digest = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()
    ).hexdigest()[:16]
    plan["digest"] = digest
    return plan


def percentiles(samples: List[float],
                qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
    """Exact client-side percentiles (sorted-sample interpolation) —
    the client half of the client-vs-server latency story; the server
    half comes from bucketed ``quantiles_from_histogram``."""
    out: Dict[str, float] = {}
    if not samples:
        return {f"p{int(round(q * 100))}": 0.0 for q in qs}
    s = sorted(samples)
    n = len(s)
    for q in qs:
        pos = q * (n - 1)
        lo = int(pos)
        hi = min(lo + 1, n - 1)
        out[f"p{int(round(q * 100))}"] = s[lo] + (s[hi] - s[lo]) * (pos - lo)
    return out


# --- minimal PG v3 frontend (simple query only) --------------------------
class _PgClient:
    """Just enough of the PG wire protocol for the reader legs: startup,
    simple query, ReadyForQuery drain. (The image ships no PG client
    library; tests/test_pg.py speaks the same dialect.)"""

    def __init__(self, addr: str, port: int, database: str = "corrosion",
                 timeout: float = 30.0):
        self.sock = socket.create_connection((addr, port), timeout=timeout)
        payload = struct.pack("!I", 196608)
        for k, v in (("user", "corroload"), ("database", database)):
            payload += k.encode() + b"\x00" + v.encode() + b"\x00"
        payload += b"\x00"
        self.sock.sendall(struct.pack("!I", len(payload) + 4) + payload)
        self._drain()

    def close(self) -> None:
        try:
            self.sock.sendall(b"X" + struct.pack("!I", 4))
        finally:
            self.sock.close()

    def _read_exact(self, n: int) -> bytes:
        data = b""
        while len(data) < n:
            chunk = self.sock.recv(n - len(data))
            if not chunk:
                raise ConnectionResetError
            data += chunk
        return data

    def _drain(self) -> List[tuple]:
        msgs = []
        while True:
            kind = self._read_exact(1)
            (length,) = struct.unpack("!I", self._read_exact(4))
            payload = self._read_exact(length - 4)
            msgs.append((kind, payload))
            if kind == b"Z":
                return msgs

    def query(self, sql: str) -> List[List[Optional[str]]]:
        """Simple query; returns decoded text rows. Raises on an
        ErrorResponse (the reader legs only issue valid SELECTs)."""
        q = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!I", len(q) + 4) + q)
        rows: List[List[Optional[str]]] = []
        for kind, payload in self._drain():
            if kind == b"D":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                row: List[Optional[str]] = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif kind == b"E":
                raise RuntimeError(f"pg error for {sql!r}: {payload!r}")
        return rows


# --- the harness ---------------------------------------------------------
def run_load(writers: int = 4, subscribers: int = 2, pg_readers: int = 2,
             write_ops: int = 32, pg_ops: int = 32, keys: int = 12,
             seed: int = 0, n_nodes: int = 16, warm_rounds: int = 8,
             deadline_s: float = 120.0) -> dict:
    """Boot a devcluster rig, run the seeded concurrent-client load, and
    return the ``BENCH_SERVE`` record (see docs/observability.md)."""
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.api.http import ApiServer
    from corrosion_tpu.client import ApiError, CorrosionApiClient
    from corrosion_tpu.db import Database
    from corrosion_tpu.pg import PgServer
    from corrosion_tpu.testing import cluster_config
    from corrosion_tpu.utils.lifecycle import spawn_counted
    from corrosion_tpu.utils.metrics import (
        parse_exposition,
        quantiles_from_histogram,
    )

    plan = plan_ops(seed, writers, write_ops, pg_readers, pg_ops, keys)
    problems: List[str] = []

    # keyspace + stop marker + headroom must fit the row budget
    cfg = cluster_config(n_nodes=n_nodes, n_rows=keys + 4)

    # per-leg results: one pre-allocated slot per thread, read only
    # after join (no shared mutation)
    w_out: List[Optional[dict]] = [None] * writers
    s_out: List[Optional[dict]] = [None] * subscribers
    p_out: List[Optional[dict]] = [None] * pg_readers

    with Agent(cfg) as agent:
        agent.wait_rounds(warm_rounds, timeout=deadline_s)
        db = Database(agent)
        with ApiServer(db, port=0) as api, PgServer(db, port=0) as pgs:
            setup = CorrosionApiClient(api.addr, api.port)
            setup.schema([LOAD_SCHEMA])
            # pre-populate the keyspace so writers are pure UPDATEs
            # (fixed row budget; INSERT-vs-UPDATE split stays seeded)
            setup.execute([
                ("INSERT INTO load_kv (k, v, who) VALUES (?, ?, ?)",
                 [f"k{i}", 0, "seed"])
                for i in range(keys)
            ])
            setup_tx_posts = 1
            agent.wait_rounds(2, timeout=deadline_s)

            def subscriber(i: int) -> None:
                out = {"lags": [], "changes": 0, "errors": 0,
                       "ready": False}
                s_out[i] = out
                c = CorrosionApiClient(api.addr, api.port)
                try:
                    stream = c.subscribe("SELECT k, v, who FROM load_kv",
                                         stream_timeout=deadline_s)
                    for ev in stream:
                        if "eoq" in ev:
                            out["ready"] = True
                        ch = ev.get("change")
                        if ch is None:
                            continue
                        _kind, key, row, _cid = ch
                        if key == _STOP_KEY:
                            break
                        out["changes"] += 1
                        if row and isinstance(row[1], int) and row[1] > 0:
                            out["lags"].append(
                                max(0.0, (time.time_ns() - row[1]) / 1e9))
                except (TimeoutError, OSError, ApiError):
                    out["errors"] += 1

            def writer(i: int) -> None:
                out = {"lat": [], "errors": 0, "http_503": 0, "posts": 0}
                w_out[i] = out
                c = CorrosionApiClient(api.addr, api.port)
                for key_idx in plan["writers"][i]:
                    t0 = time.perf_counter()
                    try:
                        out["posts"] += 1
                        c.execute([(
                            "UPDATE load_kv SET v = ?, who = ? WHERE k = ?",
                            [time.time_ns(), f"w{i}", f"k{key_idx}"],
                        )])
                        out["lat"].append(time.perf_counter() - t0)
                    except ApiError as e:
                        if e.status == 503:
                            out["http_503"] += 1
                        else:
                            out["errors"] += 1
                    except OSError:
                        out["errors"] += 1

            def pg_reader(i: int) -> None:
                out = {"lat": [], "errors": 0, "queries": 0}
                p_out[i] = out
                try:
                    client = _PgClient(pgs.addr, pgs.port)
                except OSError:
                    out["errors"] += 1
                    return
                try:
                    for key_idx in plan["pg"][i]:
                        t0 = time.perf_counter()
                        try:
                            out["queries"] += 1
                            rows = client.query(
                                "SELECT k, v, who FROM load_kv "
                                f"WHERE k = 'k{key_idx}'")
                            out["lat"].append(time.perf_counter() - t0)
                            if len(rows) != 1 or rows[0][0] != f"k{key_idx}":
                                out["errors"] += 1
                        except (RuntimeError, OSError):
                            out["errors"] += 1
                finally:
                    try:
                        client.close()
                    except OSError:
                        pass

            t_start = time.perf_counter()
            threads = [
                spawn_counted(lambda i=i: subscriber(i),
                              name=f"corro-load-sub-{i}")
                for i in range(subscribers)
            ]
            # subscribers must be attached (initial snapshot drained)
            # before the first write or early deliveries are invisible
            deadline = time.monotonic() + deadline_s
            while not all(s and s["ready"] for s in s_out):
                if time.monotonic() > deadline:
                    problems.append("subscribers never reached eoq")
                    break
                time.sleep(0.01)
            threads += [
                spawn_counted(lambda i=i: writer(i),
                              name=f"corro-load-writer-{i}")
                for i in range(writers)
            ]
            threads += [
                spawn_counted(lambda i=i: pg_reader(i),
                              name=f"corro-load-pg-{i}")
                for i in range(pg_readers)
            ]
            for t in threads[subscribers:]:
                t.join(timeout=deadline_s)
            # stop marker: subscribers exit when its change delivers
            try:
                setup.execute([(
                    "INSERT INTO load_kv (k, v, who) VALUES (?, ?, ?)",
                    [_STOP_KEY, 0, "stop"],
                )])
                setup_tx_posts += 1
            except ApiError:
                problems.append("stop-marker write failed")
            agent.wait_rounds(3, timeout=deadline_s)
            for t in threads[:subscribers]:
                t.join(timeout=deadline_s)
            duration = time.perf_counter() - t_start
            if any(t.is_alive() for t in threads):
                problems.append("load legs did not finish before deadline")

            # --- server-side scrape + agreement -----------------------
            scrape = parse_exposition(setup.metrics())
            hist = scrape["histograms"]

            def server_count(name: str, **want: str) -> int:
                total = 0
                for (pname, labels), h in hist.items():
                    if pname != name:
                        continue
                    lab = dict(labels)
                    if all(lab.get(k) == v for k, v in want.items()):
                        total += h["count"]
                return total

            def server_hist(name: str, **want: str) -> dict:
                agg = {"buckets": (), "counts": [], "sum": 0.0, "count": 0}
                for (pname, labels), h in hist.items():
                    if pname != name:
                        continue
                    lab = dict(labels)
                    if not all(lab.get(k) == v for k, v in want.items()):
                        continue
                    if not agg["counts"]:
                        agg["buckets"] = h["buckets"]
                        agg["counts"] = list(h["counts"])
                    else:
                        agg["counts"] = [
                            a + b
                            for a, b in zip(agg["counts"], h["counts"])
                        ]
                    agg["sum"] += h["sum"]
                    agg["count"] += h["count"]
                return agg

            client_tx = (sum(w["posts"] for w in w_out if w)
                         + setup_tx_posts)
            server_tx = server_count("corro_http_request_seconds",
                                     route="/v1/transactions", method="POST")
            client_pg = sum(p["queries"] for p in p_out if p)
            server_pg = server_count("corro_pg_query_seconds", kind="select")
            agreement = {
                "transactions": {"client": client_tx, "server": server_tx,
                                 "ok": client_tx == server_tx},
                "pg_select": {"client": client_pg, "server": server_pg,
                              "ok": client_pg == server_pg},
            }
            agreement["ok"] = (agreement["transactions"]["ok"]
                               and agreement["pg_select"]["ok"])
            if not agreement["ok"]:
                problems.append(f"server/client count disagreement: "
                                f"{agreement}")

            w_lat = [x for w in w_out if w for x in w["lat"]]
            p_lat = [x for p in p_out if p for x in p["lat"]]
            s_lag = [x for s in s_out if s for x in s["lags"]]
            w_errors = sum(w["errors"] for w in w_out if w)
            p_errors = sum(p["errors"] for p in p_out if p)
            s_errors = sum(s["errors"] for s in s_out if s)
            if w_errors or p_errors or s_errors:
                problems.append(
                    f"client errors: write={w_errors} pg={p_errors} "
                    f"sub={s_errors}")
            if not s_lag and subscribers:
                problems.append("subscribers observed no deliveries")

            delivery_h = server_hist("corro_subs_delivery_seconds")
            record = {
                "schema": BENCH_SERVE_SCHEMA,
                "kind": "bench_serve",
                "seed": seed,
                "plan_digest": plan["digest"],
                "n_nodes": n_nodes,
                "writers": writers,
                "subscribers": subscribers,
                "pg_readers": pg_readers,
                "write_ops_per_writer": write_ops,
                "pg_ops_per_reader": pg_ops,
                "keys": keys,
                "duration_s": duration,
                "qps": ((len(w_lat) + len(p_lat)) / duration
                        if duration > 0 else 0.0),
                "ops": {
                    "write": dict(
                        percentiles(w_lat),
                        count=len(w_lat), errors=w_errors,
                        http_503=sum(w["http_503"] for w in w_out if w),
                        qps=(len(w_lat) / duration if duration else 0.0),
                    ),
                    "pg_query": dict(
                        percentiles(p_lat),
                        count=len(p_lat), errors=p_errors,
                        qps=(len(p_lat) / duration if duration else 0.0),
                    ),
                    "subscribe_delivery": dict(
                        percentiles(s_lag),
                        count=len(s_lag), errors=s_errors,
                        changes=sum(s["changes"] for s in s_out if s),
                    ),
                },
                "server": {
                    "tx_requests": server_tx,
                    "pg_selects": server_pg,
                    "deliveries": delivery_h["count"],
                    "delivery_quantiles_s":
                        quantiles_from_histogram(delivery_h)
                        if delivery_h["count"] else None,
                    "unready_total": sum(
                        v for (n, _l), v in scrape["counters"].items()
                        if n == "corro_http_unready_total"),
                    "shed_total": sum(
                        v for (n, _l), v in scrape["counters"].items()
                        if n == "corro_subs_shed_total"),
                },
                "agreement": agreement,
                "problems": problems,
                "ok": not problems,
            }
            return record


# --- corroguard overload mode (ISSUE 17, docs/overload.md) ----------------
def plan_overload(seed: int, stages: Sequence[int], write_ops: int,
                  keys: int, closed_loop_ops: int) -> dict:
    """Deterministic overload plan: per-stage per-writer key-index
    streams plus the closed-loop client's stream, all pure in ``seed``."""
    plan: Dict[str, Any] = {
        "stages": [
            [
                [random.Random(seed * 7919 + 1009 * si + w).randrange(keys)
                 for _ in range(write_ops)]
                for w in range(n_writers)
            ]
            for si, n_writers in enumerate(stages)
        ],
        "closed_loop": [
            random.Random(seed * 104729 + 17).randrange(keys)
            for _ in range(closed_loop_ops)
        ],
    }
    digest = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()
    ).hexdigest()[:16]
    plan["digest"] = digest
    return plan


class _CountingClient:
    """The closed-loop leg: a :class:`CorrosionApiClient` with
    ``retry_503`` enabled, instrumented so every 503 the retry engine
    absorbs is still visible to the harness's server/client agreement
    accounting (each shed attempt DID traverse the server's request
    histogram)."""

    def __init__(self, addr: str, port: int, retry_503: int,
                 retry_503_max_wait: float):
        from corrosion_tpu.client import ApiUnavailable, CorrosionApiClient

        self.attempts_503 = 0
        self.retry_delays: List[float] = []
        harness = self

        class _Client(CorrosionApiClient):
            def _retry_connect(self, attempt):
                def counted():
                    try:
                        return attempt()
                    except ApiUnavailable as e:
                        harness.attempts_503 += 1
                        if e.retry_after is not None:
                            harness.retry_delays.append(
                                min(float(e.retry_after),
                                    self.retry_503_max_wait))
                        raise
                return super()._retry_connect(counted)

        self.client = _Client(addr, port, retry_503=retry_503,
                              retry_503_max_wait=retry_503_max_wait)


def _leaked_serving_threads() -> List[str]:
    """Names of still-alive serving-plane connection threads — must be
    empty once the servers' context managers have exited (the
    degradation contract's leak gate; CORROSAN covers fds/races)."""
    return sorted(
        t.name for t in threading.enumerate()
        if t.name.startswith(("corro-http-conn", "corro-pg-conn"))
    )


def run_overload(stages: Sequence[int] = (2, 4, 8), write_ops: int = 30,
                 subscribers: int = 4, slow_subs: int = 2,
                 slow_ms: float = 25.0, keys: int = 32,
                 closed_loop_ops: int = 24, pg_probes: int = 6,
                 pad_bytes: int = 1024, seed: int = 0, n_nodes: int = 16,
                 warm_rounds: int = 8, deadline_s: float = 240.0,
                 lag_bound_s: float = 2.5, closed_loop_think_s: float = 0.15,
                 guard: bool = True, serve=None) -> dict:
    """Drive the serving plane to its breaking point and report whether
    the degradation contract held (docs/overload.md).

    Open-loop writer waves ramp through ``stages`` (each wave spawns
    that many writers, each issuing ``write_ops`` seeded UPDATEs with a
    ``time.time_ns()`` stamp in the row); ``subscribers`` fast plus
    ``slow_subs`` deliberately slow NDJSON subscribers measure
    client-observed delivery lag off those stamps; one closed-loop
    client retries 503s per the server's Retry-After hint and must land
    every op. After each wave the server's cumulative shed counters are
    scraped — under guard they must rise monotonically with offered
    load while delivery lag stays under ``lag_bound_s``; without guard
    (``guard=False``) the slow subscribers' unbounded queues let lag
    diverge, which is the contract violation the bench exists to show.
    """
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.api.admission import AdmissionController
    from corrosion_tpu.api.http import ApiServer
    from corrosion_tpu.client import ApiError, CorrosionApiClient
    from corrosion_tpu.config import ServeConfig
    from corrosion_tpu.db import Database
    from corrosion_tpu.pg import PgServer
    from corrosion_tpu.testing import cluster_config
    from corrosion_tpu.utils.lifecycle import spawn_counted
    from corrosion_tpu.utils.metrics import parse_exposition

    if guard and serve is None:
        serve = ServeConfig(
            max_inflight=3, max_queue=3, queue_wait=0.05,
            max_streams=max(32, 2 * (subscribers + slow_subs)),
            retry_after_cap=5.0, shed_policy="shed-oldest",
            # small per-sub bound: a slow consumer only ever sees the
            # freshest ~sub_queue frames, so its observed lag is bounded
            # by sub_queue * service time instead of the whole backlog;
            # the sndbuf clamp keeps the kernel from hiding more backlog
            # behind the queue (frames are pad_bytes-sized on purpose)
            sub_queue=32, sub_shed_threshold=1 << 30,
            stream_sndbuf=4608,
        )
    elif not guard:
        # the EXPLICIT all-off opt-out: with measured non-zero
        # ServeConfig defaults, a bare None would hand the "unguarded"
        # arm the default guard and the A/B bench would prove nothing
        serve = ServeConfig.unlimited()

    plan = plan_overload(seed, stages, write_ops, keys, closed_loop_ops)
    problems: List[str] = []
    # writes carry a payload pad so NDJSON frames have realistic size:
    # a few KB of socket buffer then holds a few frames, not thousands
    # (which would let the kernel hide the whole backlog)
    pad = "x" * max(0, pad_bytes)
    n_subs = subscribers + slow_subs
    cfg = cluster_config(n_nodes=n_nodes, n_rows=keys + 4)

    s_out: List[Optional[dict]] = [None] * n_subs
    stage_out: List[List[Optional[dict]]] = [
        [None] * n for n in stages
    ]
    stage_stats: List[dict] = []

    with Agent(cfg) as agent:
        agent.wait_rounds(warm_rounds, timeout=deadline_s)
        db = Database(agent)
        admission = AdmissionController(serve, registry=agent.metrics)
        with ApiServer(db, port=0, serve=serve,
                       admission=admission) as api, \
                PgServer(db, port=0, admission=admission) as pgs:
            setup = CorrosionApiClient(api.addr, api.port)
            setup.schema([LOAD_SCHEMA])
            setup.execute([
                ("INSERT INTO load_kv (k, v, who) VALUES (?, ?, ?)",
                 [f"k{i}", 0, "seed"])
                for i in range(keys)
            ])
            setup_tx_posts = 1
            agent.wait_rounds(2, timeout=deadline_s)

            # warmup at peak concurrency BEFORE the measured window:
            # the first large concurrent write burst can trigger a
            # multi-second device compile for the new batch shape, which
            # would otherwise land inside the lag percentiles as a stall
            # that has nothing to do with queueing
            n_warm = max(stages) + 1
            warm_posts = [0] * n_warm

            def _warm(i: int) -> None:
                c = CorrosionApiClient(api.addr, api.port)
                for j in range(3):
                    warm_posts[i] += 1  # attempts: 503 rejects count too
                    try:
                        c.execute([(
                            "UPDATE load_kv SET v = ?, who = ? WHERE k = ?",
                            [time.time_ns(), "warm" + pad,
                             f"k{(i + j) % keys}"],
                        )])
                    except (ApiError, OSError):
                        pass

            warm_threads = [
                spawn_counted(lambda i=i: _warm(i), name=f"corro-ovl-warm{i}")
                for i in range(n_warm)
            ]
            for t in warm_threads:
                t.join(timeout=deadline_s)
            setup_tx_posts += sum(warm_posts)
            agent.wait_rounds(2, timeout=deadline_s)

            def subscriber(i: int, slow: bool) -> None:
                out = {"lags": [], "changes": 0, "errors": 0,
                       "ready": False, "resyncs": 0, "dropped": 0,
                       "slow": slow, "rejected": False}
                s_out[i] = out
                c = CorrosionApiClient(api.addr, api.port)
                try:
                    stream = c.subscribe("SELECT k, v, who FROM load_kv",
                                         stream_timeout=deadline_s)
                    if slow:
                        # a slow consumer's receive window must not act
                        # as an invisible extra queue either
                        try:
                            stream._conn.sock.setsockopt(
                                socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
                        except (OSError, AttributeError):
                            pass
                    for ev in stream:
                        if "eoq" in ev:
                            out["ready"] = True
                        ch = ev.get("change")
                        if ch is None:
                            continue
                        if slow:
                            time.sleep(slow_ms / 1e3)
                        _kind, key, row, _cid = ch
                        if key == _STOP_KEY:
                            break
                        out["changes"] += 1
                        if row and isinstance(row[1], int) and row[1] > 0:
                            out["lags"].append(
                                max(0.0, (time.time_ns() - row[1]) / 1e9))
                    out["resyncs"] = stream.resyncs
                    out["dropped"] = stream.dropped
                except ApiError as e:
                    if e.status == 503:
                        out["rejected"] = True
                    else:
                        out["errors"] += 1
                except (TimeoutError, OSError):
                    out["errors"] += 1

            def writer(si: int, i: int) -> None:
                out = {"lat": [], "errors": 0, "http_503": 0, "posts": 0}
                stage_out[si][i] = out
                c = CorrosionApiClient(api.addr, api.port)
                for key_idx in plan["stages"][si][i]:
                    t0 = time.perf_counter()
                    try:
                        out["posts"] += 1
                        c.execute([(
                            "UPDATE load_kv SET v = ?, who = ? WHERE k = ?",
                            [time.time_ns(), f"s{si}w{i}" + pad,
                             f"k{key_idx}"],
                        )])
                        out["lat"].append(time.perf_counter() - t0)
                    except ApiError as e:
                        if e.status == 503:
                            out["http_503"] += 1
                        else:
                            out["errors"] += 1
                    except OSError:
                        out["errors"] += 1

            closed = _CountingClient(api.addr, api.port, retry_503=16,
                                     retry_503_max_wait=0.25)
            closed_out = {"done": 0, "failed": 0, "lat": []}

            def closed_loop() -> None:
                from corrosion_tpu.client import ApiError as _ApiError
                for key_idx in plan["closed_loop"]:
                    # think time paces the ops across the whole ramp so
                    # the closed loop meets the heavy stages too
                    time.sleep(closed_loop_think_s)
                    t0 = time.perf_counter()
                    try:
                        closed.client.execute([(
                            "UPDATE load_kv SET v = ?, who = ? WHERE k = ?",
                            [time.time_ns(), "closed" + pad,
                             f"k{key_idx}"],
                        )])
                        closed_out["done"] += 1
                        closed_out["lat"].append(time.perf_counter() - t0)
                    except (_ApiError, OSError):
                        closed_out["failed"] += 1

            def pg_probe_wave() -> dict:
                """A burst of concurrent PG connections against the
                shared admission budget; counts how many the guard shed
                at startup (``SQLSTATE 53300`` closes the wire, which
                the minimal client sees as a reset)."""
                results = {"ok": 0, "shed": 0}
                mu = threading.Lock()

                def probe() -> None:
                    try:
                        c = _PgClient(pgs.addr, pgs.port, timeout=10.0)
                    except (OSError, ConnectionResetError):
                        with mu:
                            results["shed"] += 1
                        return
                    try:
                        c.query("SELECT k FROM load_kv WHERE k = 'k0'")
                        with mu:
                            results["ok"] += 1
                    except (RuntimeError, OSError):
                        with mu:
                            results["shed"] += 1
                    finally:
                        try:
                            c.close()
                        except OSError:
                            pass

                ts = [spawn_counted(probe, name=f"corro-ovl-pg-{j}")
                      for j in range(pg_probes)]
                for t in ts:
                    t.join(timeout=deadline_s)
                return results

            def counter_sum(scrape: dict, name: str, **want: str) -> float:
                total = 0.0
                for (pname, labels), v in scrape["counters"].items():
                    lab = dict(labels)
                    if pname == name and all(
                            lab.get(k) == w for k, w in want.items()):
                        total += v
                return total

            # attach all subscribers before the first wave
            sub_threads = [
                spawn_counted(
                    lambda i=i: subscriber(i, slow=i >= subscribers),
                    name=f"corro-ovl-sub-{i}")
                for i in range(n_subs)
            ]
            deadline = time.monotonic() + deadline_s
            while not all(
                    s and (s["ready"] or s["rejected"] or s["errors"])
                    for s in s_out):
                if time.monotonic() > deadline:
                    problems.append("subscribers never reached eoq")
                    break
                time.sleep(0.01)
            if any(s and s["rejected"] for s in s_out):
                problems.append("subscriber rejected at attach "
                                "(max_streams too small for the pool)")

            t_start = time.perf_counter()
            closed_thread = spawn_counted(closed_loop,
                                          name="corro-ovl-closed")
            pname = "corro_http_request_seconds"
            for si, n_writers in enumerate(stages):
                wave = [
                    spawn_counted(lambda si=si, i=i: writer(si, i),
                                  name=f"corro-ovl-w{si}-{i}")
                    for i in range(n_writers)
                ]
                for t in wave:
                    t.join(timeout=deadline_s)
                scrape = parse_exposition(setup.metrics())
                posts = sum(w["posts"] for w in stage_out[si] if w)
                http_503 = sum(w["http_503"] for w in stage_out[si] if w)
                stage_stats.append({
                    "stage": si,
                    "writers": n_writers,
                    "posts": posts,
                    "http_503": http_503,
                    # cumulative server-side pressure counters — the
                    # monotone half of the degradation contract
                    "admission_rejected_total": counter_sum(
                        scrape, "corro_admission_rejected_total"),
                    "subs_shed_total": counter_sum(
                        scrape, "corro_subs_shed_total"),
                    "unready_overloaded_total": counter_sum(
                        scrape, "corro_http_unready_total",
                        status="overloaded"),
                })
            pg_wave = pg_probe_wave()
            closed_thread.join(timeout=deadline_s)
            if closed_thread.is_alive():
                problems.append("closed-loop client did not finish")

            # stop marker: subscribers exit once it delivers (the slow
            # ones only after draining whatever backlog sits ahead)
            try:
                setup.execute([(
                    "INSERT INTO load_kv (k, v, who) VALUES (?, ?, ?)",
                    [_STOP_KEY, 0, "stop"],
                )])
                setup_tx_posts += 1
            except ApiError:
                problems.append("stop-marker write failed")
            agent.wait_rounds(3, timeout=deadline_s)
            for t in sub_threads:
                t.join(timeout=deadline_s)
            duration = time.perf_counter() - t_start
            if any(t.is_alive() for t in sub_threads):
                problems.append("subscriber legs did not finish")

            # --- final scrape + agreement ------------------------------
            scrape = parse_exposition(setup.metrics())
            server_tx = sum(
                h["count"] for (n, labels), h in
                scrape["histograms"].items()
                if n == pname and dict(labels).get(
                    "route") == "/v1/transactions")
            open_posts = sum(w["posts"] for wave_o in stage_out
                             for w in wave_o if w)
            client_tx = (open_posts + setup_tx_posts
                         + closed_out["done"] + closed_out["failed"]
                         + closed.attempts_503)
            agreement = {
                "transactions": {"client": client_tx, "server": server_tx,
                                 "ok": client_tx == server_tx},
            }
            agreement["ok"] = agreement["transactions"]["ok"]
            if not agreement["ok"]:
                problems.append(
                    f"server/client count disagreement: {agreement}")

    leaked = _leaked_serving_threads()
    if leaked:
        problems.append(f"leaked serving threads: {leaked}")

    all_lags = [x for s in s_out if s for x in s["lags"]]
    slow_lags = [x for s in s_out if s and s["slow"] for x in s["lags"]]
    lag_p = percentiles(all_lags)
    total_503 = (sum(st["http_503"] for st in stage_stats)
                 + closed.attempts_503)
    rejected_series = [st["admission_rejected_total"]
                       for st in stage_stats]
    shed_series = [st["subs_shed_total"] for st in stage_stats]
    pressure_series = [r + s for r, s in zip(rejected_series, shed_series)]
    shed_monotone = all(
        b >= a for a, b in zip(pressure_series, pressure_series[1:]))
    absorbed = (closed_out["failed"] == 0
                and closed_out["done"] == closed_loop_ops)
    lag_bounded = bool(all_lags) and lag_p["p99"] <= lag_bound_s
    contract = {
        "lag_bound_s": lag_bound_s,
        "delivery_p99_s": lag_p["p99"],
        "lag_bounded": lag_bounded,
        "shed_monotone": shed_monotone,
        "pressure_final": pressure_series[-1] if pressure_series else 0.0,
        "absorbed": absorbed,
        "ok": lag_bounded and shed_monotone and absorbed,
    }
    if guard and contract["pressure_final"] <= 0:
        problems.append("guarded run never shed: the ramp did not "
                        "overload the plane (raise stages/write_ops)")

    return {
        "schema": BENCH_SERVE_OVERLOAD_SCHEMA,
        "kind": "serve_overload",
        "seed": seed,
        "plan_digest": plan["digest"],
        "guard": guard,
        "serve": (None if serve is None else {
            "max_inflight": serve.max_inflight,
            "max_queue": serve.max_queue,
            "max_streams": serve.max_streams,
            "queue_wait": serve.queue_wait,
            "sub_queue": serve.sub_queue,
            "shed_policy": serve.shed_policy,
        }),
        "stages": list(stages),
        "write_ops_per_writer": write_ops,
        "subscribers": subscribers,
        "slow_subs": slow_subs,
        "slow_ms": slow_ms,
        "keys": keys,
        "n_nodes": n_nodes,
        "duration_s": duration,
        "stage_stats": stage_stats,
        "delivery_lag_s": dict(lag_p, count=len(all_lags)),
        "slow_delivery_lag_s": dict(percentiles(slow_lags),
                                    count=len(slow_lags)),
        "resyncs": sum(s["resyncs"] for s in s_out if s),
        "frames_dropped": sum(s["dropped"] for s in s_out if s),
        "http_503": total_503,
        "closed_loop": {
            "ops": closed_loop_ops,
            "done": closed_out["done"],
            "failed": closed_out["failed"],
            "attempts_503": closed.attempts_503,
            "retry_delays": closed.retry_delays[:32],
            "lat": percentiles(closed_out["lat"]),
        },
        "pg_probe": pg_wave,
        "leaked_threads": leaked,
        "agreement": agreement,
        "contract": contract,
        "problems": problems,
        "ok": not problems and contract["ok"],
    }


def run_overload_bench(**kw) -> dict:
    """Both arms of the degradation-contract story, one record: the
    guarded plane must HOLD the contract (bounded p99 delivery lag,
    monotone shed counters, closed-loop client fully absorbed) while
    the identical ramp against the unguarded plane must VIOLATE it —
    otherwise the bench proves nothing about the guard."""
    guarded = run_overload(guard=True, **kw)
    unguarded = run_overload(guard=False, **kw)
    holds = bool(guarded["contract"]["ok"]
                 and guarded["contract"]["pressure_final"] > 0
                 and not guarded["problems"])
    violated = not unguarded["contract"]["lag_bounded"]
    return {
        "schema": BENCH_SERVE_OVERLOAD_SCHEMA,
        "kind": "bench_serve_overload",
        "seed": guarded["seed"],
        "plan_digest": guarded["plan_digest"],
        "guarded": guarded,
        "unguarded": unguarded,
        "contract_holds_guarded": holds,
        "contract_violated_unguarded": violated,
        "ok": holds and violated,
    }
