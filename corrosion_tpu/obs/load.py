"""corroload: the seeded concurrent-client load harness (ISSUE 16).

The reference serves whole fleets over its HTTP API, subscriptions and
PG-wire server; this repo's serving plane had only ever seen single
test clients. ``run_load`` drives it the way a fleet would — N open-loop
writers (``POST /v1/transactions``), M NDJSON subscribers measuring
write-commit -> delivery lag client-side, and K PG-wire readers speaking
the v3 simple-query protocol — against an in-process devcluster rig
(Agent + Database + ApiServer + PgServer), and reports client-side
p50/p95/p99 per op class, sustained QPS, and error/503 counts as a
``BENCH_SERVE`` record.

Determinism: the op streams come from :func:`plan_ops`, a pure function
of the seed — the record carries the plan digest that pins them. Wall
times obviously vary run to run; WHAT was issued does not.

The record's ``agreement`` section is the harness's own oracle: the
server-side ``corro.http.request.seconds`` / ``corro.pg.query.seconds``
histograms (scraped off ``/metrics`` and parsed back through
``utils.metrics.parse_exposition``) must count exactly the requests the
clients tallied. A lost or double-counted request fails the record.

CLI: ``corrosion-tpu load`` (``--output-json`` -> the check.sh serve
stage artifact). Under ``CORROSAN=1`` the CLI wraps the whole run in a
sanitized window — every fanout/metrics path race- and leak-gated.
"""

from __future__ import annotations

import hashlib
import json
import random
import socket
import struct
import time
from typing import Any, Dict, List, Optional

BENCH_SERVE_SCHEMA = 1

LOAD_SCHEMA = (
    "CREATE TABLE load_kv (k TEXT PRIMARY KEY, v INTEGER, who TEXT);"
)
_STOP_KEY = "__stop__"


# --- seeded op planning (pure) -------------------------------------------
def plan_ops(seed: int, writers: int, write_ops: int, pg_readers: int,
             pg_ops: int, keys: int) -> dict:
    """The deterministic op plan: per-writer and per-reader key-index
    streams, derived only from ``seed`` (``random.Random`` — a stable
    algorithm across CPython versions). Returns
    ``{"writers": [[idx,...],...], "pg": [[idx,...],...], "digest"}``."""
    plan: Dict[str, Any] = {
        "writers": [
            [random.Random(seed * 7919 + w).randrange(keys)
             for _ in range(write_ops)]
            for w in range(writers)
        ],
        "pg": [
            [random.Random(seed * 104729 + 31 * r).randrange(keys)
             for _ in range(pg_ops)]
            for r in range(pg_readers)
        ],
    }
    digest = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()
    ).hexdigest()[:16]
    plan["digest"] = digest
    return plan


def percentiles(samples: List[float],
                qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
    """Exact client-side percentiles (sorted-sample interpolation) —
    the client half of the client-vs-server latency story; the server
    half comes from bucketed ``quantiles_from_histogram``."""
    out: Dict[str, float] = {}
    if not samples:
        return {f"p{int(round(q * 100))}": 0.0 for q in qs}
    s = sorted(samples)
    n = len(s)
    for q in qs:
        pos = q * (n - 1)
        lo = int(pos)
        hi = min(lo + 1, n - 1)
        out[f"p{int(round(q * 100))}"] = s[lo] + (s[hi] - s[lo]) * (pos - lo)
    return out


# --- minimal PG v3 frontend (simple query only) --------------------------
class _PgClient:
    """Just enough of the PG wire protocol for the reader legs: startup,
    simple query, ReadyForQuery drain. (The image ships no PG client
    library; tests/test_pg.py speaks the same dialect.)"""

    def __init__(self, addr: str, port: int, database: str = "corrosion",
                 timeout: float = 30.0):
        self.sock = socket.create_connection((addr, port), timeout=timeout)
        payload = struct.pack("!I", 196608)
        for k, v in (("user", "corroload"), ("database", database)):
            payload += k.encode() + b"\x00" + v.encode() + b"\x00"
        payload += b"\x00"
        self.sock.sendall(struct.pack("!I", len(payload) + 4) + payload)
        self._drain()

    def close(self) -> None:
        try:
            self.sock.sendall(b"X" + struct.pack("!I", 4))
        finally:
            self.sock.close()

    def _read_exact(self, n: int) -> bytes:
        data = b""
        while len(data) < n:
            chunk = self.sock.recv(n - len(data))
            if not chunk:
                raise ConnectionResetError
            data += chunk
        return data

    def _drain(self) -> List[tuple]:
        msgs = []
        while True:
            kind = self._read_exact(1)
            (length,) = struct.unpack("!I", self._read_exact(4))
            payload = self._read_exact(length - 4)
            msgs.append((kind, payload))
            if kind == b"Z":
                return msgs

    def query(self, sql: str) -> List[List[Optional[str]]]:
        """Simple query; returns decoded text rows. Raises on an
        ErrorResponse (the reader legs only issue valid SELECTs)."""
        q = sql.encode() + b"\x00"
        self.sock.sendall(b"Q" + struct.pack("!I", len(q) + 4) + q)
        rows: List[List[Optional[str]]] = []
        for kind, payload in self._drain():
            if kind == b"D":
                (n,) = struct.unpack("!H", payload[:2])
                off = 2
                row: List[Optional[str]] = []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", payload[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif kind == b"E":
                raise RuntimeError(f"pg error for {sql!r}: {payload!r}")
        return rows


# --- the harness ---------------------------------------------------------
def run_load(writers: int = 4, subscribers: int = 2, pg_readers: int = 2,
             write_ops: int = 32, pg_ops: int = 32, keys: int = 12,
             seed: int = 0, n_nodes: int = 16, warm_rounds: int = 8,
             deadline_s: float = 120.0) -> dict:
    """Boot a devcluster rig, run the seeded concurrent-client load, and
    return the ``BENCH_SERVE`` record (see docs/observability.md)."""
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.api.http import ApiServer
    from corrosion_tpu.client import ApiError, CorrosionApiClient
    from corrosion_tpu.db import Database
    from corrosion_tpu.pg import PgServer
    from corrosion_tpu.testing import cluster_config
    from corrosion_tpu.utils.lifecycle import spawn_counted
    from corrosion_tpu.utils.metrics import (
        parse_exposition,
        quantiles_from_histogram,
    )

    plan = plan_ops(seed, writers, write_ops, pg_readers, pg_ops, keys)
    problems: List[str] = []

    # keyspace + stop marker + headroom must fit the row budget
    cfg = cluster_config(n_nodes=n_nodes, n_rows=keys + 4)

    # per-leg results: one pre-allocated slot per thread, read only
    # after join (no shared mutation)
    w_out: List[Optional[dict]] = [None] * writers
    s_out: List[Optional[dict]] = [None] * subscribers
    p_out: List[Optional[dict]] = [None] * pg_readers

    with Agent(cfg) as agent:
        agent.wait_rounds(warm_rounds, timeout=deadline_s)
        db = Database(agent)
        with ApiServer(db, port=0) as api, PgServer(db, port=0) as pgs:
            setup = CorrosionApiClient(api.addr, api.port)
            setup.schema([LOAD_SCHEMA])
            # pre-populate the keyspace so writers are pure UPDATEs
            # (fixed row budget; INSERT-vs-UPDATE split stays seeded)
            setup.execute([
                ("INSERT INTO load_kv (k, v, who) VALUES (?, ?, ?)",
                 [f"k{i}", 0, "seed"])
                for i in range(keys)
            ])
            setup_tx_posts = 1
            agent.wait_rounds(2, timeout=deadline_s)

            def subscriber(i: int) -> None:
                out = {"lags": [], "changes": 0, "errors": 0,
                       "ready": False}
                s_out[i] = out
                c = CorrosionApiClient(api.addr, api.port)
                try:
                    stream = c.subscribe("SELECT k, v, who FROM load_kv",
                                         stream_timeout=deadline_s)
                    for ev in stream:
                        if "eoq" in ev:
                            out["ready"] = True
                        ch = ev.get("change")
                        if ch is None:
                            continue
                        _kind, key, row, _cid = ch
                        if key == _STOP_KEY:
                            break
                        out["changes"] += 1
                        if row and isinstance(row[1], int) and row[1] > 0:
                            out["lags"].append(
                                max(0.0, (time.time_ns() - row[1]) / 1e9))
                except (TimeoutError, OSError, ApiError):
                    out["errors"] += 1

            def writer(i: int) -> None:
                out = {"lat": [], "errors": 0, "http_503": 0, "posts": 0}
                w_out[i] = out
                c = CorrosionApiClient(api.addr, api.port)
                for key_idx in plan["writers"][i]:
                    t0 = time.perf_counter()
                    try:
                        out["posts"] += 1
                        c.execute([(
                            "UPDATE load_kv SET v = ?, who = ? WHERE k = ?",
                            [time.time_ns(), f"w{i}", f"k{key_idx}"],
                        )])
                        out["lat"].append(time.perf_counter() - t0)
                    except ApiError as e:
                        if e.status == 503:
                            out["http_503"] += 1
                        else:
                            out["errors"] += 1
                    except OSError:
                        out["errors"] += 1

            def pg_reader(i: int) -> None:
                out = {"lat": [], "errors": 0, "queries": 0}
                p_out[i] = out
                try:
                    client = _PgClient(pgs.addr, pgs.port)
                except OSError:
                    out["errors"] += 1
                    return
                try:
                    for key_idx in plan["pg"][i]:
                        t0 = time.perf_counter()
                        try:
                            out["queries"] += 1
                            rows = client.query(
                                "SELECT k, v, who FROM load_kv "
                                f"WHERE k = 'k{key_idx}'")
                            out["lat"].append(time.perf_counter() - t0)
                            if len(rows) != 1 or rows[0][0] != f"k{key_idx}":
                                out["errors"] += 1
                        except (RuntimeError, OSError):
                            out["errors"] += 1
                finally:
                    try:
                        client.close()
                    except OSError:
                        pass

            t_start = time.perf_counter()
            threads = [
                spawn_counted(lambda i=i: subscriber(i),
                              name=f"corro-load-sub-{i}")
                for i in range(subscribers)
            ]
            # subscribers must be attached (initial snapshot drained)
            # before the first write or early deliveries are invisible
            deadline = time.monotonic() + deadline_s
            while not all(s and s["ready"] for s in s_out):
                if time.monotonic() > deadline:
                    problems.append("subscribers never reached eoq")
                    break
                time.sleep(0.01)
            threads += [
                spawn_counted(lambda i=i: writer(i),
                              name=f"corro-load-writer-{i}")
                for i in range(writers)
            ]
            threads += [
                spawn_counted(lambda i=i: pg_reader(i),
                              name=f"corro-load-pg-{i}")
                for i in range(pg_readers)
            ]
            for t in threads[subscribers:]:
                t.join(timeout=deadline_s)
            # stop marker: subscribers exit when its change delivers
            try:
                setup.execute([(
                    "INSERT INTO load_kv (k, v, who) VALUES (?, ?, ?)",
                    [_STOP_KEY, 0, "stop"],
                )])
                setup_tx_posts += 1
            except ApiError:
                problems.append("stop-marker write failed")
            agent.wait_rounds(3, timeout=deadline_s)
            for t in threads[:subscribers]:
                t.join(timeout=deadline_s)
            duration = time.perf_counter() - t_start
            if any(t.is_alive() for t in threads):
                problems.append("load legs did not finish before deadline")

            # --- server-side scrape + agreement -----------------------
            scrape = parse_exposition(setup.metrics())
            hist = scrape["histograms"]

            def server_count(name: str, **want: str) -> int:
                total = 0
                for (pname, labels), h in hist.items():
                    if pname != name:
                        continue
                    lab = dict(labels)
                    if all(lab.get(k) == v for k, v in want.items()):
                        total += h["count"]
                return total

            def server_hist(name: str, **want: str) -> dict:
                agg = {"buckets": (), "counts": [], "sum": 0.0, "count": 0}
                for (pname, labels), h in hist.items():
                    if pname != name:
                        continue
                    lab = dict(labels)
                    if not all(lab.get(k) == v for k, v in want.items()):
                        continue
                    if not agg["counts"]:
                        agg["buckets"] = h["buckets"]
                        agg["counts"] = list(h["counts"])
                    else:
                        agg["counts"] = [
                            a + b
                            for a, b in zip(agg["counts"], h["counts"])
                        ]
                    agg["sum"] += h["sum"]
                    agg["count"] += h["count"]
                return agg

            client_tx = (sum(w["posts"] for w in w_out if w)
                         + setup_tx_posts)
            server_tx = server_count("corro_http_request_seconds",
                                     route="/v1/transactions", method="POST")
            client_pg = sum(p["queries"] for p in p_out if p)
            server_pg = server_count("corro_pg_query_seconds", kind="select")
            agreement = {
                "transactions": {"client": client_tx, "server": server_tx,
                                 "ok": client_tx == server_tx},
                "pg_select": {"client": client_pg, "server": server_pg,
                              "ok": client_pg == server_pg},
            }
            agreement["ok"] = (agreement["transactions"]["ok"]
                               and agreement["pg_select"]["ok"])
            if not agreement["ok"]:
                problems.append(f"server/client count disagreement: "
                                f"{agreement}")

            w_lat = [x for w in w_out if w for x in w["lat"]]
            p_lat = [x for p in p_out if p for x in p["lat"]]
            s_lag = [x for s in s_out if s for x in s["lags"]]
            w_errors = sum(w["errors"] for w in w_out if w)
            p_errors = sum(p["errors"] for p in p_out if p)
            s_errors = sum(s["errors"] for s in s_out if s)
            if w_errors or p_errors or s_errors:
                problems.append(
                    f"client errors: write={w_errors} pg={p_errors} "
                    f"sub={s_errors}")
            if not s_lag and subscribers:
                problems.append("subscribers observed no deliveries")

            delivery_h = server_hist("corro_subs_delivery_seconds")
            record = {
                "schema": BENCH_SERVE_SCHEMA,
                "kind": "bench_serve",
                "seed": seed,
                "plan_digest": plan["digest"],
                "n_nodes": n_nodes,
                "writers": writers,
                "subscribers": subscribers,
                "pg_readers": pg_readers,
                "write_ops_per_writer": write_ops,
                "pg_ops_per_reader": pg_ops,
                "keys": keys,
                "duration_s": duration,
                "qps": ((len(w_lat) + len(p_lat)) / duration
                        if duration > 0 else 0.0),
                "ops": {
                    "write": dict(
                        percentiles(w_lat),
                        count=len(w_lat), errors=w_errors,
                        http_503=sum(w["http_503"] for w in w_out if w),
                        qps=(len(w_lat) / duration if duration else 0.0),
                    ),
                    "pg_query": dict(
                        percentiles(p_lat),
                        count=len(p_lat), errors=p_errors,
                        qps=(len(p_lat) / duration if duration else 0.0),
                    ),
                    "subscribe_delivery": dict(
                        percentiles(s_lag),
                        count=len(s_lag), errors=s_errors,
                        changes=sum(s["changes"] for s in s_out if s),
                    ),
                },
                "server": {
                    "tx_requests": server_tx,
                    "pg_selects": server_pg,
                    "deliveries": delivery_h["count"],
                    "delivery_quantiles_s":
                        quantiles_from_histogram(delivery_h)
                        if delivery_h["count"] else None,
                    "unready_total": sum(
                        v for (n, _l), v in scrape["counters"].items()
                        if n == "corro_http_unready_total"),
                    "shed_total": sum(
                        v for (n, _l), v in scrape["counters"].items()
                        if n == "corro_subs_shed_total"),
                },
                "agreement": agreement,
                "problems": problems,
                "ok": not problems,
            }
            return record
