"""Live metrics bridge: segment infos -> the Prometheus registry.

Before ISSUE 11 the soak pipeline's per-round infos only surfaced in
``SoakResult`` after the run ended; a multi-hour soak showed nothing on
``/metrics``. The bridge drains each completed segment's infos into a
``utils.metrics.Registry`` mid-run — reusing the exact
``record_round_info`` key -> ``corro.*`` mapping the live agent round
loop uses — plus the ``corro.soak.*`` progress series, so both the
standalone Prometheus listener and the HTTP API's ``/metrics`` show a
soak advancing in real time.

Semantics per info kind (``utils.metrics.info_series``): counter keys
fold their PER-SEGMENT SUM into the counter (the cumulative scrape
value equals the straight per-round accumulation); gauge keys (queue
occupancy, activity levels) take the segment's LAST round — a gauge is
a level, not a total.
"""

from __future__ import annotations

from corrosion_tpu.utils.metrics import info_series, record_round_info


class MetricsBridge:
    """Per-run bridge onto one registry (the agent's, or a standalone
    one for CLI/bench soaks)."""

    def __init__(self, registry):
        self.registry = registry

    def on_segment(self, *, completed_rounds: int, rounds: int,
                   seconds: float, info_sum: dict, info_last: dict,
                   stats_delta: dict) -> None:
        reg = self.registry
        reg.counter("corro.soak.rounds_total", rounds)
        reg.counter("corro.soak.segments_total", 1)
        if seconds > 0:
            reg.gauge("corro.soak.rounds_per_s", rounds / seconds)
        reg.histogram("corro.soak.segment.seconds", seconds)
        # checkpoint pipeline deltas for THIS segment (the cumulative
        # stats dict is the run's; the scrape wants rates/levels)
        stall = stats_delta.get("ckpt_stall_s", 0.0)
        if stall > 0:
            reg.histogram("corro.soak.ckpt.stall.seconds", stall)
        drained = stats_delta.get("ckpt_drain_bytes", 0)
        if drained > 0:
            reg.counter("corro.soak.ckpt.drain.bytes", drained)
        if stats_delta.get("donated_segments", 0) > 0:
            reg.counter("corro.soak.segments.donated", 1)
        # round-info series: one merged record_round_info call — counter
        # keys carry the segment sum, gauge keys the last-round level
        merged = {}
        kinds = info_series()
        for key, (_name, kind) in kinds.items():
            if kind == "counter" and key in info_sum:
                merged[key] = info_sum[key]
            elif kind == "gauge" and key in info_last:
                merged[key] = info_last[key]
        record_round_info(merged, registry=reg)

    def on_end(self, *, completed_rounds: int, aborted: bool) -> None:
        reg = self.registry
        reg.gauge("corro.soak.completed.rounds", completed_rounds)
        reg.gauge("corro.soak.aborted", 1.0 if aborted else 0.0)
