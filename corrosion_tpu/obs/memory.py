"""Memory accounting: per-table nbytes audit of the simulator state.

The ROADMAP's million-node flagship item starts with a question this
module answers mechanically: *which tables of ``ScaleSimState`` are
O(N·M) and which are O(N)?* The audit walks the state pytree by FIELD
NAME (``swim.mem_id``, ``crdt.q_val``, ``crdt.store[1]`` …), records
each leaf's shape/dtype/nbytes, and classifies its scaling against the
cluster size — all from array METADATA, so auditing a live sharded
state moves zero device bytes (the sharding-contract checker treats
``.nbytes``/``.shape``/``.dtype`` as metadata, not a gather).

Exposed three ways: ``corro.mem.*`` gauges
(:func:`publish_memory_gauges`), the ``corrosion-tpu mem-report`` CLI,
and the ``hbm_bytes`` field every bench record now carries. The
invariant the obs smoke pins: the per-table audit SUMS to the measured
total state size — a table the walk misses would silently undercount
the 1M budget.

Since ISSUE 12 the audit has a STATIC twin: corrobudget
(``analysis/shapes.py``) derives the same inventory from the state
constructors' ASTs — symbolic shapes, no arrays built — and projects it
to arbitrary (N, M) (``mem-report --project``, the bench
``hbm_bytes_projected_1m`` field, and the lint-time ``mem-budget``
gate). Both planes classify leaves through the ONE
:func:`classify_leaf` below, and ``tests/test_membudget.py`` pins them
leaf-for-leaf against each other and ``jax.eval_shape``.
"""

from __future__ import annotations

import math
from typing import Optional


def _walk_leaves(obj, prefix: str, out: dict) -> None:
    """NamedTuple-aware named walk (jax's keypaths render NamedTuples as
    positional ``[i]`` entries; the audit wants ``swim.mem_id``)."""
    if hasattr(obj, "_fields"):  # NamedTuple state containers
        for f in obj._fields:
            _walk_leaves(getattr(obj, f),
                         f"{prefix}.{f}" if prefix else f, out)
    elif isinstance(obj, (tuple, list)):
        for i, v in enumerate(obj):
            _walk_leaves(v, f"{prefix}[{i}]", out)
    elif isinstance(obj, dict):
        for k in sorted(obj):
            _walk_leaves(obj[k], f"{prefix}.{k}" if prefix else str(k), out)
    elif obj is None:
        return
    else:
        out[prefix or "<leaf>"] = obj


def classify_leaf(shape, n_nodes: Optional[int]) -> str:
    """Scaling class against the cluster size: the leading axis of every
    per-node table is N, so ``[N]`` is O(N), ``[N, ...]`` is O(N·M)
    (M = the trailing extent), anything else is O(1) bookkeeping.

    THE classification — the runtime audit below and corrobudget's
    static inventory (``analysis/shapes.py``) both call it, so the two
    planes can never disagree about what a table costs."""
    if not n_nodes or not shape or shape[0] != n_nodes:
        return "O(1)"
    return "O(N)" if math.prod(shape[1:]) == 1 else "O(N*M)"


#: backward-compat alias (pre-ISSUE-12 internal name)
_classify = classify_leaf


def _fallback_nbytes(leaf) -> int:
    """nbytes for metadata-only leaves that don't carry the attribute
    (``jax.eval_shape`` returns ``ShapeDtypeStruct`` on some versions
    without it) — shape × itemsize, same arithmetic as a real array."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    import numpy as np

    return int(math.prod(shape) * np.dtype(dtype).itemsize)


def state_bytes(state) -> int:
    """Total nbytes of every array leaf — METADATA only, deliberately
    not a leaves-materializing drain (``.nbytes`` never moves device
    bytes; corrolint's shard-gather rule agrees)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(state):
        total += getattr(leaf, "nbytes", 0)
    return int(total)


def memory_report(state, n_nodes: Optional[int] = None) -> dict:
    """Per-table audit of a state pytree.

    Returns ``{"total_bytes", "n_nodes", "tables": {name: {"shape",
    "dtype", "nbytes", "class", "per_node_bytes"}}, "by_class": {cls:
    bytes}}``. ``per_node_bytes`` (O(N)/O(N·M) tables only) is the
    quantity the 1M budget multiplies: total = Σ per_node_bytes · N
    over the N-scaled tables, plus the O(1) remainder."""
    leaves: dict = {}
    _walk_leaves(state, "", leaves)
    tables = {}
    by_class: dict = {}
    total = 0
    for name, leaf in leaves.items():
        shape = tuple(int(s) for s in getattr(leaf, "shape", ()))
        nbytes = getattr(leaf, "nbytes", None)
        nbytes = int(nbytes) if nbytes is not None else (
            _fallback_nbytes(leaf))
        cls = classify_leaf(shape, n_nodes)
        entry = {
            "shape": list(shape),
            "dtype": str(getattr(leaf, "dtype", "?")),
            "nbytes": nbytes,
            "class": cls,
        }
        if cls != "O(1)" and n_nodes:
            entry["per_node_bytes"] = nbytes // n_nodes
        tables[name] = entry
        by_class[cls] = by_class.get(cls, 0) + nbytes
        total += nbytes
    return {
        "total_bytes": total,
        "n_nodes": n_nodes,
        "tables": tables,
        "by_class": by_class,
    }


def publish_memory_gauges(report: dict, registry) -> None:
    """Fold an audit into ``corro.mem.*`` gauges: the total, one gauge
    per table (labelled), and the per-class rollup — what a dashboard
    watches while the N sweep climbs toward 1M."""
    registry.gauge("corro.mem.state.bytes", report["total_bytes"])
    for name, entry in report["tables"].items():
        registry.gauge("corro.mem.table.bytes", entry["nbytes"],
                       labels={"table": name, "class": entry["class"]})
    for cls, nbytes in report["by_class"].items():
        registry.gauge("corro.mem.class.bytes", nbytes,
                       labels={"class": cls})


def static_report(cfg, mode: str = "scale",
                  n_nodes: Optional[int] = None,
                  m_slots: Optional[int] = None) -> dict:
    """STATIC projection of the state audit: corrobudget's symbolic
    inventory (``analysis/shapes.py``) evaluated at the config's
    extents, optionally rebinding N (and M). Same schema as
    :func:`memory_report` plus per-leaf ``symbolic`` shapes — and it
    never builds an array, so it prices N=1M on a laptop without
    paying for one (the old 2^19 ``validate()`` wall is gone — the
    sender election packs adaptive-width priorities now; the remaining
    ceiling is 2^30, docs/memory-budget.md)."""
    from corrosion_tpu.analysis import shapes

    inv = shapes.static_inventory(cfg, mode=mode)
    overrides = {}
    if n_nodes:
        overrides["N"] = int(n_nodes)
    if m_slots:
        overrides["M"] = int(m_slots)
    report = inv.report(overrides)
    report["mode"] = mode
    return report


def projected_bytes(cfg, n_nodes: int, mode: str = "scale") -> int:
    """Total projected HBM bytes of one state replica at ``n_nodes`` —
    the bench's ``hbm_bytes_projected_1m`` field (static projection of
    the SAME config the run used, so the recorded number prices the
    run's actual table set). A leaf the interpreter can't price is a
    loud error here, never a silent undercount — the single-number
    callers (bench JSON) have no ``unresolved`` field to look at."""
    report = static_report(cfg, mode=mode, n_nodes=n_nodes)
    if report.get("unresolved"):
        raise ValueError(
            "static projection has unpriceable leaves "
            f"{report['unresolved']}; the total would silently "
            "undercount (see docs/memory-budget.md)"
        )
    return int(report["total_bytes"])


def mem_report_cli(args) -> int:
    """``corrosion-tpu mem-report``: build the configured sim state and
    print the audit as JSON — the first step of the 1M memory-budget
    audit, runnable against any config without touching a device-sized
    cluster (state CREATION at the configured N is the only cost).

    ``--project N[,M]`` skips state creation entirely and prints the
    STATIC projection at that point instead (corrobudget's symbolic
    inventory — zero arrays, any N)."""
    import json

    from corrosion_tpu.config import Config, load_config

    cfg_file = load_config(args.config) if args.config else Config()
    if args.n_nodes:
        cfg_file.sim.n_nodes = args.n_nodes
    cfg = cfg_file.sim_config()
    mode = cfg_file.sim.mode
    if getattr(args, "project", None):
        parts = [int(p) for p in str(args.project).split(",") if p]
        n_proj = parts[0]
        m_proj = parts[1] if len(parts) > 1 else None
        report = static_report(cfg, mode=mode, n_nodes=n_proj,
                               m_slots=m_proj)
        print(json.dumps(report, indent=2))
        return 0
    if mode == "scale":
        from corrosion_tpu.sim.scale_step import ScaleSimState as StCls
    else:
        from corrosion_tpu.sim.step import SimState as StCls
    state = StCls.create(cfg)
    report = memory_report(state, cfg.n_nodes)
    report["mode"] = mode
    print(json.dumps(report, indent=2))
    return 0
