"""corrochaos: deterministic seeded fault scenarios over the segmented
soak pipeline (docs/chaos.md).

The reference survives production because Fly.io hammers Corrosion with
Antithesis-style fault workloads (PAPER.md: SWIM refutation,
anti-entropy after partitions, ``configurable_stress_test``). This
module is that discipline for the repro: **composable fault scenarios
expressed as data**, compiled into traced fault inputs for the sim
plane (``sim/scenario.compile_scale_phase``) and scripted host-plane
injections for the pipeline plane, driven through the REAL segmented
soak runner + Supervisor + AsyncCheckpointWriter, and oracle-checked.

Every scenario is a pure function of ``(name, seed)``: same seed, same
compiled trace, same injection schedule, same verdict — the
``trace_digest`` in the verdict pins it. Three oracles gate every run:

1. **convergence** — after the scripted fault phases the cluster must
   reach the converged fixpoint (``scale_crdt_metrics``: no needs,
   equal heads, equal stores over alive nodes) within the script's
   settle budget; and the chaos leg's post-script state must be
   BITWISE identical to an uninterrupted straight-scan reference of
   the same trace (preemptions, corrupt-checkpoint fallbacks, mesh
   changes and fused flips are execution noise, never semantics).
2. **checkpoint lineage** — every manifest the scenario left behind
   must either refuse to load loudly (a fault the scenario itself
   injected) or restore to a state that, replaying the remaining
   scripted rounds, lands bitwise on the SAME fixpoint as the
   uninterrupted run: no checkpoint ever restores diverged state.
3. **quiescence** — after the healed settle phase the per-node
   ``activity_masks`` (broadcast queues, partial buffers, sync needs,
   SWIM timers — the occupancy bits a future active-set round variant
   would gate on) must drain to all-zero over the alive nodes within
   the same settle budget: a converged cluster that still owes itself
   work is a liveness bug the convergence predicate alone cannot see.

Scripts are data and serialize losslessly: :func:`script_to_json` /
:func:`script_from_json` round-trip a script through plain JSON with
the ``trace_digest`` preserved (the digest hashes the identical
``dataclasses.asdict`` view) — the contract the committed
``tests/chaos_corpus/`` reproducers and ``corrosion-tpu chaos
--script FILE`` ride on (docs/chaos.md, "Corpus").

Host-plane injections (``Injection.kind``):

- ``crash_slice`` / ``crash_manifest`` — kill a save mid-write /
  between state-file write and manifest publish (the
  ``checkpoint._write_bytes`` / ``checkpoint._publish_manifest``
  seams); the soak crashes and must resume from the previous committed
  segment.
- ``preempt`` — drop the live carry at a phase boundary and resume
  from the newest valid checkpoint.
- ``corrupt_checkpoint`` — flip bytes in the newest checkpoint's first
  state file; the hash gate must refuse it and recovery must fall back
  to the previous segment.
- ``remesh`` — resume the checkpoint onto a DIFFERENT device mesh
  (e.g. 8 -> 4 chips, the PR-8 elastic-restore surface) mid-scenario.
- ``fused_flip`` — resume under a different ``config.perf.fused``
  execution mode (the PR-9 cross-mode surface).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from corrosion_tpu.checkpoint import (
    CheckpointIntegrityError,
    load_checkpoint,
)
from corrosion_tpu.resilience.retention import latest_valid_checkpoint
from corrosion_tpu.resilience.segments import (
    _key_from_json,
    _n_rounds,
    _slice_inputs,
    restore_soak_carry,
    run_segmented,
)
from corrosion_tpu.resilience.supervisor import Supervisor
from corrosion_tpu.sim.scenario import FaultPhase, compile_scale_phase
from corrosion_tpu.utils.tracing import logger

INJECTION_KINDS = (
    "preempt",
    "crash_slice",
    "crash_manifest",
    "corrupt_checkpoint",
    "remesh",
    "fused_flip",
    "quiet_flip",
)


@dataclasses.dataclass(frozen=True)
class Injection:
    """One host-plane fault. ``crash_*`` kinds arm a seam DURING phase
    ``phase`` (the checkpoint at that phase's final segment dies
    mid-commit); the other kinds apply at the boundary AFTER phase
    ``phase`` completes."""

    kind: str
    phase: int
    mesh_devices: int = 0  # remesh target (0 = single device)
    fused: str = ""  # fused_flip target execution mode
    quiet: str = ""  # quiet_flip target round variant (ISSUE 19)

    def validate(self) -> "Injection":
        if self.kind not in INJECTION_KINDS:
            raise ValueError(
                f"injection kind {self.kind!r} not in {INJECTION_KINDS}"
            )
        if self.phase < 0:
            raise ValueError(f"injection phase {self.phase} < 0")
        if self.kind == "fused_flip" and not self.fused:
            raise ValueError("fused_flip needs a target fused mode")
        if self.kind == "quiet_flip" and not self.quiet:
            raise ValueError("quiet_flip needs a target quiet mode")
        return self


@dataclasses.dataclass(frozen=True)
class ScenarioScript:
    """A whole scenario: device-plane fault phases + host-plane
    injections + the oracle budgets. Everything here is data — the
    verdict is a pure function of ``(script, seed)``."""

    name: str
    phases: Tuple[FaultPhase, ...]
    injections: Tuple[Injection, ...] = ()
    n_nodes: int = 24
    segment_rounds: int = 4
    settle_budget: int = 256  # quiet rounds allowed to reach the fixpoint
    keep_last: int = 64  # retention wide enough for the lineage oracle
    mesh_devices: int = 0  # initial mesh (0 = single device)
    fused: str = "auto"  # initial execution mode
    quiet: str = "auto"  # initial round variant (ISSUE 19)
    # minimum per-info-key sums the chaos leg must report (e.g. the
    # clock-skew script must actually trip the drift gate)
    expect_info: Tuple[Tuple[str, int], ...] = ()

    def validate(self) -> "ScenarioScript":
        if not self.phases:
            raise ValueError(f"scenario {self.name!r} has no phases")
        for ph in self.phases:
            ph.validate()
        for inj in self.injections:
            inj.validate()
            if inj.phase >= len(self.phases):
                raise ValueError(
                    f"injection {inj.kind!r} targets phase {inj.phase} "
                    f"but the script has {len(self.phases)}"
                )
        if self.segment_rounds <= 0 or self.settle_budget <= 0:
            raise ValueError("segment_rounds/settle_budget must be positive")
        return self

    @property
    def total_rounds(self) -> int:
        return sum(ph.rounds for ph in self.phases)


#: corpus/script JSON schema version (bump on incompatible script
#: field changes; ``script_from_json`` refuses other versions loudly)
SCRIPT_SCHEMA_VERSION = 1


def script_to_json(script: ScenarioScript) -> dict:
    """The script as plain JSON data — EXACTLY the
    ``dataclasses.asdict`` view :func:`compile_scenario` digests, plus
    a schema tag. A script that round-trips equal re-compiles to the
    same ``trace_digest`` (tests/test_fuzz.py pins it)."""
    script.validate()
    return {"schema": SCRIPT_SCHEMA_VERSION, **dataclasses.asdict(script)}


def script_from_json(obj: dict) -> ScenarioScript:
    """Inverse of :func:`script_to_json` (tuples restored, unknown keys
    refused, the result validated) — the loader behind corpus replay
    and ``corrosion-tpu chaos --script FILE``."""
    data = dict(obj)
    schema = int(data.pop("schema", SCRIPT_SCHEMA_VERSION))
    if schema != SCRIPT_SCHEMA_VERSION:
        raise ValueError(
            f"script schema {schema} != {SCRIPT_SCHEMA_VERSION}"
        )
    known = {f.name for f in dataclasses.fields(ScenarioScript)}
    unknown = sorted(set(data) - known)
    if unknown:
        raise ValueError(f"unknown script fields {unknown}")
    phases = tuple(
        FaultPhase(**p) for p in data.pop("phases", ())
    )
    injections = tuple(
        Injection(**i) for i in data.pop("injections", ())
    )
    expect_info = tuple(
        (str(k), int(v)) for k, v in data.pop("expect_info", ())
    )
    return ScenarioScript(
        phases=phases, injections=injections, expect_info=expect_info,
        **data,
    ).validate()


def scenario_config(script: ScenarioScript):
    """The scenario's sim config: the SAME small-N shapes as
    ``tests/test_resilience.scale_cfg`` (24 nodes, 8 slots, 4x2 grid,
    sync every 4) so chaos programs share the persistent compile cache
    with the resilience suite, plus the script's execution mode."""
    from corrosion_tpu.sim.scale_step import scale_sim_config

    return scale_sim_config(
        script.n_nodes, m_slots=8, n_origins=4, n_rows=4, n_cols=2,
        sync_interval=4, fused=script.fused, quiet=script.quiet,
    )


class PhaseTrace(NamedTuple):
    """One compiled phase: absolute round window + traced inputs."""

    start: int  # absolute first round of the phase
    rounds: int
    inputs: object  # stacked ScaleRoundInput, host-resident
    net: object  # the phase's constant NetModel
    skew: np.ndarray  # int32 [N] HLC units added at phase entry


def compile_scenario(script: ScenarioScript, seed: int):
    """-> (cfg, [PhaseTrace], trace_digest). Deterministic in
    ``(script, seed)``: the digest hashes the script declaration plus
    every compiled input/net/skew array byte-for-byte."""
    script.validate()
    cfg = scenario_config(script)
    root_key = jr.key(seed)
    h = hashlib.sha256(f"{script.name}:{seed}".encode())
    h.update(json.dumps(dataclasses.asdict(script), sort_keys=True).encode())
    traces, dead, start = [], None, 0
    for i, ph in enumerate(script.phases):
        inputs, net, skew, dead = compile_scale_phase(
            cfg, ph, jr.fold_in(root_key, i), dead
        )
        for leaf in jax.tree.leaves(inputs) + jax.tree.leaves(net):
            h.update(np.asarray(leaf).tobytes())
        h.update(skew.tobytes())
        traces.append(PhaseTrace(start, ph.rounds, inputs, net, skew))
        start += ph.rounds
    return cfg, traces, h.hexdigest()


class _StraightRunner:
    """Jitted straight-scan dispatch with the net as a traced argument:
    ONE compile per segment length serves every phase, every lineage
    replay and the settle loop, whatever the round's network shape."""

    def __init__(self, cfg):
        from corrosion_tpu.sim.scale_step import scale_run_rounds_carry

        self._cfg = cfg
        self._run = scale_run_rounds_carry
        self._fns: dict = {}

    def __call__(self, st, key, net, inputs):
        n = _n_rounds(inputs)
        if n not in self._fns:
            cfg, run = self._cfg, self._run
            self._fns[n] = jax.jit(
                lambda s, k, nt, i: run(cfg, s, nt, k, i)
            )
        (st, key), infos = self._fns[n](st, key, net, inputs)
        return st, key, infos


def _apply_skew(st, skew: np.ndarray, mesh, n_nodes: int):
    """Host-inject clock skew: bump the skewed nodes' HLCs by the
    pre-shifted amount (the scenario's analog of a wall clock running
    ahead; ``hlc_fold``'s max-drift gate is what it sweeps against)."""
    if not skew.any():
        return st
    bump = jnp.asarray(skew)
    if mesh is not None:
        from corrosion_tpu.parallel.mesh import shard_state

        bump = shard_state(mesh, n_nodes, bump)
    return st._replace(crdt=st.crdt._replace(hlc=st.crdt.hlc + bump))


def _phase_at(traces, pos: int) -> int:
    """Index of the phase whose round window contains ``pos``."""
    for i, tr in enumerate(traces):
        if tr.start <= pos < tr.start + tr.rounds:
            return i
    raise ValueError(f"round {pos} outside the scripted trace")


class _CrashSeam:
    """Arm one of the checkpoint crash seams against a specific segment
    directory; ``restore()`` always puts the real function back (the
    async writer is joined before run_segmented returns, so no write
    can race the restore)."""

    def __init__(self, kind: str, target_round: int):
        import corrosion_tpu.checkpoint as ckpt_mod

        self._mod = ckpt_mod
        target = f"seg-{target_round:08d}"
        if kind == "crash_manifest":
            self._attr, real = "_publish_manifest", ckpt_mod._publish_manifest

            def patched(tmp, final, _real=real):
                if target in final:
                    raise OSError(
                        f"corrochaos: killed between state write and "
                        f"manifest publish of {target}"
                    )
                return _real(tmp, final)
        else:  # crash_slice
            self._attr, real = "_write_bytes", ckpt_mod._write_bytes

            def patched(path, data, _real=real):
                if target in path and "shard-00000" in path:
                    raise OSError(
                        f"corrochaos: killed writing a state slice of "
                        f"{target}"
                    )
                return _real(path, data)

        self._real = real
        setattr(ckpt_mod, self._attr, patched)

    def restore(self) -> None:
        setattr(self._mod, self._attr, self._real)


def corrupt_checkpoint(path: str) -> str:
    """Flip a byte mid-way through the first state file the manifest
    records (the engine twin of ``tests/test_resilience.state_file``):
    the SHA-256 gate must refuse the directory on load."""
    with open(os.path.join(path, "manifest.json")) as f:
        files = sorted(json.load(f)["files"])
    if not files:
        raise ValueError(f"checkpoint {path} records no state files")
    fp = os.path.join(path, files[0])
    with open(fp, "rb") as f:
        data = bytearray(f.read())
    data[len(data) // 2] ^= 0xFF
    with open(fp, "wb") as f:
        f.write(bytes(data))
    return fp


def _make_mesh_or_skip(devices: int):
    """-> (mesh, skip_reason). A scenario that needs more devices than
    the process has is SKIPPED (reported, not failed) — check.sh and
    the test harness both force 8 virtual devices, so the remesh
    scripts always run there."""
    if devices <= 0:
        return None, None
    have = jax.devices()
    if len(have) < devices:
        return None, (
            f"needs {devices} devices, only {len(have)} available"
        )
    from corrosion_tpu.parallel.mesh import make_mesh

    return make_mesh(have[:devices]), None


def _place(mesh, n_nodes, *trees):
    if mesh is None:
        return trees if len(trees) > 1 else trees[0]
    from corrosion_tpu.parallel.mesh import shard_state

    placed = tuple(shard_state(mesh, n_nodes, t) for t in trees)
    return placed if len(placed) > 1 else placed[0]


def _resume_point(cfg, root: str, mesh):
    """The engine's restore path: the SAME gates a production resume
    runs (:func:`segments.restore_soak_carry` — newest VALID
    checkpoint, mode + config-identity drift refused, soak carry
    required), so the scenarios validate the restore path real soaks
    use, not a private re-implementation of it.
    -> (state, key, completed_rounds, path)."""
    return restore_soak_carry(cfg, root, mode="scale", mesh=mesh)


def _injected_crash(exc) -> bool:
    """True iff the exception chain carries a seam-injected kill (the
    ``corrochaos:`` marker the :class:`_CrashSeam` patches raise with).
    A genuine pipeline failure during an armed phase — e.g. a real
    disk-full ``OSError`` surfacing through the async writer's
    ``RuntimeError`` wrapper — must NOT be attributed to the scripted
    fault and silently recovered from."""
    seen: set = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if "corrochaos:" in str(exc):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def _host_state(st):
    """Owned host copies of a (possibly sharded) small-N scenario state
    — the oracle comparisons and the settle loop run single-device.
    Deliberate whole-state drain: chaos scenarios are 24-node rigs."""
    leaves, treedef = jax.tree.flatten(st)
    return treedef, [np.asarray(x) for x in leaves]


def _run_chaos_leg(cfg, script, traces, key0, root, rec, problems):
    """Drive the scripted trace through the REAL segmented pipeline,
    applying the host-plane injections. Returns (state, key) after the
    final scripted round (possibly mesh-placed / under a flipped
    execution config)."""
    from corrosion_tpu.ops import megakernel
    from corrosion_tpu.sim.scale_step import ScaleSimState

    mesh, skip = _make_mesh_or_skip(script.mesh_devices)
    if skip:
        return None, None, skip
    crash_by_phase = {
        inj.phase: inj for inj in script.injections
        if inj.kind in ("crash_slice", "crash_manifest")
    }
    boundary: dict = {}
    for inj in script.injections:
        if inj.kind not in ("crash_slice", "crash_manifest"):
            boundary.setdefault(inj.phase, []).append(inj)
    applied: set = set()

    run_cfg = cfg
    st = _place(mesh, cfg.n_nodes, ScaleSimState.create(cfg))
    key = key0
    total = script.total_rounds
    pos = 0
    info_sums: dict = {}
    while pos < total:
        phase_idx = _phase_at(traces, pos)
        tr = traces[phase_idx]
        if pos == tr.start:
            st = _apply_skew(st, tr.skew, mesh, cfg.n_nodes)
        inputs = _slice_inputs(tr.inputs, pos - tr.start, tr.rounds)
        net, inputs = _place(mesh, cfg.n_nodes, tr.net, inputs)
        crash = crash_by_phase.get(phase_idx)
        seam = None
        if crash is not None and id(crash) not in applied:
            seam = _CrashSeam(crash.kind, tr.start + tr.rounds)
        try:
            res = run_segmented(
                run_cfg, st, net, key, inputs, script.segment_rounds,
                mode="scale", checkpoint_root=root,
                keep_last=script.keep_last, supervisor=Supervisor(),
                start_round=pos,
            )
        except RuntimeError as e:
            if seam is None or not _injected_crash(e):
                raise
            # the injected mid-commit kill: the run died with the
            # target segment's checkpoint uncommitted — recover the
            # way a preempted soak does
            applied.add(id(crash))
            rec["faults_injected"] += 1
            seam.restore()
            seam = None
            st, key, pos, path = _resume_point(run_cfg, root, mesh)
            rec["resumes"] += 1
            logger.info("chaos %s: crashed save recovered from %s",
                        script.name, path)
            continue
        finally:
            if seam is not None:
                seam.restore()
        if seam is not None and id(crash) not in applied:
            problems.append(
                f"{crash.kind} armed for phase {phase_idx} never fired"
            )
        st, key = res.state, res.key
        pos = res.completed_rounds
        if res.aborted:
            problems.append(f"soak aborted at round {pos}")
            break
        for k, v in res.infos.items():
            info_sums[k] = info_sums.get(k, 0) + int(np.asarray(v).sum())
        if pos != tr.start + tr.rounds:
            continue
        for inj in boundary.get(phase_idx, []):
            if id(inj) in applied:
                continue
            applied.add(id(inj))
            rec["faults_injected"] += 1
            if inj.kind == "corrupt_checkpoint":
                newest = latest_valid_checkpoint(root)
                corrupt_checkpoint(newest)
                rec["corrupted"].append(os.path.basename(newest))
                try:
                    load_checkpoint(newest, verify=True)
                    problems.append(
                        f"corruption of {newest} was NOT detected"
                    )
                except CheckpointIntegrityError:
                    rec["corruptions_detected"] += 1
                st, key, pos, path = _resume_point(run_cfg, root, mesh)
                rec["resumes"] += 1
                if path == newest:
                    problems.append(
                        "recovery resumed from the corrupted checkpoint"
                    )
            elif inj.kind == "preempt":
                st, key, pos, _ = _resume_point(run_cfg, root, mesh)
                rec["resumes"] += 1
            elif inj.kind == "remesh":
                mesh, skip = _make_mesh_or_skip(inj.mesh_devices)
                if skip:
                    return None, None, skip
                st, key, pos, _ = _resume_point(run_cfg, root, mesh)
                rec["resumes"] += 1
                rec["remeshes"] += 1
            elif inj.kind == "fused_flip":
                run_cfg = dataclasses.replace(
                    cfg, fused=inj.fused).validate()
                megakernel.prime_fused(run_cfg)
                st, key, pos, _ = _resume_point(run_cfg, root, mesh)
                rec["resumes"] += 1
                rec["fused_flips"].append(inj.fused)
            elif inj.kind == "quiet_flip":
                # quiet<->dense across a resume (ISSUE 19): replace
                # from run_cfg so the flip composes with a prior
                # fused_flip instead of silently reverting it
                run_cfg = dataclasses.replace(
                    run_cfg, quiet=inj.quiet).validate()
                st, key, pos, _ = _resume_point(run_cfg, root, mesh)
                rec["resumes"] += 1
                rec["quiet_flips"].append(inj.quiet)
    rec["info_sums"] = {k: info_sums[k] for k in sorted(info_sums)}
    for inj in script.injections:
        if id(inj) not in applied:
            problems.append(
                f"injection {inj.kind!r} at phase {inj.phase} never applied"
            )
    return st, key, None


def _settle(cfg, st, key, runner, budget: int, chunk: int = 8):
    """Quiet, healed rounds until the convergence predicate holds AND
    the activity masks drain over the alive nodes (oracles 1 + 3 share
    the one settle budget).
    -> (rounds_to_converge or -1, converged,
        rounds_to_quiesce or -1, quiesced)."""
    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        activity_masks,
        scale_crdt_metrics,
    )
    from corrosion_tpu.sim.transport import NetModel

    net = NetModel.create(cfg.n_nodes)
    quiet = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (chunk,) + a.shape),
        ScaleRoundInput.quiet(cfg),
    )
    # quiescence over ALIVE nodes (the oracle-1 convention): a corpse's
    # frozen tables owe the cluster nothing — alive nodes' timers ABOUT
    # the corpse still count, and drain once the purge completes
    probe = jax.jit(lambda s: (
        scale_crdt_metrics(cfg, s)["converged"],
        jnp.any(jnp.stack([
            jnp.any(m & s.swim.alive)
            for m in activity_masks(cfg, s).values()
        ])),
    ))
    taken = 0
    conv_at = quiet_at = -1
    conv, active = (bool(x) for x in probe(st))
    if conv:
        conv_at = 0
    if not active:
        quiet_at = 0
    while (conv_at < 0 or quiet_at < 0) and taken < budget:
        st, key, _ = runner(st, key, net, quiet)
        taken += chunk
        conv, active = (bool(x) for x in probe(st))
        if conv_at < 0 and conv:
            conv_at = taken
        if quiet_at < 0 and not active:
            quiet_at = taken
    return conv_at, conv_at >= 0, quiet_at, quiet_at >= 0


def _validate_lineage(cfg, script, traces, root, ref_leaves, runner, rec,
                      problems):
    """Oracle 2: every manifest left behind restores + replays to the
    uninterrupted fixpoint, or refuses loudly."""
    total = script.total_rounds
    for name in sorted(os.listdir(root)):
        if not name.startswith("seg-"):
            continue
        path = os.path.join(root, name)
        try:
            manifest, state = load_checkpoint(path, verify=True)
        except (CheckpointIntegrityError, ValueError) as e:
            rec["checkpoints_refused"] += 1
            if name not in rec["corrupted"]:
                problems.append(
                    f"lineage: {name} refused outside an injected "
                    f"corruption: {e}"
                )
            continue
        soak = (manifest.get("extra") or {}).get("soak") or {}
        if "completed_rounds" not in soak:
            problems.append(f"lineage: {name} has no soak carry")
            continue
        pos = int(soak["completed_rounds"])
        key = _key_from_json(soak["key"])
        st = state
        while pos < total:
            tr = traces[_phase_at(traces, pos)]
            if pos == tr.start:
                st = _apply_skew(st, tr.skew, None, cfg.n_nodes)
            inputs = _slice_inputs(tr.inputs, pos - tr.start, tr.rounds)
            st, key, _ = runner(st, key, tr.net, inputs)
            pos = tr.start + tr.rounds
        for i, (got, want) in enumerate(
                zip(jax.tree.leaves(st), ref_leaves)):
            if not np.array_equal(np.asarray(got), want):
                problems.append(
                    f"lineage: {name} replays to a DIVERGED state "
                    f"(leaf {i})"
                )
                break
        else:
            rec["checkpoints_validated"] += 1
    if rec["checkpoints_validated"] == 0:
        problems.append("lineage: no checkpoint survived to validate")


def run_scenario(script: ScenarioScript, seed: int = 0,
                 workdir: Optional[str] = None,
                 keep_workdir: bool = False) -> dict:
    """Run one scenario end to end; -> the verdict record
    (deterministic in ``(script, seed)``; see module docstring for the
    oracle definitions)."""
    from corrosion_tpu.ops import megakernel

    cfg, traces, digest = compile_scenario(script, seed)
    root_dir = workdir or tempfile.mkdtemp(prefix=f"chaos-{script.name}-")
    root = os.path.join(root_dir, "ckpt")
    rec = {
        "name": script.name,
        "seed": int(seed),
        "n_nodes": cfg.n_nodes,
        "trace_digest": digest,
        "rounds_scripted": script.total_rounds,
        "phases": len(script.phases),
        "faults_injected": 0,
        "resumes": 0,
        "remeshes": 0,
        "fused_flips": [],
        "quiet_flips": [],
        "corrupted": [],
        "corruptions_detected": 0,
        "checkpoints_validated": 0,
        "checkpoints_refused": 0,
    }
    problems: list = []
    try:
        megakernel.prime_fused(cfg)
        runner = _StraightRunner(cfg)
        key0 = jr.key(seed + 1)

        # uninterrupted reference: the same compiled trace, straight
        # through — the fixpoint both oracles are judged against
        from corrosion_tpu.sim.scale_step import ScaleSimState

        ref_st, ref_key = ScaleSimState.create(cfg), key0
        for tr in traces:
            ref_st = _apply_skew(ref_st, tr.skew, None, cfg.n_nodes)
            ref_st, ref_key, _ = runner(ref_st, ref_key, tr.net, tr.inputs)
        _, ref_leaves = _host_state(ref_st)
        # content digest of the fixpoint: two runs of the same (script,
        # seed) under different EXECUTION-ONLY knobs (quiet, fused) must
        # publish the same digest — the quiet-parity probe
        # (scripts/quiet_probe.py) compares these across round variants
        h = hashlib.sha256()
        for a in ref_leaves:
            h.update(np.asarray(a).tobytes())
        rec["state_digest"] = h.hexdigest()

        # chaos leg: same trace through the segmented pipeline + faults
        st, key, skip = _run_chaos_leg(
            cfg, script, traces, key0, root, rec, problems)
        if skip:
            rec["skipped"] = skip
            rec["ok"] = True
            return rec

        treedef, chaos_leaves = _host_state(st)
        mismatch = [
            i for i, (a, b) in enumerate(zip(chaos_leaves, ref_leaves))
            if not np.array_equal(a, b)
        ]
        rec["bitwise_match"] = not mismatch
        if mismatch:
            problems.append(
                f"chaos leg diverged from the uninterrupted reference "
                f"at leaves {mismatch[:4]}"
            )

        for k, want in script.expect_info:
            got = rec.get("info_sums", {}).get(k, 0)
            rec[f"observed_{k}"] = got
            if got < want:
                problems.append(
                    f"expected info {k} >= {want}, observed {got}"
                )

        # oracle 1: settle the chaos state to the converged fixpoint;
        # oracle 3: the activity masks must then drain to all-zero
        # (same quiet rounds, same budget)
        st_host = jax.tree.unflatten(
            treedef, [jnp.asarray(x) for x in chaos_leaves])
        settle_rounds, converged, quiesce_rounds, quiesced = _settle(
            cfg, st_host, key, runner, script.settle_budget)
        rec["converged"] = converged
        rec["rounds_to_convergence"] = (
            script.total_rounds + settle_rounds if converged else -1
        )
        rec["quiesced"] = quiesced
        rec["rounds_to_quiescence"] = (
            script.total_rounds + quiesce_rounds if quiesced else -1
        )
        if not converged:
            problems.append(
                f"did not converge within {script.settle_budget} settle "
                f"rounds"
            )
        if not quiesced:
            problems.append(
                f"activity masks did not drain within "
                f"{script.settle_budget} settle rounds (oracle 3)"
            )

        # oracle 2: the checkpoint lineage
        _validate_lineage(cfg, script, traces, root, ref_leaves, runner,
                          rec, problems)
    except Exception as e:
        # a broken scenario (e.g. a user script whose injected crash
        # kills the FIRST ever save, leaving nothing to resume from)
        # fails ITS verdict — it must never take the rest of a sweep
        # down with it
        logger.exception("chaos %s: engine error", script.name)
        problems.append(f"engine error: {e!r}")
    finally:
        if workdir is None and not keep_workdir:
            shutil.rmtree(root_dir, ignore_errors=True)
    rec["ok"] = not problems
    if problems:
        rec["problems"] = problems
    return rec


# --- the shipped scenario registry ---------------------------------------
# Names are load-bearing: docs/chaos.md documents every entry (pinned by
# the tests/test_chaos.py meta-test) and `corrosion-tpu chaos` runs them
# by (name, seed).

SCENARIOS = {
    s.name: s.validate()
    for s in (
        # asymmetric partition that heals mid-sync: both islands keep
        # writing under loss, then the heal phase lets anti-entropy
        # repair the divergence
        ScenarioScript(
            name="partition-heal",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3, partition_groups=2,
                           drop_prob=0.02),
                FaultPhase(rounds=8, write_frac=0.2),
                FaultPhase(rounds=8),
            ),
            expect_info=(("syncs", 1),),
        ),
        # clock skew swept against the HLC max-drift gate: first under
        # it (folds cleanly), then far past it (receivers must REJECT
        # the stamps — and anti-entropy still converges the data)
        ScenarioScript(
            name="clock-skew",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3, clock_skew_rounds=1,
                           clock_skew_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.3, clock_skew_rounds=12,
                           clock_skew_frac=0.3),
                FaultPhase(rounds=8),
            ),
            expect_info=(("clock_drift_rejects", 1),),
        ),
        # node state-loss-and-rejoin: a quarter of the non-seed nodes
        # die (suspicion -> Down), then rejoin with bumped incarnations
        # under heavy datagram loss — the refutation machinery must
        # overturn the stale Down beliefs
        ScenarioScript(
            name="rejoin-refutation",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3, kill_frac=0.25,
                           drop_prob=0.15),
                FaultPhase(rounds=8, write_frac=0.2, revive_killed=True,
                           drop_prob=0.15),
                FaultPhase(rounds=8),
            ),
            expect_info=(("refutes", 1), ("failed_probes", 1)),
        ),
        # mid-segment preemption, both crash windows: a state-slice
        # write dies mid-file, and a later save is killed BETWEEN the
        # state write and the manifest publish — each time the soak
        # must resume from the previous committed segment
        ScenarioScript(
            name="preempt-mid-segment",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.2),
                FaultPhase(rounds=4),
            ),
            injections=(
                Injection(kind="crash_slice", phase=0),
                Injection(kind="crash_manifest", phase=1),
            ),
        ),
        # checkpoint corruption on restore: flip bytes in the newest
        # committed checkpoint, preempt, and recovery must refuse it
        # (hash gate) and fall back to the previous segment
        ScenarioScript(
            name="ckpt-corrupt",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.1),
            ),
            injections=(
                Injection(kind="corrupt_checkpoint", phase=0),
            ),
        ),
        # elastic restore onto a DIFFERENT mesh mid-scenario (the PR-8
        # surface): start sharded over 8 devices, preempt, resume the
        # same checkpoint lineage on 4
        ScenarioScript(
            name="elastic-remesh",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.1),
            ),
            injections=(
                Injection(kind="remesh", phase=0, mesh_devices=4),
            ),
            mesh_devices=8,
        ),
        # fused<->unfused execution-mode flip across a resume (the PR-9
        # surface): the pallas interpret path writes the checkpoints,
        # the XLA path resumes them — bitwise, per config_identity
        ScenarioScript(
            name="fused-flip",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.1),
            ),
            injections=(
                Injection(kind="fused_flip", phase=0, fused="off"),
            ),
            fused="interpret",
        ),
        # quiet<->dense round-variant flip across resumes (ISSUE 19):
        # the active-set round writes the checkpoints, the dense round
        # resumes them mid-lineage, then flips back — both directions
        # in one lineage, bitwise per config_identity (quiet is
        # execution-only). The tail phase is write-free so the flipped-
        # back leg actually exercises the cheap fixpoint path
        ScenarioScript(
            name="quiet-flip",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.1),
                FaultPhase(rounds=8),
            ),
            injections=(
                Injection(kind="quiet_flip", phase=0, quiet="off"),
                Injection(kind="quiet_flip", phase=1, quiet="on"),
            ),
            quiet="on",
        ),
        # --- composed multi-fault scenarios (ISSUE 18): the ROADMAP's
        # "multi-fault compositions" rungs, promoted from the fuzzer's
        # grammar into named regression scripts ------------------------
        # checkpoint corruption AND an 8->4 remesh in ONE lineage: the
        # hash-gate fallback must land on a checkpoint that still
        # restores elastically onto the smaller mesh
        ScenarioScript(
            name="corrupt-remesh",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.2),
                FaultPhase(rounds=8),
            ),
            injections=(
                Injection(kind="corrupt_checkpoint", phase=0),
                Injection(kind="remesh", phase=1, mesh_devices=4),
            ),
            mesh_devices=8,
        ),
        # HLC drift past the max-drift gate WHILE a 2-island partition
        # is live: rejected stamps and partitioned anti-entropy in the
        # same window, then the heal phase must still converge
        ScenarioScript(
            name="skew-partition",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3, partition_groups=2,
                           drop_prob=0.05, clock_skew_rounds=12,
                           clock_skew_frac=0.3),
                FaultPhase(rounds=8, write_frac=0.2),
                FaultPhase(rounds=8),
            ),
            expect_info=(("clock_drift_rejects", 1), ("syncs", 1)),
        ),
        # repeated preemption across BOTH crash windows while a quarter
        # of the non-seed nodes die and later rejoin: every resume must
        # land on a committed segment and the refutation machinery must
        # still overturn the stale Down beliefs
        ScenarioScript(
            name="preempt-storm",
            phases=(
                FaultPhase(rounds=8, write_frac=0.3, kill_frac=0.25,
                           drop_prob=0.1),
                FaultPhase(rounds=8, write_frac=0.2),
                FaultPhase(rounds=8, write_frac=0.1, revive_killed=True),
                FaultPhase(rounds=8),
            ),
            injections=(
                Injection(kind="crash_slice", phase=0),
                Injection(kind="preempt", phase=1),
                Injection(kind="crash_manifest", phase=2),
            ),
            expect_info=(("refutes", 1),),
        ),
    )
}

#: the small-N subset the tier-1 suite replays (and check.sh runs
#: under CORROSAN=1 — the rest ride the slow sweep + the check.sh
#: chaos stage). Two scripts, chosen to cover both oracle-stressing
#: host-plane families (crash windows; corruption fallback) — the
#: injection-free scripts exercise nothing the engine machinery these
#: two already drive, so tier-1 buys no coverage by adding them
TIER1_SCENARIOS = ("preempt-mid-segment", "ckpt-corrupt")


def _host_scenarios() -> dict:
    """Host-plane scenarios: serving-plane rigs judged by serving-plane
    oracles (no compiled fault traces, no device-state bitwise oracle).
    They are NOT in the default sweep — ``SCENARIOS`` stays the
    device-plane registry the sweep artifact schema is pinned to — and
    run only when named explicitly."""
    from corrosion_tpu.resilience.serve_overload import run_serve_overload

    return {"serve-overload": run_serve_overload}


def run_sweep(names=None, seed: int = 0, seed_range=None) -> dict:
    """Run a set of scenarios and fold the verdicts into one
    artifact-shaped record. Default: every device-plane scenario in
    ``SCENARIOS``; host-plane scenarios (``serve-overload``) join only
    when named explicitly.

    ``seed_range=(a, b)`` sweeps seeds ``a..b`` inclusive — every
    scenario runs once per seed and the record gains ``seed_range``
    plus a ``per_seed`` map of rounds-to-convergence per scenario, the
    determinism evidence the chaos artifact exists to carry."""
    hosts = _host_scenarios()
    names = list(names) if names else sorted(SCENARIOS)
    for name in names:
        if name not in SCENARIOS and name not in hosts:
            raise ValueError(
                f"unknown scenario {name!r}; have "
                f"{sorted(SCENARIOS) + sorted(hosts)}"
            )
    if seed_range is not None:
        a, b = int(seed_range[0]), int(seed_range[1])
        if b < a:
            raise ValueError(f"bad seed range {a}:{b}")
        seeds = list(range(a, b + 1))
    else:
        seeds = [int(seed)]
    records = []
    for s in seeds:
        for name in names:
            if name in SCENARIOS:
                records.append(run_scenario(SCENARIOS[name], seed=s))
            else:
                records.append(hosts[name](seed=s))
    out = {
        "metric": "chaos_sweep",
        "seed": int(seeds[0]),
        "platform": jax.devices()[0].platform,
        "scenarios": records,
        "ok": all(r["ok"] for r in records),
    }
    if seed_range is not None:
        out["seed_range"] = [seeds[0], seeds[-1]]
        per_seed: dict = {}
        for r in records:
            entry = per_seed.setdefault(str(r["seed"]), {})
            entry[r["name"]] = (
                r.get("rounds_to_convergence", -1)
                if not r.get("skipped") and not r.get("host_plane")
                else None
            )
        out["per_seed"] = per_seed
    return out
