"""Checkpoint retention: keep-last-K pruning + an atomic ``LATEST``
pointer.

A soak run's checkpoint root accumulates one directory per segment
(``seg-<completed rounds>`` — since manifest v3 each holds one slice
file per saving device plus the manifest; retention operates on whole
directories, so the unit of keep/prune is unchanged). Two invariants:

- ``LATEST`` is a one-line file naming the newest *committed* checkpoint
  directory, updated via write-tmp + ``os.replace`` — readers never see
  a partial pointer, and the pointer only moves AFTER the directory it
  names is fully committed (manifest-last, see ``checkpoint.py``).
- pruning never removes the directory ``LATEST`` points at, so the
  recovery point survives even a keep-last-1 policy racing a new save.
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional

from corrosion_tpu.utils.tracing import logger

LATEST_NAME = "LATEST"


def update_latest(root: str, name: str) -> None:
    """Atomically point ``root/LATEST`` at checkpoint directory ``name``
    (a path relative to ``root``)."""
    target = os.path.join(root, LATEST_NAME)
    tmp = target + ".tmp"
    with open(tmp, "w") as f:
        f.write(name + "\n")
    os.replace(tmp, target)


def read_latest(root: str) -> Optional[str]:
    """The directory name ``LATEST`` points at, or None when there is no
    pointer (or it names a directory that no longer exists)."""
    target = os.path.join(root, LATEST_NAME)
    if not os.path.exists(target):
        return None
    with open(target) as f:
        name = f.read().strip()
    if not name or not os.path.isdir(os.path.join(root, name)):
        return None
    return name


def checkpoint_dirs(root: str) -> List[str]:
    """Candidate checkpoint directory names under ``root``, newest first
    (by manifest mtime — the manifest is written last, so its mtime is
    the commit time)."""
    if not os.path.isdir(root):
        return []
    found = []
    for name in os.listdir(root):
        manifest = os.path.join(root, name, "manifest.json")
        if os.path.isfile(manifest):
            found.append((os.path.getmtime(manifest), name))
    return [name for _, name in sorted(found, reverse=True)]


def prune_checkpoints(root: str, keep_last: int) -> List[str]:
    """Delete committed checkpoints beyond the newest ``keep_last``,
    never touching the one ``LATEST`` names. Returns the pruned names."""
    keep_last = max(1, keep_last)
    names = checkpoint_dirs(root)
    pinned = read_latest(root)
    pruned = []
    for name in names[keep_last:]:
        if name == pinned:
            continue
        shutil.rmtree(os.path.join(root, name), ignore_errors=True)
        pruned.append(name)
    return pruned


def iter_valid_checkpoints(root: str):
    """Yield absolute paths of checkpoints under ``root`` that pass full
    integrity verification, newest-first (the ``LATEST`` pointer's
    target first when it is committed).

    A half-written or tampered side is logged and skipped — it must
    never mask an older good recovery point. Callers that can also fail
    AFTER verification (restore errors, config gates) keep iterating to
    the next-newest candidate."""
    from corrosion_tpu.checkpoint import verify_checkpoint

    candidates = checkpoint_dirs(root)
    pinned = read_latest(root)
    if pinned in candidates:
        candidates = [pinned] + [n for n in candidates if n != pinned]
    for name in candidates:
        path = os.path.join(root, name)
        try:
            verify_checkpoint(path)
        except Exception:  # noqa: BLE001 — fall back to the next-newest
            logger.exception("checkpoint %s fails verification; trying "
                             "the next-newest", path)
            continue
        yield path


def latest_valid_checkpoint(root: str) -> Optional[str]:
    """Absolute path of the newest checkpoint under ``root`` that passes
    full integrity verification (see :func:`iter_valid_checkpoints`)."""
    return next(iter_valid_checkpoints(root), None)
