"""Asynchronous, double-buffered segment checkpointing.

PR-3's segmented soak runner stalled the device at every segment
boundary: a synchronous ``device_get`` of the whole state, then SHA-256
hashing and a compressed ``.npz`` write — all on the hot loop, all
scaling with state size. Training stacks solve this with an async
checkpointer (snapshot to host, hand off to a background writer, keep
stepping); this module is that shape for the soak runner.

Split of work per segment boundary:

- **hot loop (synchronous)** — enqueue ``copy_to_host_async`` on every
  addressable SHARD, then materialize owned per-shard numpy slices
  (``parallel.mesh.host_shard_copy``). This is the only stall; it is
  bounded by the D2H transfer of each device's own slice — never a
  replicated whole-tree gather — so under a mesh it scales with
  per-shard state, not total state. The copies must be owned
  (``np.array``, not ``np.asarray`` views): the next segment's dispatch
  donates the device buffers, and a numpy view of a donated buffer
  would both block the donation and read freed memory.
- **worker thread (overlapped)** — per-shard slice serialization +
  SHA-256 (in parallel across shard files) + manifest write +
  ``LATEST`` pointer + retention pruning, via the exact same
  crash-consistent path as the synchronous writer
  (:func:`write_segment_checkpoint`), while the next segment's
  ``lax.scan`` runs.

Invariants preserved bit for bit from PR-1/PR-3: manifest-last commit
ordering, SHA-256 leaf hashes, ``LATEST`` moves only after the directory
is committed, pruning never deletes the pointer target. The only
semantic change is the loss window: a crash can now also lose the ONE
checkpoint still in flight on the worker (the queue is depth-1), i.e. at
most one extra segment of work.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, NamedTuple, Optional

import jax

from corrosion_tpu.checkpoint import save_checkpoint
from corrosion_tpu.resilience.retention import (
    prune_checkpoints,
    update_latest,
)
from corrosion_tpu.utils.tracing import logger


class _SegmentView:
    """The minimal agent-shaped surface ``save_checkpoint`` needs — the
    soak runner has no live Agent, just the scan carry."""

    def __init__(self, mode: str, cfg, state, round_no: int):
        self.mode = mode
        self.cfg = cfg
        self.round_no = round_no
        self._state = state

    def device_state(self):
        return self._state


def write_segment_checkpoint(cfg, mode: str, state, key_json: dict,
                             completed: int, root: str, keep_last: int,
                             db=None, io_stats=None) -> str:
    """Commit one segment checkpoint (crash-consistent ordering).

    ``state`` may be a per-shard drained tree (leaves are
    ``parallel.mesh.HostLeafShards`` — the soak runner's shape, written
    as the sharded v3 slice layout), a device pytree, or host numpy
    copies. ``key_json`` is the serialized carried PRNG key
    (``segments._key_to_json``). ``io_stats`` receives the save path's
    ``serialize_s``/``shard_files`` telemetry."""
    from corrosion_tpu.parallel.mesh import HostLeafShards
    from corrosion_tpu.utils.tracing import span

    leaves = jax.tree.leaves(state)
    shards = state if (
        leaves and isinstance(leaves[0], HostLeafShards)) else None
    name = f"seg-{completed:08d}"
    view = _SegmentView(mode, cfg, state, completed)
    # pipeline span (ISSUE 11): on the async writer this runs OVERLAPPED
    # with the next segment's dispatch — the OTLP export shows the
    # serialize span riding under soak.segment.dispatch wall time
    with span("soak.ckpt.serialize", warn_seconds=30.0, round=completed):
        path = save_checkpoint(
            view, db=db, path=os.path.join(root, name),
            extra={"soak": {
                "completed_rounds": completed,
                "key": key_json,
            }},
            shards=shards, io_stats=io_stats,
        )
    # pointer moves only AFTER the directory is fully committed; pruning
    # runs last so the recovery point is never the one being deleted
    update_latest(root, name)
    prune_checkpoints(root, keep_last)
    logger.info("soak checkpoint at round %d -> %s", completed, path)
    return path


class _Job(NamedTuple):
    state: object  # host numpy pytree (owned copies)
    key_json: dict
    completed: int
    seg_index: int  # the submitting segment's ordinal in this run


class AsyncCheckpointWriter:
    """Single background writer with a depth-1 queue (double buffering).

    At most one snapshot is in flight: submitting while the previous
    write is still running blocks until it commits, bounding host memory
    at two snapshots (the one being written + the one being staged) and
    keeping ``LATEST`` updates ordered. A write failure is re-raised on
    the next :meth:`submit` or on :meth:`close` — the soak must not keep
    running believing checkpoints are landing."""

    def __init__(self, cfg, mode: str, root: str, keep_last: int = 3,
                 db=None, progress: Optional[Callable[[], int]] = None):
        self._cfg, self._mode = cfg, mode
        self._root, self._keep_last, self._db = root, keep_last, db
        # reports the runner's current segment ordinal; a write that
        # finishes after the runner moved past its segment genuinely
        # overlapped compute
        self._progress = progress or (lambda: 0)
        self._q: "queue.Queue[Optional[_Job]]" = queue.Queue(maxsize=1)
        # the error handoff crosses threads (worker sets, submitter
        # clears): guard it — an unsynchronized check-then-clear could
        # drop a failure between the read and the reset
        self._mu = threading.Lock()
        self._error: Optional[BaseException] = None
        self.last_path: Optional[str] = None
        self.io_seconds = 0.0
        self.serialize_seconds = 0.0  # parallel per-shard serialize+hash
        self.shard_files = 0  # slice files in the newest written ckpt
        self.written = 0
        self.overlapped = 0
        from corrosion_tpu.utils.lifecycle import spawn_counted

        self._thread = spawn_counted(self._run, name="corro-async-ckpt")

    def _raise_pending(self) -> None:
        with self._mu:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "async checkpoint write failed; the previous segment has "
                "no committed recovery point"
            ) from err

    def submit(self, state, key_json: dict, completed: int,
               seg_index: int) -> None:
        """Queue one snapshot for writing. Blocks while the previous
        write is still in flight (double-buffer backpressure)."""
        self._raise_pending()
        self._q.put(_Job(state, key_json, completed, seg_index))

    def close(self) -> Optional[str]:
        """Drain outstanding writes, stop the worker, and return the
        newest committed checkpoint path. Re-raises a pending write
        failure."""
        self._q.put(None)
        self._thread.join()
        self._raise_pending()
        return self.last_path

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                t0 = time.perf_counter()
                io_stats: dict = {}
                self.last_path = write_segment_checkpoint(
                    self._cfg, self._mode, job.state, job.key_json,
                    job.completed, self._root, self._keep_last, self._db,
                    io_stats=io_stats,
                )
                self.io_seconds += time.perf_counter() - t0
                self.serialize_seconds += io_stats.get("serialize_s", 0.0)
                self.shard_files = io_stats.get("shard_files",
                                                self.shard_files)
                self.written += 1
                if self._progress() > job.seg_index:
                    self.overlapped += 1
            except BaseException as exc:  # noqa: BLE001 — surfaced on submit/close
                logger.exception(
                    "async checkpoint write for round %d failed",
                    job.completed,
                )
                with self._mu:
                    self._error = exc
