"""Watchdog supervisor: deadline-and-retry around device dispatch.

A TPU tunnel outage (PERF.md recorded a full-round one in round 5), a
preempted device, or a wedged dispatch all present the same way to the
host: the dispatch call either raises a transient runtime error or never
returns. The supervisor wraps dispatch with

- a **deadline**: the call runs on a worker thread; if it has not
  completed within ``deadline_seconds`` the supervisor raises
  :class:`DispatchTimeout` (the abandoned thread is daemonic — a truly
  wedged dispatch cannot be cancelled, only orphaned);
- **jittered retries** via :func:`corrosion_tpu.utils.backoff.retry_call`
  on the shared :class:`~corrosion_tpu.utils.backoff.Backoff` policy —
  the same 1 s -> 15 s shape the reference's sync loop uses;
- **graceful abort**: when retries are exhausted,
  :class:`SupervisorAborted` propagates and the caller stops cleanly,
  leaving the last committed checkpoint as the recovery point.

The ``state`` / ``retry_after_seconds`` surface feeds ``/v1/ready``:
while the supervisor is backing off, the API answers 503 +
``Retry-After`` instead of serving from a cluster that is not stepping.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple, Type

from corrosion_tpu.utils.backoff import Backoff, retry_call
from corrosion_tpu.utils.tracing import logger


class DispatchTimeout(TimeoutError):
    """A supervised call missed its deadline."""


class SupervisorAborted(RuntimeError):
    """Retries exhausted; the supervised workload must stop at the last
    good checkpoint."""


class _AbortPassthrough(BaseException):
    """Carrier that moves a SupervisorAborted raised INSIDE a supervised
    call past retry_call's ``except`` (which would otherwise retry it as
    a RuntimeError)."""

    def __init__(self, exc: SupervisorAborted):
        self.exc = exc


class Supervisor:
    """Deadline + retry wrapper for device dispatch.

    States: ``idle`` -> ``running`` -> (``backoff`` -> ``running``)* ->
    ``idle`` on success, or ``aborted`` once retries are exhausted.
    Thread-safe to observe from API threads while a round thread runs
    supervised calls."""

    #: exception types treated as transient by default: deadline misses
    #: and device/runtime hiccups (jaxlib surfaces transient device and
    #: tunnel errors as RuntimeError subclasses)
    DEFAULT_RETRY_ON: Tuple[Type[BaseException], ...] = (
        TimeoutError, ConnectionError, OSError, RuntimeError,
    )

    def __init__(
        self,
        deadline_seconds: Optional[float] = None,
        backoff: Optional[Backoff] = None,
        retry_on: Optional[Tuple[Type[BaseException], ...]] = None,
        sleep: Optional[Callable[[float], object]] = None,
        abort: Optional[Callable[[], bool]] = None,
    ):
        self.deadline_seconds = deadline_seconds
        self.backoff = backoff or Backoff(
            min_wait=1.0, max_wait=15.0, max_retries=4
        )
        self.retry_on = tuple(retry_on or self.DEFAULT_RETRY_ON)
        self._sleep = sleep or time.sleep
        self._abort = abort
        self._mu = threading.Lock()
        self._state = "idle"
        self._retry_at = 0.0  # wall-clock time of the next attempt
        self.retries = 0  # total retries over the supervisor's lifetime
        self.aborts = 0

    # --- observable surface (feeds /v1/health + /v1/ready) --------------
    @property
    def state(self) -> str:
        with self._mu:
            return self._state

    def retry_after_seconds(self) -> float:
        """Seconds until the next attempt (0 when not backing off)."""
        with self._mu:
            if self._state != "backoff":
                return 0.0
            return max(0.0, self._retry_at - time.time())

    def _set(self, state: str, retry_in: float = 0.0) -> None:
        with self._mu:
            self._state = state
            self._retry_at = time.time() + retry_in

    def bind_abort(self, fn: Callable[[], bool],
                   sleep: Optional[Callable[[float], object]] = None,
                   ) -> "Supervisor":
        """Late-bind the abort predicate and (optionally) an
        interruptible sleep — the Agent ties both to its tripwire so
        shutdown never sits out a backoff delay. Explicitly-constructed
        hooks are kept. Mutates under ``_mu``: binding can race an API
        thread reading supervisor state (corrolint unlocked-mutation)."""
        with self._mu:
            if self._abort is None:
                self._abort = fn
            if sleep is not None and self._sleep is time.sleep:
                self._sleep = sleep
        return self

    # --- the wrapper -----------------------------------------------------
    def call(self, fn: Callable, *args, label: str = "dispatch", **kwargs):
        """Run ``fn`` under the deadline, retrying transient failures on
        the jittered policy; raises :class:`SupervisorAborted` once the
        policy is exhausted (or ``abort()`` trips mid-backoff)."""

        def attempt():
            self._set("running")
            try:
                return self._with_deadline(fn, args, kwargs, label)
            except SupervisorAborted as e:
                # an inner supervised workload already aborted: never
                # re-run it, whatever the retry_on tuple covers (it
                # subclasses RuntimeError). BaseException carrier slips
                # past retry_call's except clause.
                raise _AbortPassthrough(e) from None

        def on_retry(exc, delay, attempt_no):
            self.retries += 1
            self._set("backoff", retry_in=delay)
            logger.warning(
                "supervisor: %s failed (%s: %s); retry %d in %.1fs",
                label, type(exc).__name__, exc, attempt_no, delay,
            )

        try:
            result = retry_call(
                attempt,
                backoff=self.backoff,
                retry_on=self.retry_on,
                sleep=self._sleep,
                abort=self._abort,
                on_retry=on_retry,
            )
        except _AbortPassthrough as w:
            self._set("aborted")
            self.aborts += 1
            raise w.exc
        except self.retry_on as e:
            self._set("aborted")
            self.aborts += 1
            raise SupervisorAborted(
                f"{label}: retries exhausted ({type(e).__name__}: {e}); "
                f"recover from the last committed checkpoint"
            ) from e
        except BaseException:
            # non-retryable (ValueError from a bad pytree, Keyboard-
            # Interrupt, ...): nothing is executing anymore — the state
            # must not stay stuck at "running" for /v1/health to report
            self._set("idle")
            raise
        self._set("idle")
        return result

    def _with_deadline(self, fn: Callable, args, kwargs, label: str):
        if self.deadline_seconds is None:
            return fn(*args, **kwargs)
        # one throwaway daemon thread per attempt: a timed-out dispatch
        # cannot be cancelled, only orphaned — and it must not block
        # interpreter exit or poison later attempts
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                box["result"] = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — relayed to the caller
                box["exc"] = e
            finally:
                done.set()

        # raw Thread, NOT spawn_counted: a wedged dispatch never
        # finishes, and counting it would hang the shutdown barrier.
        # The corro- prefix keeps sanitizer/leak reports attributable
        # (corrosan's leak gate exempts this prefix by allowlist).
        threading.Thread(
            target=run, daemon=True, name=f"corro-supervised-{label}"
        ).start()
        if not done.wait(self.deadline_seconds):
            raise DispatchTimeout(
                f"{label} missed its {self.deadline_seconds:.1f}s deadline"
            )
        if "exc" in box:
            raise box["exc"]
        return box["result"]
