"""corrofuzz — property-based chaos over the scenario grammar.

The hand-written registry (``resilience/chaos.py``, docs/chaos.md)
covers each fault axis once; this module searches the *interleaving
space*: a seeded generator draws a random-but-valid
:class:`~corrosion_tpu.resilience.chaos.ScenarioScript` composing
device-plane phases (kill/revive, partition, loss, HLC skew) with
host-plane injections (both crash seams, checkpoint corruption,
elastic remesh, fused-flip), and the three chaos oracles judge it —
a Jepsen-style randomized nemesis schedule, made deterministic.

Determinism contract: ``gen_script(seed, profile)`` is a pure function
of its arguments (``random.Random(seed)`` drives every draw), and the
verdict of the generated script is pure in the fuzz seed — the script
runs under ``run_scenario(script, seed=seed)``, whose own contract is
purity in ``(script, seed)``. Same seed, same script, same verdict
(tests/test_fuzz.py pins it with a run-twice test).

**Validity by construction.** Every draw respects the PR-12 grammar
constraints so a generated failure is a real finding, never a
malformed script:

- phase ``rounds`` are multiples of ``segment_rounds`` (the crash
  seams arm whole segments);
- crash seams and checkpoint corruption only target phases with at
  least TWO cumulative committed segments, so recovery always has a
  prior committed segment to land on (killing the first-ever save is
  the engine's designed *failure* mode, exercised separately by
  tests/test_chaos.py);
- kills draw only from non-seed nodes (``compile_scale_phase`` —
  seeds anchor bootstrap) and every kill-bearing script ends with a
  revive+heal phase so the settle budget is spent settling, not
  waiting out ``down_purge_rounds`` for corpses;
- at most one crash seam per phase (the engine arms one seam per
  phase window).

**The N ladder** is CPU-priced through corrobudget: each rung is
priced by the symbolic shape inventory
(:func:`corrosion_tpu.obs.memory.projected_bytes` — zero arrays, any
N) and rungs past ``FAST_LADDER_BYTES`` are slow-marked. The fast
profile (tier-1, check.sh) draws from the fast rungs; the ``scale``
profile climbs to 4096 nodes and runs only under ``-m slow``.

**The shrinker** delta-debugs any failing script to a 1-minimal
reproducer: drop phases (re-indexing the surviving injections), drop
injections, shrink round counts and N, zero fault knobs — greedily
restarting from every smaller script that still fails, until no
single reduction reproduces. Reproducers serialize through the
``script_to_json`` contract into ``tests/chaos_corpus/`` and replay
via ``corrosion-tpu chaos --script FILE``.

``broken_corruption_oracle`` is the mutation fixture that proves the
whole find→shrink→replay pipeline is live: it blinds the corruption
injector, so any script carrying a ``corrupt_checkpoint`` injection
must fail its verdict — and the shrinker must carve everything else
away.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import random
from typing import Callable, Optional, Tuple

from corrosion_tpu.resilience import chaos
from corrosion_tpu.resilience.chaos import (
    Injection,
    ScenarioScript,
    scenario_config,
    script_from_json,
    script_to_json,
)
from corrosion_tpu.sim.scenario import FaultPhase
from corrosion_tpu.utils.tracing import logger

#: corpus file schema (the envelope AROUND the script JSON; the script
#: itself carries chaos.SCRIPT_SCHEMA_VERSION)
CORPUS_SCHEMA_VERSION = 1

#: the N rungs the generator may draw (24 = the registry rig; the rest
#: per the ROADMAP "scenarios at N=1k-100k" ramp, capped where a CPU
#: sweep stays tractable)
LADDER_RUNGS = (24, 64, 256, 1024, 4096)

#: rungs whose corrobudget-priced state exceeds this are slow-marked —
#: they never enter the fast (tier-1 / check.sh) draw
FAST_LADDER_BYTES = 1 << 17  # 128 KiB of state: N<=64 at the chaos shapes


def fuzz_ladder(rungs=LADDER_RUNGS):
    """Price every rung through corrobudget's symbolic inventory.

    -> tuple of ``{"n_nodes", "bytes", "slow"}`` — ``bytes`` is the
    static HBM projection of one state replica at the chaos shapes
    (:func:`scenario_config`), computed without building a single
    array, and ``slow`` marks rungs past :data:`FAST_LADDER_BYTES`.
    An unpriceable rung is a loud error (``projected_bytes`` refuses
    unresolved leaves), never a silently mis-binned one."""
    from corrosion_tpu.obs.memory import projected_bytes

    out = []
    for n in rungs:
        cfg = scenario_config(probe_script(n_nodes=int(n)))
        b = projected_bytes(cfg, int(n), mode="scale")
        out.append({
            "n_nodes": int(n),
            "bytes": int(b),
            "slow": bool(b > FAST_LADDER_BYTES),
        })
    return tuple(out)


def probe_script(n_nodes: int = 24) -> ScenarioScript:
    """A minimal valid script at ``n_nodes`` — the config probe the
    ladder pricer (and nothing else) runs through
    :func:`scenario_config`."""
    return ScenarioScript(
        name=f"probe-{n_nodes}",
        phases=(FaultPhase(rounds=4),),
        n_nodes=n_nodes,
    ).validate()


# --- the generator --------------------------------------------------------

#: fused_flip transition: start mode -> flip target. Both legs of the
#: fused==unfused parity contract, both CPU-runnable (docs/fused.md)
_FUSED_FLIPS = (("interpret", "off"), ("off", "interpret"))

#: quiet_flip transition: start round variant -> flip target (ISSUE
#: 19). Both directions of the quiet==dense bitwise contract
_QUIET_FLIPS = (("on", "off"), ("off", "on"))

#: remesh chains: (initial mesh, boundary target) — descending, per
#: the elastic-restore surface (docs/elastic.md); 8 devices is the
#: tier-1 host rig (tests/conftest.py forces 8 host devices)
_REMESH_CHAINS = ((8, 4), (8, 2), (4, 2))


def gen_script(seed: int, profile: str = "fast") -> ScenarioScript:
    """Draw one valid random scenario — pure in ``(seed, profile)``.

    ``profile="fast"``: N from the fast ladder rungs, compact round
    budgets (tier-1 / check.sh wall-clock). ``profile="scale"``: N may
    climb the full corrobudget-priced ladder (slow-marked callers
    only). The returned script always ``validate()``s and always obeys
    the validity-by-construction rules in the module docstring."""
    if profile not in ("fast", "scale"):
        raise ValueError(f"unknown fuzz profile {profile!r}")
    rng = random.Random(int(seed))
    ladder = fuzz_ladder()
    rungs = [r for r in ladder if not r["slow"]] if profile == "fast" else list(ladder)
    # weight the small rungs heavily: the interleaving space is the
    # search target, N is just the stage it plays on
    weights = [1.0 / (i + 1) ** 2 for i in range(len(rungs))]
    n_nodes = rng.choices([r["n_nodes"] for r in rungs], weights)[0]

    segment_rounds = 4
    n_phases = rng.randint(2, 3)
    phases = []
    any_kill = False
    for _ in range(n_phases):
        rounds = segment_rounds * rng.randint(1, 2)
        kill_frac = rng.choice((0.0, 0.0, 0.15, 0.25))
        if kill_frac:
            any_kill = True
        skew = rng.choice((0, 0, 1, 12))
        phases.append(FaultPhase(
            rounds=rounds,
            write_frac=rng.choice((0.1, 0.2, 0.3)),
            kill_frac=kill_frac,
            revive_killed=any_kill and rng.random() < 0.3,
            partition_groups=rng.choice((1, 1, 2, 3)),
            drop_prob=rng.choice((0.0, 0.0, 0.02, 0.1)),
            clock_skew_rounds=skew,
            clock_skew_frac=0.3 if skew else 0.0,
        ))
    # the healed tail: revive every corpse, clean network, no writes —
    # the settle budget settles data, it does not wait out churn
    phases.append(FaultPhase(rounds=8, revive_killed=any_kill))
    phases = tuple(phases)

    mesh_devices = 0
    fused = "auto"
    injections = []
    # cumulative committed segments at the END of each phase — the
    # recoverability precondition for the crash/corruption draws
    segs_through = []
    acc = 0
    for ph in phases:
        acc += ph.rounds // segment_rounds
        segs_through.append(acc)
    recoverable = [i for i in range(len(phases)) if segs_through[i] >= 2]

    crash_phases = set()
    # quiet_flip joins via its own tail draw below: sampling it here
    # would reshuffle every pre-quiet seed's rng stream and invalidate
    # the corpus
    legacy_kinds = tuple(
        k for k in chaos.INJECTION_KINDS if k != "quiet_flip")
    for kind in rng.sample(legacy_kinds,
                           k=rng.choice((0, 1, 1, 2))):
        if kind in ("crash_slice", "crash_manifest"):
            open_phases = [p for p in recoverable if p not in crash_phases]
            if not open_phases:
                continue
            phase = rng.choice(open_phases)
            crash_phases.add(phase)
            injections.append(Injection(kind=kind, phase=phase))
        elif kind == "corrupt_checkpoint":
            if not recoverable:
                continue
            injections.append(Injection(
                kind=kind, phase=rng.choice(recoverable)))
        elif kind == "preempt":
            injections.append(Injection(
                kind=kind, phase=rng.choice(recoverable or [0])))
        elif kind == "remesh":
            mesh_devices, target = rng.choice(_REMESH_CHAINS)
            injections.append(Injection(
                kind=kind, phase=rng.randrange(len(phases) - 1),
                mesh_devices=target))
        elif kind == "fused_flip":
            fused, target = rng.choice(_FUSED_FLIPS)
            injections.append(Injection(
                kind=kind, phase=rng.randrange(len(phases) - 1),
                fused=target))
    # the quiet axis (ISSUE 19), drawn at the END of the rng stream so
    # every pre-quiet seed still generates its exact historical script:
    # either a quiet_flip lineage (both directions) or a static
    # non-default round variant for the whole scenario
    quiet = "auto"
    if rng.random() < 0.25:
        quiet, target = rng.choice(_QUIET_FLIPS)
        injections.append(Injection(
            kind="quiet_flip", phase=rng.randrange(len(phases) - 1),
            quiet=target))
    elif rng.random() < 0.25:
        quiet = rng.choice(("on", "off"))
    injections.sort(key=lambda i: (i.phase, i.kind))

    return ScenarioScript(
        name=f"fuzz-{int(seed):06d}",
        phases=phases,
        injections=tuple(injections),
        n_nodes=n_nodes,
        segment_rounds=segment_rounds,
        mesh_devices=mesh_devices,
        fused=fused,
        quiet=quiet,
    ).validate()


def run_fuzz(seeds, profile: str = "fast", keep_failures: bool = False):
    """Sweep a fuzz-seed budget; -> the ``artifacts/fuzz_r18.json``
    record: one verdict case per seed plus the ``per_seed`` map
    (verdict + rounds-to-convergence/quiescence) that makes flaky-seed
    regressions diffable, mirroring the chaos sweep artifact shape.

    ``keep_failures=True`` additionally attaches the failing scripts'
    JSON (``script_to_json``) so a CI failure carries its reproducer
    inline before anyone re-runs the shrinker."""
    import jax

    seeds = [int(s) for s in seeds]
    cases = []
    for seed in seeds:
        script = gen_script(seed, profile=profile)
        rec = chaos.run_scenario(script, seed=seed)
        case = {
            "name": script.name,
            "seed": seed,
            "n_nodes": script.n_nodes,
            "phases": len(script.phases),
            "injections": [i.kind for i in script.injections],
            "trace_digest": rec.get("trace_digest"),
            "ok": bool(rec["ok"]),
            "skipped": rec.get("skipped"),
            "rounds_to_convergence": rec.get("rounds_to_convergence", -1),
            "rounds_to_quiescence": rec.get("rounds_to_quiescence", -1),
        }
        if rec.get("problems"):
            case["problems"] = rec["problems"]
            if keep_failures:
                case["script"] = script_to_json(script)
        cases.append(case)
        logger.info("corrofuzz seed %d (%s): %s", seed, script.name,
                    "ok" if case["ok"] else "FAIL")
    return {
        "metric": "chaos_fuzz",
        "profile": profile,
        "platform": jax.devices()[0].platform,
        "seeds": seeds,
        "ladder": list(fuzz_ladder()),
        "cases": cases,
        "per_seed": {
            str(c["seed"]): {
                "ok": c["ok"],
                "rounds_to_convergence": c["rounds_to_convergence"],
                "rounds_to_quiescence": c["rounds_to_quiescence"],
            }
            for c in cases
        },
        "ok": all(c["ok"] for c in cases),
    }


# --- the shrinker ---------------------------------------------------------


def _drop_phase(script: ScenarioScript, i: int) -> ScenarioScript:
    """Drop phase ``i``; injections targeting it go with it, later
    injections re-index down one."""
    phases = script.phases[:i] + script.phases[i + 1:]
    injections = tuple(
        dataclasses.replace(inj, phase=inj.phase - (1 if inj.phase > i else 0))
        for inj in script.injections if inj.phase != i
    )
    return dataclasses.replace(script, phases=phases, injections=injections)


def grammar_valid(script: ScenarioScript) -> bool:
    """The validity-by-construction rules the generator obeys, as a
    predicate — the shrinker must stay inside the same grammar.
    Structural validity is ``validate()``'s job; this checks the
    SEMANTIC rules: crash/corruption only where at least two cumulative
    committed segments exist to recover to, one crash seam per phase.
    (Without this gate a shrink judged under the mutation fixture —
    whose failure needs no recovery at all — happily reduces a
    corruption script to a single committed segment, and the resulting
    "reproducer" fails the HEALTHY engine too: corrupting the only
    checkpoint leaves nothing to fall back to.)"""
    segs = 0
    segs_through = []
    for ph in script.phases:
        segs += ph.rounds // script.segment_rounds
        segs_through.append(segs)
    crash_phases = []
    for inj in script.injections:
        if inj.kind in ("crash_slice", "crash_manifest",
                        "corrupt_checkpoint"):
            if segs_through[inj.phase] < 2:
                return False
        if inj.kind in ("crash_slice", "crash_manifest"):
            crash_phases.append(inj.phase)
    return len(crash_phases) == len(set(crash_phases))


def _shrink_candidates(script: ScenarioScript):
    """Every single-step reduction of ``script``, simplest-first.
    The shrink loop keeps only candidates that ``validate()`` AND stay
    :func:`grammar_valid` — a reproducer outside the generator's
    grammar is not a finding, it is a malformed script."""
    # 1. drop a whole phase
    if len(script.phases) > 1:
        for i in range(len(script.phases)):
            yield _drop_phase(script, i)
    # 2. drop an injection
    for i in range(len(script.injections)):
        yield dataclasses.replace(
            script,
            injections=script.injections[:i] + script.injections[i + 1:],
        )
    # 3. halve a phase's rounds (floor: one segment)
    for i, ph in enumerate(script.phases):
        if ph.rounds > script.segment_rounds:
            smaller = max(
                script.segment_rounds,
                (ph.rounds // 2) // script.segment_rounds
                * script.segment_rounds,
            )
            yield dataclasses.replace(script, phases=(
                script.phases[:i]
                + (dataclasses.replace(ph, rounds=smaller),)
                + script.phases[i + 1:]
            ))
    # 4. shrink N down the ladder
    lower = [r for r in LADDER_RUNGS if r < script.n_nodes]
    if lower:
        yield dataclasses.replace(script, n_nodes=max(lower))
    # 5. zero one fault knob of one phase
    zeroed = dict(write_frac=0.0, kill_frac=0.0, revive_killed=False,
                  partition_groups=1, drop_prob=0.0, clock_skew_rounds=0,
                  clock_skew_frac=0.0)
    for i, ph in enumerate(script.phases):
        for field, z in zeroed.items():
            if getattr(ph, field) != z:
                yield dataclasses.replace(script, phases=(
                    script.phases[:i]
                    + (dataclasses.replace(ph, **{field: z}),)
                    + script.phases[i + 1:]
                ))
    # 6. drop the mesh / pin the execution mode when no injection
    #    still needs them
    kinds = {i.kind for i in script.injections}
    if script.mesh_devices and "remesh" not in kinds:
        yield dataclasses.replace(script, mesh_devices=0)
    if script.fused != "auto" and "fused_flip" not in kinds:
        yield dataclasses.replace(script, fused="auto")
    if script.quiet != "auto" and "quiet_flip" not in kinds:
        yield dataclasses.replace(script, quiet="auto")


def shrink(script: ScenarioScript, seed: int,
           failing: Optional[Callable[[ScenarioScript], bool]] = None,
           max_runs: int = 200) -> Tuple[ScenarioScript, int]:
    """Delta-debug ``script`` to a 1-minimal failing reproducer.

    ``failing(candidate) -> bool`` re-runs the oracles (default: the
    full three-oracle :func:`chaos.run_scenario` verdict at ``seed``)
    — every accepted reduction is *re-verified*, the shrinker never
    assumes monotonicity. Greedy fixpoint: restart the candidate walk
    from every smaller script that still fails; stop when no
    single-step reduction reproduces (1-minimality) or the
    ``max_runs`` oracle budget is spent.

    -> ``(minimal_script, oracle_runs_spent)``. Raises ``ValueError``
    if the input script does not fail its oracle (nothing to shrink —
    a passing script must never enter the corpus)."""
    if failing is None:
        def failing(s: ScenarioScript) -> bool:
            rec = chaos.run_scenario(s, seed=seed)
            return not rec["ok"] and not rec.get("skipped")

    runs = 1
    if not failing(script):
        raise ValueError(
            f"script {script.name!r} passes its oracles at seed {seed}; "
            "refusing to shrink a non-failure"
        )
    current = script
    progress = True
    while progress and runs < max_runs:
        progress = False
        for cand in _shrink_candidates(current):
            try:
                cand.validate()
            except ValueError:
                continue
            if not grammar_valid(cand):
                continue
            runs += 1
            if failing(cand):
                logger.info(
                    "corrofuzz shrink: %d phases/%d injections/%d rounds "
                    "still fails",
                    len(cand.phases), len(cand.injections),
                    cand.total_rounds,
                )
                current = cand
                progress = True
                break
            if runs >= max_runs:
                break
    return dataclasses.replace(
        current, name=f"{script.name}-min"), runs


# --- the corpus -----------------------------------------------------------


def corpus_dir() -> str:
    """The committed reproducer corpus: ``tests/chaos_corpus/`` at the
    repo root (resolved relative to this file so replay works from any
    CWD)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(os.path.dirname(here)),
                        "tests", "chaos_corpus")


def save_reproducer(script: ScenarioScript, seed: int, note: str = "",
                    tier1: bool = False, path: Optional[str] = None) -> str:
    """Serialize a shrunk reproducer into the corpus. -> the file path.

    The envelope carries the replay seed and provenance note; the
    ``script`` key is exactly :func:`script_to_json`, so
    ``corrosion-tpu chaos --script FILE`` replays the file and the
    round-trip preserves ``trace_digest``."""
    if path is None:
        path = os.path.join(corpus_dir(), f"{script.name}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {
        "schema": CORPUS_SCHEMA_VERSION,
        "seed": int(seed),
        "note": note,
        "tier1": bool(tier1),
        "script": script_to_json(script),
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_reproducer(path: str) -> Tuple[ScenarioScript, int, dict]:
    """Load one corpus file. -> ``(script, seed, meta)`` where ``meta``
    is the envelope minus the script. Refuses unknown envelope schemas
    and malformed scripts loudly (``script_from_json``)."""
    with open(path) as f:
        payload = json.load(f)
    schema = int(payload.get("schema", CORPUS_SCHEMA_VERSION))
    if schema != CORPUS_SCHEMA_VERSION:
        raise ValueError(
            f"{path}: corpus schema {schema} != {CORPUS_SCHEMA_VERSION}"
        )
    script = script_from_json(payload["script"])
    meta = {k: v for k, v in payload.items() if k != "script"}
    return script, int(payload.get("seed", 0)), meta


def iter_corpus(dirpath: Optional[str] = None):
    """Sorted corpus file paths (deterministic replay order)."""
    dirpath = dirpath or corpus_dir()
    if not os.path.isdir(dirpath):
        return []
    return [os.path.join(dirpath, name)
            for name in sorted(os.listdir(dirpath))
            if name.endswith(".json")]


# --- the mutation fixture -------------------------------------------------


@contextlib.contextmanager
def broken_corruption_oracle():
    """Blind the corruption injector (the mutation fixture).

    Inside the context, ``chaos.corrupt_checkpoint`` is a no-op: the
    engine *believes* it corrupted the newest checkpoint, so its
    post-corruption probe finds the load succeeding and the recovery
    resuming from the "corrupted" file — any script carrying a
    ``corrupt_checkpoint`` injection now FAILS its verdict
    deterministically. This is how tests/test_fuzz.py proves the
    fuzzer catches real oracle violations and the shrinker carves them
    to a minimal corpus reproducer — a chaos pipeline that cannot fail
    is not measuring anything."""
    real = chaos.corrupt_checkpoint

    def dark(path: str, *a, **k) -> None:
        logger.info("corrofuzz mutation fixture: corruption of %s "
                    "suppressed", path)

    chaos.corrupt_checkpoint = dark
    try:
        yield
    finally:
        chaos.corrupt_checkpoint = real
