"""Segmented soak runner: preemption-safe long simulations.

``run_rounds`` / ``scale_run_rounds`` compile an R-round run into one
``lax.scan`` — fast, but a host crash or TPU preemption at round R-1
loses everything. The segmented runner splits the scan into K-round
segments and threads the FULL scan carry (state pytree + PRNG key)
across them, so the segmented run is **bitwise identical** to the
straight-through one (the per-round key is split off the carried key
inside the scan body; chaining carries reproduces the same key
sequence). After every segment it writes a crash-consistent checkpoint
(manifest-last + SHA-256 hashes, ``checkpoint.py``), updates the
atomic ``LATEST`` pointer, and prunes to the retention budget — a
preempted run resumes from the newest committed segment, losing at most
K rounds of work. Under a mesh the checkpoint drain is PER SHARD
(each device's addressable slice, no replicated host intermediate) and
restore is mesh-shape-agnostic — resume on fewer chips or a different
mesh rank and the run stays bitwise identical (docs/checkpoints.md). The same shape transfers directly to a training
stack: segment = accumulation window, checkpoint = optimizer state.

Segments dispatch through an optional :class:`~corrosion_tpu.resilience
.supervisor.Supervisor`; on retry exhaustion the run aborts gracefully
with the last committed checkpoint as the recovery point.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from corrosion_tpu.checkpoint import load_checkpoint
from corrosion_tpu.resilience.async_ckpt import (
    AsyncCheckpointWriter,
    write_segment_checkpoint,
)
from corrosion_tpu.resilience.retention import latest_valid_checkpoint
from corrosion_tpu.resilience.supervisor import SupervisorAborted
from corrosion_tpu.utils.tracing import logger


class SoakResult(NamedTuple):
    state: object  # final device-state pytree
    key: object  # final carried PRNG key (feed back in to continue)
    infos: dict  # per-round metrics, concatenated over the rounds RUN
    completed_rounds: int  # absolute index into the run's input stack
    aborted: bool  # True when the supervisor exhausted its retries
    checkpoint: Optional[str]  # newest committed checkpoint path
    stats: dict = {}  # pipeline facts: donation, checkpoint stall/IO/overlap


def _infer_mode(cfg) -> str:
    from corrosion_tpu.sim.scale_step import ScaleSimConfig

    return "scale" if isinstance(cfg, ScaleSimConfig) else "full"


def _run_carry_fn(cfg, mode: str):
    if mode == "scale":
        from corrosion_tpu.sim.scale_step import scale_run_rounds_carry

        return scale_run_rounds_carry
    from corrosion_tpu.sim.step import run_rounds_carry

    return run_rounds_carry


def _key_to_json(key) -> dict:
    """Serialize a PRNG key (typed or raw uint32) into the manifest."""
    if jnp.issubdtype(jnp.asarray(key).dtype, jax.dtypes.prng_key):
        return {
            "typed": True,
            "impl": str(jr.key_impl(key)),
            "data": np.asarray(jr.key_data(key)).tolist(),
        }
    return {"typed": False, "data": np.asarray(key).tolist()}


def _key_from_json(d: dict):
    data = jnp.asarray(np.asarray(d["data"], np.uint32))
    # impl must round-trip too: rewrapping rbg key words as the default
    # threefry impl would resume a DIFFERENT key sequence and silently
    # break the bitwise-identity guarantee
    return jr.wrap_key_data(data, impl=d["impl"]) if d["typed"] else data


def _n_rounds(inputs) -> int:
    return int(jax.tree.leaves(inputs)[0].shape[0])


#: the jit used for segment dispatch — a module attribute so the
#: trace-stability harness (``analysis/tracecount.py``) can wrap it
#: with a compile counter without patching ``jax.jit`` globally
_jit = jax.jit


def _pipeline_stats(donate: bool, async_checkpoint: bool,
                    fused: Optional[dict] = None) -> dict:
    """A zeroed stats record (the keys every SoakResult.stats carries).

    ``fused`` is the :func:`corrosion_tpu.ops.megakernel.prime_fused`
    decision dict for the run's config (None = probes not run, e.g. a
    resume that had nothing left to do)."""
    from corrosion_tpu.ops.megakernel import fused_engaged

    fused = fused or {}
    return {
        "donate": donate,
        "async_checkpoint": async_checkpoint,
        # which execution path the segments dispatch (ISSUE 10): the
        # knob, and whether the pallas megakernels actually engage —
        # the SAME ``fused_engaged`` bit the bench records, surfaced
        # as ``pallas_fused`` next to ``donated``/``sharded``
        "fused_mode": fused.get("mode", "auto"),
        "pallas_fused": fused_engaged(fused),
        "fused_interpret": bool(fused.get("interpret")),
        # corroquiet (ISSUE 19): which quiet knob the run carries, and
        # how many segments the host fast path dispatched on the
        # active-set program (quiet="auto" resolution; a pinned
        # quiet="on" run dispatches every segment quiet but counts 0
        # here — the counter is the AUTO resolver's decision record)
        "quiet_mode": "off",
        "quiet_segments": 0,
        "segments": 0,
        "donated_segments": 0,
        "carry_reuploads": 0,
        "ckpt_stall_s": 0.0,
        "ckpt_io_s": 0.0,
        "ckpt_written": 0,
        "ckpt_overlapped_segments": 0,
        # per-shard drain telemetry (ISSUE 9): how many slices each
        # checkpoint drains, how many bytes total, the largest single
        # shard's bytes (the quantity that must NOT scale with total
        # state under a mesh), and the writer's parallel serialize+hash
        # wall time
        "ckpt_shards": 0,
        "ckpt_drain_bytes": 0,
        "ckpt_shard_bytes_max": 0,
        "ckpt_serialize_s": 0.0,
    }


def _obs_hook(obs, name: str, **kwargs) -> None:
    """Drive one observer hook, guarded: the observability plane must
    never kill (or change the result of) the soak it observes — a
    raising hook is logged and the run proceeds unobserved."""
    if obs is None:
        return
    try:
        getattr(obs, name)(**kwargs)
    except Exception:  # noqa: BLE001 — observers are caller-supplied
        logger.exception("soak observer hook %s failed; continuing", name)


def _shard_drain(tree):
    """Per-shard host drain of the carry (the ONLY hot-loop stall).

    Under a mesh each device's addressable shard drains its own slice
    via ``copy_to_host_async`` into owned numpy copies — no replicated
    whole-tree intermediate, so the stall scales with PER-SHARD state,
    not total state (this replaced the old ``_host_copy`` whole-tree
    gather, the suppressed corrolint ``shard-gather`` debt). The
    returned tree's leaves are
    :class:`~corrosion_tpu.parallel.mesh.HostLeafShards`; the async
    writer serializes the slices in parallel and ``_reupload`` puts
    them back at their original placement for donated retries."""
    from corrosion_tpu.parallel.mesh import host_shard_copy

    return host_shard_copy(tree)


def _reupload(host_shards):
    """Donated-retry / abort-handback: the consumed carry comes back
    bitwise-identical from the host slices, at its original placement."""
    from corrosion_tpu.parallel.mesh import device_put_shards

    return device_put_shards(host_shards)


def _drain_stats(host_shards):
    """-> (n_shards, total_bytes, max_shard_bytes) of one drained carry
    — the facts that prove the drain splits per shard instead of
    scaling with total state."""
    per_shard: dict = {}
    for hs in jax.tree.leaves(host_shards):
        for k, (_start, arr) in enumerate(hs.parts):
            ordinal = 0 if hs.dim is None else k
            per_shard[ordinal] = per_shard.get(ordinal, 0) + int(arr.nbytes)
    total = sum(per_shard.values())
    return len(per_shard), total, max(per_shard.values(), default=0)


def _carry_deleted(st) -> bool:
    """True when any leaf buffer was consumed by a donated dispatch."""
    from corrosion_tpu.parallel.mesh import buffers_donated

    return buffers_donated(st)


def _slice_inputs(inputs, lo: int, hi: int):
    return jax.tree.map(lambda a: a[lo:hi], inputs)


def _concat_infos(parts: list) -> dict:
    if not parts:
        return {}
    # segments dispatched on different execution paths can emit
    # different info-key sets (the quiet step adds ``quiet_*`` keys a
    # dense segment doesn't compute) — union the keys and zero-fill the
    # segments that lack one (a dense segment cheap-pathed 0 rounds)
    keys: dict = {}
    for p in parts:
        for k in p:
            keys.setdefault(k, np.asarray(p[k]).dtype)

    def col(p: dict, k: str, dt):
        if k in p:
            return np.asarray(p[k])
        n = len(np.asarray(next(iter(p.values()))))
        return np.zeros(n, dt)

    return {
        k: np.concatenate([col(p, k, dt) for p in parts])
        for k, dt in keys.items()
    }


def _inputs_quiet(seg) -> bool:
    """Host-side occupancy check of one segment's stacked inputs: True
    when the slice injects no kills/revives/writes/transactions (the
    input half of the corroquiet predicate, decided per segment)."""
    return not any(
        bool(np.any(np.asarray(getattr(seg, f))))
        for f in ("kill", "revive", "write_mask", "tx_mask")
        if hasattr(seg, f)
    )


@functools.lru_cache(maxsize=None)
def _quiet_carry_probe(cfg):
    """One tiny jitted reduce per config: is the carry provably quiet
    (no alive node owes work — ``scale_step._quiet_busy``)? Deliberately
    NOT routed through ``_jit``: the trace-stability harness counts
    segment dispatches through that seam, and this probe is not one."""
    from corrosion_tpu.sim.scale_step import _quiet_busy

    return jax.jit(lambda st: ~jnp.any(_quiet_busy(cfg, st)))


def run_segmented(
    cfg,
    st,
    net,
    key,
    inputs,
    segment_rounds: int,
    *,
    mode: Optional[str] = None,
    checkpoint_root: Optional[str] = None,
    keep_last: int = 3,
    db=None,
    supervisor=None,
    start_round: int = 0,
    donate: bool = True,
    async_checkpoint: bool = True,
    obs=None,
) -> SoakResult:
    """Run ``inputs`` (stacked per-round, leading axis = rounds) in
    K-round segments, checkpointing after each.

    Bitwise identical to ``run_rounds(cfg, st, net, key, inputs)`` on
    the same carry-in: final state leaves AND per-round infos match a
    straight-through scan exactly. ``start_round`` offsets checkpoint
    round numbers when resuming a longer run (``resume_segmented``).

    With a ``supervisor``, each segment's dispatch rides its deadline +
    retry policy; on exhaustion the run stops gracefully
    (``aborted=True``) with the last committed checkpoint intact.

    **Donation** (``donate=True``): segments after the first dispatch
    through a carry-donating jit, so a segment boundary never holds two
    device copies of the (possibly HBM-filling) state — the scan reuses
    the carry-in buffers for the carry-out. The CALLER's ``st``/``key``
    are never donated (the first segment runs un-donated), so they stay
    valid after the call. Supervised retries of a donated dispatch
    re-upload the carry from the host snapshot the checkpointer keeps;
    with no ``checkpoint_root`` there is no snapshot to retry from, so a
    supervised run without checkpoints keeps donation off.

    **Async checkpointing** (``async_checkpoint=True``, needs
    ``checkpoint_root``): the hot loop only materializes host copies of
    the carry (bounded by the D2H transfer); serialization, SHA-256
    hashing, manifest commit, ``LATEST`` and pruning all run on a
    background writer overlapped with the next segment's scan. Commit
    ordering and integrity invariants are unchanged; the crash-loss
    window grows by at most the one in-flight checkpoint. ``stats`` on
    the result records what the pipeline actually did (donated segment
    count, checkpoint stall vs overlapped IO seconds, retry re-uploads).

    **Observability** (``obs``, an :class:`corrosion_tpu.obs.flight
    .SoakObserver` or None): each completed segment appends a
    crash-safe flight-record line and drains its infos into the live
    metrics registry (``corro.soak.*`` + the round-info series), so a
    running soak is visible on ``/metrics`` and a dead one leaves a
    replayable NDJSON black box. The observer's lifetime belongs to the
    CALLER; this function only drives its run hooks. Pipeline spans
    (segment dispatch, shard drain — plus checkpoint serialize in the
    writer) export through the OTLP file exporter when one is
    configured, with ``jax.profiler`` annotation when the observer asks.
    """
    if segment_rounds <= 0:
        raise ValueError("segment_rounds must be positive")
    mode = mode or _infer_mode(cfg)
    run_carry = _run_carry_fn(cfg, mode)
    rounds = _n_rounds(inputs)
    # fused-path selection happens at trace time inside the dispatch
    # below — hoist the eager pallas probes out of it (once per
    # (backend, shape); docs/fused.md) and record what engaged
    from corrosion_tpu.ops import megakernel

    fused_decisions = megakernel.prime_fused(cfg)
    # corroquiet host fast path: under quiet="auto", an ALL-QUIET
    # segment (no input events over the slice + carry provably quiet at
    # the boundary) dispatches the active-set program
    # (``scale_sim_step_quiet`` scan body — bitwise == dense, every
    # in-segment round short-circuits to the fixpoint branch except the
    # backstop cadence); any doubt dispatches the historical dense
    # program, so existing traces see the exact same programs as before
    quiet_auto = (mode == "scale"
                  and getattr(cfg, "quiet", None) == "auto"
                  and getattr(cfg, "sync_cohort", False))
    quiet_cfg = (dataclasses.replace(cfg, quiet="on").validate()
                 if quiet_auto else cfg)
    # one jitted program per distinct (segment length, donation,
    # quiet-resolution) tuple — at most K and the final partial segment,
    # donated and not, quiet and dense
    jitted: dict = {}

    def dispatch(st, key, seg_inputs, donate_now: bool,
                 quiet_now: bool = False):
        n = (_n_rounds(seg_inputs), donate_now, quiet_now)
        seg_cfg = quiet_cfg if quiet_now else cfg
        if n not in jitted:
            jitted[n] = _jit(
                lambda s, k, i: run_carry(seg_cfg, s, net, k, i),
                donate_argnums=((0, 1) if donate_now else ()),
            )
        (st2, key2), infos = jitted[n](st, key, seg_inputs)
        # completion inside the supervised call: a wedged device shows
        # up as a deadline miss here, not as a hang at the next use
        jax.block_until_ready(st2)
        return (st2, key2), infos

    seg_box = {"index": 0}  # read by the async writer's overlap probe
    use_writer = bool(checkpoint_root and async_checkpoint)
    stats = _pipeline_stats(donate, use_writer, fused=fused_decisions)
    stats["quiet_mode"] = str(getattr(cfg, "quiet", "off") or "off")
    from corrosion_tpu.obs.spans import pipeline_span

    jax_prof = bool(obs is not None and getattr(obs, "jax_profile", False))
    # observer hooks run guarded AND before the writer thread exists: a
    # broken caller-supplied observer must neither kill the soak it only
    # observes nor leak an already-spawned corro-async-ckpt thread
    _obs_hook(obs, "open_run",
              cfg=cfg, mode=mode, total_rounds=rounds,
              start_round=start_round, segment_rounds=segment_rounds,
              stats=stats, state=st)
    writer = None
    if use_writer:
        writer = AsyncCheckpointWriter(
            cfg, mode, checkpoint_root, keep_last, db,
            progress=lambda: seg_box["index"],
        )
    host_carry = None  # (numpy state pytree, key json) at the last boundary
    info_parts: list = []
    completed = 0
    aborted = False
    crashed = False  # an exception unwound THIS run (not an outer handler)
    last_ckpt = None
    try:
        while completed < rounds:
            lo = completed
            seg_no = seg_box["index"] + 1  # 1-based, shared by span+record
            hi = min(completed + segment_rounds, rounds)
            seg = _slice_inputs(inputs, completed, hi)
            # never donate the caller's carry; supervised donated
            # dispatches additionally need a host snapshot to retry from
            donate_now = (
                donate
                and seg_box["index"] > 0
                and (supervisor is None or host_carry is not None)
            )
            # quiet resolution: the cheap input check first, the carry
            # probe (one scalar D2H) only when the inputs already passed
            quiet_now = (
                quiet_auto
                and _inputs_quiet(seg)
                and bool(_quiet_carry_probe(cfg)(st))
            )
            if quiet_now:
                stats["quiet_segments"] += 1

            def seg_dispatch():
                nonlocal st, key
                if donate_now and _carry_deleted(st):
                    # a failed donated attempt consumed the carry — the
                    # retry re-uploads the host shard slices of the same
                    # boundary at their original placement (bitwise-
                    # identical values; re-sharding is the driver's
                    # concern on a genuine device loss)
                    st = _reupload(host_carry[0])
                    key = _key_from_json(host_carry[1])
                    stats["carry_reuploads"] += 1
                    logger.warning(
                        "re-uploaded donated soak carry from the host "
                        "snapshot for retry at round %d",
                        start_round + completed,
                    )
                return dispatch(st, key, seg, donate_now, quiet_now)

            try:
                with pipeline_span(
                    "soak.segment.dispatch", jax_profile=jax_prof,
                    # segments legitimately run for minutes — the slow-
                    # span warning is for the drain/serialize phases
                    warn_seconds=float("inf"),
                    seg=seg_no, lo=start_round + lo,
                    hi=start_round + hi,
                ):
                    if supervisor is not None:
                        (st, key), infos = supervisor.call(
                            seg_dispatch,
                            label=f"segment[{start_round + completed}:"
                                  f"{start_round + hi}]",
                        )
                    else:
                        (st, key), infos = seg_dispatch()
            except SupervisorAborted:
                if host_carry is not None and _carry_deleted(st):
                    # the exhausted donated attempts consumed the carry —
                    # hand back the last boundary's values so the caller
                    # (e.g. Agent.soak) adopts a USABLE state, not
                    # deleted buffers
                    st = _reupload(host_carry[0])
                    key = _key_from_json(host_carry[1])
                logger.exception(
                    "soak aborted at round %d; last good checkpoint: %s",
                    start_round + completed, last_ckpt,
                )
                aborted = True
                break
            completed = hi
            seg_box["index"] += 1
            stats["segments"] += 1
            if donate_now:
                stats["donated_segments"] += 1
            info_parts.append(infos)
            if checkpoint_root:
                # the only synchronous cost on the hot loop: the
                # per-shard slice drain of the carry (plus writer
                # backpressure when the PREVIOUS segment's checkpoint is
                # still being written)
                t0 = time.perf_counter()
                with pipeline_span("soak.ckpt.drain",
                                   jax_profile=jax_prof,
                                   warn_seconds=30.0):
                    host_carry = (_shard_drain(st), _key_to_json(key))
                if writer is not None:
                    writer.submit(host_carry[0], host_carry[1],
                                  start_round + completed,
                                  seg_box["index"])
                stats["ckpt_stall_s"] += time.perf_counter() - t0
                n_sh, total_b, max_b = _drain_stats(host_carry[0])
                stats["ckpt_shards"] = max(stats["ckpt_shards"], n_sh)
                stats["ckpt_drain_bytes"] += total_b
                stats["ckpt_shard_bytes_max"] = max(
                    stats["ckpt_shard_bytes_max"], max_b)
                if writer is None:
                    t0 = time.perf_counter()
                    io_stats: dict = {}
                    last_ckpt = write_segment_checkpoint(
                        cfg, mode, host_carry[0], host_carry[1],
                        start_round + completed, checkpoint_root,
                        keep_last, db, io_stats=io_stats,
                    )
                    stats["ckpt_stall_s"] += time.perf_counter() - t0
                    stats["ckpt_serialize_s"] += io_stats.get(
                        "serialize_s", 0.0)
            # AFTER the checkpoint block: the segment record carries
            # this segment's checkpoint facts, not the previous one's
            _obs_hook(obs, "on_segment",
                      seg_index=seg_no, lo=start_round + lo,
                      hi=start_round + completed, infos=infos,
                      stats=stats, state=st)
    except BaseException:
        # local crash detection for the flight record: sys.exc_info()
        # would also be non-None when a CALLER invokes this function
        # from inside an except handler, mislabeling a clean run
        crashed = True
        raise
    finally:
        try:
            if writer is not None:
                # drain overlapped writes; a write failure surfaces here
                # (or earlier, on submit) rather than being silently lost
                try:
                    last_ckpt = writer.close() or last_ckpt
                except BaseException:
                    if aborted:  # don't mask the abort path's result
                        logger.exception("async checkpoint drain failed")
                    else:
                        crashed = True
                        raise
                stats["ckpt_io_s"] = writer.io_seconds
                stats["ckpt_written"] = writer.written
                stats["ckpt_overlapped_segments"] = writer.overlapped
                stats["ckpt_serialize_s"] = writer.serialize_seconds
            elif checkpoint_root:
                stats["ckpt_written"] = stats["segments"]
        finally:
            # the end record lands whatever killed the run (writer
            # failure, crash mid-dispatch, graceful abort) — the
            # black box's whole point
            _obs_hook(obs, "end_run",
                      stats=stats,
                      completed_rounds=start_round + completed,
                      aborted=aborted,
                      crashed=crashed and not aborted,
                      checkpoint=last_ckpt)
    return SoakResult(
        state=st,
        key=key,
        infos=_concat_infos(info_parts),
        completed_rounds=start_round + completed,
        aborted=aborted,
        checkpoint=(last_ckpt if last_ckpt
                    else (latest_valid_checkpoint(checkpoint_root)
                          if checkpoint_root else None)),
        stats=stats,
    )


def restore_soak_carry(cfg, checkpoint_root: str, *,
                       mode: Optional[str] = None, mesh=None):
    """Restore the newest valid soak checkpoint under
    ``checkpoint_root`` without running anything: the restore gate of
    :func:`resume_segmented`, shared with the corrochaos engine's
    recovery path (``resilience/chaos.py``) so fault scenarios exercise
    the SAME gates a production resume runs.

    -> ``(state, key, completed_rounds, path)``. Raises
    ``FileNotFoundError`` when no restorable checkpoint exists and
    ``ValueError`` on mode/config drift or a missing soak carry."""
    mode = mode or _infer_mode(cfg)
    path = latest_valid_checkpoint(checkpoint_root)
    if path is None:
        raise FileNotFoundError(
            f"no restorable checkpoint under {checkpoint_root!r}"
        )
    # latest_valid_checkpoint just ran the full hash pass on this path —
    # skip re-hashing the state it already proved clean
    manifest, state = load_checkpoint(path, verify=False, mesh=mesh)
    if manifest["mode"] != mode:
        raise ValueError(
            f"checkpoint mode {manifest['mode']!r} != run mode {mode!r}"
        )
    from corrosion_tpu.checkpoint import config_identity

    # identity minus execution-only keys: a soak checkpointed on the
    # fused path resumes on the XLA path (or interpret mode) bit for
    # bit — fused parity is pinned — while any SEMANTIC drift still
    # refuses loudly
    if config_identity(manifest["sim_config"]) != config_identity(cfg):
        raise ValueError(
            "checkpoint sim config differs from the resuming run's — "
            "resuming would not reproduce the original scan"
        )
    soak = (manifest.get("extra") or {}).get("soak")
    if not soak:
        raise ValueError(
            f"checkpoint {path} was not written by the segmented runner "
            f"(no soak carry in its manifest)"
        )
    return (state, _key_from_json(soak["key"]),
            int(soak["completed_rounds"]), path)


def resume_segmented(
    cfg,
    net,
    inputs,
    segment_rounds: int,
    *,
    checkpoint_root: str,
    keep_last: int = 3,
    db=None,
    supervisor=None,
    mode: Optional[str] = None,
    donate: bool = True,
    async_checkpoint: bool = True,
    mesh=None,
    obs=None,
) -> SoakResult:
    """Resume a segmented run from the newest valid checkpoint under
    ``checkpoint_root``.

    ``inputs`` is the FULL run's input stack (same one the interrupted
    run was started with); the restored ``completed_rounds`` selects the
    remaining slice. The restored carry (state + PRNG key) continues the
    original scan bit for bit, so straight / interrupted-and-resumed
    runs converge to identical final state. Returned ``infos`` cover
    only the rounds run by THIS call.

    ``mesh`` is the RESUMING process's mesh: the checkpoint's recorded
    slices are re-placed against it whatever shape the saving mesh had
    (8→4 chips, 1-D↔2-D ``(dcn, node)``, mesh↔single-device), so a soak
    preempted on one topology continues bit for bit on another. Pass
    ``net``/``inputs`` already placed for that mesh (``shard_state``);
    with ``mesh=None`` the restored state is host-resident and the
    first dispatch places it on the default device.

    Raises ``FileNotFoundError`` when no restorable checkpoint exists
    and ``ValueError`` on config drift (the checkpoint was written by a
    run with a different sim config)."""
    mode = mode or _infer_mode(cfg)
    state, key, completed, path = restore_soak_carry(
        cfg, checkpoint_root, mode=mode, mesh=mesh)
    rounds = _n_rounds(inputs)
    logger.info("resuming soak from %s at round %d/%d", path, completed,
                rounds)
    if completed >= rounds:
        # explicit zeroed stats: the shared class default must never be
        # handed out (mutable) and consumers index the documented keys
        return SoakResult(state, key, {}, completed, False, path,
                          stats=_pipeline_stats(donate, async_checkpoint))
    return run_segmented(
        cfg, state, net, key, _slice_inputs(inputs, completed, rounds),
        segment_rounds, mode=mode, checkpoint_root=checkpoint_root,
        keep_last=keep_last, db=db, supervisor=supervisor,
        start_round=completed, donate=donate,
        async_checkpoint=async_checkpoint, obs=obs,
    )


def make_soak_inputs(cfg, key, rounds: int, write_frac: float = 0.0,
                     mode: Optional[str] = None):
    """Stacked per-round inputs for a soak run: quiet rounds with an
    optional ``write_frac`` of nodes issuing random single-cell writes
    each round (conflict-heavy, the convergence-bench workload shape)."""
    mode = mode or _infer_mode(cfg)
    if mode == "scale":
        from corrosion_tpu.sim.scale_step import ScaleRoundInput as RI
    else:
        from corrosion_tpu.sim.step import RoundInput as RI
    quiet = RI.quiet(cfg)
    inputs = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), quiet
    )
    if write_frac <= 0.0:
        return inputs
    k_mask, k_w = jr.split(key)
    n = cfg.n_nodes
    mask = jr.uniform(k_mask, (rounds, n)) < write_frac
    if not getattr(cfg, "any_writer", False):
        # only the origin pool may write on the legacy fixed-pool path
        mask = mask & (jnp.arange(n) < cfg.n_origins)[None, :]
    if mode == "scale":
        # the ONE shared write construction (bench.py / ab_bench /
        # convergence_bench ride it too) — soak workloads follow the
        # chunked-tx path when cfg.tx_max_cells asks, instead of
        # drifting on a private copy
        from corrosion_tpu.sim.scale_step import make_write_inputs

        return make_write_inputs(cfg, k_w, rounds, mask)
    k_cell, k_val = jr.split(k_w)
    return inputs._replace(
        write_mask=mask,
        write_cell=jr.randint(k_cell, (rounds, n), 0, cfg.n_cells,
                              dtype=jnp.int32),
        write_val=jr.randint(k_val, (rounds, n), 0, 1 << 20,
                             dtype=jnp.int32),
    )
