"""Preemption-safe recovery: segmented soak runs, checkpoint retention,
and the watchdog supervisor.

The reference survives agent restarts by construction — the SQLite file
is the durable replica, sync backfills the gap (PAPER.md: backup/restore
via ``VACUUM INTO``, the 1 s -> 15 s sync backoff). The simulator's
long-``lax.scan`` runs had the inverse shape: one host crash or TPU
preemption lost the whole run. This package closes that gap:

- :mod:`segments` — split an R-round scan into K-round segments,
  threading the full scan carry (state + PRNG key) so the segmented run
  is bitwise identical to the straight-through one, with a
  crash-consistent checkpoint after every segment; internal segment
  carries are buffer-donated so boundaries never hold two device copies
  of the state;
- :mod:`async_ckpt` — the double-buffered background checkpoint writer:
  the hot loop pays only the device→host drain, hashing/serialization/
  IO overlap the next segment's scan;
- :mod:`retention` — keep-last-K pruning plus an atomic ``LATEST``
  pointer naming the newest committed checkpoint;
- :mod:`supervisor` — deadline-and-retry watchdog around device
  dispatch, built on :class:`corrosion_tpu.utils.backoff.Backoff`;
- :mod:`chaos` — corrochaos: deterministic seeded fault scenarios
  (partitions, clock skew, rejoin refutation, mid-commit crashes,
  checkpoint corruption, mesh changes, fused flips) driven through the
  real segmented pipeline and double-oracle-checked (docs/chaos.md).

``chaos`` is imported lazily (not re-exported here): it pulls the whole
sim plane in, and the package's other consumers (agent boot, HTTP
health) must stay import-light.
"""

from corrosion_tpu.resilience.async_ckpt import (  # noqa: F401
    AsyncCheckpointWriter,
    write_segment_checkpoint,
)
from corrosion_tpu.resilience.retention import (  # noqa: F401
    latest_valid_checkpoint,
    prune_checkpoints,
    read_latest,
    update_latest,
)
from corrosion_tpu.resilience.segments import (  # noqa: F401
    SoakResult,
    restore_soak_carry,
    resume_segmented,
    run_segmented,
)
from corrosion_tpu.resilience.supervisor import (  # noqa: F401
    DispatchTimeout,
    Supervisor,
    SupervisorAborted,
)
