"""corrochaos host-plane scenario: ``serve-overload`` (docs/chaos.md).

The device-plane scenarios in :mod:`corrosion_tpu.resilience.chaos`
replay compiled fault traces against the segmented soak pipeline; this
scenario instead drives the SERVING plane — a devcluster rig (Agent +
Database + ApiServer) under corroguard admission (docs/overload.md) —
through a seeded overload ramp while a mid-run ``restore_state`` of the
agent's own captured state makes ``/v1/ready`` flap, and judges it by
two oracles:

- **no lost committed write**: every key's final row is the LAST write
  the serving plane acked for it — never a 503-rejected write's value,
  never a silently vanished ack. The one tolerated exception is an ack
  landing inside the capture->apply window of the injected restore
  (restore IS a rollback to the captured snapshot; an ack racing that
  window may legitimately be superseded by the pre-capture value).
- **delivered or shed, never silently gapped**: each subscriber either
  replayed every accepted write into a replica that matches the final
  table (fast consumer), or was explicitly shed — resync marker(s) on
  the stream — and a post-stream re-query matches the final table
  (slow consumer catch-up path).

The verdict is shaped like a chaos-engine record (``name`` / ``seed`` /
``ok`` / ``problems`` / ``faults_injected`` ...) with
``host_plane: True`` so sweep artifacts can carry both families; the op
stream is pure in ``seed`` (``plan_digest`` pins it).
"""

from __future__ import annotations

import hashlib
import json
import random
import socket
import threading
import time
from typing import Any, Dict, List, Optional

SCENARIO_NAME = "serve-overload"

_SCHEMA = (
    "CREATE TABLE ovl_kv (k TEXT PRIMARY KEY, v INTEGER, who TEXT);"
)
_STOP_KEY = "__stop__"


def plan_serve_overload(seed: int, writers: int, ops: int,
                        keys: int) -> dict:
    """Seeded op plan. Each writer OWNS the keys ``k % writers == w``
    (single-owner keys make per-key ack order total, which is what lets
    the lost-write oracle demand exact final values)."""
    plan: Dict[str, Any] = {
        "writers": [
            [
                w + writers * random.Random(
                    seed * 6151 + 13 * w + j).randrange(
                        max(1, (keys - w + writers - 1) // writers))
                for j in range(ops)
            ]
            for w in range(writers)
        ],
    }
    digest = hashlib.sha256(
        json.dumps(plan, sort_keys=True).encode()
    ).hexdigest()[:16]
    plan["digest"] = digest
    return plan


def run_serve_overload(seed: int = 0, writers: int = 4, ops: int = 40,
                       keys: int = 12, n_nodes: int = 8,
                       slow_ms: float = 25.0, pad_bytes: int = 1024,
                       warm_rounds: int = 8, deadline_s: float = 240.0,
                       workdir: Optional[str] = None,
                       flight_path: Optional[str] = None) -> dict:
    """Run the scenario; -> a chaos-shaped verdict record (pure op plan
    in ``seed``; ``workdir`` is accepted for registry-signature parity
    and unused — this scenario touches no disk unless ``flight_path``
    asks for the NDJSON flight record, whose header/end pair carries
    the admission/shed snapshot so the replay shows the shed story —
    docs/observability.md)."""
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.api.admission import AdmissionController
    from corrosion_tpu.api.http import ApiServer
    from corrosion_tpu.client import ApiError, CorrosionApiClient
    from corrosion_tpu.config import ServeConfig
    from corrosion_tpu.db import Database
    from corrosion_tpu.obs.flight import (
        FLIGHT_SCHEMA_VERSION,
        FlightRecorder,
        serve_snapshot,
    )
    from corrosion_tpu.testing import cluster_config
    from corrosion_tpu.utils.lifecycle import spawn_counted
    from corrosion_tpu.utils.metrics import parse_exposition

    plan = plan_serve_overload(seed, writers, ops, keys)
    pad = "x" * max(0, pad_bytes)
    problems: List[str] = []
    rec: Dict[str, Any] = {
        "name": SCENARIO_NAME,
        "seed": int(seed),
        "n_nodes": n_nodes,
        "host_plane": True,
        "plan_digest": plan["digest"],
        "faults_injected": 0,
        "resumes": 0,
        "remeshes": 0,
        "corruptions_detected": 0,
        "checkpoints_validated": 0,
        "checkpoints_refused": 0,
    }
    serve = ServeConfig(
        max_inflight=3, max_queue=3, queue_wait=0.05, max_streams=16,
        retry_after_cap=5.0, shed_policy="shed-oldest",
        sub_queue=16, sub_shed_threshold=1 << 30, stream_sndbuf=4608,
    )
    cfg = cluster_config(n_nodes=n_nodes, n_rows=keys + 4)

    # per-key ack journal: key -> [(monotonic ack time, stamp)], owner
    # writers append in their own program order under one lock
    acks: Dict[str, List[tuple]] = {}
    acks_mu = threading.Lock()
    rejected: set = set()  # stamps of 503-shed writes (never committed)
    flap = {"t0": None, "t1": None, "applied": False, "observed": 0}
    sub_out: List[Optional[dict]] = [None, None]  # fast, slow

    flight = FlightRecorder(flight_path) if flight_path else None
    with Agent(cfg) as agent:
        agent.wait_rounds(warm_rounds, timeout=deadline_s)
        db = Database(agent)
        admission = AdmissionController(serve, registry=agent.metrics)
        if flight is not None:
            flight.record(
                "header", schema=FLIGHT_SCHEMA_VERSION,
                mode="serve-overload", n_nodes=int(n_nodes),
                start_round=0, total_rounds=0, segment_rounds=0,
                seed=int(seed), plan_digest=plan["digest"],
            )
        with ApiServer(db, port=0, serve=serve,
                       admission=admission) as api:
            setup = CorrosionApiClient(api.addr, api.port)
            setup.schema([_SCHEMA])
            setup.execute([
                ("INSERT INTO ovl_kv (k, v, who) VALUES (?, ?, ?)",
                 [f"k{i}", 0, "seed"])
                for i in range(keys)
            ])
            agent.wait_rounds(2, timeout=deadline_s)

            def subscriber(i: int, slow: bool) -> None:
                out = {"replica": {}, "errors": 0, "resyncs": 0,
                       "dropped": 0, "ready": False, "slow": slow}
                sub_out[i] = out
                c = CorrosionApiClient(api.addr, api.port)
                try:
                    stream = c.subscribe("SELECT k, v, who FROM ovl_kv",
                                         stream_timeout=deadline_s)
                    if slow:
                        try:
                            stream._conn.sock.setsockopt(
                                socket.SOL_SOCKET, socket.SO_RCVBUF,
                                4096)
                        except (OSError, AttributeError):
                            pass
                    for ev in stream:
                        if "eoq" in ev:
                            out["ready"] = True
                        if "row" in ev:
                            key, row = ev["row"]
                            out["replica"][key] = row[1]
                        ch = ev.get("change")
                        if ch is None:
                            continue
                        if slow:
                            time.sleep(slow_ms / 1e3)
                        _kind, key, row, _cid = ch
                        if key == _STOP_KEY:
                            break
                        if row is not None:
                            out["replica"][key] = row[1]
                    out["resyncs"] = stream.resyncs
                    out["dropped"] = stream.dropped
                except (TimeoutError, OSError, ApiError):
                    out["errors"] += 1

            def writer(w: int) -> None:
                # closed-loop: 503s retry per the server's Retry-After
                # hint, so (almost) every planned op eventually acks
                c = CorrosionApiClient(api.addr, api.port, retry_503=16,
                                       retry_503_max_wait=0.25)
                for key_idx in plan["writers"][w]:
                    stamp = time.time_ns()
                    try:
                        c.execute([(
                            "UPDATE ovl_kv SET v = ?, who = ? "
                            "WHERE k = ?",
                            [stamp, f"w{w}" + pad, f"k{key_idx}"],
                        )])
                        with acks_mu:
                            acks.setdefault(f"k{key_idx}", []).append(
                                (time.monotonic(), stamp))
                    except ApiError as e:
                        if e.status == 503:
                            rejected.add(stamp)
                        # non-503 errors surface through the oracle:
                        # the key's final value simply won't advance
                    except OSError:
                        pass

            def ready_prober(stop: threading.Event) -> None:
                # watches /v1/ready flap to "restoring" during the
                # injected restore (observational: the window is one
                # round boundary wide, so seeing it is best-effort)
                c = CorrosionApiClient(api.addr, api.port)
                while not stop.is_set():
                    try:
                        c._request_json("GET", "/v1/ready")
                    except ApiError as e:
                        if e.status == 503:
                            flap["observed"] += 1
                    except OSError:
                        pass
                    time.sleep(0.002)

            subs = [
                spawn_counted(lambda: subscriber(0, slow=False),
                              name="corro-sovl-sub-fast"),
                spawn_counted(lambda: subscriber(1, slow=True),
                              name="corro-sovl-sub-slow"),
            ]
            deadline = time.monotonic() + deadline_s
            while not all(s and (s["ready"] or s["errors"])
                          for s in sub_out):
                if time.monotonic() > deadline:
                    problems.append("subscribers never reached eoq")
                    break
                time.sleep(0.01)

            wthreads = [
                spawn_counted(lambda w=w: writer(w),
                              name=f"corro-sovl-w{w}")
                for w in range(writers)
            ]

            # the fault: once roughly half the planned acks landed,
            # restore the agent's own captured state — /v1/ready flaps
            # to "restoring" until the round thread applies it
            half = writers * ops // 2
            while time.monotonic() < deadline:
                with acks_mu:
                    landed = sum(len(v) for v in acks.values())
                if landed >= half or not any(
                        t.is_alive() for t in wthreads):
                    break
                time.sleep(0.005)
            stop_probe = threading.Event()
            probe = spawn_counted(lambda: ready_prober(stop_probe),
                                  name="corro-sovl-probe")
            flap["t0"] = time.monotonic()
            state = agent.device_state()
            flap["applied"] = agent.restore_state(state,
                                                  timeout=deadline_s)
            flap["t1"] = time.monotonic()
            rec["faults_injected"] += 1
            rec["resumes"] += 1
            if not flap["applied"]:
                problems.append("injected restore was never applied")
            time.sleep(0.05)
            stop_probe.set()
            probe.join(timeout=deadline_s)

            for t in wthreads:
                t.join(timeout=deadline_s)
            if any(t.is_alive() for t in wthreads):
                problems.append("writers did not finish")

            try:
                setup.execute([(
                    "INSERT INTO ovl_kv (k, v, who) VALUES (?, ?, ?)",
                    [_STOP_KEY, 0, "stop"],
                )])
            except ApiError:
                problems.append("stop-marker write failed")
            agent.wait_rounds(3, timeout=deadline_s)
            for t in subs:
                t.join(timeout=deadline_s)
            if any(t.is_alive() for t in subs):
                problems.append("subscriber legs did not finish")

            # final plane state, read through the same serving plane
            _cols, rows = setup.query("SELECT k, v FROM ovl_kv")
            final = {r[0]: r[1] for r in rows if r[0] != _STOP_KEY}
            scrape = parse_exposition(setup.metrics())
            shed_total = sum(
                v for (n, _l), v in scrape["counters"].items()
                if n == "corro_subs_shed_total")
            rejected_total = sum(
                v for (n, _l), v in scrape["counters"].items()
                if n == "corro_admission_rejected_total")

            # --- oracle 1: no lost committed write ---------------------
            lost = []
            for i in range(keys):
                k = f"k{i}"
                got = final.get(k)
                journal = acks.get(k, [])
                if not journal:
                    if got != 0:
                        lost.append(f"{k}: never acked a write but "
                                    f"final v={got!r}")
                    continue
                t_last, expect = journal[-1]
                allowed = {expect}
                if (flap["t0"] is not None
                        and flap["t0"] <= t_last <= flap["t1"]):
                    # acks inside the restore's capture->apply window
                    # may be rolled back to the newest pre-window ack
                    pre = [s for t, s in journal if t < flap["t0"]]
                    allowed.update(
                        s for t, s in journal if t >= flap["t0"])
                    allowed.add(pre[-1] if pre else 0)
                if got not in allowed:
                    lost.append(
                        f"{k}: final v={got!r} not in the acked set "
                        f"{sorted(allowed)[-3:]}")
                if got in rejected:
                    lost.append(f"{k}: final v={got!r} is a 503-shed "
                                f"write's stamp — rejects must not "
                                f"commit")
            if lost:
                problems.append("lost committed writes: "
                                + "; ".join(lost[:4]))

            # --- oracle 2: delivered or explicitly shed ----------------
            for out in sub_out:
                if out is None or out["errors"]:
                    problems.append("subscriber leg errored")
                    continue
                tag = "slow" if out["slow"] else "fast"
                if out["dropped"] == 0:
                    diverged = {
                        k: (out["replica"].get(k), v)
                        for k, v in final.items()
                        if out["replica"].get(k) != v
                    }
                    if diverged:
                        problems.append(
                            f"{tag} subscriber saw no shed yet its "
                            f"replica diverged: "
                            f"{dict(list(diverged.items())[:3])}")
                else:
                    if out["resyncs"] == 0:
                        problems.append(
                            f"{tag} subscriber lost frames without a "
                            f"resync marker")
                    # the catch-up contract: after an announced gap, a
                    # fresh re-query must converge with the plane
                    _c2, rows2 = setup.query("SELECT k, v FROM ovl_kv")
                    requeried = {r[0]: r[1] for r in rows2
                                 if r[0] != _STOP_KEY}
                    if requeried != final:
                        problems.append(
                            f"{tag} subscriber post-resync re-query "
                            f"diverged from the final table")
            if shed_total <= 0:
                problems.append(
                    "the slow subscriber was never shed — the ramp did "
                    "not overload the fanout (raise writers/ops)")

            rec["acked_writes"] = sum(len(v) for v in acks.values())
            rec["rejected_writes"] = len(rejected)
            rec["admission_rejected_total"] = rejected_total
            rec["subs_shed_total"] = shed_total
            rec["resyncs"] = sum(
                s["resyncs"] for s in sub_out if s)
            rec["frames_dropped"] = sum(
                s["dropped"] for s in sub_out if s)
            rec["ready_flap_applied"] = bool(flap["applied"])
            rec["ready_503_observed"] = flap["observed"]
            if flight is not None:
                # the shed story, replayable: corro.admission.* +
                # corro.subs.shed_total ride the end record
                flight.record(
                    "end", completed_rounds=0, aborted=False,
                    crashed=False, checkpoint=None,
                    stats={
                        "acked_writes": rec["acked_writes"],
                        "rejected_writes": rec["rejected_writes"],
                        "subs_shed_total": rec["subs_shed_total"],
                    },
                    serve=serve_snapshot(agent.metrics),
                )
    if flight is not None:
        flight.close()

    leaked = sorted(
        t.name for t in threading.enumerate()
        if t.name.startswith(("corro-http-conn", "corro-pg-conn")))
    if leaked:
        problems.append(f"leaked serving threads: {leaked}")
    rec["ok"] = not problems
    if problems:
        rec["problems"] = problems
    return rec
