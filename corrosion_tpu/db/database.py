"""Database: SQL statement execution over the TPU-resident LWW store.

The write path mirrors ``execute_statement`` /
``make_broadcastable_changes`` (``crates/corro-agent/src/api/public/
mod.rs:53-174``): statements in one transaction are translated into cell
writes on the writer node's replica and staged into the round loop
together, after which dissemination is asynchronous. The read path
mirrors ``/v1/queries``: reads observe one node's local replica only.

Supported dialect (the write/read surface the reference's API exercises):
``INSERT [OR IGNORE] INTO t (cols) VALUES (...)`` (upsert semantics, as
cr-sqlite rewrites inserts), ``UPDATE t SET c=? WHERE pk=?``,
``DELETE FROM t WHERE pk=?`` (causal-length tombstone), and
``SELECT`` with projection aliases, aggregates (COUNT/SUM/MIN/MAX/AVG/
TOTAL), ``[LEFT] JOIN ... ON a.x = b.y`` equi-joins, boolean ``WHERE``/
``HAVING`` (AND/OR/NOT with parens, SQLite three-valued logic,
``IS [NOT] NULL``, ``[NOT] LIKE/GLOB/IN``, scalar subqueries, the
``corro_json_contains`` function from ``sqlite-functions``),
non-recursive ``WITH`` CTEs, ``GROUP BY``, ``ORDER BY ... [ASC|DESC]``,
and ``LIMIT n [OFFSET m]``.
"""

from __future__ import annotations

import random as _random
import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from corrosion_tpu.db.schema import (
    CL_COL,
    RowMap,
    Schema,
    SchemaError,
    diff_schemas,
    parse_schema_sql,
)
from corrosion_tpu.db.values import NULL_ID, ValueHeap, corro_json_contains


class SqlError(ValueError):
    pass


_INSERT_RE = re.compile(
    r"INSERT\s+(?:OR\s+(?P<or>IGNORE|REPLACE)\s+)?INTO\s+(?P<table>[\w\"]+)\s*"
    r"\((?P<cols>[^)]*)\)\s*VALUES\s*\((?P<vals>.*)\)\s*"
    r"(?P<conflict>ON\s+CONFLICT.*)?$",
    re.IGNORECASE | re.DOTALL,
)
_INSERT_SELECT_RE = re.compile(
    r"INSERT\s+(?:OR\s+(?P<or>IGNORE|REPLACE)\s+)?INTO\s+(?P<table>[\w\"]+)\s*"
    r"\((?P<cols>[^)]*)\)\s*(?P<select>(?:WITH|SELECT)\s.*)$",
    re.IGNORECASE | re.DOTALL,
)
_UPDATE_RE = re.compile(
    r"UPDATE\s+(?P<table>[\w\"]+)\s+SET\s+(?P<sets>.*?)\s+WHERE\s+(?P<where>.*)$",
    re.IGNORECASE | re.DOTALL,
)
_DELETE_RE = re.compile(
    r"DELETE\s+FROM\s+(?P<table>[\w\"]+)\s+WHERE\s+(?P<where>.*)$",
    re.IGNORECASE | re.DOTALL,
)
_SELECT_RE = re.compile(r"SELECT\b", re.IGNORECASE)
# top-level clause keywords of the supported SELECT grammar:
# SELECT cols FROM t [alias] [[LEFT] JOIN t2 [alias] ON a.c = b.c]*
#   [WHERE conj] [GROUP BY cols] [ORDER BY col [ASC|DESC], ...]
#   [LIMIT n [OFFSET m]]
_KW_RE = re.compile(
    r"\b(FROM|LEFT\s+OUTER\s+JOIN|LEFT\s+JOIN|INNER\s+JOIN|JOIN|ON|WHERE|"
    r"GROUP\s+BY|HAVING|ORDER\s+BY|LIMIT|OFFSET)\b",
    re.IGNORECASE,
)
_AGG_RE = re.compile(
    r"^(?P<fn>COUNT|SUM|MIN|MAX|AVG|TOTAL)\s*\(\s*(?P<arg>\*|[\w\".]+)\s*\)"
    r"(?:\s+AS\s+(?P<alias>[\w\"]+))?$",
    re.IGNORECASE | re.DOTALL,
)
_COL_AS_RE = re.compile(
    r"^(?P<col>[\w\".]+)(?:\s+AS\s+(?P<alias>[\w\"]+))?$",
    re.IGNORECASE | re.DOTALL,
)
_COND_RE = re.compile(
    r"^(?P<col>[\w\".]+)\s*(?P<op>=|!=|<>|<=|>=|<|>)\s*(?P<val>.+)$", re.DOTALL
)
# HAVING comparisons allow an aggregate call on the left: COUNT(*) > 5
_HAVING_COND_RE = re.compile(
    r"^(?P<col>.+?)\s*(?P<op>=|!=|<>|<=|>=|<|>)\s*(?P<val>.+)$", re.DOTALL
)
_LIKE_RE = re.compile(
    r"^(?P<col>[\w\".]+)\s+(?P<neg>NOT\s+)?(?P<fn>LIKE|GLOB)\s+(?P<val>.+)$",
    re.IGNORECASE | re.DOTALL,
)
_IN_RE = re.compile(
    r"^(?P<col>[\w\".]+)\s+(?P<neg>NOT\s+)?IN\s*\((?P<body>.*)\)$",
    re.IGNORECASE | re.DOTALL,
)
_FUNC_RE = re.compile(
    r"^corro_json_contains\s*\(\s*(?P<a>[^,]+)\s*,\s*(?P<b>.+)\s*\)$",
    re.IGNORECASE | re.DOTALL,
)
_ISNULL_RE = re.compile(
    r"^(?P<col>[\w\".]+)\s+IS\s+(?P<neg>NOT\s+)?NULL$",
    re.IGNORECASE | re.DOTALL,
)
_WITH_RE = re.compile(r"^\s*WITH\s+(?:RECURSIVE\s+)?", re.IGNORECASE)
_CTE_HEAD_RE = re.compile(
    r"^\s*([\w\"]+)\s*(?:\(([^)]*)\))?\s+AS\s*\(", re.IGNORECASE
)
_UNION_ALL_RE = re.compile(r"\bUNION\s+ALL\b", re.IGNORECASE)


class _CteColumn:
    """Duck-typed column of a CTE's result (``Table.columns`` shape)."""

    __slots__ = ("name", "primary_key")

    def __init__(self, name: str):
        self.name = name
        self.primary_key = False


class _CteTable:
    """Duck-typed ``Table`` for a WITH common-table-expression: the
    parser resolves columns against the sub-select's projection names,
    and execution materializes the sub-select per node
    (``corro-pg``'s surface is full SQLite, which includes
    non-recursive CTEs; ``crates/corro-pg/src/lib.rs``)."""

    def __init__(self, name: str, col_names: List[str], ast):
        self.name = name
        self.columns = [_CteColumn(c) for c in col_names]
        self.ast = ast

    def column(self, name: str):
        for c in self.columns:
            if c.name == name:
                return c
        raise SqlError(f"no such column: {self.name}.{name}")

    def has_column(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)


class _DualTable(_CteTable):
    """One-row, zero-column pseudo table backing FROM-less SELECTs
    (``SELECT random()``, ``SELECT 1`` — the base select of the
    reference's recursive bulk-insert generator)."""

    def __init__(self):
        super().__init__("__dual__", [], ast=None)


class _RecursiveCte(_CteTable):
    """``WITH RECURSIVE name(cols) AS (base UNION ALL step)`` — the
    reference's stress drivers use exactly this as a bulk row generator
    (``INSERT INTO testsbool (id) WITH RECURSIVE cte(id) AS (SELECT
    random() UNION ALL SELECT random() FROM cte LIMIT n) ...``,
    ``agent/tests.rs:622``, ``.antithesis/.../parallel_driver_large_tx_
    sync.sh``). Evaluated iteratively: the step select sees the rows
    produced by the PREVIOUS iteration; generation stops at the body's
    LIMIT (total rows, like SQLite's compound limit) or a safety cap."""

    MAX_ROWS = 1_000_000  # runaway-recursion backstop without a LIMIT

    def __init__(self, name: str, col_names: List[str], base_ast,
                 step_ast, limit: Optional[int], self_marker,
                 self_referential: bool = True,
                 offset: Optional[int] = None):
        super().__init__(name, col_names, ast=None)
        self.base_ast = base_ast
        self.step_ast = step_ast
        self.limit = limit
        self.offset = offset
        # the step's self-reference is a plain _CteTable whose ast IS
        # this marker; execution pre-seeds the memo slot with the
        # previous iteration's rows, so the self-ref never recurses
        self.self_marker = self_marker
        # a UNION ALL whose step never reads the CTE is a plain
        # compound: base + one step pass, no iteration
        self.self_referential = self_referential


import functools
import string

# SQLite's LIKE folds case for ASCII letters ONLY ('ä' LIKE 'Ä' is 0);
# both operands are mapped through this table instead of re.IGNORECASE
_ASCII_LOWER = str.maketrans(string.ascii_uppercase, string.ascii_lowercase)


@functools.lru_cache(maxsize=512)
def _like_to_regex(pattern: str, glob: bool) -> "re.Pattern":
    """SQLite ``LIKE`` (%/_; caller pre-folds ASCII case) / ``GLOB``
    (*/?/[...], case-sensitive) pattern -> anchored regex."""
    out, i = [], 0
    while i < len(pattern):
        ch = pattern[i]
        if not glob and ch == "%":
            out.append(".*")
        elif not glob and ch == "_":
            out.append(".")
        elif glob and ch == "*":
            out.append(".*")
        elif glob and ch == "?":
            out.append(".")
        elif glob and ch == "[":
            j = pattern.find("]", i + 1)
            if j < 0:
                out.append(re.escape(ch))
            else:
                body = pattern[i + 1 : j]
                if body.startswith("^"):
                    body = "^" + re.sub(r"([\\\]])", r"\\\1", body[1:])
                else:
                    body = re.sub(r"([\\\]])", r"\\\1", body)
                out.append("[" + body + "]")
                i = j
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("^" + "".join(out) + "$")


# --- scalar expression engine (projection expressions) -------------------
# The reference runs full SQLite underneath, so projections like
# ``price * 2``, ``COALESCE(a, b)``, ``upper(name) || '!'`` just work;
# this mirrors the commonly-exercised scalar surface with SQLite's NULL
# semantics (NULL propagates; x/0 -> NULL; int/int truncates).

_EXPR_TOKEN_RE = re.compile(
    r"\s*(\|\||<>|<=|>=|!=|[+\-*/%(),=<>]|'(?:[^']|'')*'|[\w\".:$?]+)"
)

_NUM_PREFIX_RE = re.compile(r"^\s*[+-]?(\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+)")


def _num(v):
    """SQLite numeric coercion for arithmetic: text uses its numeric
    prefix (``'3x' + 1`` is 4), non-numeric text and blobs are 0."""
    if v is None or isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        m = _NUM_PREFIX_RE.match(v)
        if not m:
            return 0
        tok = m.group(0)
        try:
            return int(tok)
        except ValueError:
            return float(tok)
    return 0


def _sqlite_round(x: float, digits: int) -> float:
    """SQLite rounds halves away from zero (Python rounds half-even)."""
    import math

    s = 10.0 ** digits
    return math.copysign(math.floor(abs(x) * s + 0.5), x) / s


class _ExprParser:
    """Tiny recursive-descent parser -> ``rec -> value`` closure."""

    FUNCS = {
        "COALESCE": lambda args: next((a for a in args if a is not None),
                                      None),
        "IFNULL": lambda args: args[0] if args[0] is not None else args[1],
        "LENGTH": lambda args: (None if args[0] is None
                                else len(str(args[0]))),
        "UPPER": lambda args: (None if args[0] is None
                               else str(args[0]).upper()),
        "LOWER": lambda args: (None if args[0] is None
                               else str(args[0]).lower()),
        "ABS": lambda args: (None if args[0] is None
                             else abs(_num(args[0]))),
        # SQLite random(): a signed 64-bit integer (the reference's
        # stress drivers generate pks with it, agent/tests.rs:622)
        "RANDOM": lambda args: _random.randint(-(1 << 63), (1 << 63) - 1),
        "ROUND": lambda args: (
            None if args[0] is None
            else _sqlite_round(float(_num(args[0])),
                               int(args[1]) if len(args) > 1 else 0)
        ),
    }

    def __init__(self, s: str, resolve, p: "_Params", check_params: bool):
        self.toks: List[str] = []
        i = 0
        while i < len(s):
            m = _EXPR_TOKEN_RE.match(s, i)
            if m is None:
                if s[i:].strip():
                    raise SqlError(f"bad expression near {s[i:][:30]!r}")
                break
            self.toks.append(m.group(1))
            i = m.end()
        self.pos = 0
        self.resolve = resolve
        self.p = p
        self.check_params = check_params

    def peek(self) -> Optional[str]:
        return self.toks[self.pos] if self.pos < len(self.toks) else None

    def take(self) -> str:
        t = self.peek()
        if t is None:
            raise SqlError("unexpected end of expression")
        self.pos += 1
        return t

    def parse(self):
        fn = self._add()
        if self.peek() is not None:
            raise SqlError(f"trailing tokens in expression: {self.peek()!r}")
        return fn

    def _add(self):
        fn = self._mul()
        while self.peek() in ("+", "-"):
            op = self.take()
            rhs = self._mul()
            fn = self._arith(fn, rhs, op)
        return fn

    def _mul(self):
        fn = self._concat()
        while self.peek() in ("*", "/", "%"):
            op = self.take()
            rhs = self._concat()
            fn = self._arith(fn, rhs, op)
        return fn

    def _concat(self):
        fn = self._atom()
        while self.peek() == "||":
            self.take()
            rhs = self._atom()

            def concat(rec, a=fn, b=rhs):
                va, vb = a(rec), b(rec)
                if va is None or vb is None:
                    return None
                return str(va) + str(vb)

            fn = concat
        return fn

    @staticmethod
    def _arith(a, b, op):
        def run(rec):
            va, vb = _num(a(rec)), _num(b(rec))
            if va is None or vb is None:
                return None
            if op == "+":
                return va + vb
            if op == "-":
                return va - vb
            if op == "*":
                return va * vb
            if op == "%":
                if vb == 0:
                    return None
                # SQLite/C modulo: sign follows the dividend
                r = abs(va) % abs(vb)
                return r if va >= 0 else -r
            if vb == 0:
                return None  # SQLite: x / 0 is NULL
            if isinstance(va, int) and isinstance(vb, int):
                q = abs(va) // abs(vb)  # int/int truncates toward zero
                return q if (va >= 0) == (vb >= 0) else -q
            return va / vb

        return run

    def _atom(self):
        t = self.take()
        if t == "(":
            fn = self._add()
            if self.take() != ")":
                raise SqlError("unbalanced parens in expression")
            return fn
        if t == "-":
            inner = self._atom()

            def neg(rec):
                v = _num(inner(rec))
                return None if v is None else -v

            return neg
        up = t.upper()
        if up in self.FUNCS and self.peek() == "(":
            self.take()
            args = []
            if self.peek() != ")":
                args.append(self._add())
                while self.peek() == ",":
                    self.take()
                    args.append(self._add())
            if self.take() != ")":
                raise SqlError(f"unbalanced parens in {t}()")
            impl = self.FUNCS[up]
            return lambda rec: impl([a(rec) for a in args])
        if t.startswith("'") or t in ("?",) or t.startswith((":", "$")) \
                or up in ("NULL", "TRUE", "FALSE") or t[0].isdigit() \
                or (t[0] == "." and len(t) > 1 and t[1].isdigit()):
            v = (_parse_literal(t, self.p) if self.check_params else None)
            return lambda rec: v
        key = self.resolve(t)
        return lambda rec: rec.get(key)


def _split_expr_alias(raw: str) -> Tuple[str, Optional[str]]:
    """Split a projection expression from a trailing ``AS alias`` (or a
    bare trailing identifier alias) at paren depth 0."""
    m = re.search(r"\s+AS\s+([\w\"]+)\s*$", raw, re.IGNORECASE)
    if m:
        depth = raw[: m.start()].count("(") - raw[: m.start()].count(")")
        if depth == 0:
            return raw[: m.start()].strip(), _unquote(m.group(1))
    return raw.strip(), None


def _split_top_kw(s: str, kw: str) -> List[str]:
    """Split on a top-level keyword (``AND``/``OR``) only — occurrences
    inside parens (subqueries, groups), strings, or quoted identifiers
    don't count (ADVICE r4: ``"a or b"`` must not split)."""
    parts, start, depth, in_str = [], 0, 0, ""
    i, n, k = 0, len(s), len(kw)
    while i < n:
        ch = s[i]
        if in_str:
            if ch == in_str:
                in_str = ""
        elif ch in ("'", '"'):
            in_str = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif depth == 0 and s[i : i + k].upper() == kw and (
            i == 0 or not (s[i - 1].isalnum() or s[i - 1] in "_\"")
        ) and (
            i + k >= n or not (s[i + k].isalnum() or s[i + k] in "_\"")
        ):
            parts.append(s[start:i])
            i += k
            start = i
            continue
        i += 1
    parts.append(s[start:])
    return [p.strip() for p in parts if p.strip()]


def _split_top_and(s: str) -> List[str]:
    return _split_top_kw(s, "AND")


def _is_paren_group(s: str) -> bool:
    """Whole string is one balanced ``( ... )`` group (so the parens are
    grouping, not part of an expression like ``(a + b) > 5``)."""
    s = s.strip()
    if not (s.startswith("(") and s.endswith(")")):
        return False
    depth, in_str = 0, ""
    for i, ch in enumerate(s):
        if in_str:
            if ch == in_str:
                in_str = ""
        elif ch in ("'", '"'):
            in_str = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return i == len(s) - 1
    return False


def _unquote(ident: str) -> str:
    return ident.strip().strip('"').strip("`")


class _Params:
    """Positional ``?`` and named ``:name``/``$name`` parameter source."""

    def __init__(self, params: Any):
        self.named: Dict[str, Any] = {}
        self.positional: List[Any] = []
        if isinstance(params, dict):
            self.named = params
        elif params is not None:
            self.positional = list(params)
        self._pos = 0

    def next_positional(self) -> Any:
        if self._pos >= len(self.positional):
            raise SqlError("not enough positional parameters")
        v = self.positional[self._pos]
        self._pos += 1
        return v

    def get_named(self, name: str) -> Any:
        if name not in self.named:
            raise SqlError(f"missing named parameter :{name}")
        return self.named[name]


def _parse_literal(tok: str, params: _Params) -> Any:
    tok = tok.strip()
    if tok == "?":
        return params.next_positional()
    if tok.startswith((":", "$", "@")):
        return params.get_named(tok[1:])
    up = tok.upper()
    if up == "NULL":
        return None
    if up == "TRUE":
        return 1
    if up == "FALSE":
        return 0
    if tok.startswith("'") and tok.endswith("'") and len(tok) >= 2:
        return tok[1:-1].replace("''", "'")
    if (tok.startswith("x'") or tok.startswith("X'")) and tok.endswith("'"):
        return bytes.fromhex(tok[2:-1])
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            raise SqlError(f"unsupported literal: {tok!r}")


def _split_top_commas(s: str) -> List[str]:
    parts, depth, start = [], 0, 0
    in_str = ""
    for i, ch in enumerate(s):
        if in_str:
            if ch == in_str:
                in_str = ""
        elif ch in ("'", '"'):
            in_str = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    parts.append(s[start:])
    return [p.strip() for p in parts if p.strip()]


class ExecResult(dict):
    """``{rows_affected, time}`` — corro-api-types ``ExecResult``."""


class Database:
    """Schema + heap + row map bound to one :class:`Agent` cluster."""

    def __init__(self, agent):
        self.agent = agent
        # register for checkpoint recovery: a rollback must rewind the
        # host state (schema, heap, rows) together with the device state
        agent.recovery_db = self
        self.schema = Schema()
        self.heap = ValueHeap()
        self.rows = RowMap(agent.cfg.n_rows)
        self.n_cols = agent.cfg.n_cols
        self._mu = threading.Lock()
        self._write_hooks: List = []  # pubsub/updates change hooks
        # commit-instant stamps per (table, pk), bounded LRU (ISSUE 16):
        # the write path stamps each committed notification and the
        # NDJSON subscription streams observe write-commit -> delivery
        # latency against them (corro.subs.delivery.seconds)
        from collections import OrderedDict

        self._write_stamps: "OrderedDict[Tuple[str, Any], float]" = (
            OrderedDict()
        )
        # open StagedTxs (weak: an abandoned tx drops out on GC) — their
        # planned value ids are pinned against heap compaction
        import weakref

        self._open_txs = weakref.WeakSet()
        self._delta_tracker = None  # shared per-round delta cache

    def delta_tracker(self):
        """The shared :class:`~corrosion_tpu.pubsub.DeltaTracker` for
        this database — one plane baseline + one per-round delta
        computation, shared by subscriptions and updates feeds."""
        with self._mu:
            if self._delta_tracker is None:
                from corrosion_tpu.pubsub import DeltaTracker

                self._delta_tracker = DeltaTracker(self)
            return self._delta_tracker

    # --- schema ----------------------------------------------------------
    def apply_schema_sql(self, sql: str) -> List[Tuple[str, str]]:
        """Parse + diff + apply (``/v1/migrations`` and startup schema
        files, ``public/mod.rs:540-593``)."""
        new = parse_schema_sql(sql)
        with self._mu:
            merged = Schema(dict(self.schema.tables))
            for name, t in new.tables.items():
                merged.tables[name] = t
            changes = diff_schemas(self.schema, merged)
            for t in merged.tables.values():
                if len(t.value_columns) > self.n_cols - 1:
                    raise SchemaError(
                        f"table {t.name} has {len(t.value_columns)} value "
                        f"columns; grid supports {self.n_cols - 1} "
                        f"(raise [sim].n_cols)"
                    )
            self.schema = merged
        return changes

    def add_write_hook(self, hook) -> None:
        """hook(node, table, pk, {col: value}, deleted: bool) after a
        local write enters the round loop — the ``match_changes`` seam
        (``util.rs:1034-1037``)."""
        with self._mu:
            self._write_hooks.append(hook)

    _STAMP_CAP = 8192  # bounded: stamps for keys nobody subscribes to age out

    def _stamp_writes(self, notes: Sequence[tuple]) -> None:
        """Record the commit instant for each write notification. Called
        on the write path right after ``write_many`` returns (the write
        has entered the round loop — the reference's committed point);
        delivery observation looks the stamp up per (table, pk)."""
        if not notes:
            return
        now = time.perf_counter()
        with self._mu:
            stamps = self._write_stamps
            for table, pk, _values, _deleted in notes:
                stamps[(table, pk)] = now
                stamps.move_to_end((table, pk))
            while len(stamps) > self._STAMP_CAP:
                stamps.popitem(last=False)

    def write_stamp(self, table: str, pk: Any) -> Optional[float]:
        """Latest commit instant (``time.perf_counter`` domain) for
        (table, pk), or None if never written / aged out."""
        with self._mu:
            return self._write_stamps.get((table, pk))

    # --- cell helpers ----------------------------------------------------
    def _cell(self, row: int, col: int) -> int:
        return row * self.n_cols + col

    def _read_plane(self, node: int, row: int, col: int,
                    overlay: Optional[Dict[int, Tuple[int, int]]] = None) -> int:
        """Value-plane read; ``overlay`` holds this transaction's pending
        ``cell -> (value, clp)`` entries so later statements observe
        earlier ones (the reference runs statements sequentially inside
        one SQLite tx, ``public/mod.rs:141-174``)."""
        cell = self._cell(row, col)
        if overlay is not None and cell in overlay:
            return overlay[cell][0]
        snap = self.agent.snapshot()
        return int(snap["store"][1][node, cell])

    def _row_live(self, node: int, row: int,
                  overlay: Optional[Dict[int, Tuple[int, int]]] = None) -> bool:
        return self._read_plane(node, row, CL_COL, overlay) % 2 == 1

    def _row_record(self, node: int, table, pk, row: int,
                    overlay: Optional[Dict[int, Tuple[int, int]]] = None
                    ) -> Dict[str, Any]:
        """The row's visible values keyed by plain column name,
        overlay-aware (UPDATE expressions read the pre-update row as
        later statements in the same tx left it)."""
        snap = self.agent.snapshot()
        vals, clps = snap["store"][1], snap["store"][4]

        def read(cell: int) -> Tuple[int, int]:
            if overlay is not None and cell in overlay:
                return overlay[cell]
            return int(vals[node, cell]), int(clps[node, cell])

        row_cl, _ = read(self._cell(row, CL_COL))
        rec: Dict[str, Any] = {table.pk.name: pk}
        for c in table.value_columns:
            v, clp = read(self._cell(row, table.col_index(c.name)))
            rec[c.name] = self.heap.lookup(v) if clp == row_cl else None
        return rec

    # --- writes ----------------------------------------------------------
    def execute(self, node: int, statements: Sequence,
                wait: bool = True, timeout: float = 30.0) -> List[ExecResult]:
        """Run a transaction of statements at ``node``
        (``/v1/transactions``). Each statement is ``sql`` or
        ``(sql, params)``; returns one ``ExecResult`` per statement."""
        t0 = time.perf_counter()
        results: List[ExecResult] = []
        # cell -> (final value, causal-length lifetime) this tx (ordered)
        merged: Dict[int, Tuple[int, int]] = {}
        notifications = []
        for stmt in statements:
            sql, params = (stmt, None) if isinstance(stmt, str) else (
                stmt[0], stmt[1] if len(stmt) > 1 else None
            )
            affected, stmt_cells, notes = self._plan_write(
                node, sql, params, merged
            )
            # later statements override earlier cells for the same target —
            # last-write-wins within the transaction, like sequential
            # statements in one SQLite tx (dict update keeps first position)
            merged.update({c: (v, l) for c, v, l in stmt_cells})
            notifications.extend(notes)
            results.append(
                ExecResult(rows_affected=affected,
                           time=time.perf_counter() - t0)
            )
        cells = self._order_tx_cells(merged)
        if cells:
            self.agent.write_many(node, cells, wait=wait, timeout=timeout)
        self._stamp_writes(notifications)
        with self._mu:
            hooks = list(self._write_hooks)
        for note in notifications:
            for hook in hooks:
                hook(node, *note)
        return results

    # --- heap compaction (vacuum_db analog, handlers.rs:398-452) ---------
    def referenced_value_ids(self) -> set:
        """Every heap id referenced by device state anywhere: the store
        value planes of all nodes, in-flight broadcast queue payloads,
        and buffered partial-version payloads. The union is the live set
        a heap compaction must preserve."""
        import numpy as np

        st = self.agent.device_state()
        crdt = getattr(st, "crdt", st)
        refs: set = set()
        arrays = [np.asarray(crdt.store[1])]
        q_val = getattr(crdt, "q_val", None)
        if q_val is not None:
            # freed queue slots (origin -1) keep stale payload bytes —
            # mask them to NULL or old ids would stay referenced forever
            live = np.asarray(crdt.q_origin) >= 0
            arrays.append(np.where(live, np.asarray(q_val), 0))
        partials = getattr(crdt, "partials", None)
        if partials is not None:
            live = (np.asarray(partials.origin) >= 0)[..., None]
            arrays.append(np.where(live, np.asarray(partials.val), 0))
        for a in arrays:
            refs.update(int(x) for x in np.unique(a))
        # ids planned inside open (uncommitted) StagedTxs live only on
        # the host until COMMIT — pin them (code review r5: an idle PG
        # BEGIN block outliving the grace window must not lose values)
        with self._mu:  # WeakSet iteration races concurrent BEGIN adds
            open_txs = list(self._open_txs)
        for tx in open_txs:
            if not tx._done:
                # snapshot: the PG handler thread mutates _merged
                # concurrently with this maintenance-thread scan
                refs.update(v for v, _l in list(tx._merged.values()))
        return refs

    def compact_heap(self, grace_seconds: float = 300.0) -> int:
        """One heap-compaction pass: free ids referenced nowhere in
        device state (ids are stable — unreferenced ones go to a free
        list for reuse, device planes are never rewritten). The grace
        window protects writes planned on the host but not yet applied
        on device. Returns the number of ids freed."""
        return self.heap.compact(self.referenced_value_ids(),
                                 grace_seconds=grace_seconds)

    def begin(self, node: int) -> "StagedTx":
        """Open a multi-statement staged transaction at ``node`` — the
        PG-wire BEGIN/COMMIT surface (``corro-pg/src/lib.rs`` runs real
        SQLite transactions; here statements are planned eagerly against
        a shared overlay, so later statements read earlier writes and
        per-statement row counts are exact, and nothing reaches the
        round loop until :meth:`StagedTx.commit`)."""
        return StagedTx(self, node)

    def _order_tx_cells(self, merged: Dict[int, Tuple[int, int]]
                        ) -> List[Tuple[int, int, int]]:
        """Drain order for the transaction's net ``(cell, value, clp)``
        writes: causal-length flips that leave a row LIVE go last (the row
        only turns visible once its values are in flight) and flips that
        leave it DEAD go first. Within one ``tx_max_cells`` chunk the
        commit is atomic (one db_version, remote buffering), so order only
        matters when an oversized transaction splits into several
        versions — there, list order is chunk order is visibility order."""
        deaths, values, lives = [], [], []
        for cell, (value, clp) in merged.items():
            if cell % self.n_cols == CL_COL:
                (lives if value % 2 == 1 else deaths).append((cell, value, clp))
            else:
                values.append((cell, value, clp))
        return deaths + values + lives

    def _plan_write(self, node: int, sql: str, params: Any,
                    overlay: Optional[Dict[int, int]] = None):
        """-> (rows_affected, [(cell, interned_val)], [notifications])."""
        sql = sql.strip().rstrip(";").strip()
        p = _Params(params)
        m = _INSERT_RE.match(sql)
        if m:
            return self._plan_insert(node, m, p, overlay)
        m = _INSERT_SELECT_RE.match(sql)
        if m:
            return self._plan_insert_select(node, m, p, overlay)
        m = _UPDATE_RE.match(sql)
        if m:
            return self._plan_update(node, m, p, overlay)
        m = _DELETE_RE.match(sql)
        if m:
            return self._plan_delete(node, m, p, overlay)
        if _SELECT_RE.match(sql):
            raise SqlError("SELECT not allowed in /v1/transactions (read-only "
                           "statements go to /v1/queries)")
        raise SqlError(f"unsupported statement: {sql[:80]!r}")

    def _insert_by_col(self, table, col_names: List[str], vals: List[Any]):
        """Shared INSERT row prep: (pk, by_col with defaults filled)."""
        if len(col_names) != len(vals):
            raise SqlError(f"{len(col_names)} columns but {len(vals)} values")
        by_col = dict(zip(col_names, vals))
        pk_name = table.pk.name
        if pk_name not in by_col:
            raise SqlError(f"INSERT into {table.name} must set pk {pk_name}")
        pk = by_col.pop(pk_name)
        if pk is None:
            raise SqlError(f"pk {table.name}.{pk_name} cannot be NULL")
        for c in table.value_columns:
            if c.name not in by_col:
                by_col[c.name] = c.default
            elif by_col[c.name] is None and c.not_null:
                raise SqlError(f"NOT NULL violation: {table.name}.{c.name}")
        for name in by_col:
            table.column(name)  # raises on unknown column
        return pk, by_col

    def _plan_insert_select(self, node: int, m, p: _Params,
                            overlay: Optional[Dict[int, int]] = None):
        """``INSERT INTO t (cols) SELECT ...`` (incl. a WITH RECURSIVE
        generator select — the reference's bulk-insert stress shape,
        ``agent/tests.rs:622``). Each produced row plans like a VALUES
        insert; later rows observe earlier ones through a local overlay
        (duplicate pks upsert, like sequential inserts)."""
        table = self.schema.table(_unquote(m.group("table")))
        col_names = [_unquote(c) for c in m.group("cols").split(",")]
        ast = self._parse_select(m.group("select"), p)
        or_clause = (m.group("or") or "").upper()
        ov = dict(overlay or {})
        total, cells_acc, notes_acc = 0, [], []
        for vals in list(self._run_select(node, ast, overlay=ov)):
            pk, by_col = self._insert_by_col(table, col_names, list(vals))
            n1, cells, notes = self._plan_insert_core(
                node, table, pk, by_col, or_clause, "", p, ov)
            ov.update({c: (v, l) for c, v, l in cells})
            total += n1
            cells_acc.extend(cells)
            notes_acc.extend(notes)
        return total, cells_acc, notes_acc

    def _plan_insert(self, node: int, m, p: _Params,
                     overlay: Optional[Dict[int, int]] = None):
        table = self.schema.table(_unquote(m.group("table")))
        col_names = [_unquote(c) for c in m.group("cols").split(",")]
        vals = [_parse_literal(v, p) for v in _split_top_commas(m.group("vals"))]
        pk, by_col = self._insert_by_col(table, col_names, vals)
        return self._plan_insert_core(
            node, table, pk, by_col, (m.group("or") or "").upper(),
            (m.group("conflict") or "").strip(), p, overlay,
        )

    def _plan_insert_core(self, node: int, table, pk, by_col: Dict[str, Any],
                          or_clause: str, conflict_raw: str, p: _Params,
                          overlay: Optional[Dict[int, int]] = None):
        pk_name = table.pk.name
        row = self.rows.get_or_alloc(table.name, pk)
        cl = self._read_plane(node, row, CL_COL, overlay)
        live = cl % 2 == 1
        conflict = conflict_raw.upper()
        if live and (or_clause == "IGNORE" or "DO NOTHING" in conflict):
            return 0, [], []
        if live and "DO UPDATE" in conflict:
            # ON CONFLICT DO UPDATE SET ... (upsert with expressions;
            # the reference gets this free from SQLite). `excluded.col`
            # refers to the proposed insert values, a bare column to the
            # existing row — standard SQLite semantics.
            du = re.search(r"DO\s+UPDATE\s+SET\s+(?P<sets>.*)$",
                           conflict_raw, re.IGNORECASE | re.DOTALL)
            if du is None:
                raise SqlError(
                    f"unsupported ON CONFLICT clause: {conflict_raw!r}")
            excluded = {**by_col, pk_name: pk}

            def res(ref: str) -> str:
                ref = ref.strip()
                if "." in ref:
                    q, _, c = ref.partition(".")
                    if _unquote(q).lower() != "excluded":
                        raise SqlError(
                            f"unknown qualifier {q!r} in DO UPDATE")
                    c = _unquote(c)
                    table.column(c)
                    return f"excluded.{c}"
                c = _unquote(ref)
                table.column(c)
                return c

            rec = self._row_record(node, table, pk, row, overlay)
            rec.update({f"excluded.{k}": v for k, v in excluded.items()})
            sets: Dict[str, Any] = {}
            for part in _split_top_commas(du.group("sets")):
                if "=" not in part:
                    raise SqlError(f"bad DO UPDATE SET clause: {part!r}")
                name, _, raw = part.partition("=")
                name = _unquote(name)
                if table.column(name).primary_key:
                    raise SqlError("cannot DO UPDATE the primary key")
                try:
                    sets[name] = _parse_literal(raw, p)
                except SqlError:
                    sets[name] = _ExprParser(raw, res, p, True).parse()(rec)
            for name, value in sets.items():
                if value is None and table.column(name).not_null:
                    raise SqlError(
                        f"NOT NULL violation: {table.name}.{name}")
            cells = [
                (self._cell(row, table.col_index(name)),
                 self.heap.intern(value), cl)
                for name, value in sets.items()
            ]
            return 1, cells, [(table.name, pk, dict(sets), False)]
        # lifetime the write belongs to: the current one for a live-row
        # upsert, the NEXT odd causal length for an insert/resurrect —
        # value cells from a previous lifetime must not leak through
        # (cr-sqlite `cl` semantics, doc/crdts.md:24-40)
        lifetime = cl if live else cl + 1
        cells: List[Tuple[int, int, int]] = []
        for name, value in by_col.items():
            cells.append(
                (self._cell(row, table.col_index(name)),
                 self.heap.intern(value), lifetime)
            )
        if not live:
            # CL flip staged LAST: within a tx_max_cells chunk the commit
            # is atomic, but an oversized transaction splits into several
            # versions — the row must only turn live once its values are
            # already committed/in flight (insert atomicity for readers)
            cells.append((self._cell(row, CL_COL), cl + 1, cl + 1))
        return 1, cells, [(table.name, pk, dict(by_col), False)]

    def _split_where_pk(self, table, where: str, p: _Params):
        cond = _COND_RE.match(where.strip())
        if not cond or cond.group("op") != "=":
            raise SqlError(
                f"writes require `WHERE {table.pk.name} = <value>` "
                f"(got {where!r})"
            )
        col = _unquote(cond.group("col"))
        if col != table.pk.name:
            raise SqlError(f"writes must filter on the pk ({table.pk.name})")
        return _parse_literal(cond.group("val"), p)

    def _plan_update(self, node: int, m, p: _Params,
                     overlay: Optional[Dict[int, int]] = None):
        table = self.schema.table(_unquote(m.group("table")))
        sets: Dict[str, Any] = {}
        exprs: Dict[str, Any] = {}  # SET col = <expression over the row>

        def res(ref: str) -> str:
            c = _unquote(ref.strip())
            table.column(c)  # raises on unknown column
            return c

        set_parts = _split_top_commas(m.group("sets"))
        for part in set_parts:
            if "=" not in part:
                raise SqlError(f"bad SET clause: {part!r}")
            name, _, raw = part.partition("=")
            name = _unquote(name)
            col = table.column(name)
            if col.primary_key:
                raise SqlError("cannot UPDATE the primary key")
            try:
                sets[name] = _parse_literal(raw, p)
            except SqlError:
                # UPDATE with an expression right side (SET x = x + 1,
                # SET x = LENGTH(y) ...) — the reference gets this free
                # from SQLite (sqlite.rs:121-139); evaluated against the
                # PRE-update row, like SQL
                exprs[name] = _ExprParser(raw, res, p, True).parse()
        pk = self._split_where_pk(table, m.group("where"), p)
        row = self.rows.get(table.name, pk)
        if row is None or not self._row_live(node, row, overlay):
            return 0, [], []
        if exprs:
            rec = self._row_record(node, table, pk, row, overlay)
            for name, fn in exprs.items():
                sets[name] = fn(rec)
        for name, value in sets.items():
            if value is None and table.column(name).not_null:
                raise SqlError(f"NOT NULL violation: {table.name}.{name}")
        lifetime = self._read_plane(node, row, CL_COL, overlay)
        cells = [
            (self._cell(row, table.col_index(name)),
             self.heap.intern(value), lifetime)
            for name, value in sets.items()
        ]
        return 1, cells, [(table.name, pk, dict(sets), False)]

    def _plan_delete(self, node: int, m, p: _Params,
                     overlay: Optional[Dict[int, int]] = None):
        table = self.schema.table(_unquote(m.group("table")))
        pk = self._split_where_pk(table, m.group("where"), p)
        row = self.rows.get(table.name, pk)
        if row is None:
            return 0, [], []
        cl = self._read_plane(node, row, CL_COL, overlay)
        if cl % 2 == 0:
            return 0, [], []
        cells = [(self._cell(row, CL_COL), cl + 1, cl + 1)]
        return 1, cells, [(table.name, pk, {}, True)]

    # --- reads -----------------------------------------------------------
    def query(self, node: int, sql: str, params: Any = None
              ) -> Tuple[List[str], Iterable[List[Any]]]:
        """Read-only query against ``node``'s replica (``/v1/queries``).
        Returns ``(column_names, row_iterator)``.

        Dialect (the read surface the reference's templates/consul/admin
        tooling actually exercises over full SQLite): projection incl.
        aggregates (COUNT/SUM/MIN/MAX/AVG/TOTAL) with ``AS`` aliases,
        ``[LEFT] JOIN ... ON a.x = b.y`` equi-joins, ``WHERE``
        conjunctions, ``GROUP BY``, ``ORDER BY ... [ASC|DESC]``, and
        ``LIMIT n [OFFSET m]``."""
        ast = self._parse_select(sql, _Params(params))
        names = [c[2] for c in ast["cols"]]
        return names, self._run_select(node, ast)

    def query_filtered(self, node: int, sql: str, params: Any,
                       extra_conds: Sequence[tuple]
                       ) -> Iterable[List[Any]]:
        """Run ``sql`` with extra top-level cond tuples injected after
        parsing — the incremental subscription matcher's candidate-pk
        restriction (the analog of the reference's per-changeset
        candidate queries against the subscription DB,
        ``pubsub.rs:527-1100``). ``extra_conds`` holds evaluator cond
        tuples over resolved record keys, e.g. ``("in", "a.pk", [...])``
        or an ``("or", [branches...], None)`` disjunction of them; rows
        are returned without column names (the caller knows the
        projection)."""
        ast = self._parse_select(sql, _Params(params))
        ast = {**ast, "conds": list(ast["conds"]) + list(extra_conds)}
        return self._run_select(node, ast)

    def query_columns(self, sql: str) -> List[str]:
        """The column names a SELECT would produce — schema-only, no
        scan (used by the PG Describe phase)."""
        ast = self._parse_select(sql, _Params(None), check_params=False)
        return [c[2] for c in ast["cols"]]

    # --- SELECT parsing ---------------------------------------------------
    @staticmethod
    def _top_level_mask(sql: str) -> List[bool]:
        """True where a char sits outside quotes (both kinds) and parens."""
        mask, depth, in_str = [], 0, ""
        for ch in sql:
            if in_str:
                mask.append(False)
                if ch == in_str:
                    in_str = ""
            elif ch in ("'", '"'):
                in_str = ch
                mask.append(False)
            elif ch == "(":
                depth += 1
                mask.append(False)
            elif ch == ")":
                depth -= 1
                mask.append(False)
            else:
                mask.append(depth == 0)
        return mask

    def _parse_cte_prefix(self, sql: str, p: _Params, check_params: bool,
                          ctes: Optional[Dict[str, "_CteTable"]]):
        """Strip a leading ``WITH name AS (...), ...`` prefix, parsing
        each CTE body (earlier CTEs are visible to later ones and to the
        main select, like SQLite's non-recursive WITH)."""
        out: Dict[str, _CteTable] = dict(ctes or {})
        rest = sql[_WITH_RE.match(sql).end():]
        while True:
            hm = _CTE_HEAD_RE.match(rest)
            if hm is None:
                raise SqlError(f"malformed WITH clause near {rest[:60]!r}")
            name = _unquote(hm.group(1))
            # find the balanced close of the body paren
            depth, in_str, i = 1, False, hm.end()
            while i < len(rest) and depth:
                ch = rest[i]
                if in_str:
                    in_str = ch != "'"
                elif ch == "'":
                    in_str = True
                elif ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                i += 1
            if depth:
                raise SqlError(f"unbalanced parens in WITH {name!r}")
            body = rest[hm.end():i - 1].strip()
            head_cols = [
                _unquote(c) for c in (hm.group(2) or "").split(",")
                if c.strip()
            ]
            um = None
            for m2 in _UNION_ALL_RE.finditer(body):
                if self._top_level_mask(body)[m2.start()]:
                    um = m2
                    break
            if um is not None:
                # recursive CTE: base UNION ALL step [LIMIT total]
                base_ast = self._parse_select(body[:um.start()], p,
                                              check_params, ctes=out)
                cols = head_cols or [c[2] for c in base_ast["cols"]]
                marker = object()
                placeholder = _CteTable(name, cols, marker)
                step_ast = self._parse_select(
                    body[um.end():], p, check_params,
                    ctes={**out, name: placeholder},
                )
                # the compound's LIMIT/OFFSET (total generated rows,
                # SQLite semantics) parse as the step select's — lift
                # them off the step
                limit = step_ast.get("limit")
                offset = step_ast.get("offset")
                step_ast = {**step_ast, "limit": None, "offset": None}
                self_ref = any(
                    isinstance(t, _CteTable) and t.ast is marker
                    for t in step_ast["aliases"].values()
                )
                out[name] = _RecursiveCte(name, cols, base_ast, step_ast,
                                          limit, marker, self_ref,
                                          offset=offset)
            else:
                sub = self._parse_select(body, p, check_params, ctes=out)
                cols = head_cols or [c[2] for c in sub["cols"]]
                out[name] = _CteTable(name, cols, sub)
            rest = rest[i:].lstrip()
            if rest.startswith(","):
                rest = rest[1:].lstrip()
                continue
            return out, rest

    def _parse_select(self, sql: str, p: _Params, check_params: bool = True,
                      ctes: Optional[Dict[str, "_CteTable"]] = None):
        sql = sql.strip().rstrip(";").strip()
        if _WITH_RE.match(sql):
            ctes, sql = self._parse_cte_prefix(sql, p, check_params, ctes)
        if not _SELECT_RE.match(sql):
            raise SqlError(f"only SELECT is allowed on the query path: "
                           f"{sql[:80]!r}")
        mask = self._top_level_mask(sql)
        marks = [
            (m.start(), m.end(), re.sub(r"\s+", " ", m.group(1)).upper())
            for m in _KW_RE.finditer(sql)
            if mask[m.start()]
        ]
        from_marks = [m for m in marks if m[2] == "FROM"]
        if not from_marks:
            # FROM-less SELECT: evaluate the projection once against a
            # one-row dual table (SQLite semantics); re-parse with the
            # synthesized FROM inserted before any trailing clauses
            insert_at = marks[0][0] if marks else len(sql)
            sql2 = (sql[:insert_at] + " FROM __dual__ " + sql[insert_at:])
            return self._parse_select(
                sql2, p, check_params,
                ctes={**(ctes or {}), "__dual__": _DualTable()},
            )
        # clause segmentation: text between consecutive top-level keywords
        segs = []
        for i, (s, e, kw) in enumerate(marks):
            end = marks[i + 1][0] if i + 1 < len(marks) else len(sql)
            segs.append((kw, sql[e:end].strip()))
        cols_raw = sql[len("SELECT"):from_marks[0][0]].strip()

        # FROM + JOINs (CTE names shadow schema tables, like SQLite)
        def table_spec(raw):
            parts = raw.split()
            name = _unquote(parts[0])
            alias = _unquote(parts[-1]) if (
                len(parts) > 1 and parts[-1].upper() != "AS"
            ) else name
            if ctes and name in ctes:
                return ctes[name], alias
            return self.schema.table(name), alias

        aliases: Dict[str, Any] = {}
        joins = []
        where_raw = group_raw = order_raw = limit_raw = offset_raw = None
        having_raw = None
        i = 0
        while i < len(segs):
            kw, seg = segs[i]
            if kw == "FROM":
                base_table, base_alias = table_spec(seg)
                aliases[base_alias] = base_table
            elif kw.endswith("JOIN"):
                jtype = "left" if kw.startswith("LEFT") else "inner"
                if i + 1 >= len(segs) or segs[i + 1][0] != "ON":
                    raise SqlError(f"JOIN without ON: {seg!r}")
                t, a = table_spec(seg)
                if a in aliases:
                    raise SqlError(f"duplicate table alias {a!r}")
                aliases[a] = t
                cond = segs[i + 1][1]
                cm = re.match(
                    r"^([\w\".]+)\s*=\s*([\w\".]+)$", cond.strip()
                )
                if cm is None:
                    raise SqlError(
                        f"only equi-join ON a.x = b.y supported: {cond!r}"
                    )
                joins.append((jtype, a, cm.group(1), cm.group(2)))
                i += 1
            elif kw == "ON":
                raise SqlError("ON outside a JOIN")
            elif kw == "WHERE":
                where_raw = seg
            elif kw == "GROUP BY":
                group_raw = seg
            elif kw == "HAVING":
                having_raw = seg
            elif kw == "ORDER BY":
                order_raw = seg
            elif kw == "LIMIT":
                limit_raw = seg
            elif kw == "OFFSET":
                offset_raw = seg
            i += 1

        def resolve(ref: str) -> str:
            """Column reference -> record key ('alias.col')."""
            ref = ref.strip()
            if "." in ref:
                q, _, c = ref.partition(".")
                q, c = _unquote(q), _unquote(c)
                if q not in aliases:
                    raise SqlError(f"unknown table alias {q!r}")
                aliases[q].column(c)  # raises on unknown column
                return f"{q}.{c}"
            c = _unquote(ref)
            owners = [a for a, t in aliases.items() if t.has_column(c)]
            if not owners:
                raise SqlError(f"unknown column {c!r}")
            if len(owners) > 1:
                raise SqlError(f"ambiguous column {c!r} (qualify it)")
            return f"{owners[0]}.{c}"

        # projection
        cols = []  # (kind, payload, output name)
        for raw in _split_top_commas(cols_raw):
            raw = raw.strip()
            if raw == "*":
                for a, t in aliases.items():
                    for c in t.columns:
                        cols.append(("col", f"{a}.{c.name}", c.name))
                continue
            am = _AGG_RE.match(raw)
            if am:
                fn = am.group("fn").upper()
                arg = am.group("arg")
                key = None if arg == "*" else resolve(arg)
                if key is None and fn != "COUNT":
                    raise SqlError(f"{fn}(*) is not valid SQL")
                name = _unquote(am.group("alias") or "") or re.sub(
                    r"\s+", "", raw.split(" AS ")[0].split(" as ")[0]
                )
                cols.append(("agg", (fn, key), name))
                continue
            cm = _COL_AS_RE.match(raw)
            if cm is not None:
                try:
                    key = resolve(cm.group("col"))
                except SqlError:
                    cm = None  # literal projection (SELECT 5, NULL, ...)
                if cm is not None:
                    name = (_unquote(cm.group("alias") or "")
                            or key.split(".", 1)[1])
                    cols.append(("col", key, name))
                    continue
            # scalar expression projection (price * 2, COALESCE(a, b), ...)
            expr_raw, alias = _split_expr_alias(raw)
            fn = _ExprParser(expr_raw, resolve, p, check_params).parse()
            name = alias or re.sub(r"\s+", "", expr_raw)
            cols.append(("expr", fn, name))

        # WHERE / HAVING conjunctions (shared grammar; HAVING resolves its
        # left sides per group at execution time, so they stay raw here)
        conds = (self._parse_conds(where_raw, p, resolve, check_params,
                                   ctes=ctes)
                 if where_raw else [])
        having = (self._parse_conds(having_raw, p, resolve, check_params,
                                    defer_lhs=True, ctes=ctes)
                  if having_raw else [])

        # GROUP BY entries: plain columns resolve to record keys, output
        # aliases group by their projected payload (SQLite allows both),
        # anything else groups by a computed expression
        out_names = {name for _k, _p, name in cols}
        by_name = {name: (kind, payload) for kind, payload, name in cols}
        group = []
        if group_raw:
            for g in _split_top_commas(group_raw):
                alias = _unquote(g)
                if alias in by_name:
                    kind, payload = by_name[alias]
                    if kind == "agg":
                        raise SqlError(
                            f"cannot GROUP BY aggregate {alias!r}"
                        )
                    group.append(payload if kind == "col"
                                 else ("\x00expr", payload))
                    continue
                try:
                    group.append(resolve(g))
                except SqlError:
                    group.append(
                        ("\x00expr",
                         _ExprParser(g, resolve, p, check_params).parse())
                    )
        order = []
        if order_raw:
            for part in _split_top_commas(order_raw):
                # strip a trailing ASC/DESC without re-joining tokens —
                # whitespace inside string literals must survive intact
                m_dir = re.search(r"\s+(ASC|DESC)\s*$", part, re.IGNORECASE)
                desc = bool(m_dir) and m_dir.group(1).upper() == "DESC"
                ref = (part[: m_dir.start()] if m_dir else part).strip()
                if re.fullmatch(r"\d+", ref):
                    # SQLite: a bare integer is an output-column ordinal
                    k = int(ref)
                    if not 1 <= k <= len(cols):
                        raise SqlError(
                            f"ORDER BY ordinal {k} out of range"
                        )
                    order.append((cols[k - 1][2], None, desc))
                    continue
                # output aliases and plain columns sort through the row
                # lookup; anything else is an ORDER BY expression
                fn = None
                if _unquote(ref) not in out_names:
                    try:
                        resolve(ref)
                    except SqlError:
                        fn = _ExprParser(ref, resolve, p,
                                         check_params).parse()
                order.append((ref, fn, desc))

        def int_or_param(raw):
            if raw is None:
                return None
            raw = raw.strip()
            if not check_params:
                return 0 if raw in ("?",) or raw.startswith((":", "$")) else int(raw)
            v = _parse_literal(raw, p)
            if not isinstance(v, int) or v < 0:
                raise SqlError(f"LIMIT/OFFSET must be a non-negative int: {raw!r}")
            return v

        return {
            "aliases": aliases, "base": base_alias, "joins": joins,
            "cols": cols, "conds": conds, "having": having, "group": group,
            "order": order,
            "limit": int_or_param(limit_raw),
            "offset": int_or_param(offset_raw),
            "resolve": resolve,
        }

    def _parse_conds(self, raw: str, p: _Params, resolve, check_params,
                     defer_lhs: bool = False, ctes=None) -> List[tuple]:
        """Parse a WHERE/HAVING boolean expression into a cond list.

        Leaves are ``(op, lhs, rhs)`` tuples — comparison operators,
        ``[not] like``/``[not] glob``, ``[not] in`` (literal list or
        subquery), ``json_contains``; an rhs of ``(SELECT ...)`` parses
        into a ``("subq"/"subq_list", ast)`` marker resolved against the
        queried node at execution (``corro-pg``'s sqlparser surface,
        ``crates/corro-pg/src/lib.rs``). The boolean structure rides the
        same shape: a list is an AND-conjunction whose entries may also
        be ``("or", [branch-conds...], None)`` / ``("not", conds, None)``
        nodes, evaluated with SQLite's three-valued logic (NULL-involved
        comparisons are UNKNOWN, excluded at the top level, and NOT
        preserves UNKNOWN rather than flipping it to true)."""
        or_parts = _split_top_kw(raw, "OR")
        if len(or_parts) > 1:
            return [(
                "or",
                [self._parse_conds(part, p, resolve, check_params,
                                   defer_lhs, ctes)
                 for part in or_parts],
                None,
            )]
        conds: List[tuple] = []
        res = (lambda r: r.strip()) if defer_lhs else resolve
        for clause in _split_top_and(raw):
            # NOT <group-or-clause> (but not the NOT of "NOT LIKE"/
            # "NOT IN", which the leaf regexes own)
            nm = re.match(r"NOT\s+(?=\()|NOT\s+(?!LIKE\b|GLOB\b|IN\b)",
                          clause, re.IGNORECASE)
            if nm and not _LIKE_RE.match(clause) and not _IN_RE.match(
                    clause):
                conds.append((
                    "not",
                    self._parse_conds(clause[nm.end():], p, resolve,
                                      check_params, defer_lhs, ctes),
                    None,
                ))
                continue
            # a grouping paren (never a subquery: those appear only as
            # rhs / IN bodies, which the leaf paths below handle)
            if _is_paren_group(clause) and not _SELECT_RE.match(
                    clause[1:-1].strip()):
                conds.extend(
                    self._parse_conds(clause[1:-1], p, resolve,
                                      check_params, defer_lhs, ctes)
                )
                continue
            fm = _FUNC_RE.match(clause)
            if fm:
                needle = (_parse_literal(fm.group("b"), p)
                          if check_params else None)
                conds.append(("json_contains", res(fm.group("a")), needle))
                continue
            lm = _LIKE_RE.match(clause)
            if lm:
                op = (("not " if lm.group("neg") else "")
                      + lm.group("fn").lower())
                conds.append(
                    (op, res(lm.group("col")),
                     self._parse_rhs(lm.group("val"), p, check_params,
                                     ctes))
                )
                continue
            km = _ISNULL_RE.match(clause)
            if km:
                conds.append((
                    "is not null" if km.group("neg") else "is null",
                    res(km.group("col")), None,
                ))
                continue
            im = _IN_RE.match(clause)
            if im:
                op = "not in" if im.group("neg") else "in"
                body = im.group("body").strip()
                if _SELECT_RE.match(body):
                    val = ("subq_list", self._parse_select(
                        body, p, check_params, ctes=ctes))
                else:
                    val = [
                        (_parse_literal(t, p) if check_params else None)
                        for t in _split_top_commas(body)
                    ]
                conds.append((op, res(im.group("col")), val))
                continue
            cm = (_HAVING_COND_RE if defer_lhs else _COND_RE).match(clause)
            if cm is not None:
                conds.append(
                    (cm.group("op"), res(cm.group("col")),
                     self._parse_rhs(cm.group("val"), p, check_params,
                                     ctes))
                )
                continue
            # expression left side: WHERE a + b > 5, LENGTH(name) = 3 ...
            em = _HAVING_COND_RE.match(clause)
            if em is not None and not defer_lhs:
                fn = _ExprParser(em.group("col"), resolve, p,
                                 check_params).parse()
                conds.append(
                    (em.group("op"), ("\x00expr", fn),
                     self._parse_rhs(em.group("val"), p, check_params,
                                     ctes))
                )
                continue
            raise SqlError(
                f"unsupported WHERE/HAVING clause: {clause!r}"
            )
        return conds

    def _parse_rhs(self, raw: str, p: _Params, check_params, ctes=None):
        raw = raw.strip()
        if (raw.startswith("(") and raw.endswith(")")
                and _SELECT_RE.match(raw[1:-1].strip())):
            return ("subq", self._parse_select(
                raw[1:-1].strip(), p, check_params, ctes=ctes))
        return _parse_literal(raw, p) if check_params else None

    # --- SELECT execution -------------------------------------------------
    def _table_records(self, node: int, table, alias: str, vals, clps,
                       cte_memo=None, overlay=None):
        """All live rows of one table as {'alias.col': value} dicts.
        A CTE materializes its sub-select against the same node ONCE
        per top-level execution (``cte_memo``): chained/self-joined CTE
        references reuse the rows, matching SQLite's materialization.
        ``overlay`` (tx-pending cells) flows into CTE bodies so an
        ``INSERT ... WITH ... SELECT`` inside a transaction sees earlier
        statements, same as the plain-select form."""
        if isinstance(table, _DualTable):
            return [{}]  # one empty record: constant projections emit once
        if isinstance(table, _RecursiveCte):
            names = [c.name for c in table.columns]
            memo = cte_memo if cte_memo is not None else {}
            key = (node, id(table))
            if key not in memo:
                memo[key] = self._run_recursive_cte(node, table, memo,
                                                    overlay=overlay)
            return [
                {f"{alias}.{k}": v for k, v in zip(names, row)}
                for row in memo[key]
            ]
        if isinstance(table, _CteTable):
            names = [c.name for c in table.columns]
            memo = cte_memo if cte_memo is not None else {}
            key = (node, id(table.ast))
            if key not in memo:
                if not isinstance(table.ast, dict):
                    # the bare self-reference marker outside a seeded
                    # recursive evaluation (e.g. referenced from a
                    # subquery, which runs with a fresh memo)
                    raise SqlError(
                        f"recursive reference to {table.name!r} is only "
                        f"supported in the step's FROM/JOIN"
                    )
                memo[key] = list(
                    self._run_select(node, table.ast, cte_memo=memo,
                                     overlay=overlay)
                )
            return [
                {f"{alias}.{k}": v for k, v in zip(names, row)}
                for row in memo[key]
            ]
        out = []
        for pk, row in self.rows.rows_of(table.name):
            if int(vals[self._cell(row, CL_COL)]) % 2 == 0:
                continue
            rec = self._materialize(table, pk, vals, clps, row)
            out.append({f"{alias}.{k}": v for k, v in rec.items()})
        return out

    def _resolve_subqueries(self, node: int, conds: List[tuple]) -> List[tuple]:
        """Materialize ``("subq"/"subq_list", ast)`` rhs markers against
        ``node``'s replica: scalar = first row's first column (None when
        empty, like SQLite), list = every row's first column."""
        out = []
        for op, lhs, val in conds:
            if op == "or":
                lhs = [self._resolve_subqueries(node, b) for b in lhs]
            elif op == "not":
                lhs = self._resolve_subqueries(node, lhs)
            elif (isinstance(val, tuple) and len(val) == 2
                    and val[0] in ("subq", "subq_list")):
                rows = list(self._run_select(node, val[1]))
                if val[0] == "subq":
                    val = rows[0][0] if rows else None
                else:
                    val = [r[0] for r in rows]
            out.append((op, lhs, val))
        return out

    def _run_select(self, node: int, ast,
                    cte_memo=None, overlay=None) -> Iterable[List[Any]]:
        if cte_memo is None:
            cte_memo = {}
        ast = {
            **ast,
            "conds": self._resolve_subqueries(node, ast["conds"]),
            "having": self._resolve_subqueries(node, ast.get("having", [])),
        }
        snap = self.agent.snapshot()
        vals = snap["store"][1][node]
        clps = snap["store"][4][node]
        if overlay:
            # transaction-local pending cells (INSERT ... SELECT inside
            # a multi-statement tx must see earlier statements' writes,
            # like every other write path); nested subqueries still read
            # the committed store. Patched planes are memoized per
            # (node, overlay) so a recursive CTE's per-iteration
            # re-entry doesn't re-copy the full planes every time.
            memo_key = ("__overlay__", node, id(overlay))
            patched = cte_memo.get(memo_key)
            if patched is None:
                import numpy as np

                vals = np.array(vals)
                clps = np.array(clps)
                for cell, (v, lf) in overlay.items():
                    vals[cell] = v
                    clps[cell] = lf
                cte_memo[memo_key] = (vals, clps)
            else:
                vals, clps = patched
        aliases = ast["aliases"]
        has_agg = any(k == "agg" for k, _, _ in ast["cols"])
        if (not ast["joins"] and not ast["group"] and not ast["order"]
                and not has_agg and not ast["having"]
                and not isinstance(aliases[ast["base"]], _CteTable)):
            # streaming fast path: plain filtered scan short-circuits at
            # LIMIT without materializing the table (the /v1/queries
            # NDJSON stream shape); CTE bases always materialize
            yield from self._stream_select(node, ast, vals, clps)
            return
        records = self._table_records(
            node, aliases[ast["base"]], ast["base"], vals, clps,
            cte_memo=cte_memo, overlay=overlay,
        )
        # hash equi-joins, in declaration order
        for jtype, a, lref, rref in ast["joins"]:
            lkey, rkey = ast["resolve"](lref), ast["resolve"](rref)
            # probe side = the newly joined table's rows
            right = self._table_records(node, aliases[a], a, vals, clps,
                                        cte_memo=cte_memo, overlay=overlay)
            probe_key = rkey if rkey.startswith(f"{a}.") else lkey
            build_key = lkey if probe_key == rkey else rkey
            if not probe_key.startswith(f"{a}."):
                raise SqlError(
                    f"JOIN ON must reference the joined table {a!r}"
                )
            index: Dict[Any, List[dict]] = {}
            for r in right:
                if r[probe_key] is not None:  # SQL: NULL = NULL is not true
                    index.setdefault(r[probe_key], []).append(r)
            joined = []
            for rec in records:
                bkey = rec.get(build_key)
                matches = index.get(bkey, []) if bkey is not None else []
                if matches:
                    for mrec in matches:
                        joined.append({**rec, **mrec})
                elif jtype == "left":
                    joined.append(
                        {**rec, **{f"{a}.{c.name}": None
                                   for c in aliases[a].columns}}
                    )
            records = joined
        # WHERE
        records = [
            r for r in records
            if all(self._eval(c, r) for c in ast["conds"])
        ]
        # GROUP BY / aggregates / HAVING
        if ast["group"] or has_agg or ast["having"]:
            groups: Dict[tuple, List[dict]] = {}
            for r in records:
                gkey = tuple(
                    g[1](r) if isinstance(g, tuple) and g[0] == "\x00expr"
                    else r.get(g)
                    for g in ast["group"]
                )
                groups.setdefault(gkey, []).append(r)
            if not records and not ast["group"]:
                groups[()] = []  # aggregates over an empty table emit 1 row
            rows = []
            for gkey, grp in groups.items():
                out = {}
                for kind, payload, name in ast["cols"]:
                    if kind == "col":
                        out[name] = grp[0].get(payload) if grp else None
                    elif kind == "expr":
                        out[name] = payload(grp[0]) if grp else None
                    else:
                        out[name] = self._aggregate(payload, grp)
                if not self._having_ok(ast, out, grp):
                    continue
                # representative source row: lets ORDER BY evaluate the
                # grouping expression (constant within a group) or a
                # grouped input column, like SQLite
                out["\x00src"] = grp[0] if grp else None
                rows.append(out)
        else:
            rows = [
                {
                    name: (payload(r) if kind == "expr" else r.get(payload))
                    for kind, payload, name in ast["cols"]
                }
                for r in records
            ]
            # keep source record reachable for ORDER BY non-projected cols
            for out, src in zip(rows, records):
                out["\x00src"] = src
        # ORDER BY: output alias first, then projected source column,
        # then (non-aggregate queries) any input column
        by_payload = {
            payload: name for kind, payload, name in ast["cols"]
            if kind == "col"
        }
        for ref, fn, desc in reversed(ast["order"]):
            name = _unquote(ref)

            def key_of(row, name=name, ref=ref, fn=fn):
                if name in row:
                    v = row[name]
                elif fn is not None:
                    src = row.get("\x00src")
                    # src is None only for the empty-aggregate row
                    v = fn(src) if src is not None else None
                else:
                    key = ast["resolve"](ref)
                    if key in by_payload:
                        v = row[by_payload[key]]
                    else:
                        src = row.get("\x00src")
                        if src is None:
                            raise SqlError(f"cannot ORDER BY {ref!r} here")
                        v = src.get(key)
                # SQLite: NULLs sort first ASC; type-tag mixed values
                return (v is not None, isinstance(v, (bytes, str)), v)

            rows.sort(key=key_of, reverse=desc)
        off = ast["offset"] or 0
        if off:
            rows = rows[off:]
        if ast["limit"] is not None:
            rows = rows[:ast["limit"]]
        names = [c[2] for c in ast["cols"]]
        for row in rows:
            yield [row[n] for n in names]

    def _stream_select(self, node: int, ast, vals, clps):
        """Lazy single-table scan: filter, offset, project, stop at
        LIMIT — the early-exit path the bounded read APIs rely on."""
        alias = ast["base"]
        table = ast["aliases"][alias]
        emitted, skipped = 0, 0
        off = ast["offset"] or 0
        for pk, row in self.rows.rows_of(table.name):
            if int(vals[self._cell(row, CL_COL)]) % 2 == 0:
                continue
            rec = self._materialize(table, pk, vals, clps, row)
            rec = {f"{alias}.{k}": v for k, v in rec.items()}
            if not all(self._eval(c, rec) for c in ast["conds"]):
                continue
            if skipped < off:
                skipped += 1
                continue
            yield [
                payload(rec) if kind == "expr" else rec.get(payload)
                for kind, payload, _n in ast["cols"]
            ]
            emitted += 1
            if ast["limit"] is not None and emitted >= ast["limit"]:
                return

    def _having_ok(self, ast, out: dict, grp: List[dict]) -> bool:
        """Evaluate HAVING conditions on one group. A left side may be an
        aggregate expression (``COUNT(*) > 5``), an output alias, or a
        grouped input column; the boolean structure (AND lists with
        or/not nodes) evaluates with the same three-valued logic as
        WHERE."""

        def eval_one(cond):
            op, lhs, val = cond
            if op == "or":
                acc = False
                for branch in lhs:
                    r = eval_conj(branch)
                    if r is True:
                        return True
                    if r is None:
                        acc = None
                return acc
            if op == "not":
                r = eval_conj(lhs)
                return None if r is None else not r
            if not isinstance(lhs, str):
                # a parsed expression node (('\x00expr', fn) tuple) —
                # arbitrary expressions aren't supported on a HAVING
                # left side; fail as a SqlError, not a TypeError
                raise SqlError("unsupported HAVING left side (expression)")
            am = _AGG_RE.match(lhs)
            if am:
                fn = am.group("fn").upper()
                arg = am.group("arg")
                key = None if arg == "*" else ast["resolve"](arg)
                v = self._aggregate((fn, key), grp)
            else:
                name = _unquote(lhs)
                if name in out:
                    v = out[name]
                else:
                    v = grp[0].get(ast["resolve"](lhs)) if grp else None
            return self._eval((op, "\x00v", val), {"\x00v": v})

        def eval_conj(conds):
            acc = True
            for c in conds:
                r = eval_one(c)
                if r is False:
                    return False
                if r is None:
                    acc = None
            return acc

        return eval_conj(ast.get("having", [])) is True

    @staticmethod
    def _aggregate(payload, grp: List[dict]):
        fn, key = payload
        vals = ([r.get(key) for r in grp if r.get(key) is not None]
                if key is not None else grp)
        if fn == "COUNT":
            return len(vals)
        if not vals:
            return 0.0 if fn == "TOTAL" else None
        if fn == "SUM":
            return sum(vals)
        if fn == "TOTAL":
            return float(sum(vals))
        if fn == "MIN":
            return min(vals)
        if fn == "MAX":
            return max(vals)
        if fn == "AVG":
            return sum(vals) / len(vals)
        raise SqlError(f"unknown aggregate {fn}")

    def _run_recursive_cte(self, node: int, cte: _RecursiveCte,
                           memo: dict, overlay=None) -> List[list]:
        """Iterative evaluation: rows = base; repeat step (which sees
        only the previous iteration's rows through the pre-seeded memo
        slot) until no new rows, the total LIMIT (+OFFSET skip, SQLite
        compound semantics), or the safety cap."""
        off = cte.offset or 0
        cap = (cte.limit + off) if cte.limit is not None else cte.MAX_ROWS
        rows = list(self._run_select(node, cte.base_ast, cte_memo=memo,
                                     overlay=overlay))
        frontier = rows
        self_key = (node, id(cte.self_marker))
        if not cte.self_referential:
            rows.extend(self._run_select(node, cte.step_ast,
                                         cte_memo=memo, overlay=overlay))
            return rows[off:cap]
        while frontier and len(rows) < cap:
            # overwrite the self-ref slot: the step sees ONLY the
            # previous iteration's rows (other CTEs stay memoized once)
            memo[self_key] = frontier
            frontier = list(
                self._run_select(node, cte.step_ast, cte_memo=memo,
                                 overlay=overlay)
            )
            rows.extend(frontier)
            if cte.limit is None and len(rows) > cte.MAX_ROWS:
                raise SqlError(
                    f"recursive CTE {cte.name!r} exceeded "
                    f"{cte.MAX_ROWS} rows without a LIMIT"
                )
        return rows[off:cap]

    def _materialize(self, table, pk, vals, clps, row) -> Dict[str, Any]:
        """A row's visible values: a cell counts only if it was written in
        the row's CURRENT causal-length lifetime — values from before a
        delete/resurrect cycle read as NULL, matching SQLite's fresh-row
        semantics (cr-sqlite `cl`, doc/crdts.md:24-40)."""
        row_cl = int(vals[self._cell(row, CL_COL)])
        rec = {table.pk.name: pk}
        for c in table.value_columns:
            cell = self._cell(row, table.col_index(c.name))
            if int(clps[cell]) == row_cl:
                rec[c.name] = self.heap.lookup(int(vals[cell]))
            else:
                rec[c.name] = None
        return rec

    def read_row(self, node: int, table_name: str, pk: Any
                 ) -> Optional[Dict[str, Any]]:
        """One row of ``node``'s replica, or None if absent/deleted."""
        table = self.schema.table(table_name)
        row = self.rows.get(table_name, pk)
        if row is None:
            return None
        snap = self.agent.snapshot()
        vals = snap["store"][1][node]
        clps = snap["store"][4][node]
        if int(vals[self._cell(row, CL_COL)]) % 2 == 0:
            return None
        return self._materialize(table, pk, vals, clps, row)

    @classmethod
    def _eval_conj(cls, conds, rec):
        """Three-valued AND over a cond list: False dominates, then
        UNKNOWN (None), then True. ``all(_eval(...))`` at the callers
        treats UNKNOWN as falsy — SQL's WHERE-excludes-unknown."""
        out = True
        for c in conds:
            r = cls._eval(c, rec)
            if r is False:
                return False
            if r is None:
                out = None
        return out

    @classmethod
    def _eval(cls, cond, rec):
        """Evaluate one cond to SQLite's three-valued logic:
        True / False / None (UNKNOWN — a NULL-involved comparison).
        Callers gate rows on ``is True``-like truthiness, so UNKNOWN
        excludes; NOT preserves UNKNOWN instead of flipping it."""
        op, col, ref = cond
        if op == "or":
            out = False
            for branch in col:
                r = cls._eval_conj(branch, rec)
                if r is True:
                    return True
                if r is None:
                    out = None
            return out
        if op == "not":
            r = cls._eval_conj(col, rec)
            return None if r is None else not r
        if isinstance(col, tuple) and col and col[0] == "\x00expr":
            v = col[1](rec)
        else:
            v = rec.get(col)
        if op == "is null":
            return v is None  # never UNKNOWN: IS is a 2-valued test
        if op == "is not null":
            return v is not None
        if op == "json_contains":
            try:
                return corro_json_contains(v, ref)
            except (TypeError, ValueError):
                return False
        if op in ("like", "not like", "glob", "not glob"):
            # SQLite coerces numeric operands to text for LIKE/GLOB
            # (SELECT 15 LIKE '1%' -> 1); NULL operands -> UNKNOWN
            if v is None or ref is None:
                return None
            if isinstance(v, (int, float)):
                v = str(v)
            if isinstance(ref, (int, float)):
                ref = str(ref)
            if not isinstance(v, str) or not isinstance(ref, str):
                return False  # blobs never LIKE-match
            glob = "glob" in op
            if not glob:  # ASCII-only case folding, like SQLite's LIKE
                v = v.translate(_ASCII_LOWER)
                ref = ref.translate(_ASCII_LOWER)
            hit = _like_to_regex(ref, glob).match(v) is not None
            return (not hit) if op.startswith("not") else hit
        if op in ("in", "not in"):
            if v is None:
                return None
            hit = any(v == x for x in ref if x is not None)
            if op == "not in":
                # x NOT IN (..., NULL) is UNKNOWN unless x matched a
                # non-NULL member
                if hit:
                    return False
                return None if any(x is None for x in ref) else True
            if not hit and any(x is None for x in ref):
                return None  # x IN (..., NULL) with no match is UNKNOWN
            return hit
        if v is None or ref is None:
            return None
        try:
            if op == "=":
                return v == ref
            if op in ("!=", "<>"):
                return v != ref
            if op == "<":
                return v < ref
            if op == "<=":
                return v <= ref
            if op == ">":
                return v > ref
            if op == ">=":
                return v >= ref
        except TypeError:
            return False
        raise SqlError(f"unsupported operator {op!r}")

    # --- stats & checkpoint ----------------------------------------------
    def table_stats(self, node: int = 0) -> Dict[str, Dict[str, int]]:
        """``/v1/table_stats`` analog: row counts per table on ``node``."""
        snap = self.agent.snapshot()
        vals = snap["store"][1][node]
        out: Dict[str, Dict[str, int]] = {}
        for name in self.schema.tables:
            rows = self.rows.rows_of(name)
            live = sum(
                1 for _, r in rows
                if int(vals[self._cell(r, CL_COL)]) % 2 == 1
            )
            out[name] = {"allocated": len(rows), "live": live}
        return out

    def schema_sql(self) -> str:
        parts = []
        for t in self.schema.tables.values():
            cols = []
            for c in t.columns:
                bits = [c.name, c.sql_type]
                if c.primary_key:
                    bits.append("PRIMARY KEY")
                elif c.not_null:
                    bits.append("NOT NULL")
                if c.default is not None:
                    d = (f"'{c.default}'" if isinstance(c.default, str)
                         else str(c.default))
                    bits.append(f"DEFAULT {d}")
                cols.append(" ".join(bits))
            parts.append(f"CREATE TABLE {t.name} ({', '.join(cols)});")
        return "\n".join(parts)

    def state_dict(self) -> dict:
        return {
            "schema_sql": self.schema_sql(),
            "heap": self.heap.state_dict(),
            "rows": self.rows.state_dict(),
        }

    def load_state_dict(self, state: dict) -> None:
        with self._mu:
            self.schema = parse_schema_sql(state["schema_sql"])
            self.heap = ValueHeap.from_state_dict(state["heap"])
            self.rows = RowMap.from_state_dict(state["rows"])


class StagedTx:
    """A buffered multi-statement transaction (PG ``BEGIN``/``COMMIT``).

    Statements are planned eagerly — ``execute()`` runs the same
    ``_plan_write`` path as :meth:`Database.execute`, against a
    transaction-local overlay, so each statement's row count is exact
    and later statements observe earlier writes. Nothing is visible to
    the cluster (or to reads outside the tx) until :meth:`commit`
    stages the net cell writes into one round-loop transaction;
    :meth:`rollback` discards everything. Mirrors the reference's PG
    server running real SQLite txs over the corrosion write path
    (``corro-pg/src/lib.rs``)."""

    def __init__(self, db: Database, node: int):
        self.db = db
        self.node = node
        self._merged: Dict[int, Tuple[int, int]] = {}
        self._notes: List[tuple] = []
        self._results: List[ExecResult] = []
        self._done = False
        with db._mu:  # pin planned value ids vs compaction
            db._open_txs.add(self)

    def execute(self, sql: str, params: Any = None) -> ExecResult:
        if self._done:
            raise SqlError("transaction already finished")
        t0 = time.perf_counter()
        affected, cells, notes = self.db._plan_write(
            self.node, sql, params, self._merged
        )
        self._merged.update({c: (v, l) for c, v, l in cells})
        self._notes.extend(notes)
        res = ExecResult(rows_affected=affected,
                         time=time.perf_counter() - t0)
        self._results.append(res)
        return res

    def commit(self, wait: bool = True, timeout: float = 30.0
               ) -> List[ExecResult]:
        if self._done:
            raise SqlError("transaction already finished")
        self._done = True
        with self.db._mu:
            self.db._open_txs.discard(self)
        cells = self.db._order_tx_cells(self._merged)
        if cells:
            self.db.agent.write_many(self.node, cells, wait=wait,
                                     timeout=timeout)
        self.db._stamp_writes(self._notes)
        with self.db._mu:
            hooks = list(self.db._write_hooks)
        for note in self._notes:
            for hook in hooks:
                hook(self.node, *note)
        return self._results

    def rollback(self) -> None:
        self._done = True
        with self.db._mu:
            self.db._open_txs.discard(self)
        self._merged.clear()
        self._notes.clear()
