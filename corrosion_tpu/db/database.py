"""Database: SQL statement execution over the TPU-resident LWW store.

The write path mirrors ``execute_statement`` /
``make_broadcastable_changes`` (``crates/corro-agent/src/api/public/
mod.rs:53-174``): statements in one transaction are translated into cell
writes on the writer node's replica and staged into the round loop
together, after which dissemination is asynchronous. The read path
mirrors ``/v1/queries``: reads observe one node's local replica only.

Supported dialect (the write/read surface the reference's API exercises):
``INSERT [OR IGNORE] INTO t (cols) VALUES (...)`` (upsert semantics, as
cr-sqlite rewrites inserts), ``UPDATE t SET c=? WHERE pk=?``,
``DELETE FROM t WHERE pk=?`` (causal-length tombstone), and
``SELECT cols FROM t [WHERE simple-conjunction] [LIMIT n]`` with the
``corro_json_contains`` function from ``sqlite-functions``.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from corrosion_tpu.db.schema import (
    CL_COL,
    RowMap,
    Schema,
    SchemaError,
    diff_schemas,
    parse_schema_sql,
)
from corrosion_tpu.db.values import NULL_ID, ValueHeap, corro_json_contains


class SqlError(ValueError):
    pass


_INSERT_RE = re.compile(
    r"INSERT\s+(?:OR\s+(?P<or>IGNORE|REPLACE)\s+)?INTO\s+(?P<table>[\w\"]+)\s*"
    r"\((?P<cols>[^)]*)\)\s*VALUES\s*\((?P<vals>.*)\)\s*"
    r"(?P<conflict>ON\s+CONFLICT.*)?$",
    re.IGNORECASE | re.DOTALL,
)
_UPDATE_RE = re.compile(
    r"UPDATE\s+(?P<table>[\w\"]+)\s+SET\s+(?P<sets>.*?)\s+WHERE\s+(?P<where>.*)$",
    re.IGNORECASE | re.DOTALL,
)
_DELETE_RE = re.compile(
    r"DELETE\s+FROM\s+(?P<table>[\w\"]+)\s+WHERE\s+(?P<where>.*)$",
    re.IGNORECASE | re.DOTALL,
)
_SELECT_RE = re.compile(
    r"SELECT\s+(?P<cols>.*?)\s+FROM\s+(?P<table>[\w\"]+)"
    r"(?:\s+WHERE\s+(?P<where>.*?))?(?:\s+LIMIT\s+(?P<limit>\d+))?\s*$",
    re.IGNORECASE | re.DOTALL,
)
_COND_RE = re.compile(
    r"^(?P<col>[\w\"]+)\s*(?P<op>=|!=|<>|<=|>=|<|>)\s*(?P<val>.+)$", re.DOTALL
)
_FUNC_RE = re.compile(
    r"^corro_json_contains\s*\(\s*(?P<a>[^,]+)\s*,\s*(?P<b>.+)\s*\)$",
    re.IGNORECASE | re.DOTALL,
)


def _unquote(ident: str) -> str:
    return ident.strip().strip('"').strip("`")


class _Params:
    """Positional ``?`` and named ``:name``/``$name`` parameter source."""

    def __init__(self, params: Any):
        self.named: Dict[str, Any] = {}
        self.positional: List[Any] = []
        if isinstance(params, dict):
            self.named = params
        elif params is not None:
            self.positional = list(params)
        self._pos = 0

    def next_positional(self) -> Any:
        if self._pos >= len(self.positional):
            raise SqlError("not enough positional parameters")
        v = self.positional[self._pos]
        self._pos += 1
        return v

    def get_named(self, name: str) -> Any:
        if name not in self.named:
            raise SqlError(f"missing named parameter :{name}")
        return self.named[name]


def _parse_literal(tok: str, params: _Params) -> Any:
    tok = tok.strip()
    if tok == "?":
        return params.next_positional()
    if tok.startswith((":", "$", "@")):
        return params.get_named(tok[1:])
    up = tok.upper()
    if up == "NULL":
        return None
    if up == "TRUE":
        return 1
    if up == "FALSE":
        return 0
    if tok.startswith("'") and tok.endswith("'") and len(tok) >= 2:
        return tok[1:-1].replace("''", "'")
    if (tok.startswith("x'") or tok.startswith("X'")) and tok.endswith("'"):
        return bytes.fromhex(tok[2:-1])
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            raise SqlError(f"unsupported literal: {tok!r}")


def _split_top_commas(s: str) -> List[str]:
    parts, depth, start = [], 0, 0
    in_str = False
    for i, ch in enumerate(s):
        if in_str:
            if ch == "'":
                in_str = False
        elif ch == "'":
            in_str = True
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(s[start:i])
            start = i + 1
    parts.append(s[start:])
    return [p.strip() for p in parts if p.strip()]


class ExecResult(dict):
    """``{rows_affected, time}`` — corro-api-types ``ExecResult``."""


class Database:
    """Schema + heap + row map bound to one :class:`Agent` cluster."""

    def __init__(self, agent):
        self.agent = agent
        self.schema = Schema()
        self.heap = ValueHeap()
        self.rows = RowMap(agent.cfg.n_rows)
        self.n_cols = agent.cfg.n_cols
        self._mu = threading.Lock()
        self._write_hooks: List = []  # pubsub/updates change hooks

    # --- schema ----------------------------------------------------------
    def apply_schema_sql(self, sql: str) -> List[Tuple[str, str]]:
        """Parse + diff + apply (``/v1/migrations`` and startup schema
        files, ``public/mod.rs:540-593``)."""
        new = parse_schema_sql(sql)
        with self._mu:
            merged = Schema(dict(self.schema.tables))
            for name, t in new.tables.items():
                merged.tables[name] = t
            changes = diff_schemas(self.schema, merged)
            for t in merged.tables.values():
                if len(t.value_columns) > self.n_cols - 1:
                    raise SchemaError(
                        f"table {t.name} has {len(t.value_columns)} value "
                        f"columns; grid supports {self.n_cols - 1} "
                        f"(raise [sim].n_cols)"
                    )
            self.schema = merged
        return changes

    def add_write_hook(self, hook) -> None:
        """hook(node, table, pk, {col: value}, deleted: bool) after a
        local write enters the round loop — the ``match_changes`` seam
        (``util.rs:1034-1037``)."""
        self._write_hooks.append(hook)

    # --- cell helpers ----------------------------------------------------
    def _cell(self, row: int, col: int) -> int:
        return row * self.n_cols + col

    def _read_plane(self, node: int, row: int, col: int,
                    overlay: Optional[Dict[int, Tuple[int, int]]] = None) -> int:
        """Value-plane read; ``overlay`` holds this transaction's pending
        ``cell -> (value, clp)`` entries so later statements observe
        earlier ones (the reference runs statements sequentially inside
        one SQLite tx, ``public/mod.rs:141-174``)."""
        cell = self._cell(row, col)
        if overlay is not None and cell in overlay:
            return overlay[cell][0]
        snap = self.agent.snapshot()
        return int(snap["store"][1][node, cell])

    def _row_live(self, node: int, row: int,
                  overlay: Optional[Dict[int, Tuple[int, int]]] = None) -> bool:
        return self._read_plane(node, row, CL_COL, overlay) % 2 == 1

    # --- writes ----------------------------------------------------------
    def execute(self, node: int, statements: Sequence,
                wait: bool = True, timeout: float = 30.0) -> List[ExecResult]:
        """Run a transaction of statements at ``node``
        (``/v1/transactions``). Each statement is ``sql`` or
        ``(sql, params)``; returns one ``ExecResult`` per statement."""
        t0 = time.perf_counter()
        results: List[ExecResult] = []
        # cell -> (final value, causal-length lifetime) this tx (ordered)
        merged: Dict[int, Tuple[int, int]] = {}
        notifications = []
        for stmt in statements:
            sql, params = (stmt, None) if isinstance(stmt, str) else (
                stmt[0], stmt[1] if len(stmt) > 1 else None
            )
            affected, stmt_cells, notes = self._plan_write(
                node, sql, params, merged
            )
            # later statements override earlier cells for the same target —
            # last-write-wins within the transaction, like sequential
            # statements in one SQLite tx (dict update keeps first position)
            merged.update({c: (v, l) for c, v, l in stmt_cells})
            notifications.extend(notes)
            results.append(
                ExecResult(rows_affected=affected,
                           time=time.perf_counter() - t0)
            )
        cells = self._order_tx_cells(merged)
        if cells:
            self.agent.write_many(node, cells, wait=wait, timeout=timeout)
        for note in notifications:
            for hook in self._write_hooks:
                hook(node, *note)
        return results

    def _order_tx_cells(self, merged: Dict[int, Tuple[int, int]]
                        ) -> List[Tuple[int, int, int]]:
        """Drain order for the transaction's net ``(cell, value, clp)``
        writes: causal-length flips that leave a row LIVE go last (the row
        only turns visible once its values are in flight) and flips that
        leave it DEAD go first — ``write_many`` drains one cell per round,
        so list order is visibility order for local readers."""
        deaths, values, lives = [], [], []
        for cell, (value, clp) in merged.items():
            if cell % self.n_cols == CL_COL:
                (lives if value % 2 == 1 else deaths).append((cell, value, clp))
            else:
                values.append((cell, value, clp))
        return deaths + values + lives

    def _plan_write(self, node: int, sql: str, params: Any,
                    overlay: Optional[Dict[int, int]] = None):
        """-> (rows_affected, [(cell, interned_val)], [notifications])."""
        sql = sql.strip().rstrip(";").strip()
        p = _Params(params)
        m = _INSERT_RE.match(sql)
        if m:
            return self._plan_insert(node, m, p, overlay)
        m = _UPDATE_RE.match(sql)
        if m:
            return self._plan_update(node, m, p, overlay)
        m = _DELETE_RE.match(sql)
        if m:
            return self._plan_delete(node, m, p, overlay)
        if _SELECT_RE.match(sql):
            raise SqlError("SELECT not allowed in /v1/transactions (read-only "
                           "statements go to /v1/queries)")
        raise SqlError(f"unsupported statement: {sql[:80]!r}")

    def _plan_insert(self, node: int, m, p: _Params,
                     overlay: Optional[Dict[int, int]] = None):
        table = self.schema.table(_unquote(m.group("table")))
        col_names = [_unquote(c) for c in m.group("cols").split(",")]
        vals = [_parse_literal(v, p) for v in _split_top_commas(m.group("vals"))]
        if len(col_names) != len(vals):
            raise SqlError(f"{len(col_names)} columns but {len(vals)} values")
        by_col = dict(zip(col_names, vals))
        pk_name = table.pk.name
        if pk_name not in by_col:
            raise SqlError(f"INSERT into {table.name} must set pk {pk_name}")
        pk = by_col.pop(pk_name)
        if pk is None:
            raise SqlError(f"pk {table.name}.{pk_name} cannot be NULL")
        for c in table.value_columns:
            if c.name not in by_col:
                by_col[c.name] = c.default
            elif by_col[c.name] is None and c.not_null:
                raise SqlError(f"NOT NULL violation: {table.name}.{c.name}")
        for name in by_col:
            table.column(name)  # raises on unknown column

        row = self.rows.get_or_alloc(table.name, pk)
        cl = self._read_plane(node, row, CL_COL, overlay)
        live = cl % 2 == 1
        or_clause = (m.group("or") or "").upper()
        conflict = (m.group("conflict") or "").upper().strip()
        if live and (or_clause == "IGNORE" or "DO NOTHING" in conflict):
            return 0, [], []
        # lifetime the write belongs to: the current one for a live-row
        # upsert, the NEXT odd causal length for an insert/resurrect —
        # value cells from a previous lifetime must not leak through
        # (cr-sqlite `cl` semantics, doc/crdts.md:24-40)
        lifetime = cl if live else cl + 1
        cells: List[Tuple[int, int, int]] = []
        for name, value in by_col.items():
            cells.append(
                (self._cell(row, table.col_index(name)),
                 self.heap.intern(value), lifetime)
            )
        if not live:
            # CL flip staged LAST: write_many drains one cell per round, so
            # the row must only turn live once its values are already in
            # flight — otherwise readers observe a live all-NULL row for
            # n_value_columns rounds (insert atomicity)
            cells.append((self._cell(row, CL_COL), cl + 1, cl + 1))
        return 1, cells, [(table.name, pk, dict(by_col), False)]

    def _split_where_pk(self, table, where: str, p: _Params):
        cond = _COND_RE.match(where.strip())
        if not cond or cond.group("op") != "=":
            raise SqlError(
                f"writes require `WHERE {table.pk.name} = <value>` "
                f"(got {where!r})"
            )
        col = _unquote(cond.group("col"))
        if col != table.pk.name:
            raise SqlError(f"writes must filter on the pk ({table.pk.name})")
        return _parse_literal(cond.group("val"), p)

    def _plan_update(self, node: int, m, p: _Params,
                     overlay: Optional[Dict[int, int]] = None):
        table = self.schema.table(_unquote(m.group("table")))
        sets: Dict[str, Any] = {}
        set_parts = _split_top_commas(m.group("sets"))
        for part in set_parts:
            if "=" not in part:
                raise SqlError(f"bad SET clause: {part!r}")
            name, _, raw = part.partition("=")
            name = _unquote(name)
            col = table.column(name)
            if col.primary_key:
                raise SqlError("cannot UPDATE the primary key")
            sets[name] = _parse_literal(raw, p)
        pk = self._split_where_pk(table, m.group("where"), p)
        row = self.rows.get(table.name, pk)
        if row is None or not self._row_live(node, row, overlay):
            return 0, [], []
        for name, value in sets.items():
            if value is None and table.column(name).not_null:
                raise SqlError(f"NOT NULL violation: {table.name}.{name}")
        lifetime = self._read_plane(node, row, CL_COL, overlay)
        cells = [
            (self._cell(row, table.col_index(name)),
             self.heap.intern(value), lifetime)
            for name, value in sets.items()
        ]
        return 1, cells, [(table.name, pk, dict(sets), False)]

    def _plan_delete(self, node: int, m, p: _Params,
                     overlay: Optional[Dict[int, int]] = None):
        table = self.schema.table(_unquote(m.group("table")))
        pk = self._split_where_pk(table, m.group("where"), p)
        row = self.rows.get(table.name, pk)
        if row is None:
            return 0, [], []
        cl = self._read_plane(node, row, CL_COL, overlay)
        if cl % 2 == 0:
            return 0, [], []
        cells = [(self._cell(row, CL_COL), cl + 1, cl + 1)]
        return 1, cells, [(table.name, pk, {}, True)]

    # --- reads -----------------------------------------------------------
    def query(self, node: int, sql: str, params: Any = None
              ) -> Tuple[List[str], Iterable[List[Any]]]:
        """Read-only query against ``node``'s replica (``/v1/queries``).
        Returns ``(column_names, row_iterator)``."""
        sql = sql.strip().rstrip(";").strip()
        m = _SELECT_RE.match(sql)
        if m is None:
            raise SqlError(f"only SELECT is allowed on the query path: "
                           f"{sql[:80]!r}")
        p = _Params(params)
        table = self.schema.table(_unquote(m.group("table")))
        names = self._select_names(table, m.group("cols"))
        conds = self._parse_where(table, m.group("where"), p)
        limit = int(m.group("limit")) if m.group("limit") else None
        return names, self._scan(node, table, names, conds, limit)

    @staticmethod
    def _select_names(table, raw_cols: str) -> List[str]:
        raw_cols = raw_cols.strip()
        if raw_cols == "*":
            return [c.name for c in table.columns]
        names = [_unquote(c) for c in raw_cols.split(",")]
        for n in names:
            table.column(n)
        return names

    def query_columns(self, sql: str) -> List[str]:
        """The column names a SELECT would produce — schema-only, no
        scan (used by the PG Describe phase)."""
        m = _SELECT_RE.match(sql.strip().rstrip(";").strip())
        if m is None:
            raise SqlError(f"not a SELECT: {sql[:80]!r}")
        table = self.schema.table(_unquote(m.group("table")))
        return self._select_names(table, m.group("cols"))

    def _parse_where(self, table, where: Optional[str], p: _Params):
        if not where:
            return []
        conds = []
        for clause in re.split(r"\s+AND\s+", where.strip(), flags=re.IGNORECASE):
            clause = clause.strip()
            fm = _FUNC_RE.match(clause)
            if fm:
                col = _unquote(fm.group("a"))
                table.column(col)
                needle = _parse_literal(fm.group("b"), p)
                conds.append(("json_contains", col, needle))
                continue
            cm = _COND_RE.match(clause)
            if cm is None:
                raise SqlError(f"unsupported WHERE clause: {clause!r}")
            col = _unquote(cm.group("col"))
            table.column(col)
            conds.append(
                (cm.group("op"), col, _parse_literal(cm.group("val"), p))
            )
        return conds

    def _scan(self, node: int, table, names, conds, limit):
        snap = self.agent.snapshot()
        vals = snap["store"][1][node]
        clps = snap["store"][4][node]
        emitted = 0
        for pk, row in self.rows.rows_of(table.name):
            if int(vals[self._cell(row, CL_COL)]) % 2 == 0:
                continue
            rec = self._materialize(table, pk, vals, clps, row)
            if all(self._eval(c, rec) for c in conds):
                yield [rec[n] for n in names]
                emitted += 1
                if limit is not None and emitted >= limit:
                    return

    def _materialize(self, table, pk, vals, clps, row) -> Dict[str, Any]:
        """A row's visible values: a cell counts only if it was written in
        the row's CURRENT causal-length lifetime — values from before a
        delete/resurrect cycle read as NULL, matching SQLite's fresh-row
        semantics (cr-sqlite `cl`, doc/crdts.md:24-40)."""
        row_cl = int(vals[self._cell(row, CL_COL)])
        rec = {table.pk.name: pk}
        for c in table.value_columns:
            cell = self._cell(row, table.col_index(c.name))
            if int(clps[cell]) == row_cl:
                rec[c.name] = self.heap.lookup(int(vals[cell]))
            else:
                rec[c.name] = None
        return rec

    def read_row(self, node: int, table_name: str, pk: Any
                 ) -> Optional[Dict[str, Any]]:
        """One row of ``node``'s replica, or None if absent/deleted."""
        table = self.schema.table(table_name)
        row = self.rows.get(table_name, pk)
        if row is None:
            return None
        snap = self.agent.snapshot()
        vals = snap["store"][1][node]
        clps = snap["store"][4][node]
        if int(vals[self._cell(row, CL_COL)]) % 2 == 0:
            return None
        return self._materialize(table, pk, vals, clps, row)

    @staticmethod
    def _eval(cond, rec) -> bool:
        op, col, ref = cond
        v = rec.get(col)
        if op == "json_contains":
            try:
                return corro_json_contains(v, ref)
            except (TypeError, ValueError):
                return False
        if v is None or ref is None:
            return False
        try:
            if op == "=":
                return v == ref
            if op in ("!=", "<>"):
                return v != ref
            if op == "<":
                return v < ref
            if op == "<=":
                return v <= ref
            if op == ">":
                return v > ref
            if op == ">=":
                return v >= ref
        except TypeError:
            return False
        raise SqlError(f"unsupported operator {op!r}")

    # --- stats & checkpoint ----------------------------------------------
    def table_stats(self, node: int = 0) -> Dict[str, Dict[str, int]]:
        """``/v1/table_stats`` analog: row counts per table on ``node``."""
        snap = self.agent.snapshot()
        vals = snap["store"][1][node]
        out: Dict[str, Dict[str, int]] = {}
        for name in self.schema.tables:
            rows = self.rows.rows_of(name)
            live = sum(
                1 for _, r in rows
                if int(vals[self._cell(r, CL_COL)]) % 2 == 1
            )
            out[name] = {"allocated": len(rows), "live": live}
        return out

    def schema_sql(self) -> str:
        parts = []
        for t in self.schema.tables.values():
            cols = []
            for c in t.columns:
                bits = [c.name, c.sql_type]
                if c.primary_key:
                    bits.append("PRIMARY KEY")
                elif c.not_null:
                    bits.append("NOT NULL")
                if c.default is not None:
                    d = (f"'{c.default}'" if isinstance(c.default, str)
                         else str(c.default))
                    bits.append(f"DEFAULT {d}")
                cols.append(" ".join(bits))
            parts.append(f"CREATE TABLE {t.name} ({', '.join(cols)});")
        return "\n".join(parts)

    def state_dict(self) -> dict:
        return {
            "schema_sql": self.schema_sql(),
            "heap": self.heap.state_dict(),
            "rows": self.rows.state_dict(),
        }

    def load_state_dict(self, state: dict) -> None:
        with self._mu:
            self.schema = parse_schema_sql(state["schema_sql"])
            self.heap = ValueHeap.from_state_dict(state["heap"])
            self.rows = RowMap.from_state_dict(state["rows"])
