"""DB layer: schema management, value storage, SQL statement execution.

Plays the role of the reference's layer 1 (``crates/corro-types/src/
{schema,sqlite}.rs`` + ``sqlite-pool`` + the SQLite file itself) on top of
the TPU-resident LWW store: named tables/columns are mapped onto the
simulator's ``[N, n_rows, n_cols]`` cell grid, values live in a host-side
interned heap (the device gossips compact int32 ids), and a small SQL
dialect covers the reference's write/read statement surface.
"""

from corrosion_tpu.db.database import Database
from corrosion_tpu.db.schema import Schema, SchemaError, parse_schema_sql
from corrosion_tpu.db.values import NULL_ID, ValueHeap

__all__ = [
    "Database",
    "Schema",
    "SchemaError",
    "parse_schema_sql",
    "ValueHeap",
    "NULL_ID",
]
