"""Interned value heap: SQL values <-> compact int32 ids.

The reference's CRDT cells hold arbitrary SQL values (``SqliteValue``,
``crates/corro-api-types/src/lib.rs:422-433``). The TPU store holds int32
planes — so the host keeps an append-only interning heap mapping every
distinct value (NULL, integer, real, text, blob) to a stable id, and the
device gossips ids. The heap is process-global state shared by all
simulated nodes (one process hosts the whole cluster), so id assignment
is trivially consistent across replicas.

Deviation from the reference, by design: the LWW tie-break on equal
``col_version`` orders by *intern id* (assignment order) rather than by
serialized value bytes (``doc/crdts.md:14-16``). Both are deterministic
total orders; parity checks against the CPU oracle use the same heap, so
convergence results are unaffected.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Optional

NULL_ID = 0

# type tags for serialization
_T_NULL, _T_INT, _T_REAL, _T_TEXT, _T_BLOB = "n", "i", "r", "t", "b"


def _key(value: Any):
    """Hashable identity key: 1 and 1.0 intern separately (SQL types)."""
    if value is None:
        return (_T_NULL,)
    if isinstance(value, bool):  # bools are ints in SQLite
        return (_T_INT, int(value))
    if isinstance(value, int):
        return (_T_INT, value)
    if isinstance(value, float):
        return (_T_REAL, value)
    if isinstance(value, str):
        return (_T_TEXT, value)
    if isinstance(value, (bytes, bytearray)):
        return (_T_BLOB, bytes(value))
    raise TypeError(f"unsupported SQL value type: {type(value).__name__}")


class ValueHeap:
    """Thread-safe append-only value interning table. Id 0 is NULL."""

    def __init__(self):
        self._values: list = [None]
        self._ids: dict = {(_T_NULL,): NULL_ID}
        self._mu = threading.Lock()

    def intern(self, value: Any) -> int:
        k = _key(value)
        with self._mu:
            vid = self._ids.get(k)
            if vid is None:
                vid = len(self._values)
                if vid >= (1 << 31):
                    raise OverflowError("value heap exceeded int32 id space")
                self._values.append(
                    bytes(value) if isinstance(value, bytearray) else value
                )
                self._ids[k] = vid
            return vid

    def lookup(self, vid: int) -> Any:
        if vid == NULL_ID:
            return None
        return self._values[vid]

    def __len__(self) -> int:
        return len(self._values)

    # --- checkpoint support ----------------------------------------------
    def state_dict(self) -> dict:
        out = []
        for v in self._values[1:]:
            if isinstance(v, bytes):
                out.append([_T_BLOB, v.hex()])
            elif isinstance(v, str):
                out.append([_T_TEXT, v])
            elif isinstance(v, float):
                out.append([_T_REAL, v])
            else:
                out.append([_T_INT, v])
        return {"values": out}

    @classmethod
    def from_state_dict(cls, state: dict) -> "ValueHeap":
        heap = cls()
        for tag, raw in state["values"]:
            if tag == _T_BLOB:
                heap.intern(bytes.fromhex(raw))
            elif tag == _T_REAL:
                heap.intern(float(raw))
            elif tag == _T_INT:
                heap.intern(int(raw))
            else:
                heap.intern(raw)
        return heap


def corro_json_contains(outer: Any, inner: Any) -> bool:
    """The custom SQL function from ``sqlite-functions``
    (``crates/sqlite-functions/src/lib.rs:5-30``): true when ``inner``'s
    JSON object/array is recursively contained in ``outer``'s."""
    a = json.loads(outer) if isinstance(outer, (str, bytes)) else outer
    b = json.loads(inner) if isinstance(inner, (str, bytes)) else inner
    return _contains(a, b)


def _contains(outer: Any, inner: Any) -> bool:
    if isinstance(inner, dict):
        return isinstance(outer, dict) and all(
            k in outer and _contains(outer[k], v) for k, v in inner.items()
        )
    if isinstance(inner, list):
        return isinstance(outer, list) and all(
            any(_contains(o, v) for o in outer) for v in inner
        )
    return outer == inner
