"""Interned value heap: SQL values <-> compact int32 ids.

The reference's CRDT cells hold arbitrary SQL values (``SqliteValue``,
``crates/corro-api-types/src/lib.rs:422-433``). The TPU store holds int32
planes — so the host keeps an append-only interning heap mapping every
distinct value (NULL, integer, real, text, blob) to a stable id, and the
device gossips ids. The heap is process-global state shared by all
simulated nodes (one process hosts the whole cluster), so id assignment
is trivially consistent across replicas.

Deviation from the reference, by design: the LWW tie-break on equal
``col_version`` orders by *intern id* (assignment order) rather than by
serialized value bytes (``doc/crdts.md:14-16``). Both are deterministic
total orders; parity checks against the CPU oracle use the same heap, so
convergence results are unaffected.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Optional

NULL_ID = 0

# type tags for serialization
_T_NULL, _T_INT, _T_REAL, _T_TEXT, _T_BLOB = "n", "i", "r", "t", "b"
_T_FREE = "f"  # compacted hole (id awaiting reuse)


class _Free:
    """Sentinel marking a compacted heap slot."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - debugging aid
        return "<freed>"


_FREE = _Free()


def _key(value: Any):
    """Hashable identity key: 1 and 1.0 intern separately (SQL types)."""
    if value is None:
        return (_T_NULL,)
    if isinstance(value, bool):  # bools are ints in SQLite
        return (_T_INT, int(value))
    if isinstance(value, int):
        return (_T_INT, value)
    if isinstance(value, float):
        return (_T_REAL, value)
    if isinstance(value, str):
        return (_T_TEXT, value)
    if isinstance(value, (bytes, bytearray)):
        return (_T_BLOB, bytes(value))
    raise TypeError(f"unsupported SQL value type: {type(value).__name__}")


class ValueHeap:
    """Thread-safe value interning table with compaction. Id 0 is NULL.

    Ids are STABLE for the lifetime of their value: :meth:`compact`
    (the ``vacuum_db`` analog, ``handlers.rs:398-452``) never remaps —
    it frees ids no longer referenced anywhere (device store planes,
    in-flight queue/partial buffers) onto a free list that later
    :meth:`intern` calls reuse, so device state is never rewritten."""

    def __init__(self):
        self._values: list = [None]
        self._ids: dict = {(_T_NULL,): NULL_ID}
        self._free: list = []  # compacted ids awaiting reuse (LIFO)
        self._touch: dict = {}  # vid -> monotonic ts of last intern()
        self._freed_total = 0
        self._mu = threading.Lock()

    def intern(self, value: Any) -> int:
        k = _key(value)
        with self._mu:
            vid = self._ids.get(k)
            if vid is None:
                if self._free:
                    vid = self._free.pop()
                    self._values[vid] = (
                        bytes(value) if isinstance(value, bytearray)
                        else value
                    )
                else:
                    vid = len(self._values)
                    if vid >= (1 << 31):
                        raise OverflowError(
                            "value heap exceeded int32 id space")
                    self._values.append(
                        bytes(value) if isinstance(value, bytearray)
                        else value
                    )
                self._ids[k] = vid
            self._touch[vid] = time.monotonic()
            return vid

    def lookup(self, vid: int) -> Any:
        if vid == NULL_ID:
            return None
        v = self._values[vid]
        if v is _FREE:
            raise LookupError(
                f"value id {vid} was compacted away (heap corruption or "
                f"a reference the compaction scan missed)"
            )
        # refresh the grace clock on READ too (GIL-atomic dict write).
        # Honest contract: this protects ids a reader RE-dereferences;
        # an id a stale snapshot has not read yet is protected only by
        # the grace window itself — a streaming consumer iterating a
        # snapshot older than grace_seconds can hit a loud LookupError
        # (never silent reuse inside the window). Size grace_seconds
        # above the longest expected reader.
        self._touch[vid] = time.monotonic()  # corrolint: disable=unlocked-mutation -- deliberate GIL-atomic dict write; taking _mu here would serialize every read (see contract above)
        return v

    def __len__(self) -> int:
        return len(self._values)

    @property
    def live_count(self) -> int:
        """Interned values currently reachable (len minus free slots)."""
        with self._mu:
            return len(self._values) - len(self._free)

    @property
    def freed_total(self) -> int:
        return self._freed_total

    def compact(self, referenced, grace_seconds: float = 300.0) -> int:
        """Free every id not in ``referenced`` and not interned within
        the last ``grace_seconds`` (a write planned on the host may not
        have reached device state yet — the grace window keeps its id
        alive until it does). Returns the number of ids freed."""
        cutoff = time.monotonic() - grace_seconds
        referenced = set(int(r) for r in referenced)
        freed = 0
        with self._mu:
            for k, vid in list(self._ids.items()):
                if vid == NULL_ID or vid in referenced:
                    continue
                if self._touch.get(vid, 0.0) > cutoff:
                    continue
                del self._ids[k]
                self._values[vid] = _FREE
                self._free.append(vid)
                self._touch.pop(vid, None)
                freed += 1
            self._freed_total += freed
        return freed

    # --- checkpoint support ----------------------------------------------
    def state_dict(self) -> dict:
        out = []
        for v in self._values[1:]:
            if v is _FREE:
                out.append([_T_FREE])
            elif isinstance(v, bytes):
                out.append([_T_BLOB, v.hex()])
            elif isinstance(v, str):
                out.append([_T_TEXT, v])
            elif isinstance(v, float):
                out.append([_T_REAL, v])
            else:
                out.append([_T_INT, v])
        return {"values": out}

    @classmethod
    def from_state_dict(cls, state: dict) -> "ValueHeap":
        heap = cls()
        for entry in state["values"]:
            tag, raw = entry[0], (entry[1] if len(entry) > 1 else None)
            vid = len(heap._values)
            if tag == _T_FREE:
                # preserve the hole: ids position-encode device state
                heap._values.append(_FREE)
                heap._free.append(vid)
                continue
            if tag == _T_BLOB:
                value = bytes.fromhex(raw)
            elif tag == _T_REAL:
                value = float(raw)
            elif tag == _T_INT:
                value = int(raw)
            else:
                value = raw
            heap._values.append(value)
            heap._ids[_key(value)] = vid
        return heap


def corro_json_contains(outer: Any, inner: Any) -> bool:
    """The custom SQL function from ``sqlite-functions``
    (``crates/sqlite-functions/src/lib.rs:5-30``): true when ``inner``'s
    JSON object/array is recursively contained in ``outer``'s."""
    a = json.loads(outer) if isinstance(outer, (str, bytes)) else outer
    b = json.loads(inner) if isinstance(inner, (str, bytes)) else inner
    return _contains(a, b)


def _contains(outer: Any, inner: Any) -> bool:
    if isinstance(inner, dict):
        return isinstance(outer, dict) and all(
            k in outer and _contains(outer[k], v) for k, v in inner.items()
        )
    if isinstance(inner, list):
        return isinstance(outer, list) and all(
            any(_contains(o, v) for o in outer) for v in inner
        )
    return outer == inner
