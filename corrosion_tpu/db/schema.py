"""Schema management: parse CREATE TABLE SQL, diff, apply onto the grid.

Mirrors the reference's ``crates/corro-types/src/schema.rs``: schema files
are parsed into a ``Schema`` (``parse_sql``, ``schema.rs:747``), diffed
against the current one and applied non-destructively (``apply_schema``,
``schema.rs:287``), with the same constraint posture — every table needs a
primary key, unique indexes are forbidden, destructive changes (dropping
tables/columns, changing types) are rejected (``schema.rs:113-200``).

Grid mapping (TPU reframing of ``crsql_as_crr``): each table's rows live
anywhere in the simulator's ``[n_rows, n_cols]`` cell grid via a
host-global row map (``RowMap``); column 0 of every row is the
causal-length register ``cl`` — odd = live, even = deleted — exactly
cr-sqlite's delete tracking (``doc/crdts.md:24-40``); user columns occupy
cols 1..n_cols-1 in declaration order.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

CL_COL = 0  # causal-length register column (cr-sqlite `cl`)

_TYPE_ALIASES = {
    "INT": "INTEGER",
    "INTEGER": "INTEGER",
    "BIGINT": "INTEGER",
    "SMALLINT": "INTEGER",
    "TEXT": "TEXT",
    "VARCHAR": "TEXT",
    "CHAR": "TEXT",
    "REAL": "REAL",
    "FLOAT": "REAL",
    "DOUBLE": "REAL",
    "BLOB": "BLOB",
    "ANY": "ANY",
    "BOOLEAN": "INTEGER",
}


class SchemaError(ValueError):
    """Constraint violation or unsupported schema construct."""


@dataclass(frozen=True)
class Column:
    name: str
    sql_type: str
    primary_key: bool = False
    not_null: bool = False
    default: Optional[object] = None


@dataclass
class Table:
    name: str
    columns: List[Column]

    @property
    def pk(self) -> Column:
        return next(c for c in self.columns if c.primary_key)

    @property
    def value_columns(self) -> List[Column]:
        return [c for c in self.columns if not c.primary_key]

    def column(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise SchemaError(f"no such column: {self.name}.{name}")

    def has_column(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    def col_index(self, name: str) -> int:
        """Grid column for a value column (pk is implicit in the row map)."""
        idx = CL_COL + 1
        for c in self.columns:
            if c.primary_key:
                continue
            if c.name == name:
                return idx
            idx += 1
        raise SchemaError(f"no such column: {self.name}.{name}")


@dataclass
class Schema:
    tables: Dict[str, Table] = field(default_factory=dict)

    def table(self, name: str) -> Table:
        t = self.tables.get(name)
        if t is None:
            raise SchemaError(f"no such table: {name}")
        return t


# --- SQL parsing ---------------------------------------------------------

_CREATE_RE = re.compile(
    r"CREATE\s+TABLE\s+(?:IF\s+NOT\s+EXISTS\s+)?"
    r"(?P<name>[\w\"]+)\s*\((?P<body>.*?)\)\s*(?:;|$)",
    re.IGNORECASE | re.DOTALL,
)
_INDEX_RE = re.compile(
    r"CREATE\s+(?P<unique>UNIQUE\s+)?INDEX\b", re.IGNORECASE
)


def _split_commas(body: str) -> List[str]:
    """Split on top-level commas (respecting parens and quotes)."""
    parts, depth, start, i = [], 0, 0, 0
    in_str: Optional[str] = None
    while i < len(body):
        ch = body[i]
        if in_str:
            if ch == in_str:
                in_str = None
        elif ch in "'\"":
            in_str = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(body[start:i])
            start = i + 1
        i += 1
    parts.append(body[start:])
    return [p.strip() for p in parts if p.strip()]


def _unquote(ident: str) -> str:
    return ident.strip().strip('"').strip("`")


def _parse_default(tokens: List[str]) -> object:
    raw = tokens[0] if tokens else "NULL"
    if raw.upper() == "NULL":
        return None
    if raw.startswith("'") and raw.endswith("'"):
        return raw[1:-1]
    try:
        return int(raw)
    except ValueError:
        try:
            return float(raw)
        except ValueError:
            raise SchemaError(f"unsupported DEFAULT expression: {raw!r}")


def _parse_column(defn: str, table: str) -> Column:
    tokens = defn.split()
    name = _unquote(tokens[0])
    if len(tokens) < 2:
        raise SchemaError(f"column {table}.{name} needs a type")
    sql_type = _TYPE_ALIASES.get(tokens[1].split("(")[0].upper())
    if sql_type is None:
        raise SchemaError(f"unsupported type {tokens[1]!r} for {table}.{name}")
    rest = " ".join(tokens[2:]).upper()
    primary_key = "PRIMARY KEY" in rest
    not_null = "NOT NULL" in rest or primary_key
    default = None
    m = re.search(r"DEFAULT\s+(\S+)", " ".join(tokens[2:]), re.IGNORECASE)
    if m:
        default = _parse_default([m.group(1)])
    if "UNIQUE" in rest and not primary_key:
        # same posture as the reference: unique constraints other than the
        # pk break CRDT merge (schema.rs:113-200)
        raise SchemaError(
            f"UNIQUE constraint on {table}.{name} is not allowed on CRR tables"
        )
    if "AUTOINCREMENT" in rest:
        raise SchemaError(f"AUTOINCREMENT not allowed on CRR table {table}")
    return Column(name, sql_type, primary_key, not_null, default)


def parse_schema_sql(sql: str) -> Schema:
    """Parse a schema file's CREATE TABLE statements into a ``Schema``."""
    if _INDEX_RE.search(sql) and any(
        m.group("unique") for m in _INDEX_RE.finditer(sql)
    ):
        raise SchemaError("unique indexes are not allowed on CRR tables")
    schema = Schema()
    for m in _CREATE_RE.finditer(sql):
        name = _unquote(m.group("name"))
        columns: List[Column] = []
        table_pk: List[str] = []
        for defn in _split_commas(m.group("body")):
            upper = defn.upper()
            if upper.startswith("PRIMARY KEY"):
                inner = defn[defn.index("(") + 1 : defn.rindex(")")]
                table_pk = [_unquote(c) for c in inner.split(",")]
                continue
            if upper.startswith(("UNIQUE", "CHECK", "FOREIGN KEY", "CONSTRAINT")):
                raise SchemaError(
                    f"table constraint not allowed on CRR table {name}: {defn!r}"
                )
            columns.append(_parse_column(defn, name))
        if table_pk:
            if len(table_pk) != 1:
                raise SchemaError(
                    f"composite primary keys are not supported (table {name})"
                )
            columns = [
                Column(c.name, c.sql_type, c.name == table_pk[0],
                       c.not_null or c.name == table_pk[0], c.default)
                for c in columns
            ]
        pks = [c for c in columns if c.primary_key]
        if len(pks) != 1:
            raise SchemaError(
                f"table {name} must have exactly one primary key column "
                f"(found {len(pks)}) — required for CRR conversion"
            )
        if name in schema.tables:
            raise SchemaError(f"duplicate table {name}")
        schema.tables[name] = Table(name, columns)
    return schema


# --- diff & apply --------------------------------------------------------

def diff_schemas(old: Schema, new: Schema) -> List[Tuple[str, str]]:
    """List of (kind, detail) changes; raises on destructive ones
    (``apply_schema`` posture, ``schema.rs:287-360``)."""
    changes: List[Tuple[str, str]] = []
    for name in old.tables:
        if name not in new.tables:
            raise SchemaError(f"cannot drop table {name} (destructive)")
    for name, table in new.tables.items():
        if name not in old.tables:
            changes.append(("create_table", name))
            continue
        old_t = old.tables[name]
        old_cols = {c.name: c for c in old_t.columns}
        for c in old_t.columns:
            if c.name not in {x.name for x in table.columns}:
                raise SchemaError(f"cannot drop column {name}.{c.name}")
        for i, c in enumerate(table.columns):
            prev = old_cols.get(c.name)
            if prev is None:
                if i < len(old_t.columns):
                    raise SchemaError(
                        f"new column {name}.{c.name} must be appended last"
                    )
                if c.primary_key:
                    raise SchemaError(f"cannot add pk column {name}.{c.name}")
                changes.append(("add_column", f"{name}.{c.name}"))
            elif (prev.sql_type, prev.primary_key) != (c.sql_type, c.primary_key):
                raise SchemaError(f"cannot alter column {name}.{c.name}")
    return changes


class RowMap:
    """Host-global (table, pk) -> grid row allocator, shared by all
    simulated nodes. Append-only: rows are never reclaimed (deletes are
    causal-length tombstones, like cr-sqlite)."""

    def __init__(self, n_rows: int):
        self.n_rows = n_rows
        self._rows: Dict[Tuple[str, object], int] = {}
        self._by_table: Dict[str, List[Tuple[object, int]]] = {}
        self._by_row: Dict[int, Tuple[str, object]] = {}
        self._next = 0
        self._mu = threading.Lock()

    def get(self, table: str, pk: object) -> Optional[int]:
        return self._rows.get((table, pk))

    def get_or_alloc(self, table: str, pk: object) -> int:
        with self._mu:
            row = self._rows.get((table, pk))
            if row is None:
                if self._next >= self.n_rows:
                    raise SchemaError(
                        f"grid row capacity exhausted ({self.n_rows}); raise "
                        f"[sim].n_rows"
                    )
                row = self._next
                self._next += 1
                self._rows[(table, pk)] = row
                self._by_table.setdefault(table, []).append((pk, row))
                self._by_row[row] = (table, pk)
            return row

    def table_pk_of(self, row: int) -> Optional[Tuple[str, object]]:
        """Reverse lookup: grid row -> (table, pk). Used by the
        incremental subscription matcher to turn applied cell deltas
        into candidate pks (the ``match_changes`` seam,
        ``pubsub.rs:527-1100``)."""
        with self._mu:
            return self._by_row.get(row)

    def rows_of(self, table: str) -> List[Tuple[object, int]]:
        with self._mu:
            return list(self._by_table.get(table, ()))

    def __len__(self) -> int:
        return self._next

    def state_dict(self) -> dict:
        def enc(pk):
            if isinstance(pk, bytes):
                return ["b", pk.hex()]
            if isinstance(pk, float):
                return ["r", pk]
            if isinstance(pk, int):
                return ["i", pk]
            return ["t", pk]

        return {
            "n_rows": self.n_rows,
            "rows": [[t, enc(pk), row] for (t, pk), row in self._rows.items()],
        }

    @classmethod
    def from_state_dict(cls, state: dict) -> "RowMap":
        rm = cls(state["n_rows"])

        def dec(e):
            tag, raw = e
            if tag == "b":
                return bytes.fromhex(raw)
            if tag == "r":
                return float(raw)
            if tag == "i":
                return int(raw)
            return raw

        for t, pk_enc, row in sorted(state["rows"], key=lambda x: x[2]):
            got = rm.get_or_alloc(t, dec(pk_enc))
            if got != row:
                raise SchemaError(
                    f"row map restore out of order: expected row {row}, "
                    f"allocated {got}"
                )
        return rm
