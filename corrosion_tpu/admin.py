"""Admin socket: JSON-framed command server on a Unix domain socket.

Mirrors ``crates/corro-admin``: a UDS server running inside the agent
(``start_server``, ``lib.rs:49``) speaking newline-delimited JSON frames
(the reference uses tokio-serde length-delimited JSON), with the same
command set (``Command`` enum, ``lib.rs:102-148``):

- ``ping``;
- ``sync`` — per-node sync-state dump (used by the Antithesis
  ``check_bookkeeping`` convergence check);
- ``locks`` — top-N held locks from the lock registry;
- ``cluster members`` / ``cluster set-id`` / ``cluster rejoin``;
- ``actor version`` — probe one (node, origin) head;
- ``log`` — dynamic log filter reload.

Plus the fault-injection surface the reference gets externally from
Antithesis (SURVEY §4): ``kill`` / ``revive`` / ``partition`` / ``heal``,
and ``checkpoint`` / ``restore`` hooks.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
from typing import Optional

import numpy as np

from corrosion_tpu.utils.tracing import logger, set_level


class AdminServer:
    def __init__(self, agent, uds_path: str, db=None):
        self.agent = agent
        self.db = db
        self.uds_path = uds_path
        self.cluster_id = 0
        if os.path.exists(uds_path):
            os.unlink(uds_path)
        handler = _make_handler(self)
        self.server = socketserver.ThreadingUnixStreamServer(uds_path, handler)
        self.server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "AdminServer":
        from corrosion_tpu.utils.lifecycle import spawn_counted

        self._thread = spawn_counted(
            self.server.serve_forever, name="corro-admin-uds"
        )
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if os.path.exists(self.uds_path):
            os.unlink(self.uds_path)
        if self._thread:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # --- command dispatch -------------------------------------------------
    def handle(self, cmd: dict) -> dict:
        agent = self.agent
        name = cmd.get("command")
        if name == "ping":
            return {"ok": "pong"}
        if name == "sync":
            # cross-process trace propagation: the caller's traceparent
            # rides the command and parents our serving span — the
            # SyncTraceContextV1 inject/extract seam (sync.rs:33-67,
            # peer/mod.rs:1017-1020,1414-1416)
            from corrosion_tpu.utils.tracing import span

            from corrosion_tpu.utils.tracing import inject_traceparent

            node = cmd.get("node")
            with span("admin.sync_state", traceparent=cmd.get("traceparent"),
                      node=node if node is not None else "all"):
                if node is not None:
                    state = agent.sync_state(int(node))
                    # return the serving span so the caller can link
                    # both sides (SyncTraceContextV1 round-trip)
                    state["traceparent"] = inject_traceparent()
                    return {"ok": state}
                return {
                    "ok": [agent.sync_state(i) for i in range(agent.n_nodes)]
                }
        if name == "locks":
            top = int(cmd.get("top", 10))
            snap = sorted(
                agent.locks.snapshot(),
                key=lambda e: e.get("held_seconds", 0), reverse=True,
            )
            return {"ok": snap[:top]}
        if name == "cluster_members":
            return {"ok": agent.members()}
        if name == "cluster_set_id":
            # live ClusterId change (corro-admin/src/lib.rs:135-140): the
            # id gates payload delivery — nodes on a different id stop
            # exchanging traffic until ids agree again
            new_id = int(cmd["cluster_id"])
            nodes = cmd.get("nodes")  # None = whole cluster
            agent.set_cluster_id(new_id, nodes=nodes)
            if nodes is None:  # the server-wide id only moves wholesale
                self.cluster_id = new_id
            return {"ok": new_id}
        if name == "cluster_rejoin":
            agent.revive_node(int(cmd["node"]))
            return {"ok": True}
        if name == "actor_version":
            snap = agent.snapshot()
            node, origin = int(cmd["node"]), int(cmd["origin"])
            return {"ok": {
                "head": int(snap["head"][node, origin]),
                "known_max": int(snap["known_max"][node, origin]),
            }}
        if name == "log":
            set_level(cmd.get("level", "info"))
            return {"ok": cmd.get("level", "info")}
        if name == "assertions":
            from corrosion_tpu.utils.assertions import REGISTRY

            return {"ok": {**REGISTRY.snapshot(),
                           "liveness": REGISTRY.liveness_report()}}
        if name == "reload":
            # `corrosion reload` analog: re-apply schema files + log level
            # from the (possibly edited) config file (command/reload.rs)
            from corrosion_tpu.config import load_config

            cfg = load_config(cmd["config"])
            applied = []
            if self.db is not None:
                for path in cfg.db.schema_paths:
                    with open(path) as f:
                        applied.extend(self.db.apply_schema_sql(f.read()))
            set_level(cfg.log.level)
            return {"ok": {"schema_changes": [list(c) for c in applied],
                           "log_level": cfg.log.level}}
        # --- fault injection (Antithesis driver analog) -------------------
        if name == "kill":
            agent.kill_node(int(cmd["node"]))
            return {"ok": True}
        if name == "revive":
            agent.revive_node(int(cmd["node"]))
            return {"ok": True}
        if name == "partition":
            groups = np.asarray(cmd["groups"], np.int32)
            agent.set_partition(groups)
            return {"ok": True}
        if name == "heal":
            agent.heal_partition()
            return {"ok": True}
        # --- durability ---------------------------------------------------
        if name == "checkpoint":
            from corrosion_tpu.checkpoint import save_checkpoint

            path = save_checkpoint(agent, db=self.db,
                                   path=cmd.get("path", "./checkpoint"))
            return {"ok": path}
        if name == "restore":
            from corrosion_tpu.checkpoint import restore_checkpoint

            man = restore_checkpoint(agent, cmd["path"], db=self.db)
            return {"ok": {"round": man["round"]}}
        if name == "backup":
            from corrosion_tpu.checkpoint import backup_node

            path = backup_node(agent, int(cmd.get("node", 0)), db=self.db,
                               path=cmd.get("path", "./backup.npz"))
            return {"ok": path}
        if name == "restore_backup":
            from corrosion_tpu.checkpoint import restore_backup

            node = restore_backup(
                agent, cmd["path"],
                node=int(cmd["node"]) if "node" in cmd else None,
                db=self.db, repivot=bool(cmd.get("repivot", True)),
            )
            return {"ok": {"node": node}}
        if name == "compact":
            # operator-triggered heap compaction (the vacuum_db analog;
            # the maintenance loop also runs it on cadence)
            if self.db is None:
                return {"error": "no database attached"}
            # floor the grace on a LIVE agent: ids interned by writes
            # not yet applied to device state are protected only by
            # this window (values.py lookup contract); 0/negative would
            # free them mid-flight. Tests hit Database.compact_heap
            # directly when they need an immediate pass.
            grace = max(5.0, float(cmd.get("grace_seconds", 300.0)))
            freed = self.db.compact_heap(grace_seconds=grace)
            return {"ok": {"freed": freed,
                           "live": self.db.heap.live_count,
                           "len": len(self.db.heap)}}
        return {"error": f"unknown command {name!r}"}


def _make_handler(server: AdminServer):
    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            for raw in self.rfile:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    cmd = json.loads(raw)
                    resp = server.handle(cmd)
                except Exception as e:  # noqa: BLE001
                    logger.exception("admin command failed")
                    resp = {"error": str(e)}
                try:
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    return

    return Handler


class AdminClient:
    """Line-framed JSON client (the CLI's admin transport)."""

    def __init__(self, uds_path: str, timeout: float = 30.0):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(timeout)
        self.sock.connect(uds_path)
        self._file = self.sock.makefile("rwb")

    def call(self, command: str, **kw) -> dict:
        # inject the current trace context (the sync client's
        # traceparent injection, peer/mod.rs:1017-1020)
        from corrosion_tpu.utils.tracing import inject_traceparent

        tp = inject_traceparent()
        if tp and "traceparent" not in kw:
            kw["traceparent"] = tp
        self._file.write(json.dumps({"command": command, **kw}).encode() + b"\n")
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("admin socket closed")
        resp = json.loads(line)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["ok"]

    def close(self) -> None:
        try:
            self._file.close()
        finally:
            self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
