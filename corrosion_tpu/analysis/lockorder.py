"""lock-order: deadlock freedom as a graph property.

PR 5's lock-discipline checker deliberately scoped itself to
single-lock classes — which lock guards which attribute is not
inferable for multi-lock classes, and cross-class nesting was
invisible to a per-function pass. This checker lifts both limits for
the one property that IS inferable mechanically: the **acquisition
order**. It builds a directed graph over every lock in the linted set
(``self.<attr> = threading.Lock()/RLock()`` per class, module-level
``_mu = threading.Lock()``) with an edge A -> B wherever B is acquired
while A is held — through direct ``with`` nesting AND through calls
(``Supervisor.call`` taking its lock inside a method that already
holds the writer's, a ``*_locked`` helper acquiring someone else's
lock), resolved over the project call graph with per-function
"acquires transitively" summaries run to a fixed point.

Two rules fall out of the graph:

- **lock-cycle** — a non-reentrant ``threading.Lock`` re-acquired
  while already held (a self-edge): certain single-thread deadlock.
  RLocks are exempt from self-edges by construction.
- **lock-inversion** — two locks acquired in opposite orders on two
  code paths (a 2-cycle), or any longer cycle: the classic ABBA
  deadlock, needing two threads and the right interleaving — exactly
  the bug class runtime tests only catch on the path they happen to
  take.

Unresolvable acquisitions (``other_obj._mu`` where the receiver's
class is unknown) grow NO edge: with every lock in this repo named
``_mu``, guessing by attribute name would invent cycles that don't
exist. Precision over recall, as with every checker here.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from corrosion_tpu.analysis.base import Finding, dotted_name, walk_shallow
from corrosion_tpu.analysis.callgraph import (
    FunctionInfo,
    Project,
    fixpoint,
)

RULE_CYCLE = "lock-cycle"
RULE_INVERSION = "lock-inversion"

_LOCK_CTORS = {
    "threading.Lock": "Lock", "threading.RLock": "RLock",
    "Lock": "Lock", "RLock": "RLock",
}


@dataclasses.dataclass(frozen=True)
class LockNode:
    name: str  # "mod.Class._mu" or "mod._lock"
    kind: str  # "Lock" | "RLock"

    def __repr__(self):
        return self.name


@dataclasses.dataclass(frozen=True)
class Site:
    path: str
    line: int
    where: str  # human context: "Class.method" or "func"


def _self_attr(node) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _collect_locks(project: Project) -> Tuple[
        Dict[Tuple[str, str, str], LockNode],
        Dict[Tuple[str, str], LockNode],
        Dict[LockNode, Tuple[str, int]]]:
    """(class locks keyed by (module, class name, attr) — two
    same-named classes in different modules own DIFFERENT locks —
    module locks keyed by (module name, var), creation sites keyed by
    node). The creation site is the line of the ``threading.Lock()``
    call itself — the runtime sanitizer names live lock objects by
    matching the frame that executes that line, so the dynamic witness
    and this static graph share one node namespace."""
    class_locks: Dict[Tuple[str, str, str], LockNode] = {}
    module_locks: Dict[Tuple[str, str], LockNode] = {}
    sites: Dict[LockNode, Tuple[str, int]] = {}
    for mod in project.modules:
        for top in mod.tree.body:
            if isinstance(top, ast.Assign) and isinstance(
                    top.value, ast.Call):
                kind = _LOCK_CTORS.get(dotted_name(top.value.func))
                if kind:
                    for tgt in top.targets:
                        if isinstance(tgt, ast.Name):
                            lock = LockNode(
                                f"{mod.name}.{tgt.id}", kind)
                            module_locks[(mod.name, tgt.id)] = lock
                            sites[lock] = (mod.path, top.value.lineno)
            if not isinstance(top, ast.ClassDef):
                continue
            # walk the class's own body without descending into nested
            # classes — their locks belong to THEIR instances
            stack: List[ast.AST] = list(top.body)
            while stack:
                node = stack.pop()
                if isinstance(node, ast.ClassDef):
                    continue
                stack.extend(ast.iter_child_nodes(node))
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                kind = _LOCK_CTORS.get(dotted_name(node.value.func))
                if not kind:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr:
                        lock = LockNode(
                            f"{mod.name}.{top.name}.{attr}", kind)
                        class_locks[(mod.name, top.name, attr)] = lock
                        sites[lock] = (mod.path, node.value.lineno)
    return class_locks, module_locks, sites


class _Edges:
    def __init__(self):
        self.edges: Dict[Tuple[LockNode, LockNode], List[Site]] = {}

    def add(self, held: LockNode, acquired: LockNode, site: Site) -> None:
        self.edges.setdefault((held, acquired), []).append(site)


class _FnScan:
    """One function: held-set tracking + (acquire, call) events.

    ``summaries`` maps qualname -> frozenset[LockNode] acquired
    transitively. With ``edges`` given, A->B edges are recorded."""

    def __init__(self, fn: FunctionInfo, project: Project,
                 class_locks, module_locks,
                 summaries: Dict[str, FrozenSet[LockNode]],
                 edges: Optional[_Edges]):
        self.fn = fn
        self.project = project
        self.class_locks = class_locks
        self.module_locks = module_locks
        self.summaries = summaries
        self.edges = edges
        self.acquired: Set[LockNode] = set()
        self._own = [
            lock for (m, c, _), lock in class_locks.items()
            if fn.cls is not None and c == fn.cls.name
            and m == fn.module.name
        ]

    def _where(self) -> str:
        return (f"{self.fn.cls.name}.{self.fn.name}" if self.fn.cls
                else self.fn.name)

    def _entry_held(self) -> FrozenSet[LockNode]:
        # the *_locked convention: the (single) class lock is held by
        # the caller on entry; with several class locks the convention
        # is ambiguous and we assume nothing
        if self.fn.name.endswith("_locked") and len(self._own) == 1:
            return frozenset(self._own)
        return frozenset()

    def _resolve_lock(self, expr: ast.AST) -> Optional[LockNode]:
        attr = _self_attr(expr)
        if attr is not None and self.fn.cls is not None:
            return self.class_locks.get(
                (self.fn.module.name, self.fn.cls.name, attr))
        if isinstance(expr, ast.Name):
            return self.module_locks.get(
                (self.fn.module.name, expr.id))
        return None

    def _note_acquire(self, lock: LockNode, held: FrozenSet[LockNode],
                      node: ast.AST) -> None:
        self.acquired.add(lock)
        if self.edges is None:
            return
        site = Site(self.fn.path, node.lineno, self._where())
        for h in held:
            if h == lock and lock.kind == "RLock":
                continue  # reentrant by design
            self.edges.add(h, lock, site)

    def _note_call(self, call: ast.Call, held: FrozenSet[LockNode]
                   ) -> None:
        callee = self.project.resolve_call(call, self.fn)
        if callee is None:
            return
        acq = self.summaries.get(callee.qualname) or frozenset()
        self.acquired |= acq
        if self.edges is None or not held:
            return
        site = Site(self.fn.path, call.lineno,
                    f"{self._where()} -> {callee.name}()")
        for h in held:
            for lock in acq:
                if h == lock and lock.kind == "RLock":
                    continue
                self.edges.add(h, lock, site)

    def run(self) -> FrozenSet[LockNode]:
        self._scan(list(self.fn.node.body), self._entry_held())
        return frozenset(self.acquired)

    def _scan_expr(self, node: Optional[ast.AST],
                   held: FrozenSet[LockNode]) -> None:
        # lambda bodies run LATER, lock long released — calls inside
        # them must not grow held->acquired edges. walk_shallow skips
        # nested lambdas; the root-is-a-lambda case needs its own guard
        if node is None or isinstance(node, ast.Lambda):
            return
        for sub in walk_shallow(node):
            if isinstance(sub, ast.Call):
                self._note_call(sub, held)

    def _scan(self, body: List[ast.stmt],
              held: FrozenSet[LockNode]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # closures run with no lock held, later
            if isinstance(stmt, ast.With):
                inner = held
                for item in stmt.items:
                    self._scan_expr(item.context_expr, held)
                    lock = self._resolve_lock(item.context_expr)
                    if lock is not None:
                        self._note_acquire(lock, inner, stmt)
                        inner = inner | {lock}
                self._scan(stmt.body, inner)
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._scan(sub, held)
            for handler in getattr(stmt, "handlers", []):
                self._scan(handler.body, held)
            for attr in ("value", "test", "iter", "exc", "targets"):
                sub = getattr(stmt, attr, None)
                if isinstance(sub, ast.AST):
                    self._scan_expr(sub, held)
                elif isinstance(sub, list):
                    for s in sub:
                        self._scan_expr(s, held)


def _find_cycles(edges: Dict[Tuple[LockNode, LockNode], List[Site]]
                 ) -> List[List[LockNode]]:
    """Elementary cycles, shortest-first, each reported once (the graph
    here has a handful of nodes — simple DFS is plenty)."""
    graph: Dict[LockNode, Set[LockNode]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    seen: Set[FrozenSet[LockNode]] = set()
    cycles: List[List[LockNode]] = []

    max_len = len(graph)  # elementary cycles can't exceed the node count

    def dfs(start: LockNode, node: LockNode, path: List[LockNode]):
        for nxt in sorted(graph.get(node, ()), key=repr):
            if nxt == start and len(path) >= 1:
                key = frozenset(path)
                if key not in seen:
                    seen.add(key)
                    cycles.append(list(path))
            elif nxt not in path and len(path) < max_len:
                dfs(start, nxt, path + [nxt])

    for node in sorted(graph, key=repr):
        dfs(node, node, [node])
    cycles.sort(key=len)
    return cycles


@dataclasses.dataclass
class LockGraph:
    """The static lock model: every lock in the walked set plus the
    acquisition-order edges derived over the call graph. Consumed by
    :func:`check_project` below AND by the runtime sanitizer
    (``analysis/sanitizer``), whose witnessed edges must stay a subset
    of ``edges`` — the static/dynamic cross-check ISSUE 8 is built on."""

    class_locks: Dict[Tuple[str, str, str], LockNode]
    module_locks: Dict[Tuple[str, str], LockNode]
    #: LockNode -> (path, line) of the ``threading.Lock()`` call
    creation_sites: Dict[LockNode, Tuple[str, int]]
    edges: Dict[Tuple[LockNode, LockNode], List[Site]]

    def edge_names(self) -> Set[Tuple[str, str]]:
        return {(a.name, b.name) for (a, b) in self.edges}


def build_lock_graph(project: Project) -> LockGraph:
    """Collect every lock and every statically-derivable acquisition
    edge (direct ``with`` nesting + transitive-acquire call summaries
    run to a fixed point)."""
    class_locks, module_locks, sites = _collect_locks(project)

    def summarize(fn: FunctionInfo, summaries):
        return _FnScan(fn, project, class_locks, module_locks,
                       summaries, edges=None).run()

    summaries = fixpoint(project, summarize)
    edges = _Edges()
    for fn in project.iter_functions():
        _FnScan(fn, project, class_locks, module_locks, summaries,
                edges).run()
    return LockGraph(class_locks=class_locks, module_locks=module_locks,
                     creation_sites=sites, edges=edges.edges)


def check_project(project: Project) -> List[Finding]:
    edges = _Edges()
    edges.edges = build_lock_graph(project).edges

    findings: List[Finding] = []
    # self-edges: non-reentrant re-acquisition (RLocks filtered above)
    for (a, b), sites in sorted(edges.edges.items(), key=repr):
        if a == b:
            site = sites[0]
            findings.append(Finding(
                path=site.path, line=site.line, rule=RULE_CYCLE,
                message=f"non-reentrant {a.name} re-acquired while "
                        f"already held (in {site.where}) — "
                        "single-thread deadlock",
                hint="split a *_locked helper, or make the lock an "
                     "RLock if re-entry is genuinely intended",
            ))
    # multi-lock cycles: inversion (len 2) and longer cycles
    for cycle in _find_cycles(edges.edges):
        if len(cycle) < 2:
            continue  # self-edges already reported
        ring = cycle + [cycle[0]]
        sites = [
            edges.edges[(ring[i], ring[i + 1])][0]
            for i in range(len(cycle))
            if (ring[i], ring[i + 1]) in edges.edges
        ]
        order = " -> ".join(n.name for n in ring)
        rule = RULE_INVERSION if len(cycle) == 2 else RULE_CYCLE
        detail = "; ".join(
            f"{s.path}:{s.line} ({s.where})" for s in sites[:4])
        findings.append(Finding(
            path=sites[0].path, line=sites[0].line, rule=rule,
            message=f"lock acquisition cycle {order} — opposite-order "
                    f"paths can deadlock; acquisition sites: {detail}",
            hint="pick one global order for these locks and re-nest "
                 "the odd path out (or stage data and call unlocked)",
        ))
    return sorted(findings)
