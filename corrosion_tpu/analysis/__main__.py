"""``python -m corrosion_tpu.analysis`` — run corrolint.

Exit status: 0 clean, 1 findings, 2 usage error. ``--format json``
emits a machine-readable findings array (one object per finding, the
``Finding`` fields verbatim) for editor/CI integration.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from corrosion_tpu.analysis.base import RULES
from corrosion_tpu.analysis.runner import ALL_CHECKERS, run_paths


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m corrosion_tpu.analysis",
        description="corrolint: donation-safety, lock-discipline, "
                    "strippable-assert, and trace-hygiene checks",
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to check (default: the installed "
             "corrosion_tpu package, wherever the CLI runs from)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="findings output format",
    )
    parser.add_argument(
        "--checkers", default=None,
        help=f"comma-separated subset of {sorted(ALL_CHECKERS)}",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}: {desc}")
        return 0

    checkers = (
        [c.strip() for c in args.checkers.split(",") if c.strip()]
        if args.checkers else None
    )
    paths = args.paths
    if not paths:
        # default to the package the CLI shipped in — a cwd-relative
        # default would exit 2 anywhere but the checkout root
        import corrosion_tpu

        paths = [os.path.dirname(os.path.abspath(corrosion_tpu.__file__))]
    try:
        findings = run_paths(paths, checkers)
    except (ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"\n{len(findings)} finding(s). Suppress deliberate "
                  "ones with `# corrolint: disable=<rule> -- <reason>`.")
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe — normal unix behavior
        try:
            sys.stdout.close()
        finally:
            sys.exit(0)
