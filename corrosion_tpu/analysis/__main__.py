"""``python -m corrosion_tpu.analysis`` — run corrolint.

Exit status: 0 clean, 1 findings, 2 usage error. ``--format json``
emits a machine-readable findings array (one object per finding, the
``Finding`` fields verbatim) for editor/CI integration;
``--output-json PATH`` additionally writes a report artifact (findings
+ per-rule counts + file count — ``scripts/check.sh`` publishes it as
``artifacts/lint_r06.json``). ``--changed <git-ref>`` lints only the
Python files touched since the ref (plus untracked ones) for fast
pre-commit runs — interprocedural facts are then derived from the
touched subset only, so the full walk remains the gate of record.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from corrosion_tpu.analysis.base import RULES
from corrosion_tpu.analysis.runner import (
    ALL_CHECKERS,
    PROJECT_CHECKERS,
    _select,
    lint_report,
)


def changed_python_files(ref: str) -> List[str]:
    """Tracked files changed vs ``ref`` plus untracked ones, limited
    to existing ``.py`` paths (repo-root relative, resolved to cwd)."""
    root = subprocess.check_output(
        ["git", "rev-parse", "--show-toplevel"], text=True
    ).strip()
    # -z: NUL-delimited, unquoted output — names with spaces or
    # non-ASCII must not be silently dropped from a pre-commit lint
    diff = subprocess.check_output(
        ["git", "diff", "--name-only", "--diff-filter=d", "-z", ref,
         "--", "*.py"], text=True, cwd=root,
    )
    untracked = subprocess.check_output(
        ["git", "ls-files", "--others", "--exclude-standard", "-z",
         "--", "*.py"], text=True, cwd=root,
    )
    names = {n for n in diff.split("\0") + untracked.split("\0") if n}
    out = []
    for rel in sorted(names):
        path = os.path.join(root, rel)
        if os.path.isfile(path):
            out.append(path)
    return out


def _write_report(path: str, findings, n_files: int) -> None:
    rule_counts: dict = {}
    for f in findings:
        rule_counts[f.rule] = rule_counts.get(f.rule, 0) + 1
    report = {
        "findings": [f.to_json() for f in findings],
        "rule_counts": rule_counts,
        "files_checked": n_files,
        "rules_available": sorted(RULES),
        "clean": not findings,
    }
    out_dir = os.path.dirname(path)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m corrosion_tpu.analysis",
        description="corrolint: donation-safety, lock-discipline, "
                    "strippable-assert, trace-hygiene, the v2 "
                    "interprocedural sharding-contract / dtype-flow / "
                    "lock-order / donation-flow checks, and the v3 "
                    "corrobudget mem-budget / densify symbolic-shape "
                    "gate (docs/memory-budget.md)",
    )
    parser.add_argument(
        "paths", nargs="*", default=None,
        help="files/directories to check (default: the installed "
             "corrosion_tpu package, wherever the CLI runs from)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="findings output format",
    )
    parser.add_argument(
        "--checkers", default=None,
        help="comma-separated subset of "
             f"{sorted(ALL_CHECKERS) + sorted(PROJECT_CHECKERS)}",
    )
    parser.add_argument(
        "--changed", metavar="GIT_REF", default=None,
        help="lint only .py files changed vs the git ref (plus "
             "untracked ones); zero changed files exits 0",
    )
    parser.add_argument(
        "--output-json", metavar="PATH", default=None,
        help="also write a machine-readable report (findings, rule "
             "counts, files walked) to PATH",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}: {desc}")
        return 0

    checkers = (
        [c.strip() for c in args.checkers.split(",") if c.strip()]
        if args.checkers else None
    )
    if checkers is not None:
        # validate names up front (via the runner's own rule, so the
        # message can never drift) — a typo'd --checkers must fail
        # even on the zero-changed early exit, not lie dormant until
        # the next commit that touches files
        try:
            _select(checkers)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
    paths = args.paths
    if args.changed is not None:
        # explicit paths must exist even in --changed mode — a typo'd
        # scope path would otherwise filter everything out and read as
        # "nothing changed, clean" forever (the same silent-clean the
        # empty-walk error guards against)
        for p in paths or ():
            if not os.path.exists(p):
                print(f"lint path {p!r} does not exist "
                      f"(cwd: {os.getcwd()})", file=sys.stderr)
                return 2
        try:
            changed = changed_python_files(args.changed)
        except (subprocess.CalledProcessError, OSError) as e:
            print(f"--changed failed: {e}", file=sys.stderr)
            return 2
        # keep only changed files inside the lint scope (the given
        # paths, or the gate's default surface: the package, bench.py,
        # scripts/) — test files keep their pytest asserts by design
        # and must not drown a pre-commit run
        if paths:
            scope = [os.path.abspath(p) for p in paths]
        else:
            root = subprocess.check_output(
                ["git", "rev-parse", "--show-toplevel"], text=True
            ).strip()
            scope = [
                p for p in (
                    os.path.join(root, "corrosion_tpu"),
                    os.path.join(root, "bench.py"),
                    os.path.join(root, "scripts"),
                ) if os.path.exists(p)
            ]
        if scope:
            changed = [
                f for f in changed
                if any(os.path.abspath(f) == s
                       or os.path.abspath(f).startswith(s + os.sep)
                       for s in scope)
            ]
        paths = changed
        if not paths:
            # genuinely nothing to lint — distinct from the empty-walk
            # error below, which guards against typo'd paths. The
            # report artifact (if asked for) still gets refreshed so
            # trend tracking never republishes a stale run as current.
            if args.output_json:
                _write_report(args.output_json, [], 0)
            # keep stdout machine-readable under --format json (an
            # empty findings array); the human note goes to stderr
            if args.format == "json":
                print("[]")
            print(f"no python files changed vs {args.changed} "
                  "(within the lint scope)",
                  file=sys.stderr if args.format == "json" else
                  sys.stdout)
            return 0
    if not paths:
        # default to the package the CLI shipped in — a cwd-relative
        # default would exit 2 anywhere but the checkout root
        import corrosion_tpu

        paths = [os.path.dirname(os.path.abspath(corrosion_tpu.__file__))]
    try:
        findings, n_files = lint_report(paths, checkers)
    except (ValueError, OSError) as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.output_json:
        _write_report(args.output_json, findings, n_files)

    if args.format == "json":
        print(json.dumps([f.to_json() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        if findings:
            print(f"\n{len(findings)} finding(s). Suppress deliberate "
                  "ones with `# corrolint: disable=<rule> -- <reason>`.")
    return 1 if findings else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe — normal unix behavior
        try:
            sys.stdout.close()
        finally:
            sys.exit(0)
