"""sharding-contract: sharded state stays on the mesh.

At flagship scale the carry IS the HBM working set sharded over the
node axis; two cross-function mistakes silently collapse that story:

- **shard-gather** — host-materializing sharded state. A
  ``jax.device_get``/``np.asarray`` on a value that derives from the
  sharded mesh entry points funnels the whole working set through one
  host, doubling host memory and serializing the drain — the failure
  mode the per-shard checkpoint pipeline
  (``parallel.mesh.host_shard_copy``, docs/checkpoints.md) exists to
  avoid. Flagged
  both **at the call site** when tainted state flows into a
  materializer — including a helper that materializes its argument
  somewhere down the call graph (the interprocedural part) — and **at
  the definition** of any ``_host_copy``-style whole-pytree drain
  (``jax.tree.map(np.array, tree)``, ``[np.asarray(x) for x in
  leaves]``) outside the :data:`DRAIN_REGISTRY`.
- **shard-spec-drift** — passing freshly-built (never placed) state
  into a sharded entry point's state slot. The run still works — XLA
  re-lays the arrays out — but the inputs silently arrive replicated /
  default-placed instead of riding the ``P("node")`` specs
  ``shard_state`` stamps, so the "sharded" bench record measures a
  single-device layout. Values of unknown origin (parameters, loads)
  never flag; only a provably-fresh build (``*.create(...)``,
  ``make_soak_inputs``) flowing in unplaced does.

Taint sources are the registries below (the ``parallel/mesh.py``
surfaces); propagation runs on :mod:`~corrosion_tpu.analysis.dataflow`
with union-join, so a value that MAY be sharded on one branch keeps the
taint, while a maybe-placed value never raises spec-drift.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from corrosion_tpu.analysis.base import Finding, dotted_name
from corrosion_tpu.analysis.callgraph import (
    FunctionInfo,
    Project,
    fixpoint,
)
from corrosion_tpu.analysis.dataflow import Env, ForwardAnalysis, TupleVal

RULE_GATHER = "shard-gather"
RULE_DRIFT = "shard-spec-drift"

#: sharded mesh entry points: positions that must receive PLACED state
#: (st, net, inputs — the key replicates and may come from anywhere)
SHARDED_STATE_PARAMS: Dict[str, Tuple[int, ...]] = {
    "sharded_step": (2, 3, 5),
    "sharded_run": (2, 3, 5),
    "sharded_scale_run": (2, 3, 5),
    "sharded_scale_run_carry": (2, 3, 5),
}

#: entry point -> abstract return shape with the sharded paths marked
#: (built lazily; P("node") rides exactly these outputs)
def _sharded_returns() -> Dict[str, Any]:
    S = frozenset({"sharded"})
    return {
        "shard_state": S,  # whole result is placed
        "sharded_step": TupleVal((S, None)),
        "sharded_run": TupleVal((S, None)),
        "sharded_scale_run": TupleVal((S, None)),
        # ((st, key), infos): st is the sharded carry; the key is tiny
        # and replicated — reading it back is not a gather
        "sharded_scale_run_carry": TupleVal((TupleVal((S, None)), None)),
    }


#: call names whose RESULT is freshly-built, never-placed device state
FRESH_BUILDERS = {"create", "make_soak_inputs", "make_write_inputs",
                  "quiet"}

#: direct host materializers (dotted and bare forms)
MATERIALIZERS = {
    "np.array", "np.asarray", "numpy.array", "numpy.asarray",
    "onp.array", "onp.asarray", "jax.device_get", "device_get",
    "float", "int",
}
MATERIALIZER_METHODS = {"item", "tolist"}

#: functions whose whole-pytree host drain is sanctioned — the drain
#: registry the issue's checkpoint/restore machinery rides. Every entry
#: carries its reason; anything else doing a tree-wide materialization
#: is a finding.
DRAIN_REGISTRY: Dict[str, str] = {
    # checkpoint serialization: operates on host-staged slices from the
    # per-shard drain (soak path) or drains the SINGLE-DEVICE agent
    # state whole-leaf (the live-agent checkpoint path, never a mesh)
    "save_checkpoint": "serializes host-staged shard slices (soak) or "
                       "the single-device agent state for the "
                       "crash-consistent commit path",
    # save_checkpoint's whole-leaf branch for shards=None saves: drains
    # the SINGLE-DEVICE agent state (the sharded soak path stages
    # HostLeafShards and never reaches this comprehension)
    "_normalized_leaf_records": "whole-leaf drain of the single-device "
                                "agent state when no per-shard drain "
                                "was staged (shards=None saves)",
    # the ISSUE 9 per-shard drain: each device's addressable shard
    # materializes its own slice (copy_to_host_async per shard) — the
    # sanctioned replacement for the old _host_copy whole-tree gather
    "host_shard_copy": "per-shard slice drain: owned host copies of "
                       "each device's addressable shard, no replicated "
                       "whole-tree intermediate (docs/checkpoints.md)",
    # the live donated round loop holds ONE device copy of the state;
    # checkpoint/backup readers take an owned host copy under the
    # agent's state lease (single-device serving path, never a mesh)
    "device_state": "owned host copy under the Agent state lease while "
                    "the round carry is donated (single-device path)",
    # trace-stability probe: deliberately exercises the checkpoint
    # resume drain on tiny probe state
    "_host_roundtrip": "tracecount probe of the resume path on "
                       "probe-sized state",
    # the fused probe's donate-safe variant of the same roundtrip
    # (owned jnp.array re-upload; the chained dispatch donates)
    "_host_roundtrip_owned": "tracecount probe of the donated resume "
                             "path on probe-sized state",
}


def _tags(value: Any) -> FrozenSet:
    """Every tag reachable in a (possibly tuple-nested) value."""
    if isinstance(value, frozenset):
        return value
    if isinstance(value, TupleVal):
        out: FrozenSet = frozenset()
        for el in value.elements:
            out = out | _tags(el)
        return out
    return frozenset()


def _strip_params(value: Any) -> Any:
    """Return-summary hygiene: a callee's param tags must not leak
    into its caller's environment — but "sharded"/"fresh" are global
    facts that DO travel (a factory helper wrapping ``create()``
    still returns never-placed state)."""
    if isinstance(value, frozenset):
        kept = frozenset(t for t in value if t in ("sharded", "fresh"))
        return kept or None
    if isinstance(value, TupleVal):
        return TupleVal(_strip_params(el) for el in value.elements)
    return None


def _lambda_materializes(node: ast.AST) -> bool:
    """``lambda a: np.array(a)``-shaped materializer?"""
    if not isinstance(node, ast.Lambda):
        return dotted_name(node) in MATERIALIZERS
    for sub in ast.walk(node.body):
        if isinstance(sub, ast.Call) and dotted_name(
                sub.func) in MATERIALIZERS:
            return True
    return False


def _is_tree_map(name: str) -> bool:
    return name.endswith("tree_map") or name.endswith("tree.map")


def _is_leaves(name: str) -> bool:
    return name.endswith("tree_leaves") or name.endswith(
        "tree.leaves") or name.endswith("_leaves")


class _Analysis(ForwardAnalysis):
    """One function: taint propagation + gather/drift sinks.

    ``summaries`` maps qualname -> (gathered param indices, return
    value); during the summary fixpoint ``collect`` is False and no
    findings are emitted."""

    def __init__(self, fn: FunctionInfo, project: Project,
                 summaries: Dict[str, tuple], collect: bool,
                 findings: List[Finding]):
        super().__init__(fn, fn.path, findings)
        self.project = project
        self.summaries = summaries
        self.collect = collect
        self.gathered_params: set = set()
        self.returns_table = _sharded_returns()

    # -- environment -------------------------------------------------------

    def initial_env(self) -> Env:
        return {
            name: frozenset({("param", i)})
            for i, name in enumerate(self.fn.param_names())
        }

    def join(self, a, b):
        if a is None:
            return b
        if b is None:
            return a
        if isinstance(a, TupleVal) or isinstance(b, TupleVal):
            if (isinstance(a, TupleVal) and isinstance(b, TupleVal)
                    and len(a.elements) == len(b.elements)):
                return TupleVal(self.join(x, y)
                                for x, y in zip(a.elements, b.elements))
            return _tags(a) | _tags(b) or None
        return a | b

    #: static metadata reads — host facts, not device data; taint ends
    _META_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                   "nbytes", "itemsize"}

    def eval_attr(self, node, base, env):
        # attribute reads keep taint: st.crdt of sharded st is sharded
        # — but metadata like .shape/.dtype never moves device bytes,
        # so `int(st.crdt.shape[0])` must not read as a gather
        if node.attr in self._META_ATTRS:
            return None
        return _tags(base) or None

    def eval_subscript(self, node, base, env):
        picked = super().eval_subscript(node, base, env)
        if picked is not None:
            return picked
        return _tags(base) or None

    def eval_binop(self, node, left, right, env):
        return (_tags(left) | _tags(right)) or None

    # -- calls -------------------------------------------------------------

    def _flag(self, node: ast.AST, rule: str, message: str,
              hint: str) -> None:
        if self.collect:
            self.findings.append(Finding(
                path=self.path, line=node.lineno, rule=rule,
                message=message, hint=hint))

    def _note_gather(self, node: ast.AST, value: Any, what: str) -> None:
        tags = _tags(value)
        for tag in tags:
            if isinstance(tag, tuple) and tag[0] == "param":
                self.gathered_params.add(tag[1])
        if "sharded" in tags:
            self._flag(
                node, RULE_GATHER,
                f"node-sharded state is host-materialized by {what}",
                hint="keep the drain per-shard (or route through the "
                     "sharding drain registry with a reason)",
            )

    def eval_call(self, node, env, args, keywords):
        name = dotted_name(node.func)
        last = name.rsplit(".", 1)[-1]

        # whole-pytree drain shape: jax.tree.map(materializer, X)
        if _is_tree_map(name) and node.args and _lambda_materializes(
                node.args[0]):
            if self.fn.name not in DRAIN_REGISTRY:
                self._flag(
                    node, RULE_GATHER,
                    f"`{self.fn.name}` funnels a whole pytree through "
                    "the host (tree-wide materialization)",
                    hint="drain per shard, or register the function in "
                         "sharding.DRAIN_REGISTRY with a reason",
                )
            for value in args[1:]:
                self._note_gather(node, value, f"{name}(...)")

        # direct materializer
        if name in MATERIALIZERS:
            for value in args:
                self._note_gather(node, value, f"{name}()")
            return None
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in MATERIALIZER_METHODS):
            self._note_gather(node, self.eval_expr(node.func.value, env),
                              f".{node.func.attr}()")
            return None

        # sharded entry points: spec-drift sink + tainted returns
        if last in SHARDED_STATE_PARAMS:
            for pos in SHARDED_STATE_PARAMS[last]:
                if pos < len(args) and "fresh" in _tags(args[pos]) and (
                        "sharded" not in _tags(args[pos])):
                    self._flag(
                        node, RULE_DRIFT,
                        f"freshly-built state reaches `{last}` arg "
                        f"{pos} without `shard_state` placement — the "
                        "run silently drops the P(\"node\") layout",
                        hint="place it with parallel.mesh.shard_state("
                             "mesh, n_nodes, ...) first",
                    )
            return self.returns_table.get(last)
        if last in self.returns_table:
            return self.returns_table[last]

        if last in FRESH_BUILDERS:
            return frozenset({"fresh"})

        # interprocedural: a callee that gathers one of its params
        resolved = self.project.resolve_call(node, self.fn)
        if resolved is not None:
            summary = self.summaries.get(resolved.qualname)
            if summary:
                gathers, returns = summary
                # a method's param 0 is its receiver; call-site args
                # start at param 1
                params = resolved.param_names()
                offset = 1 if (resolved.cls is not None and params
                               and params[0] == "self") else 0
                if resolved.name not in DRAIN_REGISTRY:
                    for raw in gathers:
                        i = raw - offset
                        if not 0 <= i < len(args):
                            continue
                        tags = _tags(args[i])
                        # transitive summary: OUR param flowing into a
                        # gathering callee makes US a gatherer too, so
                        # two-hop drains flag at the outermost call
                        for tag in tags:
                            if isinstance(tag, tuple) and (
                                    tag[0] == "param"):
                                self.gathered_params.add(tag[1])
                        if "sharded" in tags:
                            self._flag(
                                node, RULE_GATHER,
                                f"node-sharded state is passed to "
                                f"`{resolved.name}()` which "
                                "host-materializes it "
                                f"({resolved.path.rsplit('/', 1)[-1]})",
                                hint="drain per shard, or register the "
                                     "callee in sharding.DRAIN_REGISTRY "
                                     "with a reason",
                            )
                return returns
        return None


def _comprehension_drains(fn: FunctionInfo) -> List[ast.AST]:
    """``[np.asarray(x) for x in tree.leaves(state)]``-shaped whole-tree
    drains (the other spelling of ``_host_copy``)."""
    out: List[ast.AST] = []
    for sub in ast.walk(fn.node):
        if not isinstance(sub, (ast.ListComp, ast.GeneratorExp)):
            continue
        if not (sub.generators and isinstance(
                sub.generators[0].iter, ast.Call) and _is_leaves(
                dotted_name(sub.generators[0].iter.func))):
            continue
        for part in ast.walk(sub.elt):
            if isinstance(part, ast.Call) and dotted_name(
                    part.func) in MATERIALIZERS:
                out.append(sub)
                break
    return out


def _summarize(fn: FunctionInfo, project: Project,
               summaries: Dict[str, tuple]) -> tuple:
    run = _Analysis(fn, project, summaries, collect=False, findings=[])
    try:
        ret = run.analyze()
    except RecursionError:  # pragma: no cover - pathological nesting
        return (frozenset(), None)
    return (frozenset(run.gathered_params), _strip_params(ret))


def check_project(project: Project) -> List[Finding]:
    summaries = fixpoint(
        project, lambda fn, s: _summarize(fn, project, s))
    findings: List[Finding] = []
    for fn in project.iter_functions():
        _Analysis(fn, project, summaries, collect=True,
                  findings=findings).analyze()
        if fn.name in DRAIN_REGISTRY:
            continue
        for site in _comprehension_drains(fn):
            findings.append(Finding(
                path=fn.path, line=site.lineno, rule=RULE_GATHER,
                message=f"`{fn.name}` materializes every pytree leaf "
                        "on the host (leaves-comprehension drain)",
                hint="drain per shard, or register the function in "
                     "sharding.DRAIN_REGISTRY with a reason",
            ))
    return findings
