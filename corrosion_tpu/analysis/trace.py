"""trace-hygiene: keep jitted code jittable and retrace-free.

The PERF.md numbers assume every hot entry point compiles once and
replays; three lexically-detectable mistakes break that silently:

- **tracer-branch** — Python ``if``/``while`` on a traced argument
  inside a ``@jax.jit`` function. Best case it raises
  ``TracerBoolConversionError`` on the first call; worst case (when the
  value is concrete on some calls) it works in tests and retraces per
  value in production. Shape/dtype/None checks are static and stay
  allowed (``x.shape``, ``x.ndim``, ``x.dtype``, ``len(x)``,
  ``x is None``, ``isinstance(x, ...)``).
- **import-time-jnp** — ``jnp.*`` / ``jax.random.*`` calls in module
  scope (including argument defaults) run device work at import, before
  backends/meshes are configured — and a module first imported inside a
  trace bakes a leaked tracer into a global.
- **unhashable-static-default** — a ``static_argnums`` parameter whose
  default is a list/dict/set literal: the first defaulted call dies in
  jit's hashability check, far from the definition.

Only decorator-visible jits are analyzed (``@jax.jit``,
``@partial(jax.jit, ...)``); dynamically constructed jits are covered
by the trace-stability harness (``tracecount.py``), which counts actual
compilations of the registered hot entry points.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set, Tuple

from corrosion_tpu.analysis.base import (
    Finding,
    dotted_name,
    jit_call,
    walk_shallow,
)

RULE_BRANCH = "tracer-branch"
RULE_IMPORT = "import-time-jnp"
RULE_STATIC_DEFAULT = "unhashable-static-default"

#: attribute reads on a tracer that are static facts, safe to branch on
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
#: calls whose result is static even on tracer arguments
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type",
                 "jnp.shape", "jnp.ndim", "jnp.result_type"}
#: module prefixes whose calls do device work at import time
_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "jr.", "jax.random.")


def _static_spec(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        try:
            spec = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            continue
        if isinstance(spec, int):
            nums.add(spec)
        elif isinstance(spec, str):
            names.add(spec)
        elif isinstance(spec, (tuple, list)):
            for item in spec:
                (nums if isinstance(item, int) else names).add(
                    item if isinstance(item, int) else str(item))
    return nums, names


def _traced_params(fn, jit_call: ast.Call) -> Set[str]:
    nums, names = _static_spec(jit_call)
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    traced = {
        p for i, p in enumerate(params)
        if i not in nums and p not in names and p != "self"
    }
    # keyword-only args are traced too (static_argnums cannot reach
    # them — only static_argnames can)
    traced.update(
        a.arg for a in fn.args.kwonlyargs if a.arg not in names
    )
    return traced


class _TestScan(ast.NodeVisitor):
    """Find hazardous loads of traced params in a test expression: a
    bare Name that is not consumed by a static attribute/call."""

    def __init__(self, traced: Set[str]):
        self.traced = traced
        self.hits: List[ast.Name] = []

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _STATIC_ATTRS:
            return  # x.shape and friends are static facts
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if dotted_name(node.func) in _STATIC_CALLS:
            return
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # `x is None` / `x is not None` — identity on a tracer is a
        # static fact (the optional-argument idiom)
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            consts = [node.left] + list(node.comparators)
            if any(isinstance(c, ast.Constant) and c.value is None
                   for c in consts):
                return
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id in self.traced:
            self.hits.append(node)


def _check_jitted_fn(fn, jit_call: ast.Call, path: str,
                     findings: List[Finding]) -> None:
    traced = _traced_params(fn, jit_call)
    nums, names = _static_spec(jit_call)
    # unhashable defaults on static params
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    defaults = fn.args.defaults
    offset = len(params) - len(defaults)
    for i, default in enumerate(defaults):
        pos = offset + i
        if pos >= len(params):
            continue
        is_static = pos in nums or params[pos] in names
        if is_static and isinstance(default, (ast.List, ast.Dict, ast.Set)):
            findings.append(Finding(
                path=path, line=default.lineno, rule=RULE_STATIC_DEFAULT,
                message=f"static arg `{params[pos]}` of jitted "
                        f"`{fn.name}` defaults to an unhashable "
                        f"{type(default).__name__.lower()} literal",
                hint="use a tuple/frozenset or None-and-normalize",
            ))
    # keyword-only statics (reachable via static_argnames only)
    for arg, default in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if default is None or arg.arg not in names:
            continue
        if isinstance(default, (ast.List, ast.Dict, ast.Set)):
            findings.append(Finding(
                path=path, line=default.lineno, rule=RULE_STATIC_DEFAULT,
                message=f"static arg `{arg.arg}` of jitted `{fn.name}` "
                        f"defaults to an unhashable "
                        f"{type(default).__name__.lower()} literal",
                hint="use a tuple/frozenset or None-and-normalize",
            ))
    # Python control flow on traced values (nested defs — scan bodies —
    # are traced too, so the walk descends into them)
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            scan = _TestScan(traced)
            scan.visit(node.test)
            for hit in scan.hits:
                findings.append(Finding(
                    path=path, line=node.lineno, rule=RULE_BRANCH,
                    message=f"Python {type(node).__name__.lower()} on "
                            f"traced arg `{hit.id}` inside jitted "
                            f"`{fn.name}`",
                    hint="use jnp.where / lax.cond / lax.while_loop, or "
                         "mark the arg static",
                ))


def _module_level_device_calls(tree: ast.Module, path: str,
                               findings: List[Finding]) -> None:
    def flag_calls(node: ast.AST) -> None:
        for sub in walk_shallow(node):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted_name(sub.func)
            if name.startswith(_DEVICE_PREFIXES):
                findings.append(Finding(
                    path=path, line=sub.lineno, rule=RULE_IMPORT,
                    message=f"`{name}(...)` runs at module import time",
                    hint="build inside a function (or use a numpy "
                         "constant; np scalars don't touch the device)",
                ))

    def scan_scope(body) -> None:
        # statements that RUN at import: module body and class bodies,
        # but never function bodies
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.ClassDef):
                scan_scope(stmt.body)
                continue
            flag_calls(stmt)

    scan_scope(tree.body)
    # argument defaults evaluate at import time too, wherever the def is
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None]:
                flag_calls(default)


def check(tree: ast.AST, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    _module_level_device_calls(tree, path, findings)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            call = jit_call(dec)
            if call is not None:
                _check_jitted_fn(node, call, path, findings)
                break
    return findings
