"""corrocost collective auditor (v4, ISSUE 20): every byte that will
cross a shard boundary is declared, priced, and pinned BEFORE the
tunnel opens.

The sharded entry points (``parallel/mesh.py`` —
``SHARDED_ENTRY_POINTS``) contain no explicit collectives: GSPMD infers
every all-gather/all-reduce/collective-permute while partitioning the
donated jit. That inference is invisible at the jaxpr tier — the ONLY
place the real cross-shard traffic exists is the compiled, optimized
per-device HLO. So this module audits exactly that: it lowers the real
registered jits (static config, donation intact) on the virtual 8-way
mesh with abstract ``ShapeDtypeStruct`` arguments carrying
``NamedSharding``s — no arrays, no execution — and extracts a
**collective manifest** (op kind -> definition count, operand bytes)
from ``compiled.as_text()``.

Manifests are gated two ways:

- **kind gate** — every kind that appears must carry a reasoned entry
  in :data:`COLLECTIVE_BUDGET`; a NEW collective kind fails lint until
  argued in;
- **pin gate** — per knob combo, the manifest must match the committed
  pin **bit for bit** (definition counts AND bytes). GSPMD is
  deterministic for a fixed program: any drift means the partitioner
  started moving different bytes, which is exactly the regression this
  tier exists to catch. ``tests/test_cost.py`` proves the gate fires by
  smuggling an accidental full-table gather
  (:func:`smuggled_gather_entry`) past the same audit.

Two mesh layouts are audited: the flat 1-D ``("node",)`` mesh and the
2-D ``("dcn", "node")`` multihost mesh with the joint
``P(("dcn", "node"))`` spec. The repo's sharding contract says these
must compile to the SAME program — the audit asserts the manifests are
identical (``dcn_matches_flat``), turning a latent invariant into a
pinned one.

The static half (:func:`check_project`, the ``collective-budget`` lint
rule) runs with **no jax import**: an AST scan of the runtime surface
(``sim/``, ``ops/``, ``parallel/``, ``resilience/``) for EXPLICIT
collective spellings (``lax.psum``, ``all_gather``,
``with_sharding_constraint``, ...). Today the registry of declared
sites is EMPTY by design — all cross-shard traffic is GSPMD-inferred —
so any hand-written collective anywhere in the runtime surface fails
lint until it is declared with a reason.

CI face: ``scripts/cost_probe.py`` -> ``artifacts/cost_r20.json``
(full 16-combo knob matrix x both entries); tier-1 runs a reduced
combo set. Regenerate pins after an intentional change with::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m corrosion_tpu.analysis.collectives --regen
"""

from __future__ import annotations

import ast
import dataclasses
import functools
import math
import os
import re
from fractions import Fraction
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from corrosion_tpu.analysis.base import Finding, dotted_name
from corrosion_tpu.analysis.callgraph import Project

RULE = "collective-budget"

# --------------------------------------------------------------------------
# static half: explicit collective call sites (no jax — lint engine safe)
# --------------------------------------------------------------------------

#: qualname -> reason. EMPTY BY DESIGN: the runtime surface contains no
#: hand-written collectives — GSPMD infers all cross-shard traffic from
#: shardings, and the pinned manifests below audit what it inferred.
#: Adding an explicit ``lax.psum``/``all_gather``/
#: ``with_sharding_constraint`` site means arguing it in HERE with the
#: reason, and re-pinning the manifests it changes in the same PR.
DECLARED_COLLECTIVE_SITES: Dict[str, str] = {}

#: call spellings (last dotted component) that move or place bytes
#: across shards when traced under a mesh
COLLECTIVE_CALLS = frozenset({
    "all_gather", "psum", "pmean", "pmax", "pmin", "ppermute",
    "all_to_all", "psum_scatter", "pshuffle", "pdot", "pbroadcast",
    "axis_index_groups", "with_sharding_constraint", "reshard",
})

_SCOPES = ("/sim/", "/ops/", "/parallel/", "/resilience/")


def in_scope(path: str) -> bool:
    """The runtime surface the rule polices. Mirrors
    ``dtypes.in_scope``: nonexistent paths (lint fixtures) are always
    in scope so tests can probe the rule with blobs."""
    ap = os.path.abspath(path)
    if not os.path.exists(ap):
        return True
    return any(s in ap for s in _SCOPES)


def _call_sites(tree: ast.AST) -> List[Tuple[int, str]]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if not name:
            continue
        last = name.rsplit(".", 1)[-1]
        if last in COLLECTIVE_CALLS:
            out.append((node.lineno, last))
    return out


def check_project(project: Project) -> List[Finding]:
    """``collective-budget`` (static half): every explicit collective
    spelling in the runtime surface must be a declared, reasoned site.
    """
    findings: List[Finding] = []
    seen_funcs = set()
    for fn in project.functions.values():
        if not in_scope(fn.path):
            continue
        seen_funcs.add(id(fn.node))
        for line, call in _call_sites(fn.node):
            if fn.qualname in DECLARED_COLLECTIVE_SITES:
                continue
            findings.append(Finding(
                path=fn.path, line=line, rule=RULE,
                message=(
                    f"explicit collective `{call}` in {fn.qualname} has "
                    "no DECLARED_COLLECTIVE_SITES entry — cross-shard "
                    "traffic must be argued into the collective budget, "
                    "not smuggled"),
                hint=("declare the site with a reason in "
                      "analysis/collectives.py and re-pin the manifests "
                      "it changes (scripts/cost_probe.py)"),
            ))
    for mod in project.modules:
        if not in_scope(mod.path):
            continue
        for top in mod.tree.body:
            if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                continue  # function/method bodies handled above
            for line, call in _call_sites(top):
                findings.append(Finding(
                    path=mod.path, line=line, rule=RULE,
                    message=(
                        f"module-level collective `{call}` in "
                        f"{mod.name} — import-time cross-shard traffic "
                        "can never be budgeted"),
                    hint="move it under a declared entry point",
                ))
    return findings


# --------------------------------------------------------------------------
# manifest extraction from compiled HLO
# --------------------------------------------------------------------------

#: HLO op kinds that move bytes across shards
COLLECTIVE_HLO_KINDS = (
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "all-to-all", "collective-broadcast",
)

_KIND_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?\S+ = (\w+)\[([\d,]*)\][^ ]* ("
    + "|".join(COLLECTIVE_HLO_KINDS) + r")(-start)?\(")

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_DTSIZE = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4,
           "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "bf16": 2,
           "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1}

_PAIRS_RE = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}\}")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[(\d+)\]")


def _shape_bytes(dt: str, shape: str) -> int:
    n = 1
    for d in shape.split(","):
        if d:
            n *= int(d)
    return n * _DTSIZE.get(dt, 4)


def _line_groups(line: str) -> Optional[List[List[int]]]:
    """Replica groups / permute pairs on an HLO op line, or None when
    the line carries neither (or a form we do not parse)."""
    m = _IOTA_RE.search(line)
    if m:
        g, k, total = (int(x) for x in m.groups())
        if g * k != total or "T(" in line[m.end():m.end() + 8]:
            return None
        ids = list(range(total))
        return [ids[i * k:(i + 1) * k] for i in range(g)]
    m = _GROUPS_RE.search(line)
    if m:
        try:
            return [[int(x) for x in grp.split(",") if x.strip()]
                    for grp in m.group(1).strip("{}").split("},{")]
        except ValueError:
            return None
    m = _PAIRS_RE.search(line)
    if m:
        try:
            return [[int(x) for x in pair.split(",")]
                    for pair in m.group(1).strip("{}").split("},{")]
        except ValueError:
            return None
    return None


def manifest_from_text(txt: str, dcn_row: int = 0) -> Dict[str, List[int]]:
    """``{kind: [definition_count, operand_bytes]}`` over an optimized
    HLO module. ``-start`` halves count once; ``-done`` never counts.
    With ``dcn_row`` > 0 (devices per dcn row), a third slot counts the
    bytes whose replica groups SPAN rows — traffic the 2-D mesh would
    put on the slow axis (unparseable groups count as spanning)."""
    out: Dict[str, List[int]] = {}
    for line in txt.splitlines():
        m = _KIND_RE.match(line)
        if m is None:
            if "-done(" in line:
                continue
            # -start forms output tuples: `(f32[..], f32[..]) kind-start(`
            hit = next(
                (k for k in COLLECTIVE_HLO_KINDS if k + "-start(" in line),
                None)
            if hit is None:
                continue
            shapes = _SHAPE_RE.findall(line.split("=", 1)[0])
            b = sum(_shape_bytes(dt, sh) for dt, sh in shapes[:1])
            kind = hit
        else:
            dt, shape, kind, _ = m.groups()
            b = _shape_bytes(dt, shape)
        entry = out.setdefault(kind, [0, 0] + ([0] if dcn_row else []))
        entry[0] += 1
        entry[1] += b
        if dcn_row:
            groups = _line_groups(line)
            spans = (groups is None or any(
                len({i // dcn_row for i in g}) > 1 for g in groups))
            if spans:
                entry[2] += b
    return out


# --------------------------------------------------------------------------
# the audited entry points, knob matrix, and pinned budget
# --------------------------------------------------------------------------

#: the audit shape — ``tracecount``'s canonical small config family
AUDIT_N = 24
AUDIT_ROUNDS = 2
#: N sweep for the per-round traffic fit (single-round programs).
#: Starts at 48, NOT the audit's 24: at 3 nodes/shard the compiler
#: emits a structurally different program (even the permute
#: instruction count differs), so N=24 sits below the asymptotic
#: traffic line; for N >= 48 every kind is exactly affine (verified
#: by hand through N=384).
FIT_NS = (48, 96)
FIT_HOLDOUT_N = 192
MESH_DEVICES = 8


def audit_config(n: int = AUDIT_N, **knobs):
    from corrosion_tpu.sim.scale_step import scale_sim_config

    cfg = scale_sim_config(n, m_slots=8, n_origins=4, n_rows=4,
                           n_cols=2, sync_interval=4)
    if knobs:
        cfg = dataclasses.replace(cfg, **knobs).validate()
    return cfg


def knob_matrix() -> List[Tuple[str, Dict[str, object]]]:
    """The full 16-combo label -> knob dict sweep:
    quiet x fused(interpret) x narrow_int8 x narrow_q_int8."""
    out = []
    for quiet in ("off", "on"):
        for fused in ("off", "interpret"):
            for i8 in (False, True):
                for q8 in (False, True):
                    label = "-".join(
                        ["quiet" if quiet == "on" else "dense"]
                        + (["fused"] if fused == "interpret" else [])
                        + (["i8"] if i8 else [])
                        + (["q8"] if q8 else []))
                    out.append((label, dict(
                        quiet=quiet, fused=fused, narrow_int8=i8,
                        narrow_q_int8=q8)))
    return out


#: tier-1's reduced sweep (the probe runs the full matrix)
TIER1_LABELS = ("dense", "quiet-fused-i8-q8")


def have_mesh_devices() -> bool:
    import jax

    return len(jax.devices()) >= MESH_DEVICES


def _mesh(kind: str):
    import jax

    from corrosion_tpu.parallel import mesh as pmesh

    devs = jax.devices()[:MESH_DEVICES]
    if kind == "node":
        return pmesh.make_mesh(devs)
    if kind == "dcn,node":
        return pmesh.make_multihost_mesh(2, devs)
    raise ValueError(f"unknown mesh kind {kind!r}")


def sharded_specs(cfg, mesh, rounds: int):
    """Abstract sharded arguments: ``ShapeDtypeStruct``s carrying the
    real ``node_sharding`` specs — lowering sees exactly what
    ``device_put_shards`` would place, with zero bytes allocated."""
    import jax
    import jax.random as jr

    from corrosion_tpu.parallel.mesh import node_sharding
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
    )
    from corrosion_tpu.sim.transport import NetModel

    sp = node_sharding(mesh, cfg.n_nodes)

    def shard(tree):
        return jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=sp(a)), tree)

    st = shard(jax.eval_shape(lambda: ScaleSimState.create(cfg)))
    net = shard(jax.eval_shape(
        lambda: NetModel.create(cfg.n_nodes, drop_prob=0.05)))
    key = shard(jax.eval_shape(lambda: jr.key(0)))
    mask = jax.ShapeDtypeStruct((rounds, cfg.n_nodes), bool)
    inputs = shard(jax.eval_shape(
        lambda m: make_write_inputs(cfg, jr.key(8), rounds, m), mask))
    return st, net, key, inputs


def lower_entry(name: str, cfg, mesh, rounds: int = AUDIT_ROUNDS,
                fn: Optional[Callable] = None):
    """Compile one registered sharded entry (or an override ``fn`` with
    the ``scale_run_rounds`` signature — the mutation fixtures) against
    abstract sharded arguments. Donation and the static config travel
    exactly as the production dispatch sends them."""
    import jax

    from corrosion_tpu.parallel import mesh as pmesh

    if cfg.fused in ("on", "interpret"):
        from corrosion_tpu.ops import megakernel

        megakernel.prime_fused(cfg)  # eager probes BEFORE lowering
    st, net, key, inputs = sharded_specs(cfg, mesh, rounds)
    if fn is not None:
        jitted = jax.jit(functools.partial(fn, cfg), donate_argnums=(0,))
        return jitted.lower(st, net, key, inputs).compile()
    jitted = pmesh.SHARDED_ENTRY_POINTS[name]
    if name == "sharded_scale_run_carry":
        return jitted.lower(cfg, st, key, net, inputs).compile()
    return jitted.lower(cfg, st, net, key, inputs).compile()


def collective_manifest(name: str, label: str = "dense",
                        mesh_kind: str = "node", n: int = AUDIT_N,
                        rounds: int = AUDIT_ROUNDS,
                        fn: Optional[Callable] = None,
                        dcn_row: int = 0) -> Dict[str, List[int]]:
    knobs = dict(knob_matrix()).get(label)
    if knobs is None:
        raise KeyError(f"unknown knob combo {label!r}")
    cfg = audit_config(n, **knobs)
    comp = lower_entry(name, cfg, _mesh(mesh_kind), rounds, fn=fn)
    return manifest_from_text(comp.as_text(), dcn_row=dcn_row)


def smuggled_gather_entry(cfg, st, net, key, inputs):
    """Mutation fixture: the dense run plus an ACCIDENTAL full-table
    gather — a replicate constraint on the sharded CRDT store, the
    classic "small debug read of the whole table" mistake. The audit
    must fail its pin gate on this (tests/test_cost.py,
    scripts/cost_probe.py assert it does)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from corrosion_tpu.sim.scale_step import scale_run_rounds

    st2, infos = scale_run_rounds(cfg, st, net, key, inputs)
    mesh = _mesh("node")
    gathered = jax.tree.map(
        lambda a: jax.lax.with_sharding_constraint(
            a, NamedSharding(mesh, P())), st2.crdt.store)
    return st2._replace(crdt=st2.crdt._replace(store=gathered)), infos


# --------------------------------------------------------------------------
# the budget registry: reasoned kinds + bit-for-bit pins
# --------------------------------------------------------------------------

#: why each collective kind is allowed to exist in the lowered modules.
#: A kind absent here failing the gate is the POINT: new cross-shard
#: traffic gets argued in with a reason, or it does not ship.
COLLECTIVE_KIND_REASONS: Dict[str, str] = {
    "all-gather": (
        "GSPMD materializes row views for the cross-node reads the "
        "round genuinely needs (sync peer sampling, membership views): "
        "bounded per-lane gathers, never the CRDT store"),
    "all-reduce": (
        "node-axis reductions for round infos and convergence metrics "
        "(alive counts, needs totals) — scalar-per-round traffic"),
    "collective-permute": (
        "neighbor rotations GSPMD inserts for peer-indexed lane "
        "shuffles (ring reads of per-node lanes)"),
    "reduce-scatter": (
        "fused reduce+shard GSPMD may emit instead of "
        "all-reduce+slice for node-sharded reduction outputs"),
}

#: {entry: {label: {kind: [defs, bytes]}}} at the audit shape
#: (N=24, m_slots=8, rounds=2, 8-way mesh). Machine-generated — run
#: ``python -m corrosion_tpu.analysis.collectives --regen`` after an
#: intentional change and paste, with the PR arguing the delta.
COLLECTIVE_PINS: Dict[str, Dict[str, Dict[str, List[int]]]] = {
    "sharded_scale_run": {
        "dense": {"all-gather": [115, 75440], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-q8": {"all-gather": [115, 74576], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-i8": {"all-gather": [115, 75440], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-i8-q8": {"all-gather": [115, 74576], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-fused": {"all-gather": [50, 24608], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "dense-fused-q8": {"all-gather": [50, 24512], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "dense-fused-i8": {"all-gather": [50, 24608], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "dense-fused-i8-q8": {"all-gather": [50, 24512], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "quiet": {"all-gather": [115, 75440], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-q8": {"all-gather": [115, 74576], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-i8": {"all-gather": [115, 75440], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-i8-q8": {"all-gather": [115, 74576], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-fused": {"all-gather": [50, 24608], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
        "quiet-fused-q8": {"all-gather": [50, 24512], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
        "quiet-fused-i8": {"all-gather": [50, 24608], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
        "quiet-fused-i8-q8": {"all-gather": [50, 24512], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
    },
    "sharded_scale_run_carry": {
        "dense": {"all-gather": [115, 75440], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-q8": {"all-gather": [115, 74576], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-i8": {"all-gather": [115, 75440], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-i8-q8": {"all-gather": [115, 74576], "all-reduce": [63, 15399], "collective-permute": [135, 3298]},
        "dense-fused": {"all-gather": [50, 24608], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "dense-fused-q8": {"all-gather": [50, 24512], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "dense-fused-i8": {"all-gather": [50, 24608], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "dense-fused-i8-q8": {"all-gather": [50, 24512], "all-reduce": [53, 10391], "collective-permute": [131, 3228]},
        "quiet": {"all-gather": [115, 75440], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-q8": {"all-gather": [115, 74576], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-i8": {"all-gather": [115, 75440], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-i8-q8": {"all-gather": [115, 74576], "all-reduce": [85, 15778], "collective-permute": [153, 3418]},
        "quiet-fused": {"all-gather": [50, 24608], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
        "quiet-fused-q8": {"all-gather": [50, 24512], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
        "quiet-fused-i8": {"all-gather": [50, 24608], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
        "quiet-fused-i8-q8": {"all-gather": [50, 24512], "all-reduce": [75, 10770], "collective-permute": [149, 3348]},
    },
}

COLLECTIVE_BUDGET = {
    "sharded_scale_run": {
        "kinds": COLLECTIVE_KIND_REASONS,
        "pins": COLLECTIVE_PINS.get("sharded_scale_run", {}),
    },
    "sharded_scale_run_carry": {
        "kinds": COLLECTIVE_KIND_REASONS,
        "pins": COLLECTIVE_PINS.get("sharded_scale_run_carry", {}),
    },
}


def check_manifest(entry: str, label: str,
                   man: Dict[str, List[int]]) -> List[str]:
    """Kind gate + bit-for-bit pin gate; returns problem strings."""
    problems: List[str] = []
    budget = COLLECTIVE_BUDGET[entry]
    for kind in sorted(man):
        if kind not in budget["kinds"]:
            problems.append(
                f"{entry}/{label}: collective kind `{kind}` has no "
                "reasoned COLLECTIVE_KIND_REASONS entry")
    pin = budget["pins"].get(label)
    if pin is None:
        problems.append(f"{entry}/{label}: no committed pin")
        return problems
    got = {k: list(v[:2]) for k, v in man.items()}
    want = {k: list(v[:2]) for k, v in pin.items()}
    if got != want:
        problems.append(
            f"{entry}/{label}: manifest drifted — got {got}, "
            f"pinned {want}")
    return problems


def audit_entry(entry: str,
                labels: Optional[Sequence[str]] = None,
                mesh_kinds: Sequence[str] = ("node", "dcn,node")) -> dict:
    """Audit one entry across combos and mesh layouts. The flat and 2-D
    manifests must be identical (same program — the sharding contract);
    pins are stored once and gate both."""
    labels = list(labels or [lb for lb, _ in knob_matrix()])
    rec = {"entry": entry, "labels": {}, "problems": []}
    for label in labels:
        flat = collective_manifest(entry, label, "node")
        lrec = {"manifest": {k: list(v) for k, v in sorted(flat.items())}}
        if "dcn,node" in mesh_kinds:
            dcn = collective_manifest(entry, label, "dcn,node",
                                      dcn_row=MESH_DEVICES // 2)
            lrec["dcn_matches_flat"] = (
                {k: v[:2] for k, v in dcn.items()}
                == {k: list(v) for k, v in flat.items()})
            lrec["dcn_cross_row_bytes"] = {
                k: v[2] for k, v in sorted(dcn.items())}
            if not lrec["dcn_matches_flat"]:
                rec["problems"].append(
                    f"{entry}/{label}: 2-D (dcn,node) mesh compiled a "
                    "DIFFERENT collective manifest than the flat mesh")
        probs = check_manifest(entry, label, flat)
        lrec["pin_ok"] = not probs
        rec["problems"].extend(probs)
        rec["labels"][label] = lrec
    return rec


# --------------------------------------------------------------------------
# per-round traffic fit and 1M projection
# --------------------------------------------------------------------------


def per_round_manifest(entry: str = "sharded_scale_run",
                       label: str = "dense",
                       n: int = AUDIT_N) -> Dict[str, List[int]]:
    """The SINGLE-round program's manifest: a static per-round upper
    bound on cross-shard traffic (loop-body collectives execute once
    per round; boundary collectives are amortized upper-bounded)."""
    return collective_manifest(entry, label, "node", n=n, rounds=1)


def collective_fit(entry: str = "sharded_scale_run",
                   label: str = "dense") -> dict:
    """Per-kind polynomial fit of single-round collective BYTES in N
    over :data:`FIT_NS`, holdout-verified at :data:`FIT_HOLDOUT_N`,
    projected to the 1M point. Affine first (exact holdout required);
    quadratic fallback through all three N when traffic is genuinely
    superlinear (recorded — the roofline then says so)."""
    ns = list(FIT_NS) + [FIT_HOLDOUT_N]
    mans = {n: per_round_manifest(entry, label, n) for n in ns}
    kinds = sorted({k for m in mans.values() for k in m})
    out = {"entry": entry, "label": label, "ns": ns, "kinds": {},
           "projected_1m_bytes": 0}
    for kind in kinds:
        ys = {n: mans[n].get(kind, [0, 0])[1] for n in ns}
        n1, n2 = FIT_NS
        b = Fraction(ys[n2] - ys[n1], n2 - n1)
        a = Fraction(ys[n1]) - b * n1
        exact = a + b * FIT_HOLDOUT_N == ys[FIT_HOLDOUT_N]
        if exact:
            proj = a + b * 1_000_000
            rec = {"poly": f"{a} + {b}*N", "degree": 1, "exact": True}
        else:
            # quadratic through all three points — no holdout left, so
            # the projection is flagged as unverified extrapolation
            x1, x2, x3 = ns
            d = Fraction(
                (ys[x3] - ys[x1]) * (x2 - x1)
                - (ys[x2] - ys[x1]) * (x3 - x1),
                (x3 - x1) * (x3 - x2) * (x2 - x1))
            b2 = Fraction(ys[x2] - ys[x1], x2 - x1) - d * (x1 + x2)
            a2 = Fraction(ys[x1]) - b2 * x1 - d * x1 * x1
            proj = a2 + b2 * 1_000_000 + d * 1_000_000 ** 2
            rec = {"poly": f"{a2} + {b2}*N + {d}*N^2", "degree": 2,
                   "exact": False}
        rec["bytes_at"] = {str(n): ys[n] for n in ns}
        rec["projected_1m"] = int(proj)
        out["kinds"][kind] = rec
        out["projected_1m_bytes"] += int(proj)
    out["all_exact"] = all(r["exact"] for r in out["kinds"].values())
    return out


def projected_collective_bytes(cfg, mesh, entry_fn=None,
                               rounds: int = 1) -> Optional[int]:
    """Per-round cross-shard bytes of a LIVE run's program (the bench
    ``collective_bytes_per_round`` field): lower the measured config on
    the measured mesh for one round and sum the manifest. Returns None
    when lowering fails (e.g. exotic backends) — provenance degrades,
    benches never crash."""
    import jax

    from corrosion_tpu.sim.scale_step import scale_run_rounds

    try:
        if cfg.fused in ("on", "interpret"):
            from corrosion_tpu.ops import megakernel

            megakernel.prime_fused(cfg)
        st, net, key, inputs = sharded_specs(cfg, mesh, rounds)
        fn = entry_fn or scale_run_rounds
        comp = jax.jit(functools.partial(fn, cfg),
                       donate_argnums=(0,)).lower(
            st, net, key, inputs).compile()
        man = manifest_from_text(comp.as_text())
        return sum(v[1] for v in man.values())
    except Exception:
        return None


def _regen(entries=("sharded_scale_run", "sharded_scale_run_carry"),
           labels: Optional[Sequence[str]] = None) -> str:
    """Print the COLLECTIVE_PINS literal for the current tree."""
    labels = list(labels or [lb for lb, _ in knob_matrix()])
    lines = ["COLLECTIVE_PINS: Dict[str, Dict[str, Dict[str, "
             "List[int]]]] = {"]
    for entry in entries:
        lines.append(f'    "{entry}": {{')
        for label in labels:
            man = collective_manifest(entry, label, "node")
            body = ", ".join(
                f'"{k}": {list(v)}' for k, v in sorted(man.items()))
            lines.append(f'        "{label}": {{{body}}},')
        lines.append("    },")
    lines.append("}")
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover - maintenance CLI
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--regen", action="store_true")
    ap.add_argument("--labels", default=None,
                    help="comma-separated combo labels (default: all)")
    args = ap.parse_args()
    if args.regen:
        labels = args.labels.split(",") if args.labels else None
        print(_regen(labels=labels))
