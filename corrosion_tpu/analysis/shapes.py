"""corrobudget: symbolic shape/memory abstract interpreter (tier 3).

The ROADMAP's million-node flagship opens with a question PR 10's
``obs/memory.py`` answers only at RUNTIME: *which tables of
``ScaleSimState`` are O(N·M) vs O(N), and what do they cost at N=1M?*
Nothing stopped a PR from landing a new O(N·M) table, a silent dtype
widening, or an N×N trace-time intermediate that fits at 100k and OOMs
at 1M. corrobudget closes that gap the way the reference's CR-SQLite
clock tables make CRDT storage cost a schema-level, statically-knowable
quantity (PAPER.md §1): the state *constructors* are the schema, so the
HBM bill is decidable at lint time.

Built on the PR-6 dataflow engine (:class:`ForwardAnalysis`), this
module interprets the state constructors in ``sim/scale.py`` /
``sim/scale_step.py`` / ``sim/step.py`` (and their ``ops/`` table
classes) with **symbolic shapes**: every dimension is a polynomial in
the ``ScaleSimConfig`` extents —

    N = n_nodes      M = m_slots      Q = bcast_queue   O = n_origins
    C = n_cells      B = buf_slots    P = partial_slots K = tx_max_cells

From the interpretation come three deliverables:

- a **static table inventory** (:func:`build_inventory`): every
  ``ScaleSimState``/``SimState`` leaf with symbolic shape, dtype and
  projected nbytes at arbitrary (N, M) — cross-checked leaf-for-leaf
  against the runtime ``obs/memory.py`` audit and ``jax.eval_shape``
  ground truth by ``tests/test_membudget.py``;
- the **``mem-budget`` rule** (:func:`check_budget`): evaluates the
  walked tree's OWN constructor ASTs at the declared N=1M point
  (:data:`HBM_BUDGET`) and fails lint when a PR's projection exceeds a
  per-complexity-class budget;
- the **``densify`` rule** (:func:`check_densify`): flags trace-time
  intermediates whose N-degree exceeds every input's (the N×N pairwise
  broadcast), with the usual reasoned-suppression pipeline.

Projection methodology, the declared budget, and the ranked offender
table live in ``docs/memory-budget.md``. The per-leaf complexity
classification is shared with the runtime audit through
``obs.memory.classify_leaf`` — one source, two enforcement planes.

Like the rest of the analysis package this module never imports jax:
the interpreter runs on ASTs and arithmetic only, which is also why it
can project past runtime walls (the sender-election packing is now
adaptive-width, so ``ScaleConfig.validate`` admits the 1M flagship
point and only refuses N > 2^30; the budget gate prices N=1M either
way).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Dict, List, Optional, Tuple

from corrosion_tpu.analysis.base import Finding, dotted_name
from corrosion_tpu.analysis.callgraph import (
    FunctionInfo,
    ModuleInfo,
    Project,
    module_name_for,
)
from corrosion_tpu.analysis.dataflow import Env, ForwardAnalysis, TupleVal
from corrosion_tpu.obs.memory import classify_leaf

BUDGET_RULE = "mem-budget"
DENSIFY_RULE = "densify"

# --- symbolic integers ----------------------------------------------------


class Poly:
    """Integer polynomial over the config extents: ``{monomial: coeff}``
    with each monomial a sorted tuple of symbol names (with repetition,
    so N·M is ``("M", "N")`` and N² is ``("N", "N")``)."""

    __slots__ = ("terms",)

    def __init__(self, terms: Dict[Tuple[str, ...], int]):
        self.terms = {m: c for m, c in terms.items() if c}

    @staticmethod
    def const(c: int) -> "Poly":
        return Poly({(): int(c)})

    @staticmethod
    def var(name: str) -> "Poly":
        return Poly({(name,): 1})

    def __add__(self, other):
        if isinstance(other, int):
            other = Poly.const(other)
        if not isinstance(other, Poly):
            return SymOp("add", (self, other))
        out = dict(self.terms)
        for m, c in other.terms.items():
            out[m] = out.get(m, 0) + c
        return Poly(out)

    def __neg__(self):
        return Poly({m: -c for m, c in self.terms.items()})

    def __sub__(self, other):
        if isinstance(other, int):
            other = Poly.const(other)
        if not isinstance(other, Poly):
            return SymOp("sub", (self, other))
        return self + (-other)

    def __mul__(self, other):
        if isinstance(other, int):
            other = Poly.const(other)
        if not isinstance(other, Poly):
            return SymOp("mul", (self, other))
        out: Dict[Tuple[str, ...], int] = {}
        for ma, ca in self.terms.items():
            for mb, cb in other.terms.items():
                mono = tuple(sorted(ma + mb))
                out[mono] = out.get(mono, 0) + ca * cb
        return Poly(out)

    def evaluate(self, env: Dict[str, int]) -> int:
        total = 0
        for mono, c in self.terms.items():
            v = c
            for s in mono:
                v *= env[s]  # KeyError = missing binding, caller handles
            total += v
        return total

    def degree(self, name: str) -> int:
        return max((m.count(name) for m in self.terms), default=0)

    def is_const(self) -> bool:
        return all(m == () for m in self.terms)

    def render(self) -> str:
        if not self.terms:
            return "0"
        parts = []
        for mono, c in sorted(self.terms.items(),
                              key=lambda kv: (-len(kv[0]), kv[0])):
            body = "*".join(mono)
            if not mono:
                parts.append(str(c))
            elif c == 1:
                parts.append(body)
            else:
                parts.append(f"{c}*{body}")
        return " + ".join(parts)

    def __eq__(self, other):
        return isinstance(other, Poly) and self.terms == other.terms

    def __hash__(self):
        return hash(frozenset(self.terms.items()))

    def __repr__(self):
        return f"Poly({self.render()})"


_OP_EVAL = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "floordiv": lambda a, b: a // b,
    "mod": lambda a, b: a % b,
    "max": max,
    "min": min,
    "neg": lambda a: -a,
}


class SymOp:
    """Opaque symbolic integer (``max``/``min``/``//``/``%``/mixed
    arithmetic) — still evaluable and degree-bounded, just not a
    polynomial normal form."""

    __slots__ = ("op", "args")

    def __init__(self, op: str, args):
        self.op = op
        self.args = tuple(
            Poly.const(a) if isinstance(a, int) else a for a in args
        )

    def evaluate(self, env: Dict[str, int]) -> int:
        return _OP_EVAL[self.op](*(a.evaluate(env) for a in self.args))

    def degree(self, name: str) -> int:
        degs = [a.degree(name) for a in self.args]
        if self.op in ("floordiv", "mod"):
            # //k keeps the numerator's growth; %k is bounded by the
            # divisor, which carries its own degree
            return degs[0] if self.op == "floordiv" else (
                self.args[1].degree(name))
        return max(degs, default=0)

    def render(self) -> str:
        inner = ", ".join(sym_render(a) for a in self.args)
        if self.op in ("max", "min"):
            return f"{self.op}({inner})"
        if self.op == "neg":
            return f"-({sym_render(self.args[0])})"
        sign = {"add": "+", "sub": "-", "mul": "*", "floordiv": "//",
                "mod": "%"}[self.op]
        return f"({sym_render(self.args[0])} {sign} "\
               f"{sym_render(self.args[1])})"

    def __eq__(self, other):
        return (isinstance(other, SymOp) and self.op == other.op
                and self.args == other.args)

    def __hash__(self):
        return hash((self.op, self.args))

    def __repr__(self):
        return f"SymOp({self.render()})"


def is_sym(v) -> bool:
    return isinstance(v, (Poly, SymOp))


def sym_render(v) -> str:
    return v.render() if is_sym(v) else str(v)


def sym_eval(v, env: Dict[str, int]) -> Optional[int]:
    try:
        return v.evaluate(env)
    except KeyError:
        return None


def sym_binop(op: str, a, b):
    if isinstance(a, int):
        a = Poly.const(a)
    if isinstance(b, int):
        b = Poly.const(b)
    if not (is_sym(a) and is_sym(b)):
        return None
    if isinstance(a, Poly) and isinstance(b, Poly):
        if op == "add":
            return a + b
        if op == "sub":
            return a - b
        if op == "mul":
            return a * b
    if op in _OP_EVAL:
        return SymOp(op, (a, b))
    return None


# --- abstract values ------------------------------------------------------

_DTYPE_SIZES = {
    "bool": 1, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
    "bfloat16": 2, "float16": 2, "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8,
}

#: dotted spellings that denote a concrete dtype in this codebase
_DTYPE_BASES = ("jnp", "np", "numpy", "jax.numpy")


class DtypeVal:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = "bool" if name == "bool_" else name

    def __eq__(self, other):
        return isinstance(other, DtypeVal) and self.name == other.name

    def __hash__(self):
        return hash(("dtype", self.name))

    def __repr__(self):
        return f"DtypeVal({self.name})"


class BoolVal:
    __slots__ = ("value",)

    def __init__(self, value: bool):
        self.value = bool(value)

    def __eq__(self, other):
        return isinstance(other, BoolVal) and self.value == other.value

    def __hash__(self):
        return hash(("bool", self.value))

    def __repr__(self):
        return f"BoolVal({self.value})"


class ArrayVal:
    """Abstract array: symbolic dims + dtype + creation site. A dim may
    be ``None`` (unknown) — such arrays grow no budget/densify facts."""

    __slots__ = ("dims", "dtype", "site")

    def __init__(self, dims, dtype: Optional[str],
                 site: Optional[Tuple[str, int]] = None):
        self.dims = tuple(dims)
        self.dtype = dtype
        self.site = site

    def known(self) -> bool:
        return all(d is not None for d in self.dims)

    def key(self):
        return (tuple(sym_render(d) if d is not None else "?"
                      for d in self.dims), self.dtype)

    def __eq__(self, other):
        return isinstance(other, ArrayVal) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        dims = ", ".join(sym_render(d) if d is not None else "?"
                         for d in self.dims)
        return f"ArrayVal([{dims}], {self.dtype})"


class StructVal:
    """Abstract NamedTuple state: field name -> abstract value, ordered
    by the class definition (so flattening matches the runtime walk)."""

    __slots__ = ("cls_name", "field_order", "fields")

    def __init__(self, cls_name: str, field_order, fields: Dict[str, Any]):
        self.cls_name = cls_name
        self.field_order = tuple(field_order)
        self.fields = fields

    def replace(self, updates: Dict[str, Any]) -> "StructVal":
        out = dict(self.fields)
        out.update(updates)
        return StructVal(self.cls_name, self.field_order, out)

    def __eq__(self, other):
        return (isinstance(other, StructVal)
                and self.cls_name == other.cls_name
                and self.fields == other.fields)

    def __hash__(self):
        return hash(self.cls_name)

    def __repr__(self):
        return f"StructVal({self.cls_name})"


class LambdaVal:
    """A local ``lambda`` with its definition-time environment — the
    ``z = lambda *s: jnp.zeros(s, jnp.int32)`` constructor idiom."""

    __slots__ = ("node", "env")

    def __init__(self, node: ast.Lambda, env: Env):
        self.node = node
        self.env = dict(env)


class AtVal:
    """``arr.at[...]`` chain marker: ``.set/.add/.max/...`` returns the
    base array's shape unchanged."""

    __slots__ = ("array",)

    def __init__(self, array: ArrayVal):
        self.array = array


class ClassRef:
    __slots__ = ("info",)

    def __init__(self, info: "ClassInfo"):
        self.info = info


class FnRef:
    __slots__ = ("fn",)

    def __init__(self, fn: FunctionInfo):
        self.fn = fn


# --- config abstraction ---------------------------------------------------

#: config attr -> shape symbol (the polynomial variables)
SYMBOLS: Dict[str, str] = {
    "n_nodes": "N",
    "m_slots": "M",
    "bcast_queue": "Q",
    "n_origins": "O",
    "buf_slots": "B",
    "partial_slots": "P",
    "tx_max_cells": "K",
}
#: derived properties that get their own symbol (bound from the live
#: property value)
PROPERTY_SYMBOLS: Dict[str, str] = {"n_cells": "C"}

#: the lint gate's template extents: the FLAGSHIP scale config
#: (``scale_sim_config(100_000)`` — ``tests/test_membudget.py``'s
#: registry-sync meta-test pins these against the real dataclass, so
#: they cannot drift silently)
DEFAULT_EXTENTS: Dict[str, int] = {
    "N": 100_000, "M": 64, "Q": 32, "O": 16, "B": 32, "P": 8, "K": 1,
    "C": 64,
}
#: flagship structure flags (same meta-test pins them)
DEFAULT_FLAGS: Dict[str, bool] = {
    "narrow_dtypes": True,
    "narrow_int8": False,
    "narrow_q_int8": False,
    "any_writer": True,
}

#: The declared 1M budget (docs/memory-budget.md): per-complexity-class
#: HBM bytes for one replica of the scale state at N=1M, M=64 under the
#: flagship dtype set. Current audited footprint: 3648 B/node O(N·M),
#: 53 B/node O(N) — the headroom (~52 B/node O(N·M)) is deliberately
#: smaller than one int32 [N, M] plane (256 B/node), so landing a new
#: full-width table without re-pricing the budget FAILS the gate.
HBM_BUDGET: Dict[str, Any] = {
    "root": "ScaleSimState",
    "point": {"N": 1_000_000, "M": 64},
    "per_class_bytes": {
        "O(N*M)": 3_700_000_000,
        "O(N)": 64_000_000,
        "O(1)": 1_000_000,
    },
}


class ConfigVal:
    """Abstract sim config: extent attrs evaluate to their polynomial
    symbols (with a concrete binding for branch decisions and budget
    evaluation), bool fields to concrete :class:`BoolVal`, dtype
    properties to the dtype the real property would pick."""

    __slots__ = ("bindings", "flags", "extras", "sync_tracks_sym")

    def __init__(self, bindings: Dict[str, int], flags: Dict[str, bool],
                 extras: Optional[Dict[str, int]] = None,
                 sync_tracks_sym: str = "M"):
        self.bindings = dict(bindings)
        self.flags = dict(flags)
        self.extras = dict(extras or {})
        self.sync_tracks_sym = sync_tracks_sym

    @staticmethod
    def default() -> "ConfigVal":
        return ConfigVal(DEFAULT_EXTENTS, DEFAULT_FLAGS)

    @staticmethod
    def from_config(cfg) -> "ConfigVal":
        """Bindings from a live dataclass config (obs/CLI projection
        path). ``sync_tracks`` follows the class's own property: the
        full-view sim tracks per peer id (N), the scale sim per member
        slot (M)."""
        bindings: Dict[str, int] = {}
        extras: Dict[str, int] = {}
        flags: Dict[str, bool] = {}
        for field in dataclasses.fields(cfg):
            v = getattr(cfg, field.name)
            if isinstance(v, bool):
                flags[field.name] = v
            elif isinstance(v, int):
                if field.name in SYMBOLS:
                    bindings[SYMBOLS[field.name]] = v
                else:
                    extras[field.name] = v
        for prop, symbol in PROPERTY_SYMBOLS.items():
            if hasattr(cfg, prop):
                bindings[symbol] = int(getattr(cfg, prop))
        sync_sym = "N" if type(cfg).__name__ == "SimConfig" else "M"
        flags.setdefault("narrow_dtypes", False)
        flags.setdefault("narrow_int8", False)
        flags.setdefault("narrow_q_int8", False)
        return ConfigVal(bindings, flags, extras, sync_tracks_sym=sync_sym)

    def has(self, name: str) -> bool:
        return (name in SYMBOLS or name in PROPERTY_SYMBOLS
                or name in self.flags or name in self.extras
                or name in ("sync_tracks", "timer_dtype", "tx_dtype",
                            "q_dtype"))

    def attr(self, name: str):
        if name in SYMBOLS:
            return Poly.var(SYMBOLS[name])
        if name in PROPERTY_SYMBOLS:
            return Poly.var(PROPERTY_SYMBOLS[name])
        if name == "sync_tracks":
            return Poly.var(self.sync_tracks_sym)
        if name == "timer_dtype":
            # mirrors ScaleConfig/ScaleSimConfig.timer_dtype
            return DtypeVal(
                "int16" if self.flags.get("narrow_dtypes") else "int32")
        if name == "tx_dtype":
            # mirrors ScaleConfig/ScaleSimConfig.tx_dtype (ISSUE 12
            # int8 shrink): int8 budget planes under narrow_int8
            if self.flags.get("narrow_int8"):
                return DtypeVal("int8")
            return self.attr("timer_dtype")
        if name == "q_dtype":
            # mirrors ScaleSimConfig.q_dtype (ISSUE 19 int8 tier):
            # int8 q_tx/q_seq/q_nseq counter planes under narrow_q_int8
            if self.flags.get("narrow_q_int8"):
                return DtypeVal("int8")
            return self.attr("timer_dtype")
        if name in self.flags:
            return BoolVal(self.flags[name])
        if name in self.extras:
            return Poly.const(self.extras[name])
        return None


# --- class index ----------------------------------------------------------


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: ModuleInfo
    node: ast.ClassDef
    fields: Tuple[str, ...]  # AnnAssign field order (NamedTuple schema)


def _class_has_create(node: ast.ClassDef) -> bool:
    return any(isinstance(b, (ast.FunctionDef, ast.AsyncFunctionDef))
               and b.name == "create" for b in node.body)


def index_classes(project: Project) -> Dict[str, ClassInfo]:
    """Top-level classes with annotated fields, keyed by bare name. A
    name defined in several modules keeps the first *state-like* one
    (has a ``create`` — checked on the class body itself, NOT the
    project-wide (class, method) table, which can't tell two same-named
    classes apart) — precision over recall, same as call resolution."""
    out: Dict[str, ClassInfo] = {}
    for mod in project.modules:
        for top in mod.tree.body:
            if not isinstance(top, ast.ClassDef):
                continue
            fields = tuple(
                t.target.id for t in top.body
                if isinstance(t, ast.AnnAssign)
                and isinstance(t.target, ast.Name)
            )
            if not fields:
                continue
            if top.name in out:
                if (_class_has_create(out[top.name].node)
                        or not _class_has_create(top)):
                    continue
            out[top.name] = ClassInfo(top.name, mod, top, fields)
    return out


# --- the interpreter ------------------------------------------------------

_CREATION_FNS = {"zeros", "ones", "empty", "full"}
_LIKE_FNS = {"zeros_like", "ones_like", "full_like", "empty_like"}
_ELEMENTWISE_FNS = {
    "where", "minimum", "maximum", "add", "multiply", "remainder", "mod",
    "power", "clip", "floor_divide", "bitwise_and", "bitwise_or",
    "bitwise_xor", "logical_and", "logical_or", "logical_not", "equal",
    "not_equal", "abs", "negative", "sign", "astype",
}
_PASS_FIRST_FNS = {"clip", "abs", "negative", "sign", "sort", "flip",
                   "roll", "cumsum", "asarray", "optimization_barrier",
                   "stop_gradient"}
_REDUCTION_FNS = {"sum", "prod", "max", "min", "any", "all", "mean",
                  "argmax", "argmin", "count_nonzero"}
_AT_METHODS = {"set", "add", "max", "min", "mul", "divide", "power",
               "apply", "or_", "and_"}

#: shape summaries for the dense-op helpers the step bodies lean on —
#: a registry, not interpretation: their bodies are backend-conditional
#: (``ops/dense.py``) and their SHAPES are contractual
_HELPER_SHAPES = {
    # (table, idx, ...) -> idx-shaped gather of table values
    "select_cols": "gather",
    "lookup_cols": "gather",
    # (dest, idx, vals, valid) -> dest-shaped scatter
    "scatter_cols_set": "dest",
    "scatter_cols_max": "dest",
    "scatter_cols_add": "dest",
    "scatter_cols_or": "dest",
    # (mask, k, key) -> ([N, k] int32 slots, [N, k] bool ok)
    "sample_k": "sample_k",
    # (mask, weight, k, key) -> same
    "sample_k_biased": "sample_k_biased",
    # (mask, key) -> ([N] int32, [N] bool)
    "sample_one": "sample_one",
    # (card, idx) -> idx.shape + card.shape[1:]
    "card_at": "card_at",
    # (a, b) -> broadcast int32
    "pack_inc_state": "pack_int32",
}


class ShapeContext:
    """Shared interpretation state: project, class index, bindings for
    branch decisions, call stack, per-class inventory cache."""

    def __init__(self, project: Project, config: ConfigVal,
                 interprocedural: bool = True):
        self.project = project
        self.classes = index_classes(project)
        self.config = config
        self.interprocedural = interprocedural
        self.stack: List[str] = []
        self.struct_cache: Dict[str, Any] = {}

    def bindings(self) -> Dict[str, int]:
        return self.config.bindings


class ShapeAnalysis(ForwardAnalysis):
    """Forward shape interpretation of one function body."""

    def __init__(self, ctx: ShapeContext, fn: Optional[FunctionInfo],
                 path: str, findings: Optional[List[Finding]] = None,
                 densify: bool = False, depth: int = 0):
        super().__init__(fn, path, findings)
        self.ctx = ctx
        self.densify = densify
        self.depth = depth

    # -- joins -------------------------------------------------------------

    def join(self, a, b):
        if isinstance(a, ArrayVal) and isinstance(b, ArrayVal):
            return a if a == b else None
        if isinstance(a, StructVal) and isinstance(b, StructVal) and (
                a.cls_name == b.cls_name):
            fields = {
                f: self.join(a.fields.get(f), b.fields.get(f))
                for f in set(a.fields) | set(b.fields)
            }
            return StructVal(a.cls_name, a.field_order, fields)
        if is_sym(a) and is_sym(b):
            return a if sym_render(a) == sym_render(b) else None
        return super().join(a, b)

    # -- leaves ------------------------------------------------------------

    def eval_constant(self, node, env):
        if isinstance(node.value, bool):
            return BoolVal(node.value)
        if isinstance(node.value, int):
            return Poly.const(node.value)
        if isinstance(node.value, str):
            return node.value
        return None

    def eval_expr(self, node, env):
        if isinstance(node, ast.Name) and node.id not in env:
            if node.id == "bool":
                return DtypeVal("bool")
            if node.id in self.ctx.classes:
                return ClassRef(self.ctx.classes[node.id])
            return None
        if isinstance(node, ast.Compare):
            return self._eval_compare(node, env)
        if isinstance(node, ast.IfExp):
            test = self.eval_expr(node.test, env)
            if isinstance(test, BoolVal):
                return self.eval_expr(
                    node.body if test.value else node.orelse, env)
            return self.join(self.eval_expr(node.body, env),
                             self.eval_expr(node.orelse, env))
        if isinstance(node, ast.UnaryOp):
            v = self.eval_expr(node.operand, env)
            if isinstance(node.op, ast.Not):
                return BoolVal(not v.value) if isinstance(v, BoolVal) \
                    else None
            if isinstance(node.op, ast.USub):
                if isinstance(v, Poly):
                    return -v
                if isinstance(v, SymOp):
                    return SymOp("neg", (v,))
                return v if isinstance(v, ArrayVal) else None
            return v
        if isinstance(node, ast.Lambda):
            self.on_nested_def(node, env)
            return LambdaVal(node, env)
        return super().eval_expr(node, env)

    def _eval_compare(self, node: ast.Compare, env):
        vals = [self.eval_expr(node.left, env)] + [
            self.eval_expr(c, env) for c in node.comparators
        ]
        arrays = [v for v in vals if isinstance(v, ArrayVal)]
        if arrays:
            out = self._broadcast(vals, "bool", node)
            self._check_dense(node, out, vals)
            return out
        # concrete decision for config-extent guards (branch picking)
        concrete = []
        for v in vals:
            if isinstance(v, BoolVal):
                concrete.append(int(v.value))
                continue
            if not is_sym(v):
                return None
            ev = sym_eval(v, self.ctx.bindings())
            if ev is None:
                return None
            concrete.append(ev)
        ok = True
        for op, a, b in zip(node.ops, concrete, concrete[1:]):
            table = {
                ast.Lt: a < b, ast.LtE: a <= b, ast.Gt: a > b,
                ast.GtE: a >= b, ast.Eq: a == b, ast.NotEq: a != b,
            }
            res = table.get(type(op))
            if res is None:
                return None
            ok = ok and res
        return BoolVal(ok)

    # -- attributes / subscripts -------------------------------------------

    def eval_attr(self, node, base, env):
        name = node.attr
        if isinstance(base, ConfigVal):
            return base.attr(name)
        if isinstance(base, StructVal):
            return base.fields.get(name)
        if isinstance(base, ArrayVal):
            if name == "at":
                return AtVal(base)
            if name == "shape":
                return TupleVal(base.dims)
            if name == "dtype":
                return DtypeVal(base.dtype) if base.dtype else None
            if name == "T":
                return ArrayVal(tuple(reversed(base.dims)), base.dtype,
                                base.site)
            if name == "ndim":
                return Poly.const(len(base.dims))
            if name == "size":
                out = Poly.const(1)
                for d in base.dims:
                    if d is None:
                        return None
                    out = sym_binop("mul", out, d)
                return out
            return None
        if isinstance(base, ClassRef):
            cands = self.ctx.project.methods.get((base.info.name, name), [])
            own = [c for c in cands if c.module is base.info.module]
            if len(own) == 1:
                return FnRef(own[0])
            return FnRef(cands[0]) if len(cands) == 1 else None
        # dtype literal spellings (jnp.int32, np.uint8, ...)
        dotted = dotted_name(node)
        if "." in dotted:
            head, leaf = dotted.rsplit(".", 1)
            canon = "bool" if leaf == "bool_" else leaf
            if head in _DTYPE_BASES and canon in _DTYPE_SIZES:
                return DtypeVal(canon)
        return None

    def eval_subscript(self, node, base, env):
        if isinstance(base, AtVal):
            return base  # .at[ix] keeps the base shape for the updater
        if isinstance(base, ArrayVal):
            return self._index(node, base, env)
        return super().eval_subscript(node, base, env)

    def _index(self, node: ast.Subscript, base: ArrayVal, env):
        elts = (list(node.slice.elts)
                if isinstance(node.slice, ast.Tuple) else [node.slice])
        out_dims: List[Any] = []
        adv: List[ArrayVal] = []
        adv_pos: Optional[int] = None
        dim_i = 0
        for elt in elts:
            if isinstance(elt, ast.Slice):
                if dim_i >= len(base.dims):
                    return None
                out_dims.append(self._slice_dim(elt, base.dims[dim_i], env))
                dim_i += 1
                continue
            if isinstance(elt, ast.Constant) and elt.value is None:
                out_dims.append(Poly.const(1))  # newaxis
                continue
            v = self.eval_expr(elt, env)
            if dim_i >= len(base.dims):
                return None
            if isinstance(v, ArrayVal):
                if v.dims == ():
                    dim_i += 1  # scalar-array index drops the dim
                    continue
                if adv_pos is None:
                    adv_pos = len(out_dims)
                adv.append(v)
                dim_i += 1
                continue
            if is_sym(v) or isinstance(elt, ast.Constant):
                dim_i += 1  # integer index drops the dim
                continue
            return None  # unknown index form
        out_dims.extend(base.dims[dim_i:])
        if adv:
            bc = self._broadcast_dims([a.dims for a in adv])
            if bc is None:
                return None
            out_dims[adv_pos:adv_pos] = list(bc)
        out = ArrayVal(tuple(out_dims), base.dtype, base.site)
        self._check_dense(node, out, [base] + adv)
        return out

    def _slice_dim(self, s: ast.Slice, dim, env):
        if s.step is not None:
            return None
        lo = self.eval_expr(s.lower, env) if s.lower is not None else None
        hi = self.eval_expr(s.upper, env) if s.upper is not None else None
        if s.lower is None and s.upper is None:
            return dim
        if s.lower is None and is_sym(hi):
            return hi  # [:k] — k elements (k <= dim by contract)
        if s.upper is None and is_sym(lo) and dim is not None:
            return sym_binop("sub", dim, lo)
        if is_sym(lo) and is_sym(hi):
            return sym_binop("sub", hi, lo)
        return None

    # -- operators ---------------------------------------------------------

    def eval_binop(self, node, left, right, env):
        if isinstance(left, TupleVal) and isinstance(right, TupleVal) \
                and isinstance(getattr(node, "op", None), ast.Add):
            return TupleVal(left.elements + right.elements)
        if isinstance(left, ArrayVal) or isinstance(right, ArrayVal):
            out = self._broadcast([left, right], None, node)
            self._check_dense(node, out, [left, right])
            return out
        if is_sym(left) and is_sym(right):
            op = {
                ast.Add: "add", ast.Sub: "sub", ast.Mult: "mul",
                ast.FloorDiv: "floordiv", ast.Mod: "mod",
            }.get(type(getattr(node, "op", None)))
            if op is None:
                return None
            return sym_binop(op, left, right)
        return None

    def _broadcast_dims(self, dim_lists):
        """Right-aligned numpy broadcast over symbolic dims; ``None``
        on an unknown or provably mismatched pairing."""
        rank = max(len(d) for d in dim_lists)
        out = []
        for i in range(rank):
            cur = None
            for dims in dim_lists:
                j = i - (rank - len(dims))
                if j < 0:
                    continue
                d = dims[j]
                if d is None:
                    return None
                if isinstance(d, Poly) and d.is_const() and (
                        d.evaluate({}) == 1):
                    continue
                if cur is None:
                    cur = d
                elif sym_render(cur) != sym_render(d):
                    return None  # can't prove compatible
            out.append(cur if cur is not None else Poly.const(1))
        return tuple(out)

    def _broadcast(self, vals, dtype: Optional[str], node) -> Optional[
            ArrayVal]:
        arrays = [v for v in vals if isinstance(v, ArrayVal)]
        if not arrays or any(not a.known() for a in arrays):
            return None
        if any(not (isinstance(v, (ArrayVal, BoolVal, DtypeVal))
                    or is_sym(v) or v is None) for v in vals):
            return None
        dims = self._broadcast_dims([a.dims for a in arrays])
        if dims is None:
            return None
        if dtype is None:
            dtypes = {a.dtype for a in arrays}
            dtype = dtypes.pop() if len(dtypes) == 1 else None
        site = arrays[0].site
        return ArrayVal(dims, dtype, site)

    # -- calls -------------------------------------------------------------

    def eval_call(self, node, env, args, keywords):
        name = dotted_name(node.func)
        last = name.rsplit(".", 1)[-1]

        # method-style calls: evaluate the receiver ourselves (the base
        # engine does not evaluate node.func)
        if isinstance(node.func, ast.Attribute):
            base = self.eval_expr(node.func.value, env)
            attr = node.func.attr
            if isinstance(base, AtVal) and attr in _AT_METHODS:
                return base.array
            if isinstance(base, StructVal) and attr == "_replace":
                updates = {
                    kw.arg: keywords.get(kw.arg)
                    for kw in node.keywords if kw.arg is not None
                }
                return base.replace(updates)
            if isinstance(base, ArrayVal):
                return self._array_method(node, base, attr, args,
                                          keywords, env)
            if isinstance(base, FnRef):
                return self._call_fn(base.fn, node, args, keywords)
            if isinstance(base, ClassRef):
                fn = self.eval_attr(node.func, base, env)
                if isinstance(fn, FnRef):
                    return self._call_fn(fn.fn, node, args, keywords)
                return None

        # local lambda / class constructor / resolvable function
        if isinstance(node.func, ast.Name):
            fv = env.get(node.func.id)
            if isinstance(fv, LambdaVal):
                return self._call_lambda(fv, args, keywords)
            if isinstance(fv, ClassRef):
                return self._construct(fv.info, node, args, keywords)
            if node.func.id in self.ctx.classes and (
                    self.fn is None
                    or node.func.id not in self.fn.local_names()):
                return self._construct(self.ctx.classes[node.func.id],
                                       node, args, keywords)

        # builtins
        if name == "getattr" and len(node.args) >= 2:
            if isinstance(args[0], ConfigVal) and isinstance(args[1], str):
                if args[0].has(args[1]):
                    return args[0].attr(args[1])
                return args[2] if len(args) > 2 else None
            return None
        if name in ("max", "min") and len(args) >= 2:
            if all(is_sym(a) or isinstance(a, int) for a in args):
                return SymOp(name, args)
            return None
        if name == "int" and args:
            return args[0] if is_sym(args[0]) else None
        if name == "len":
            if isinstance(args[0], TupleVal):
                return Poly.const(len(args[0].elements))
            return None

        # jnp surface
        out = self._jnp_call(node, name, last, args, keywords, env)
        if out is not None:
            return out

        # registered helper shapes (ops/dense, ops/select, transport)
        helper = _HELPER_SHAPES.get(last)
        if helper is not None:
            return self._helper_call(node, helper, args)

        # resolvable project call (budget mode: constructors + helpers)
        if self.ctx.interprocedural and self.fn is not None:
            fn = self.ctx.project.resolve_call(node, self.fn)
            if fn is not None:
                return self._call_fn(fn, node, args, keywords)
        return None

    def _array_method(self, node, base: ArrayVal, attr, args, keywords,
                      env):
        if attr == "astype":
            dt = self._as_dtype(
                args[0] if args else keywords.get("dtype"),
                node.args[0] if node.args else None)
            return ArrayVal(base.dims, dt, base.site)
        if attr == "reshape":
            shape = (args[0] if len(args) == 1 else TupleVal(args))
            dims = self._as_dims(shape)
            if dims is None:
                return None
            out = ArrayVal(dims, base.dtype, base.site)
            self._check_dense(node, out, [base])
            return out
        if attr in _REDUCTION_FNS:
            return self._reduce(base, node, args, keywords)
        if attr in ("copy", "block_until_ready"):
            return base
        return None

    def _reduce(self, base: ArrayVal, node, args, keywords):
        axis_node = next((kw.value for kw in node.keywords
                          if kw.arg == "axis"), None)
        if axis_node is None and len(node.args) >= 2:
            axis_node = node.args[1]
        if axis_node is None:
            return ArrayVal((), base.dtype, base.site)
        if isinstance(axis_node, ast.Constant) and isinstance(
                axis_node.value, int):
            ax = axis_node.value
            if -len(base.dims) <= ax < len(base.dims):
                dims = list(base.dims)
                del dims[ax]
                return ArrayVal(tuple(dims), base.dtype, base.site)
        return None

    def _as_dtype(self, val, node) -> Optional[str]:
        if isinstance(val, DtypeVal):
            return val.name
        if isinstance(val, str):
            return val if val in _DTYPE_SIZES else None
        if node is not None:
            leaf = dotted_name(node).rsplit(".", 1)[-1]
            leaf = "bool" if leaf == "bool_" else leaf
            if leaf in _DTYPE_SIZES:
                return leaf
        return None

    def _as_dims(self, shape_val) -> Optional[Tuple]:
        if is_sym(shape_val):
            return (shape_val,)
        if isinstance(shape_val, TupleVal):
            dims = []
            for e in shape_val.elements:
                if not is_sym(e):
                    return None
                dims.append(e)
            return tuple(dims)
        return None

    def _jnp_call(self, node, name, last, args, keywords, env):
        site = (self.path, node.lineno)
        kw_nodes = {kw.arg: kw.value for kw in node.keywords
                    if kw.arg is not None}

        def dtype_at(pos: int) -> Optional[str]:
            if "dtype" in keywords or "dtype" in kw_nodes:
                return self._as_dtype(keywords.get("dtype"),
                                      kw_nodes.get("dtype"))
            if len(args) > pos:
                return self._as_dtype(
                    args[pos],
                    node.args[pos] if len(node.args) > pos else None)
            return None

        if last in _CREATION_FNS:
            dims = self._as_dims(args[0]) if args else None
            if dims is None:
                return None
            pos = 2 if last == "full" else 1
            dt = dtype_at(pos)
            if dt is None and last != "full":
                dt = "float32"  # jnp default
            out = ArrayVal(dims, dt, site)
            self._check_dense(node, out, [])
            return out
        if last in _LIKE_FNS and args and isinstance(args[0], ArrayVal):
            dt = dtype_at(99) or args[0].dtype
            return ArrayVal(args[0].dims, dt, site)
        if last == "arange":
            dt = dtype_at(99)
            if len(node.args) == 1 and is_sym(args[0]):
                return ArrayVal((args[0],), dt or "int32", site)
            if len(node.args) >= 2 and is_sym(args[0]) and is_sym(args[1]):
                return ArrayVal((sym_binop("sub", args[1], args[0]),),
                                dt or "int32", site)
            return None
        if last == "eye" and args and is_sym(args[0]):
            out = ArrayVal((args[0], args[0]), dtype_at(99) or "float32",
                           site)
            self._check_dense(node, out, [])
            return out
        if last == "broadcast_to" and len(args) >= 2:
            dims = self._as_dims(args[1])
            if dims is None:
                return None
            out = ArrayVal(
                dims,
                args[0].dtype if isinstance(args[0], ArrayVal) else None,
                site)
            self._check_dense(
                node, out,
                [args[0]] if isinstance(args[0], ArrayVal) else [])
            return out
        if last == "reshape" and len(args) >= 2 and isinstance(
                args[0], ArrayVal):
            dims = self._as_dims(args[1])
            if dims is None:
                return None
            return ArrayVal(dims, args[0].dtype, site)
        if last == "concatenate" and node.args:
            return self._concat(node, args, keywords, env, stack=False)
        if last == "stack" and node.args:
            return self._concat(node, args, keywords, env, stack=True)
        if last in _ELEMENTWISE_FNS:
            arrays = [a for a in args if isinstance(a, ArrayVal)]
            if not arrays:
                return None
            out = self._broadcast(args, None, node)
            self._check_dense(node, out, args)
            return out
        if last in _PASS_FIRST_FNS and args and isinstance(
                args[0], ArrayVal):
            return args[0]
        if last in _REDUCTION_FNS and args and isinstance(
                args[0], ArrayVal):
            return self._reduce(args[0], node, args, keywords)
        if last in ("randint", "uniform", "normal", "bernoulli") and (
                len(node.args) >= 2):
            dims = self._as_dims(args[1])
            if dims is None:
                return None
            dt = dtype_at(99) or (
                "float32" if last in ("uniform", "normal") else None)
            out = ArrayVal(dims, dt, site)
            self._check_dense(node, out, [])
            return out
        # jnp.int32(x)-style scalar casts
        canon = "bool" if last == "bool_" else last
        if canon in _DTYPE_SIZES and "." in name and (
                name.rsplit(".", 1)[0] in _DTYPE_BASES):
            if args and isinstance(args[0], ArrayVal):
                return ArrayVal(args[0].dims, canon, site)
            return ArrayVal((), canon, site)
        return None

    def _concat(self, node, args, keywords, env, stack: bool):
        if not isinstance(node.args[0], (ast.List, ast.Tuple)):
            return None
        parts = [self.eval_expr(e, env) for e in node.args[0].elts]
        if not parts or any(not isinstance(p, ArrayVal) or not p.known()
                            for p in parts):
            return None
        axis = 0
        ax_node = next((kw.value for kw in node.keywords
                        if kw.arg == "axis"), None)
        if ax_node is not None:
            if not (isinstance(ax_node, ast.Constant)
                    and isinstance(ax_node.value, int)):
                return None
            axis = ax_node.value
        dtypes = {p.dtype for p in parts}
        dt = dtypes.pop() if len(dtypes) == 1 else None
        site = parts[0].site
        if stack:
            dims = list(parts[0].dims)
            if any(p.dims != parts[0].dims for p in parts):
                return None
            if not -len(dims) - 1 <= axis <= len(dims):
                return None
            if axis < 0:
                axis += len(dims) + 1
            dims.insert(axis, Poly.const(len(parts)))
            return ArrayVal(tuple(dims), dt, site)
        rank = len(parts[0].dims)
        if any(len(p.dims) != rank for p in parts) or not (
                -rank <= axis < rank):
            return None
        axis %= rank
        total = parts[0].dims[axis]
        for p in parts[1:]:
            total = sym_binop("add", total, p.dims[axis])
        dims = list(parts[0].dims)
        dims[axis] = total
        return ArrayVal(tuple(dims), dt, site)

    def _helper_call(self, node, kind: str, args):
        def arr(i):
            return args[i] if (len(args) > i
                               and isinstance(args[i], ArrayVal)
                               and args[i].known()) else None

        if kind == "gather":
            table, idx = arr(0), arr(1)
            if table is None or idx is None:
                return None
            return ArrayVal(idx.dims, table.dtype, idx.site)
        if kind == "dest":
            return arr(0)
        if kind in ("sample_k", "sample_k_biased"):
            mask = arr(0)
            k = args[2] if kind == "sample_k_biased" else (
                args[1] if len(args) > 1 else None)
            if mask is None or not is_sym(k) or not mask.dims:
                return None
            lead = mask.dims[0]
            return TupleVal((ArrayVal((lead, k), "int32", mask.site),
                             ArrayVal((lead, k), "bool", mask.site)))
        if kind == "sample_one":
            mask = arr(0)
            if mask is None or not mask.dims:
                return None
            lead = mask.dims[0]
            return TupleVal((ArrayVal((lead,), "int32", mask.site),
                             ArrayVal((lead,), "bool", mask.site)))
        if kind == "card_at":
            card, idx = arr(0), arr(1)
            if card is None or idx is None or len(card.dims) < 2:
                return None
            return ArrayVal(idx.dims + card.dims[1:], card.dtype,
                            idx.site)
        if kind == "pack_int32":
            out = self._broadcast(args, "int32", node)
            self._check_dense(node, out, args)
            return out
        return None

    # -- interprocedural ---------------------------------------------------

    def _call_lambda(self, lv: LambdaVal, args, keywords):
        a = lv.node.args
        env = dict(lv.env)
        params = [p.arg for p in a.posonlyargs + a.args]
        for pname, val in zip(params, args):
            env[pname] = val
        defaults = a.defaults
        for pname, d in zip(params[len(params) - len(defaults):],
                            defaults):
            env.setdefault(pname, self.eval_expr(d, dict(lv.env)))
        if a.vararg is not None:
            env[a.vararg.arg] = TupleVal(args[len(params):])
        env.update(keywords)
        # a lambda body is textually inside the caller, so the densify
        # patrol follows the call in — `z = lambda *s: jnp.zeros(s, ..)`
        # building an [N, N] must flag exactly like the direct form
        sub = ShapeAnalysis(self.ctx, self.fn, self.path, self.findings,
                            densify=self.densify, depth=self.depth + 1)
        return sub.eval_expr(lv.node.body, env)

    def _call_fn(self, fn: FunctionInfo, node, args, keywords):
        if self.depth >= 12 or fn.qualname in self.ctx.stack:
            return None
        a = fn.node.args
        params = [p.arg for p in a.posonlyargs + a.args]
        env: Env = {}
        for pname, val in zip(params, args):
            env[pname] = val
        defaults = a.defaults
        for pname, d in zip(params[len(params) - len(defaults):],
                            defaults):
            if pname not in env:
                sub0 = ShapeAnalysis(self.ctx, fn, fn.path, self.findings,
                                     depth=self.depth + 1)
                env[pname] = sub0.eval_expr(d, {})
        for kw in a.kwonlyargs:
            env.setdefault(kw.arg, None)
        for pname, val in keywords.items():
            if pname in params or any(k.arg == pname
                                      for k in a.kwonlyargs):
                env[pname] = val
        self.ctx.stack.append(fn.qualname)
        try:
            sub = ShapeAnalysis(self.ctx, fn, fn.path, self.findings,
                                densify=False, depth=self.depth + 1)
            sub.run(list(fn.node.body), env)
            return sub.return_value
        finally:
            self.ctx.stack.pop()

    def _construct(self, info: ClassInfo, node, args, keywords):
        fields: Dict[str, Any] = {}
        for fname, val in zip(info.fields, args):
            fields[fname] = val
        for kw in node.keywords:
            if kw.arg is not None and kw.arg in info.fields:
                fields[kw.arg] = keywords.get(kw.arg)
        return StructVal(info.name, info.fields, fields)

    # -- concrete statements -----------------------------------------------

    def _stmt(self, stmt, env):
        # config-extent guards decide concretely: `if cfg.tx_max_cells
        # > 1:` runs ONE branch, matching the real constructor (a join
        # of both would lose the partial-buffer shapes)
        if isinstance(stmt, ast.If):
            test = self.eval_expr(stmt.test, env)
            if isinstance(test, BoolVal):
                return self.run(stmt.body if test.value else stmt.orelse,
                                env)
        return super()._stmt(stmt, env)

    # -- densify -----------------------------------------------------------

    def _n_degree(self, arr: ArrayVal) -> Optional[int]:
        if not arr.known():
            return None
        return sum(d.degree("N") for d in arr.dims)

    def _check_dense(self, node, out, inputs) -> None:
        """Flag a provably-superlinear intermediate: the output's
        N-degree is >= 2 and exceeds every input array's. Config
        extents (M, Q, ...) are bounded constants — only N scales with
        the cluster, so only N-degree growth densifies."""
        if not self.densify or not isinstance(out, ArrayVal):
            return
        out_deg = self._n_degree(out)
        if out_deg is None or out_deg < 2:
            return
        in_degs = []
        for v in inputs:
            if isinstance(v, ArrayVal):
                d = self._n_degree(v)
                if d is None:
                    return  # unknown operand: cannot prove growth
                in_degs.append(d)
            elif not (is_sym(v) or isinstance(v, (BoolVal, DtypeVal))
                      or v is None):
                return
        if in_degs and max(in_degs) >= out_deg:
            return
        shape = "[" + ", ".join(sym_render(d) for d in out.dims) + "]"
        self.findings.append(Finding(
            path=self.path, line=node.lineno, rule=DENSIFY_RULE,
            message=f"trace-time intermediate of shape {shape} is "
                    f"O(N^{out_deg}) but every input is "
                    f"O(N^{max(in_degs, default=0)}) — fits at 100k, "
                    "OOMs at the 1M point (docs/memory-budget.md)",
            hint="restructure as gathers/scatters over [N, const] "
                 "tables, or suppress with a reason if the dense form "
                 "is deliberate",
        ))


# --- inventory ------------------------------------------------------------


@dataclasses.dataclass
class LeafShape:
    name: str
    dims: Optional[Tuple]  # symbolic dims, None = unresolved
    dtype: Optional[str]
    path: str = ""
    line: int = 0

    def shape_str(self) -> str:
        if self.dims is None:
            return "?"
        return "[" + ", ".join(sym_render(d) for d in self.dims) + "]"

    def nbytes(self, bindings: Dict[str, int]) -> Optional[int]:
        if self.dims is None or self.dtype not in _DTYPE_SIZES:
            return None
        total = _DTYPE_SIZES[self.dtype]
        for d in self.dims:
            ev = sym_eval(d, bindings)
            if ev is None:
                return None
            total *= ev
        return total

    def shape_at(self, bindings: Dict[str, int]) -> Optional[Tuple[int,
                                                                   ...]]:
        if self.dims is None:
            return None
        out = []
        for d in self.dims:
            ev = sym_eval(d, bindings)
            if ev is None:
                return None
            out.append(int(ev))
        return tuple(out)


@dataclasses.dataclass
class Inventory:
    root: str
    leaves: Dict[str, LeafShape]
    bindings: Dict[str, int]
    flags: Dict[str, bool]

    def report(self, overrides: Optional[Dict[str, int]] = None) -> dict:
        """Static projection in the runtime audit's schema
        (``obs.memory.memory_report``): evaluate every symbolic leaf at
        the (possibly overridden) bindings and classify with the SHARED
        ``classify_leaf``. ``overrides`` rebinds symbols (``{"N":
        1_000_000}``) — the other extents keep their config values."""
        bindings = dict(self.bindings)
        bindings.update(overrides or {})
        n_nodes = bindings.get("N")
        tables: Dict[str, dict] = {}
        by_class: Dict[str, int] = {}
        total = 0
        unresolved = []
        for name, leaf in self.leaves.items():
            shape = leaf.shape_at(bindings)
            nbytes = leaf.nbytes(bindings)
            if shape is None or nbytes is None:
                unresolved.append(name)
                continue
            cls = classify_leaf(shape, n_nodes)
            entry = {
                "shape": list(shape),
                "dtype": leaf.dtype,
                "nbytes": nbytes,
                "class": cls,
                "symbolic": leaf.shape_str(),
            }
            if cls != "O(1)" and n_nodes:
                entry["per_node_bytes"] = nbytes // n_nodes
            tables[name] = entry
            by_class[cls] = by_class.get(cls, 0) + nbytes
            total += nbytes
        return {
            "total_bytes": total,
            "n_nodes": n_nodes,
            "tables": tables,
            "by_class": by_class,
            "unresolved": unresolved,
            "source": "static",
            "root": self.root,
        }


def _flatten(val, prefix: str, out: Dict[str, LeafShape]) -> None:
    if isinstance(val, StructVal):
        for f in val.field_order:
            _flatten(val.fields.get(f), f"{prefix}.{f}" if prefix else f,
                     out)
        return
    if isinstance(val, TupleVal):
        for i, v in enumerate(val.elements):
            _flatten(v, f"{prefix}[{i}]", out)
        return
    name = prefix or "<leaf>"
    if isinstance(val, ArrayVal) and val.known():
        path, line = val.site or ("", 0)
        out[name] = LeafShape(name, val.dims, val.dtype, path, line)
    else:
        out[name] = LeafShape(name, None, None)


def build_inventory(project: Project, root: str,
                    config: Optional[ConfigVal] = None) -> Optional[
                        Inventory]:
    """Interpret ``<root>.create(cfg)`` symbolically over the project's
    own ASTs. Returns None when the root class (or its ``create``) is
    not in the walked set."""
    config = config or ConfigVal.default()
    ctx = ShapeContext(project, config)
    info = ctx.classes.get(root)
    if info is None:
        return None
    creates = [c for c in project.methods.get((root, "create"), [])
               if c.module is info.module]
    if not creates:
        return None
    fn = creates[0]
    driver = ShapeAnalysis(ctx, fn, fn.path)
    result = driver._call_fn(fn, fn.node, [config], {})
    leaves: Dict[str, LeafShape] = {}
    _flatten(result, "", leaves)
    if not isinstance(result, StructVal):
        leaves = {"<root>": LeafShape("<root>", None, None)}
    return Inventory(root, leaves, dict(config.bindings),
                     dict(config.flags))


# --- the repo-facing entry points ----------------------------------------

#: the sim/ops files whose ASTs define the state schema — the obs/CLI
#: projection path parses exactly these (the lint gate instead uses the
#: walked set, so a PR's modified source is what gets priced)
STATE_FILES = (
    "sim/scale.py", "sim/scale_step.py", "sim/broadcast.py",
    "sim/step.py", "sim/swim.py", "sim/transport.py",
    "ops/versions.py", "ops/partials.py",
)

#: mode -> state root class (mirrors ``obs.memory.mem_report_cli``)
ROOTS = {"scale": "ScaleSimState", "full": "SimState"}


def state_project() -> Project:
    """Parse the installed package's state-schema files into a Project
    (no jax import, no bytecode execution)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    modules = []
    for rel in STATE_FILES:
        path = os.path.join(pkg, rel)
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        modules.append(ModuleInfo(
            path=path, name=module_name_for(path), tree=ast.parse(source),
            source=source, suppressions={}, bad_suppressions=[],
        ))
    return Project(modules)


def static_inventory(cfg=None, mode: str = "scale") -> Inventory:
    """The static inventory for a live config instance (or the flagship
    defaults): the ``obs/memory.py`` projection hook and the
    ``mem-report --project`` backend."""
    config = ConfigVal.from_config(cfg) if cfg is not None else (
        ConfigVal.default())
    inv = build_inventory(state_project(), ROOTS[mode], config)
    if inv is None:
        raise RuntimeError(
            f"state root {ROOTS[mode]!r} not found in {STATE_FILES}")
    return inv


# --- the two rules --------------------------------------------------------


def check_budget(project: Project) -> List[Finding]:
    """``mem-budget``: price the walked tree's own state constructors at
    the declared 1M point and fail when a complexity class exceeds its
    budget (or when a leaf's static shape cannot be resolved — an
    unpriceable table is a gate hole, not a pass)."""
    findings: List[Finding] = []
    root = HBM_BUDGET["root"]
    ctx_classes = index_classes(project)
    info = ctx_classes.get(root)
    if info is None:
        return findings  # walked subset does not define the state
    inv = build_inventory(project, root, ConfigVal.default())
    if inv is None:
        return findings
    overrides = dict(HBM_BUDGET["point"])
    report = inv.report(overrides)
    for name in report["unresolved"]:
        findings.append(Finding(
            path=info.module.path, line=info.node.lineno,
            rule=BUDGET_RULE,
            message=f"state leaf `{name}` of {root} has no statically "
                    "resolvable shape — the 1M budget cannot price it",
            hint="keep constructor shapes as config-extent expressions "
                 "the interpreter covers (analysis/shapes.py)",
        ))
    budgets = HBM_BUDGET["per_class_bytes"]
    for cls, budget in budgets.items():
        used = report["by_class"].get(cls, 0)
        if used <= budget:
            continue
        offenders = sorted(
            ((n, e) for n, e in report["tables"].items()
             if e["class"] == cls),
            key=lambda kv: -kv[1]["nbytes"])
        worst_name, worst = offenders[0]
        leaf = inv.leaves[worst_name]
        path = leaf.path or info.module.path
        line = leaf.line or info.node.lineno
        top = ", ".join(
            f"{n}={e['nbytes'] / 1e6:.0f}MB" for n, e in offenders[:3])
        findings.append(Finding(
            path=path, line=line, rule=BUDGET_RULE,
            message=f"{cls} state footprint at N="
                    f"{overrides['N']:,} is {used / 1e9:.3f} GB, over "
                    f"the declared {budget / 1e9:.3f} GB budget "
                    f"(worst: {top})",
            hint="shrink a table (docs/memory-budget.md) or re-price "
                 "HBM_BUDGET with the PR that justifies the growth",
        ))
    unknown = set(report["by_class"]) - set(budgets)
    for cls in sorted(unknown):
        findings.append(Finding(
            path=info.module.path, line=info.node.lineno,
            rule=BUDGET_RULE,
            message=f"complexity class {cls} has no declared budget "
                    f"(used {report['by_class'][cls] / 1e9:.3f} GB at "
                    "the 1M point)",
            hint="add the class to HBM_BUDGET per_class_bytes",
        ))
    return findings


#: full-view modules where O(N^2) planes are the DESIGN (sim/swim.py's
#: [N, N] view; sim/step.py drives it) — densify only patrols the
#: scale-capable surfaces
_DENSIFY_EXCLUDE = ("/sim/step.py", "/sim/swim.py")


def densify_in_scope(path: str) -> bool:
    p = os.path.abspath(path)
    if not os.path.exists(p):
        return True  # fixture / bare source blob
    norm = p.replace("\\", "/")
    if any(norm.endswith(x) for x in _DENSIFY_EXCLUDE):
        return False
    return "/sim/" in norm or "/ops/" in norm


#: annotation name -> treat the parameter as a config
_CONFIG_ANNOTATIONS = ("Config",)


def _seed_param(ctx: ShapeContext, name: str, annotation: Optional[str],
                findings: List[Finding]):
    """Abstract value for a function parameter in densify mode: configs
    become :class:`ConfigVal`, annotated state types get their create-
    derived StructVal, extent-named ints their symbol."""
    if name == "cfg" or (annotation or "").endswith(_CONFIG_ANNOTATIONS):
        return ctx.config
    if annotation and annotation in ctx.classes:
        cached = ctx.struct_cache.get(annotation)
        if annotation not in ctx.struct_cache:
            cached = _class_struct(ctx, annotation, findings)
            ctx.struct_cache[annotation] = cached
        return cached
    if name in SYMBOLS:
        return Poly.var(SYMBOLS[name])
    return None


def _class_struct(ctx: ShapeContext, cls_name: str,
                  findings: List[Finding]):
    info = ctx.classes.get(cls_name)
    creates = [c for c in ctx.project.methods.get((cls_name, "create"), [])
               if info is not None and c.module is info.module]
    if not creates:
        return None
    fn = creates[0]
    a = fn.node.args
    params = [p.arg for p in a.posonlyargs + a.args]
    args = []
    for pname in params:
        if pname == "cfg":
            args.append(ctx.config)
        elif pname in SYMBOLS:
            args.append(Poly.var(SYMBOLS[pname]))
        else:
            args.append(None)
    driver = ShapeAnalysis(ctx, fn, fn.path, findings)
    return driver._call_fn(fn, fn.node, args, {})


def check_densify(project: Project) -> List[Finding]:
    """``densify``: walk every scale-path function with shape-seeded
    parameters and flag provably-superlinear intermediates."""
    findings: List[Finding] = []
    ctx = ShapeContext(project, ConfigVal.default(),
                       interprocedural=False)
    for fn in project.iter_functions():
        if not densify_in_scope(fn.path):
            continue
        a = fn.node.args
        env: Env = {}
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            ann = ""
            if p.annotation is not None:
                ann = dotted_name(p.annotation).rsplit(".", 1)[-1] or (
                    p.annotation.value
                    if isinstance(p.annotation, ast.Constant)
                    and isinstance(p.annotation.value, str) else "")
            env[p.arg] = _seed_param(ctx, p.arg, ann or None, [])
        analysis = ShapeAnalysis(ctx, fn, fn.path, findings,
                                 densify=True)
        analysis.run(list(fn.node.body), env)
    return findings
