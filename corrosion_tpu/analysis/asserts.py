"""strippable-assert: bare ``assert`` in library code.

``python -O`` compiles ``assert`` statements away entirely — every
invariant they guard silently stops being checked in exactly the
deployments that run optimized. PR 4 fixed one such landmine in
``make_multihost_mesh`` (a mis-shaped mesh would have crashed far away
in ``device_put``); this checker makes that precedent mechanical.

The fix is one of:

- ``raise ValueError(...)`` — caller handed in bad arguments/config;
- ``raise CheckpointIntegrityError(...)`` — persisted artifact fails
  validation;
- ``registry.always(cond, name)`` — an internal invariant worth
  counting/reporting through the Antithesis-style registry.

Test code keeps its asserts (pytest rewrites them); point the runner at
library paths only.
"""

from __future__ import annotations

import ast
from typing import List

from corrosion_tpu.analysis.base import Finding

RULE = "bare-assert"


def check(tree: ast.AST, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assert):
            continue
        try:
            cond = ast.unparse(node.test)
        except Exception:  # pragma: no cover - unparse is total on 3.9+
            cond = "<condition>"
        if len(cond) > 60:
            cond = cond[:57] + "..."
        findings.append(Finding(
            path=path, line=node.lineno, rule=RULE,
            message=f"bare assert `{cond}` is stripped under python -O",
            hint="raise ValueError/CheckpointIntegrityError, or route "
                 "through assertions.REGISTRY.always(...)",
        ))
    return findings
