"""Module-level call graph over the linted file set.

The v1 checkers are single-function: a property that crosses a call
boundary (a helper that donates its argument, a lock taken inside a
callee, a whole-state gather buried two frames down) is invisible to
them. This module builds the shared substrate the v2 interprocedural
passes (``dataflow.py``, ``sharding.py``, ``lockorder.py``, the
donation summary pass) run on:

- :class:`Project` — every parsed module plus an index of every
  function/method by qualified name;
- :meth:`Project.resolve_call` — best-effort, *precision-over-recall*
  callee resolution (see below);
- :func:`fixpoint` — a worklist driver for computing per-function
  summaries (donating positions, acquired locks, gathered params) to a
  fixed point over the graph.

Resolution rules — deliberately conservative, an unresolved call simply
grows no edge (never a wrong one):

- ``name(...)``       -> abstain if the name is bound locally (param,
  store, nested def — Python scoping shadows everything else); else a
  function in the SAME module; else the unique function with that bare
  name across the project (bare names reach other modules through
  imports, so a project-unique match is the imported function); else
  unresolved;
- ``self.m(...)``     -> method ``m`` of the enclosing class, else the
  unique method named ``m`` project-wide, else unresolved;
- ``obj.m(...)``      -> exactly one candidate named ``m`` in the
  CALLER'S OWN module, else unresolved. External receivers share
  method names (``.submit``, ``.get``, ``.put``), so project-wide
  resolution here would mint wrong facts for every stdlib call that
  collides; cross-module object calls deliberately grow no facts.

Names that are ambiguous at the applicable scope (two helpers both
called ``check``) therefore never carry interprocedural facts; the
per-file lexical checkers still cover them.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from corrosion_tpu.analysis.base import Finding, dotted_name


@dataclasses.dataclass
class ModuleInfo:
    path: str
    name: str  # dotted module name derived from the path
    tree: ast.Module
    source: str
    suppressions: Dict[int, set]
    bad_suppressions: List[Finding]


@dataclasses.dataclass
class FunctionInfo:
    qualname: str  # "pkg.mod.Class.method" / "pkg.mod.func"
    name: str  # bare name
    module: ModuleInfo
    cls: Optional[ast.ClassDef]
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    _local_names: Optional[frozenset] = None

    @property
    def path(self) -> str:
        return self.module.path

    def param_names(self) -> List[str]:
        a = self.node.args
        return [p.arg for p in a.posonlyargs + a.args]

    def local_names(self) -> frozenset:
        """Names bound inside this function (params, stores, nested
        defs): a call to one of these is a LOCAL binding — Python
        scoping shadows any same-named module function, so resolution
        must abstain rather than attribute someone else's facts."""
        if self._local_names is None:
            a = self.node.args
            names = {p.arg for p in (a.posonlyargs + a.args
                                     + a.kwonlyargs)}
            for extra in (a.vararg, a.kwarg):
                if extra is not None:
                    names.add(extra.arg)
            for sub in ast.walk(self.node):
                if isinstance(sub, ast.Name) and isinstance(
                        sub.ctx, ast.Store):
                    names.add(sub.id)
                elif isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and (
                        sub is not self.node):
                    names.add(sub.name)
            self._local_names = frozenset(names)
        return self._local_names


def module_name_for(path: str) -> str:
    """Dotted module name from a file path: everything from the LAST
    ``corrosion_tpu`` component down, or the full path dotted for
    out-of-package files — two distinct files must never share a module
    name (qualnames would collide and per-module donating tables would
    cross-contaminate)."""
    norm = os.path.normpath(path)
    parts = [p for p in norm.split(os.sep) if p and p != "."]
    if "corrosion_tpu" in parts:
        last = len(parts) - 1 - parts[::-1].index("corrosion_tpu")
        parts = parts[last:]
    else:
        parts = [p if p != ".." else "__up__" for p in parts]
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1] or ["__init__"]
    return ".".join(parts)


class Project:
    """The linted file set, indexed for interprocedural passes."""

    def __init__(self, modules: Iterable[ModuleInfo]):
        self.modules: List[ModuleInfo] = list(modules)
        self.functions: Dict[str, FunctionInfo] = {}
        #: bare name -> every function carrying it (resolution fodder)
        self.by_name: Dict[str, List[FunctionInfo]] = {}
        #: (class name, method name) -> FunctionInfo list
        self.methods: Dict[Tuple[str, str], List[FunctionInfo]] = {}
        for mod in self.modules:
            self._index_module(mod)

    def _index_module(self, mod: ModuleInfo) -> None:
        def add(node, cls: Optional[ast.ClassDef]) -> None:
            qual = (f"{mod.name}.{cls.name}.{node.name}" if cls
                    else f"{mod.name}.{node.name}")
            info = FunctionInfo(
                qualname=qual, name=node.name, module=mod, cls=cls,
                node=node,
            )
            self.functions[qual] = info
            self.by_name.setdefault(node.name, []).append(info)
            if cls is not None:
                self.methods.setdefault((cls.name, node.name), []).append(
                    info)

        for top in mod.tree.body:
            if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add(top, None)
            elif isinstance(top, ast.ClassDef):
                for sub in top.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        add(sub, top)

    # -- resolution --------------------------------------------------------

    def _unique(self, name: str) -> Optional[FunctionInfo]:
        cands = self.by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def resolve_call(self, call: ast.Call,
                     caller: FunctionInfo) -> Optional[FunctionInfo]:
        """The callee FunctionInfo, or None when it cannot be pinned
        down without guessing."""
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in caller.local_names():
                return None  # locally bound (closure/param/rebind):
                # the local binding shadows any module-level function
            mod_qual = f"{caller.module.name}.{func.id}"
            if mod_qual in self.functions:
                return self.functions[mod_qual]
            return self._unique(func.id)
        if isinstance(func, ast.Attribute):
            base = dotted_name(func.value)
            if base == "self" and caller.cls is not None:
                own = self.methods.get((caller.cls.name, func.attr), [])
                for cand in own:
                    if cand.module is caller.module:
                        return cand
                if len(own) == 1:
                    return own[0]
            # unknown receiver: external types share method names
            # (.submit, .get, .put...) — resolving to a project-unique
            # function regardless of receiver would mint wrong facts
            # for every stdlib/third-party call that happens to
            # collide. Resolve only when exactly ONE candidate lives
            # in the CALLER'S OWN module (cross-module object calls
            # grow no facts; the registries cover the hot surfaces).
            local = [
                cand for cand in self.by_name.get(func.attr, [])
                if cand.module is caller.module
            ]
            return local[0] if len(local) == 1 else None
        return None

    def iter_functions(self) -> Iterable[FunctionInfo]:
        return self.functions.values()


def fixpoint(
    project: Project,
    summarize: Callable[[FunctionInfo, Dict[str, object]], object],
    max_rounds: int = 12,
) -> Dict[str, object]:
    """Compute per-function summaries to a fixed point.

    ``summarize(fn, summaries)`` returns fn's summary given the current
    (possibly incomplete) summaries of everyone else, keyed by qualname;
    the driver iterates until nothing changes. ``max_rounds`` bounds
    pathological ping-pong (the repo's call graph converges in 2-3) —
    the summaries are monotone in every checker here, so a truncated
    run only loses findings, never invents them.
    """
    summaries: Dict[str, object] = {}
    for _ in range(max_rounds):
        changed = False
        for fn in project.iter_functions():
            new = summarize(fn, summaries)
            if summaries.get(fn.qualname) != new:
                summaries[fn.qualname] = new
                changed = True
        if not changed:
            break
    return summaries
