"""donation-safety: no reads after a buffer was donated away.

End-to-end carry donation (PR 4) made the flagship pipeline hold ONE
device copy of the state — and created the repo's sharpest silent bug
class: pass an array to a ``donate_argnums`` jit, then read the same
variable again, and you get a ``DeletedBuffer`` error **only on the
code path that actually reuses it** (``resilience/segments.py`` handles
the one legitimate case by re-uploading host snapshots). This checker
flags the lexical shape of the hazard:

1. collect **donating callables** visible in the file — ``x =
   jax.jit(f, donate_argnums=...)`` assignments, ``@partial(jax.jit,
   donate_argnums=...)`` decorated defs — plus the repo's registered
   cross-module donating entry points (:data:`KNOWN_DONATING`);
2. inside each function, after a call that passes a plain variable in a
   donated position, flag any later read of that variable **before it
   is re-bound**.

Known limits (precision over recall): tracking is lexical within one
function body — a donating call under a loop whose next iteration
re-reads the carry, or donation through a dict of jits
(``segments.py``'s ``jitted[n]``), is invisible; any re-binding (even
on one branch of an ``if``) ends tracking. The trace-stability harness
and the donation probes in the runtime tests cover what this pass
cannot see.

The v2 **interprocedural** pass (:func:`check_project`, registered as
``donation-flow``) removes the two blind spots the lexical pass
documents:

- **transitive donation** — a helper that passes its own parameter
  into a donated position is itself donating at that position; the
  summary propagates over the call graph to a fixed point, so ``out =
  helper(st); st.sum()`` is caught at the call site even though the
  jit lives two frames down. Only project-unique bare names carry the
  summary (an ambiguous name grows no fact, never a wrong one).
- **closures** — a nested ``def`` that reads an outer variable via
  closure is invisible to the statement scan; the project pass treats
  a call to a local closure as a read of its free variables, so
  ``def report(): return st.sum()`` called after ``step(st)`` flags.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.analysis.base import (
    Finding,
    dotted_name,
    jit_call,
    walk_shallow,
)

RULE = "donation-reuse"

#: cross-module donating entry points: terminal call name -> donated
#: positional-arg indices. These are the repo's public donating
#: surfaces (``parallel/mesh.py``); keep in sync when adding one.
KNOWN_DONATING: Dict[str, Tuple[int, ...]] = {
    # sharded_scale_run(cfg, mesh, st, net, key, inputs) — st donated
    "sharded_scale_run": (2,),
    # sharded_scale_run_carry(cfg, mesh, st, net, key, inputs) — st+key
    "sharded_scale_run_carry": (2, 4),
}

def _donated_indices(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated positions from a ``jax.jit(...)`` call, None if it does
    not donate (or the spec is not a literal we can read)."""
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        try:
            spec = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            return ()  # donates, but positions unknown: track nothing
        if isinstance(spec, int):
            return (spec,)
        if isinstance(spec, (tuple, list)) and all(
                isinstance(i, int) for i in spec):
            return tuple(spec)
        return ()
    return None


def _collect_donating(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """File-local donating callables by name."""
    table = dict(KNOWN_DONATING)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            call = jit_call(node.value)
            if call is None:
                continue
            idx = _donated_indices(call)
            if not idx:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    table[tgt.id] = idx
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = jit_call(dec)
                if call is None:
                    continue
                idx = _donated_indices(call)
                if idx:
                    table[node.name] = idx
    return table


def _stores_in(node) -> set:
    return {
        sub.id for sub in walk_shallow(node)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store)
    }


class _FunctionScan:
    def __init__(self, donating: Dict[str, Tuple[int, ...]], path: str,
                 findings: List[Finding],
                 closures: Optional[Dict[str, frozenset]] = None):
        self.donating = donating
        self.path = path
        self.findings = findings
        # nested-def name -> outer variables it reads via closure
        # (project pass only; the lexical pass passes None)
        self.closures = closures or {}
        # var -> (donating call name, call line); tracked until re-bound
        self.tracked: Dict[str, Tuple[str, int]] = {}

    def _note_call(self, call: ast.Call) -> None:
        name = dotted_name(call.func).rsplit(".", 1)[-1]
        idx = self.donating.get(name)
        if not idx:
            return
        for i in idx:
            if i < len(call.args) and isinstance(call.args[i], ast.Name):
                self.tracked[call.args[i].id] = (name, call.lineno)

    def scan_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs get their own scan via check()
            if isinstance(stmt, (ast.If, ast.While, ast.For)):
                # header expressions first; then each branch starts
                # from the PRE-branch state — `if fast: out = step(st)
                # else: out = other(st)` must not leak the if-branch's
                # donation into the mutually exclusive else. After the
                # statement the branch states merge by union: a var
                # donated on EITHER path may be dead, so later reads
                # still flag.
                for header in self._headers(stmt):
                    self._process(header)
                pre = dict(self.tracked)
                merged: Dict[str, Tuple[str, int]] = {}
                for field in ("body", "orelse"):
                    self.tracked = dict(pre)
                    self.scan_body(getattr(stmt, field, []))
                    merged.update(self.tracked)
                self.tracked = merged
                continue
            if isinstance(stmt, (ast.Try, ast.With)):
                # these bodies DO run in sequence (with-body after the
                # items; handlers/finalbody after a partial try-body)
                for header in self._headers(stmt):
                    self._process(header)
                for field in ("body", "orelse", "finalbody"):
                    self.scan_body(getattr(stmt, field, []))
                for handler in getattr(stmt, "handlers", []):
                    self.scan_body(handler.body)
                continue
            self._process(stmt)

    @staticmethod
    def _headers(stmt) -> List[ast.AST]:
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, ast.For):
            return [stmt.iter, stmt.target]
        if isinstance(stmt, ast.With):
            return [it.context_expr for it in stmt.items] + [
                it.optional_vars for it in stmt.items
                if it.optional_vars is not None
            ]
        return []

    def _process(self, stmt: ast.AST) -> None:
        """One simple statement (or header expr), in lexical order."""
        if self.tracked:
            for var, node in self._loads_before_store(stmt).items():
                fn, line = self.tracked.pop(var)
                self.findings.append(Finding(
                    path=self.path, line=node.lineno, rule=RULE,
                    message=f"`{var}` read after being donated to "
                            f"{fn}() on line {line}",
                    hint="re-bind the variable from the call's result, "
                         "or keep a host copy (np.array) before "
                         "donating",
                ))
        if self.tracked and self.closures:
            # the closure blind spot: calling a local def whose body
            # reads a donated variable IS a read of that variable
            for sub in walk_shallow(stmt):
                if not (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id in self.closures):
                    continue
                for var in sorted(self.closures[sub.func.id]):
                    if var not in self.tracked:
                        continue
                    fn, line = self.tracked.pop(var)
                    self.findings.append(Finding(
                        path=self.path, line=sub.lineno, rule=RULE,
                        message=f"closure `{sub.func.id}` reads `{var}`"
                                f" which was donated to {fn}() on "
                                f"line {line}",
                        hint="re-bind the variable from the donating "
                             "call's result before invoking the "
                             "closure",
                    ))
        # record donations in this statement LAST: a var donated and
        # re-bound in the same statement (st, _ = f(st, ...)) is the
        # correct donation idiom
        for sub in walk_shallow(stmt):
            if isinstance(sub, ast.Call):
                self._note_call(sub)
        stores = _stores_in(stmt)
        for var in list(self.tracked):
            if var in stores:
                self.tracked.pop(var)

    def _loads_before_store(self, stmt) -> Dict[str, ast.Name]:
        """Tracked vars loaded by this statement (first Name node each).

        A load in the same statement that also re-binds the var (``v =
        g(v)``) still reads the donated buffer — flagged."""
        out: Dict[str, ast.Name] = {}
        for sub in walk_shallow(stmt):
            if (isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                    and sub.id in self.tracked
                    and sub.id not in out):
                out[sub.id] = sub
        return out


def check(tree: ast.AST, source: str, path: str) -> List[Finding]:
    donating = _collect_donating(tree)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FunctionScan(donating, path, findings).scan_body(node.body)
    return findings


# --- interprocedural pass (donation-flow) ---------------------------------


def _def_params(node) -> set:
    """Every parameter name a def/lambda binds, incl. *args/**kwargs."""
    a = node.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.add(extra.arg)
    return names


def _closure_free_reads(fn_node: ast.AST) -> Dict[str, frozenset]:
    """name -> outer variables each nested def reads via closure.

    Conservative scoping: only defs bound in THIS function's own scope
    are mapped (a deeper def is not callable from the outer body by
    its bare name, and keying it here could overwrite the one that
    is); two same-scope defs sharing a name carry no facts at all.
    Inside a mapped def, any bound name — its own params, stores, and
    the params of defs/lambdas nested deeper (which shadow in their
    own scopes) — is treated as bound throughout, so a deeper def's
    parameter never reads as a free read of the outer variable. Trades
    rare true positives for never flagging correct code."""
    def own_scope_defs(root):
        """Defs bound in root's own scope: reachable without crossing
        another def/lambda boundary (yielded, not descended into)."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node
                continue
            if isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))

    out: Dict[str, frozenset] = {}
    collided: set = set()
    for node in own_scope_defs(fn_node):
        if node.name in out:
            collided.add(node.name)  # redefinition: facts ambiguous
        bound = _def_params(node)
        loads = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                if isinstance(sub.ctx, ast.Store):
                    bound.add(sub.id)
                elif isinstance(sub.ctx, ast.Load):
                    loads.add(sub.id)
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and sub is not node:
                bound |= _def_params(sub)
                if not isinstance(sub, ast.Lambda):
                    bound.add(sub.name)
        # `nonlocal` names are writes-through, still reads of the outer
        # binding for donation purposes — keep them in `loads`
        for sub in ast.walk(node):
            if isinstance(sub, ast.Nonlocal):
                loads.update(sub.names)
                bound -= set(sub.names)
        out[node.name] = frozenset(loads - bound)
    for name in collided:
        out.pop(name, None)
    return out


def _fn_param_donations(fn, donating: Dict[str, Tuple[int, ...]]
                        ) -> Tuple[int, ...]:
    """Param positions ``fn`` passes straight into a donated slot of a
    known donating callee — i.e. positions ``fn`` itself donates.

    A param that is EVER re-bound in the body is excluded: after ``st =
    st + 1`` the name no longer aliases the caller's buffer, so a later
    donation of it must not mark the caller's arg dead (this trades the
    rare rebind-after-donate true positive for never flagging correct
    code — the same never-a-wrong-fact contract as call resolution)."""
    params = fn.param_names()
    rebound = _stores_in(fn.node)
    positions: set = set()
    # walk_shallow: a nested def/lambda runs later with its OWN scope —
    # a shadowed param name there must not mark the outer fn donating
    for node in walk_shallow(fn.node):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func).rsplit(".", 1)[-1]
        idx = donating.get(name)
        if not idx:
            continue
        for i in idx:
            if i < len(node.args) and isinstance(node.args[i], ast.Name):
                arg = node.args[i].id
                if arg in params and arg not in rebound:
                    positions.add(params.index(arg))
    return tuple(sorted(positions))


def compute_project_donating(project) -> Dict[str, Tuple[int, ...]]:
    """Project-wide donating table: ``KNOWN_DONATING`` + every
    file-local donating jit + module-level functions that transitively
    pass a parameter into a donated position (to a fixed point).

    Only bare names unique across the project carry a transitive fact;
    methods are excluded (their call-site arg numbering shifts by the
    receiver and a wrong offset would flag the wrong variable)."""
    from corrosion_tpu.analysis.callgraph import fixpoint

    local_tables = {
        mod.name: _collect_donating(mod.tree) for mod in project.modules
    }

    def summarize(fn, summaries):
        if fn.cls is not None:
            return ()
        table = dict(local_tables[fn.module.name])
        for qual, positions in summaries.items():
            if not positions:
                continue
            other = project.functions[qual]
            if len(project.by_name.get(other.name, ())) == 1:
                table.setdefault(other.name, tuple(positions))
        return _fn_param_donations(fn, table)

    summaries = fixpoint(project, summarize)
    out: Dict[str, Tuple[int, ...]] = {}
    for qual, positions in summaries.items():
        if not positions:
            continue
        fn = project.functions[qual]
        if len(project.by_name.get(fn.name, ())) == 1:
            out[fn.name] = tuple(positions)
    return out


def check_project(project) -> List[Finding]:
    """The interprocedural donation pass: the lexical scan, run with
    the project-wide donating table and closure free-variable maps."""
    transitive = compute_project_donating(project)
    findings: List[Finding] = []
    for mod in project.modules:
        donating = dict(transitive)
        donating.update(_collect_donating(mod.tree))  # local names win
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FunctionScan(
                    donating, mod.path, findings,
                    closures=_closure_free_reads(node),
                ).scan_body(node.body)
    return findings
