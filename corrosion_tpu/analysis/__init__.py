"""corrolint: repo-specific static analysis.

The runtime layers lean on Antithesis-style always/sometimes
instrumentation (``utils/assertions.py``) — check the invariant
everywhere, mechanically. This package applies the same philosophy
*before* runtime: four AST checkers over the codebase catch the bug
classes the last PRs introduced machinery for, where a runtime test only
catches them on the path it happens to take:

- **donation-safety** (``donation.py``) — a variable read after being
  passed in donated position to a jit is a ``DeletedBuffer`` landmine
  (the hazard ``resilience/segments.py`` handles by re-uploading host
  snapshots).
- **lock-discipline** (``locks.py``) — threaded writers/supervisors
  guarding shared state with one ``threading.Lock``: mutations outside
  the lock, blocking IO under it.
- **strippable-assert** (``asserts.py``) — bare ``assert`` in library
  code vanishes under ``python -O`` (the bug class PR 4 fixed one
  instance of in ``make_multihost_mesh``).
- **trace-hygiene** (``trace.py``) — Python control flow on traced
  values, ``jnp`` work at import time, unhashable static-arg defaults:
  each one is a retrace (or a crash) per call, collapsing the PERF.md
  story.

Since v2 a second tier of **interprocedural** checkers runs over a
module-level call graph (``callgraph.py``) and a forward dataflow
engine (``dataflow.py``) — cross-function properties a lexical pass
provably cannot see:

- **donation-flow** (``donation.check_project``) — transitive
  donation: a helper that passes its parameter into a donated slot is
  donating too, so its callers' reuse flags at the call site; plus the
  closure blind spot (a nested def reading a donated variable).
- **sharding-contract** (``sharding.py``) — ``shard-gather`` /
  ``shard-spec-drift``: sharded mesh state host-materialized outside
  the drain registry, or fresh state entering a sharded entry point
  unplaced.
- **dtype-flow** (``dtypes.py``) — ``dtype-widen``: jnp promotion
  simulated through the hot sim/ops modules; silent widening of a
  declared-narrow (int16) leaf at a carry/kernel boundary.
- **lock-order** (``lockorder.py``) — ``lock-cycle`` /
  ``lock-inversion``: the cross-class lock-acquisition-order graph
  must stay acyclic.

``python -m corrosion_tpu.analysis [--format text|json] [paths]`` runs
them all and exits nonzero on findings (``--changed <git-ref>`` lints
only touched files; ``--output-json`` writes the CI artifact). Inline
suppressions: ``# corrolint: disable=<rule> -- <reason>`` (the reason
is required).

What static analysis cannot see — "this refactor made the hot path
retrace per call" — is covered by the trace-stability harness
(``tracecount.py``): it jit-wraps the registered hot entry points with a
compile counter and asserts exactly one compilation across
representative re-invocations.
"""

from corrosion_tpu.analysis.base import Finding, RULES
from corrosion_tpu.analysis.runner import (
    ALL_CHECKERS,
    PROJECT_CHECKERS,
    check_source,
    iter_python_files,
    lint_report,
    run_paths,
)

__all__ = [
    "ALL_CHECKERS",
    "PROJECT_CHECKERS",
    "Finding",
    "RULES",
    "check_source",
    "iter_python_files",
    "lint_report",
    "run_paths",
]
