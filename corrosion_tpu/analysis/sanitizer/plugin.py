"""pytest plugin: sanitize a whole test run.

Opt-in via ``--corrosan`` or ``CORROSAN=1`` (the tier-1 command stays
un-instrumented; ``scripts/check.sh`` runs the threaded test modules a
second time under this plugin). One session-wide window opens at
configure time — before test modules import, so module-level locks in
late-imported code are instrumented too — and gates at session finish:

- unsuppressed findings are printed and FAIL the run (exit status 1);
- the run section of the report lands in ``CORROSAN_REPORT`` (default
  ``artifacts/san_r08.json``), alongside the fixture-replay section the
  ``corrosion-tpu san`` CLI writes.
"""

from __future__ import annotations

import os


def pytest_addoption(parser):
    group = parser.getgroup("corrosan")
    group.addoption(
        "--corrosan", action="store_true", default=False,
        help="instrument threading/locks/files with the corrosan "
             "runtime sanitizer and gate the session on its findings",
    )


def _enabled(config) -> bool:
    return bool(config.getoption("--corrosan")
                or os.environ.get("CORROSAN") == "1")


def pytest_configure(config):
    if not _enabled(config):
        return
    from corrosion_tpu.analysis.sanitizer.runtime import Sanitizer

    san = Sanitizer()
    san.install()
    config._corrosan = san


def pytest_sessionfinish(session, exitstatus):
    san = getattr(session.config, "_corrosan", None)
    if san is None:
        return
    session.config._corrosan = None
    san.uninstall()
    findings = san.gate()
    payload = san.report_payload(findings)
    payload["pytest_exitstatus"] = int(exitstatus)
    report_path = os.environ.get("CORROSAN_REPORT",
                                 os.path.join("artifacts", "san_r08.json"))
    from corrosion_tpu.analysis.sanitizer.report import write_section

    write_section(report_path, "pytest", payload)
    print(f"\ncorrosan: {len(payload['witnessed_edges'])} witnessed lock "
          f"edges, {payload['threads_spawned']} threads spawned, "
          f"{len(findings)} finding(s) (report: {report_path})")
    if findings:
        for f in findings:
            print(f"corrosan: {f.render()}")
        session.exitstatus = 1
