"""Thread / executor leak gate.

The reference's shutdown story is ``spawn_counted`` + tripwire: every
task is counted and shutdown waits for all of them. The leak gate is
the test-time enforcement of that story — anything spawned inside the
sanitized window that still runs at the gate either carries an
allow-listed name (``corro-supervised-*``: orphaned-by-design deadline
dispatches) or is a leak. Registrations hold only weakrefs: the gate
must never keep a thread or executor alive itself.
"""

from __future__ import annotations

import weakref
from typing import List, Tuple

from corrosion_tpu.analysis.sanitizer.allowlist import ALLOWED_LEAK_PREFIXES
from corrosion_tpu.analysis.sanitizer.report import SanFinding


class LeakRegistry:
    def __init__(self):
        self._threads: List[Tuple[weakref.ref, str]] = []
        self._executors: List[Tuple[weakref.ref, str]] = []

    def on_thread_start(self, thread, site: str) -> None:
        self._threads.append((weakref.ref(thread), site))

    def on_executor(self, executor, site: str) -> None:
        self._executors.append((weakref.ref(executor), site))

    def spawned_count(self) -> int:
        return len(self._threads)

    def check(self) -> List[SanFinding]:
        findings: List[SanFinding] = []
        for ref, site in self._threads:
            t = ref()
            if t is None or not t.is_alive():
                continue
            name = t.name or "<unnamed>"
            if any(name.startswith(p) for p in ALLOWED_LEAK_PREFIXES):
                continue
            findings.append(SanFinding(
                kind="thread-leak", subject=name,
                message=(
                    "thread spawned in the sanitized window is still "
                    f"alive at the gate (daemon={t.daemon}) — its owner "
                    "never joined/stopped it"
                ),
                site=site,
            ))
        for ref, site in self._executors:
            ex = ref()
            if ex is None:
                continue
            if not getattr(ex, "_shutdown", True):
                findings.append(SanFinding(
                    kind="executor-leak",
                    subject=type(ex).__name__,
                    message="ThreadPoolExecutor was never shut down",
                    site=site,
                ))
        return findings
