"""corrosan: runtime concurrency sanitizer + leak gate.

The dynamic complement to corrolint (ISSUE 8): where the static
checkers prove properties about source text, corrosan *witnesses* one
execution —

- a **vector-clock happens-before race detector** over the
  lock-disciplined classes corrolint indexes (``attrs.py``);
- a **runtime lock-order witness** whose edges must stay a subset of
  ``analysis/lockorder.py``'s static graph (``witness.py``);
- a **filesystem witness** for the unsubscribe-vs-persist resurrection
  bug class (``fsops.py``);
- a **thread / executor / fd leak gate** at teardown (``leaks.py``).

Entry points: ``with sanitized() as san: ...; san.gate()`` for scoped
windows (the tier-1 meta-tests), the pytest plugin (``plugin.py``,
``--corrosan`` / ``CORROSAN=1``) for whole sanitized runs, and
``corrosion-tpu san`` (``__main__.py``) to replay the seeded-race
fixtures into ``artifacts/san_r08.json``.
"""

from corrosion_tpu.analysis.sanitizer.fixtures import (
    FIXTURES,
    FixtureResult,
    run_all_fixtures,
    run_fixture,
)
from corrosion_tpu.analysis.sanitizer.hooks import watch_dir
from corrosion_tpu.analysis.sanitizer.report import (
    KINDS,
    SanFinding,
    findings_payload,
    write_section,
)
from corrosion_tpu.analysis.sanitizer.runtime import Sanitizer, sanitized
from corrosion_tpu.analysis.sanitizer.witness import static_lock_graph

__all__ = [
    "FIXTURES",
    "FixtureResult",
    "KINDS",
    "SanFinding",
    "Sanitizer",
    "findings_payload",
    "run_all_fixtures",
    "run_fixture",
    "sanitized",
    "static_lock_graph",
    "watch_dir",
    "write_section",
]
