"""Runtime lock-order witness, cross-checked against corrolint.

Every instrumented lock acquisition that happens while other
instrumented locks are held records an edge ``held -> acquired``. Locks
are *named* by creation site: when a lock is constructed, the creation
stack is matched against the static lock graph's creation-site map
(``lockorder.build_lock_graph``) — a lock born on the
``self._mu = threading.Lock()`` line of ``pubsub.Matcher`` IS the
static node ``corrosion_tpu.pubsub.Matcher._mu``, so the witnessed
graph and corrolint's static graph share one namespace by construction.
Locks born anywhere else (stdlib queue mutexes, fixture locks) are
anonymous, keyed per-instance.

Gate:

- **subset**: every witnessed edge between two NAMED locks must be an
  edge of the static graph (or carry an ``ALLOWED_LOCK_EDGES`` entry
  with a reason) — a dynamically-created edge static call resolution
  provably cannot see must be argued in, never silently absorbed;
- **cycles**: the union of witnessed edges and static edges must stay
  acyclic (anonymous locks participate per-instance: a witnessed ABBA
  on fixture locks is a cycle even though the subset check cannot see
  it).
"""

from __future__ import annotations

import _thread
import dataclasses
import os
from typing import Dict, Iterator, List, Set, Tuple

from corrosion_tpu.analysis.sanitizer.allowlist import ALLOWED_LOCK_EDGES
from corrosion_tpu.analysis.sanitizer.frames import (
    call_site,
    iter_call_frames,
    realpath_cached,
)
from corrosion_tpu.analysis.sanitizer.report import SanFinding

_GRAPH_CACHE = None


def static_lock_graph():
    """The package's static lock graph (parsed once per process)."""
    global _GRAPH_CACHE
    if _GRAPH_CACHE is None:
        import ast

        import corrosion_tpu
        from corrosion_tpu.analysis.callgraph import (
            ModuleInfo,
            Project,
            module_name_for,
        )
        from corrosion_tpu.analysis.lockorder import build_lock_graph
        from corrosion_tpu.analysis.runner import iter_python_files

        pkg = os.path.dirname(os.path.abspath(corrosion_tpu.__file__))
        modules = []
        for path in iter_python_files([pkg]):
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            try:
                tree = ast.parse(source)
            except SyntaxError:
                continue  # the lint gate owns reporting unparseable files
            modules.append(ModuleInfo(
                path=path, name=module_name_for(path), tree=tree,
                source=source, suppressions={}, bad_suppressions=[],
            ))
        _GRAPH_CACHE = build_lock_graph(Project(modules))
    return _GRAPH_CACHE


@dataclasses.dataclass
class _EdgeRec:
    frm: str
    to: str
    named: bool  # both endpoints are static nodes
    same_node: bool  # same static node, distinct instances
    site: str
    thread: str
    count: int = 1
    # strong refs to anonymous endpoints: their graph key is id(), and
    # letting one die would free its address for a NEW lock to reuse —
    # aliasing a dead lock's edges into phantom cycles. Bounded by the
    # (small) count of distinct witnessed edges.
    anchors: tuple = ()


class LockWitness:
    def __init__(self, san):
        self._san = san
        self._ilock = _thread.allocate_lock()
        self.graph = None  # static LockGraph, set by prepare()
        self._site_map: Dict[Tuple[str, int], object] = {}
        self._edges: Dict[Tuple[object, object], _EdgeRec] = {}

    def prepare(self) -> None:
        self.graph = static_lock_graph()
        for node, (path, line) in self.graph.creation_sites.items():
            self._site_map[(realpath_cached(path), line)] = node

    # --- naming -----------------------------------------------------------
    def name_new_lock(self, lock, kind: str) -> None:
        """Match the creation stack against the static creation-site
        map; first hit names the lock (the ``TrackedLock`` wrapper's
        inner RLock matches the wrapper's own creation line, exactly as
        the static model sees it)."""
        for filename, lineno in iter_call_frames(skip=2):
            node = self._site_map.get((realpath_cached(filename), lineno))
            if node is not None:
                lock.san_node = node
                return
        lock.san_node = None
        lock.san_site = call_site()

    @staticmethod
    def _key(lock):
        node = getattr(lock, "san_node", None)
        if node is not None:
            return node.name
        return id(lock)

    @staticmethod
    def _label(lock) -> str:
        node = getattr(lock, "san_node", None)
        if node is not None:
            return node.name
        site = getattr(lock, "san_site", "") or "?"
        return f"anon:{site}"

    # --- recording --------------------------------------------------------
    def on_edge(self, held: list, lock, st) -> None:
        kb = self._key(lock)
        thread_name = self._san.thread_display_name(st)
        for h in held:
            if h is lock:
                continue
            ka = self._key(h)
            ek = (ka, kb)
            with self._ilock:
                rec = self._edges.get(ek)
                if rec is not None:
                    rec.count += 1
                    continue
                h_named = getattr(h, "san_node", None) is not None
                l_named = getattr(lock, "san_node", None) is not None
                self._edges[ek] = _EdgeRec(
                    frm=self._label(h), to=self._label(lock),
                    named=h_named and l_named,
                    same_node=(ka == kb),
                    site=call_site(), thread=thread_name,
                    anchors=tuple(
                        obj for obj, named in ((h, h_named), (lock, l_named))
                        if not named
                    ),
                )

    # --- gate -------------------------------------------------------------
    def named_edges(self) -> Set[Tuple[str, str]]:
        with self._ilock:
            return {(r.frm, r.to) for r in self._edges.values()
                    if r.named and not r.same_node}

    def edges_payload(self) -> List[dict]:
        static_names = self.graph.edge_names() if self.graph else set()
        with self._ilock:
            return [
                {
                    "from": r.frm, "to": r.to, "count": r.count,
                    "named": r.named, "site": r.site, "thread": r.thread,
                    "in_static": r.named and (r.frm, r.to) in static_names,
                }
                for r in sorted(self._edges.values(),
                                key=lambda r: (r.frm, r.to))
            ]

    def check(self) -> List[SanFinding]:
        findings: List[SanFinding] = []
        static_names = self.graph.edge_names() if self.graph else set()
        with self._ilock:
            recs = list(self._edges.items())
        graph: Dict[object, Set[object]] = {}
        for (ka, kb), rec in recs:
            graph.setdefault(ka, set()).add(kb)
            graph.setdefault(kb, set())
            if not rec.named:
                continue
            if ((rec.frm, rec.to) in static_names
                    or (rec.frm, rec.to) in ALLOWED_LOCK_EDGES):
                continue
            if rec.same_node:
                findings.append(SanFinding(
                    kind="lock-edge-unknown",
                    subject=f"{rec.frm} -> {rec.to}",
                    message=(
                        "two distinct instances of the same lock node "
                        "nested — instance-level ordering the static "
                        "model cannot express; pick an order and "
                        "allow-list it with the argument"
                    ),
                    site=rec.site, thread=rec.thread,
                ))
                continue
            findings.append(SanFinding(
                kind="lock-edge-unknown",
                subject=f"{rec.frm} -> {rec.to}",
                message=(
                    f"witnessed {rec.count}x but absent from "
                    "corrolint's static lock-order graph — a "
                    "dynamically-created edge the static model cannot "
                    "see; teach lockorder.py the path or allow-list "
                    "it with a reason"
                ),
                site=rec.site, thread=rec.thread,
            ))
        # static edges join the cycle search: a witnessed edge that
        # closes a loop AGAINST a static edge is a real ABBA even when
        # each edge alone looks fine
        for (a, b) in static_names:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for cycle in _find_cycles(graph):
            labels = [self._node_label(k) for k in cycle]
            ring = " -> ".join(labels + [labels[0]])
            findings.append(SanFinding(
                kind="lock-cycle", subject=ring,
                message=(
                    "witnessed acquisitions complete a lock cycle — "
                    "two threads taking opposite paths deadlock"
                ),
            ))
        return findings

    def _node_label(self, key) -> str:
        if isinstance(key, str):
            return key
        with self._ilock:
            for (ka, kb), rec in self._edges.items():
                if ka == key:
                    return rec.frm
                if kb == key:
                    return rec.to
        return f"anon:{key}"


def _find_cycles(graph: Dict[object, Set[object]]
                 ) -> Iterator[List[object]]:
    """Elementary cycles of length >= 2, each reported once. Self-loops
    are excluded here — the subset check reports same-node nesting with
    better context."""
    seen: Set[frozenset] = set()
    max_len = len(graph)
    order = sorted(graph, key=repr)

    def dfs(start, node, path):
        for nxt in sorted(graph.get(node, ()), key=repr):
            if nxt == start and len(path) >= 2:
                key = frozenset(path)
                if key not in seen:
                    seen.add(key)
                    yield list(path)
            elif nxt != start and nxt not in path and len(path) < max_len:
                yield from dfs(start, nxt, path + [nxt])

    for node in order:
        yield from dfs(node, node, [node])
