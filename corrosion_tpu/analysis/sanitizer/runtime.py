"""corrosan runtime: instrumentation of the threading surface.

One :class:`Sanitizer` session patches, for its lifetime:

- ``threading.Lock`` / ``threading.RLock`` -> shadowed wrappers that
  carry a vector clock (release publishes the holder's clock, acquire
  joins it — the classic lock-based happens-before edge) and feed the
  lock-order witness. Everything built ON these primitives inside the
  window — ``Condition``, ``Event``, ``Barrier``, ``queue.Queue`` —
  inherits the clocks for free, because the stdlib resolves
  ``threading.Lock`` at call time;
- ``threading.Thread`` -> a subclass that hands the parent's clock to
  the child at ``start()`` (covering ``utils.lifecycle.spawn_counted``
  and every server/worker spawn) and joins the child's final clock back
  on ``join()``;
- ``concurrent.futures.ThreadPoolExecutor`` -> ``submit`` threads the
  submitter's clock into the task (the work queue is a C
  ``SimpleQueue`` the lock patch cannot see);
- ``builtins.open`` / ``os.unlink`` / ``os.remove`` / ``os.replace`` /
  ``os.rename`` -> the filesystem witness, for paths under registered
  watch roots.

Locks/threads that exist BEFORE the window opens keep working
untouched; they simply carry no clocks. That is the safe direction:
the attribute detector only shadows objects born in-window, so missing
history can never masquerade as a race.
"""

from __future__ import annotations

import _thread
import builtins
import concurrent.futures
import concurrent.futures.thread as _cf_thread
import contextlib
import os
import threading
from typing import List, Optional

from corrosion_tpu.analysis.sanitizer import vc as _vc
from corrosion_tpu.analysis.sanitizer.attrs import AttrRaces
from corrosion_tpu.analysis.sanitizer.frames import call_site
from corrosion_tpu.analysis.sanitizer.fsops import FsWitness
from corrosion_tpu.analysis.sanitizer.leaks import LeakRegistry
from corrosion_tpu.analysis.sanitizer.report import (
    SanFinding,
    findings_payload,
)
from corrosion_tpu.analysis.sanitizer.witness import LockWitness

#: originals captured at import — wrappers must reach the real
#: primitives even while the module attributes are patched
_REAL = {
    "allocate": _thread.allocate_lock,
    "Lock": threading.Lock,
    "RLock": threading.RLock,
    "Thread": threading.Thread,
    "Executor": concurrent.futures.ThreadPoolExecutor,
    "open": builtins.open,
    "unlink": os.unlink,
    "remove": os.remove,
    "replace": os.replace,
    "rename": os.rename,
}

_ACTIVE: Optional["Sanitizer"] = None

_tls = threading.local()
_tid_lock = _REAL["allocate"]()
_tid_counter = [0]


class _ThreadState:
    """Per-thread sanitizer state. ``tid`` is sanitizer-assigned and
    never reused (OS thread idents are), ``busy`` breaks reentrancy
    when sanitizer bookkeeping itself touches instrumented surfaces."""

    __slots__ = ("san", "tid", "vc", "held", "busy", "name")

    def __init__(self, san: "Sanitizer"):
        with _tid_lock:
            _tid_counter[0] += 1
            self.tid = _tid_counter[0]
        self.san = san
        self.vc = _vc.fresh(self.tid)
        self.held: list = []
        self.busy = False
        # resolved lazily (see Sanitizer.thread_display_name):
        # threading.current_thread() during thread BOOTSTRAP mints a
        # _DummyThread whose Event acquires an instrumented lock, which
        # would re-enter state creation before _tls.st is assigned —
        # unbounded recursion
        self.name: Optional[str] = None


class SanLock:
    """Drop-in ``threading.Lock`` with a clock and a witness feed."""

    __slots__ = ("_lock", "vc", "san_node", "san_site")

    def __init__(self):
        self._lock = _REAL["allocate"]()
        self.vc = {}
        self.san_node = None
        self.san_site = ""
        san = _ACTIVE
        if san is not None and san.active:
            san.witness.name_new_lock(self, "Lock")

    def acquire(self, blocking=True, timeout=-1):
        rc = self._lock.acquire(blocking, timeout)
        if rc:
            san = _ACTIVE
            if san is not None and san.active:
                san.on_acquire(self)
        return rc

    def release(self):
        san = _ACTIVE
        if san is not None and san.active:
            san.on_release(self)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False


class SanRLock:
    """Drop-in ``threading.RLock`` (the pure-Python ``_RLock`` shape,
    including the ``_release_save`` / ``_acquire_restore`` /
    ``_is_owned`` surface ``threading.Condition`` duck-types against)."""

    __slots__ = ("_block", "_owner", "_count", "vc", "san_node",
                 "san_site")

    def __init__(self):
        self._block = _REAL["allocate"]()
        self._owner = None
        self._count = 0
        self.vc = {}
        self.san_node = None
        self.san_site = ""
        san = _ACTIVE
        if san is not None and san.active:
            san.witness.name_new_lock(self, "RLock")

    def acquire(self, blocking=True, timeout=-1):
        me = _thread.get_ident()
        if self._owner == me:
            self._count += 1
            return 1
        rc = self._block.acquire(blocking, timeout)
        if rc:
            self._owner = me
            self._count = 1
            san = _ACTIVE
            if san is not None and san.active:
                san.on_acquire(self)
        return rc

    def release(self):
        if self._owner != _thread.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if not self._count:
            san = _ACTIVE
            if san is not None and san.active:
                san.on_release(self)
            self._owner = None
            self._block.release()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition integration (threading.Condition duck-types these)
    def _release_save(self):
        if self._count == 0:
            raise RuntimeError("cannot release un-acquired lock")
        state = (self._count, self._owner)
        san = _ACTIVE
        if san is not None and san.active:
            san.on_release(self)
        self._count = 0
        self._owner = None
        self._block.release()
        return state

    def _acquire_restore(self, state):
        self._block.acquire()
        self._count, self._owner = state
        san = _ACTIVE
        if san is not None and san.active:
            san.on_acquire(self)

    def _is_owned(self):
        return self._owner == _thread.get_ident()


class SanThread(_REAL["Thread"]):
    """``threading.Thread`` with clock inheritance + leak tracking."""

    def start(self):
        san = _ACTIVE
        if san is not None and san.active:
            st = san.thread_state()
            parent_clock = dict(st.vc)
            st.vc[st.tid] = st.vc.get(st.tid, 1) + 1
            san.leaks.on_thread_start(self, call_site())
            orig_run = self.run
            me = self

            def _san_run():
                cst = san.thread_state()
                _vc.join(cst.vc, parent_clock)
                try:
                    orig_run()
                finally:
                    me._san_final = dict(cst.vc)

            self.run = _san_run
        super().start()

    def join(self, timeout=None):
        super().join(timeout)
        san = _ACTIVE
        if san is not None and san.active and not self.is_alive():
            final = getattr(self, "_san_final", None)
            if final:
                _vc.join(san.thread_state().vc, final)


class SanExecutor(_REAL["Executor"]):
    """Executor whose ``submit`` threads the submitter's clock through
    the (clock-invisible) C work queue into the task."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        san = _ACTIVE
        if san is not None and san.active:
            san.leaks.on_executor(self, call_site())

    def submit(self, fn, /, *args, **kwargs):
        san = _ACTIVE
        if san is None or not san.active:
            return super().submit(fn, *args, **kwargs)
        st = san.thread_state()
        snapshot = dict(st.vc)
        st.vc[st.tid] = st.vc.get(st.tid, 1) + 1

        def _san_task(*a, **kw):
            cst = san.thread_state()
            _vc.join(cst.vc, snapshot)
            return fn(*a, **kw)

        return super().submit(_san_task, *args, **kwargs)


def _san_open(file, mode="r", *args, **kwargs):
    fobj = _REAL["open"](file, mode, *args, **kwargs)
    san = _ACTIVE
    if san is not None and san.active and isinstance(mode, str):
        san.fs.on_open(file, mode, fobj)
    return fobj


def _san_unlink(path, *args, **kwargs):
    _REAL["unlink"](path, *args, **kwargs)
    san = _ACTIVE
    if san is not None and san.active:
        san.fs.on_delete(path)


def _san_replace(src, dst, *args, **kwargs):
    _REAL["replace"](src, dst, *args, **kwargs)
    san = _ACTIVE
    if san is not None and san.active:
        san.fs.on_replace(src, dst)


def _san_rename(src, dst, *args, **kwargs):
    _REAL["rename"](src, dst, *args, **kwargs)
    san = _ACTIVE
    if san is not None and san.active:
        san.fs.on_replace(src, dst)


class Sanitizer:
    """One sanitized window: install() .. uninstall(), then gate().

    Components: :class:`AttrRaces` (happens-before attribute races),
    :class:`LockWitness` (runtime lock order vs the static graph),
    :class:`FsWitness` (watched-path write/delete ordering + fd leaks),
    :class:`LeakRegistry` (threads/executors)."""

    def __init__(self, watch_roots=()):
        self.active = False
        self.attrs = AttrRaces(self)
        self.witness = LockWitness(self)
        self.fs = FsWitness(self)
        self.leaks = LeakRegistry()
        for root in watch_roots:
            self.fs.watch(root)

    # --- thread state -----------------------------------------------------
    def thread_state(self) -> _ThreadState:
        st = getattr(_tls, "st", None)
        if st is None or st.san is not self:
            st = _ThreadState(self)
            _tls.st = st
        return st

    def thread_display_name(self, st: Optional[_ThreadState] = None) -> str:
        """The current thread's name for reports, resolved lazily (see
        ``_ThreadState.name``). Safe once a state exists: a dummy-thread
        detour through instrumented locks re-enters plumbing that finds
        the EXISTING state and terminates."""
        st = st or self.thread_state()
        if st.name is None:
            if st.busy:
                return f"tid-{st.tid}"  # mid-plumbing: don't recurse
            st.busy = True
            try:
                st.name = threading.current_thread().name
            finally:
                st.busy = False
        return st.name

    # --- clock plumbing (wrappers route here) -----------------------------
    def on_acquire(self, lock) -> None:
        st = self.thread_state()
        _vc.join(st.vc, lock.vc)
        if st.held and not st.busy:
            st.busy = True
            try:
                self.witness.on_edge(st.held, lock, st)
            finally:
                st.busy = False
        st.held.append(lock)

    def on_release(self, lock) -> None:
        st = self.thread_state()
        lock.vc = dict(st.vc)
        st.vc[st.tid] = st.vc.get(st.tid, 1) + 1
        for i in range(len(st.held) - 1, -1, -1):
            if st.held[i] is lock:
                del st.held[i]
                break

    # --- fixture/test seam ------------------------------------------------
    def track(self, cls: type) -> None:
        """Add a class to the race-tracked set (fixtures register toy
        classes; the curated production set installs automatically)."""
        self.attrs.track(cls)

    def watch_dir(self, root) -> None:
        self.fs.watch(root)

    # --- lifecycle --------------------------------------------------------
    def install(self) -> "Sanitizer":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("a corrosan session is already active")
        self.witness.prepare()
        self.attrs.install()
        threading.Lock = SanLock
        threading.RLock = SanRLock
        threading.Thread = SanThread
        concurrent.futures.ThreadPoolExecutor = SanExecutor
        _cf_thread.ThreadPoolExecutor = SanExecutor
        builtins.open = _san_open
        os.unlink = _san_unlink
        os.remove = _san_unlink
        os.replace = _san_replace
        os.rename = _san_rename
        _ACTIVE = self
        self.active = True
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is not self:
            return
        self.active = False
        _ACTIVE = None
        threading.Lock = _REAL["Lock"]
        threading.RLock = _REAL["RLock"]
        threading.Thread = _REAL["Thread"]
        concurrent.futures.ThreadPoolExecutor = _REAL["Executor"]
        _cf_thread.ThreadPoolExecutor = _REAL["Executor"]
        builtins.open = _REAL["open"]
        os.unlink = _REAL["unlink"]
        os.remove = _REAL["remove"]
        os.replace = _REAL["replace"]
        os.rename = _REAL["rename"]
        self.attrs.uninstall()

    # --- gate -------------------------------------------------------------
    def gate(self) -> List[SanFinding]:
        """All unsuppressed findings of this window, every detector."""
        findings = list(self.attrs.findings())
        findings.extend(self.witness.check())
        findings.extend(self.fs.check())
        findings.extend(self.leaks.check())
        return sorted(findings)

    def report_payload(self, findings: Optional[List[SanFinding]] = None
                       ) -> dict:
        """The pytest-section report body. Pass the findings from an
        earlier :meth:`gate` call to keep the printed and serialized
        findings one computation (the detectors re-inspect live state,
        e.g. ``os.path.exists``, so two gates can diverge)."""
        payload = findings_payload(
            self.gate() if findings is None else findings)
        payload["witnessed_edges"] = self.witness.edges_payload()
        payload["threads_spawned"] = self.leaks.spawned_count()
        payload["fs_ops"] = self.fs.ops_payload()
        return payload


@contextlib.contextmanager
def sanitized(watch_roots=()):
    """``with sanitized() as san: ...`` — scoped window; the caller
    gates explicitly (``san.gate()``) after the block.

    Composes with a session-wide window (the ``CORROSAN=1`` pytest
    plugin): an active outer session is suspended for the scope and
    re-installed after, so the sanitizer's own fixture tests can run
    inside a sanitized run. The outer window simply does not observe
    events that happen while it is suspended — its patched classes and
    clocks resume untouched."""
    outer = _ACTIVE
    if outer is not None:
        outer.uninstall()
    san = Sanitizer(watch_roots=watch_roots)
    san.install()
    try:
        yield san
    finally:
        san.uninstall()
        if outer is not None:
            outer.install()
