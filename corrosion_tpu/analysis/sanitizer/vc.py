"""Vector clocks + epochs: the happens-before substrate.

Classic DJIT+/FastTrack bookkeeping, sized for a test-process sanitizer
rather than a production TSan: clocks are plain dicts keyed by a
sanitizer-assigned thread id (NOT ``threading.get_ident()``, which the
OS reuses after a thread dies — a reused ident would alias a dead
thread's epochs onto a fresh thread and invent spurious orderings).

- a **clock** maps tid -> counter;
- an **epoch** ``(tid, c)`` is the cheap record of one event: the
  accessing thread's own counter at access time. ``epoch_before``
  answers "did that event happen-before this thread's present?" with
  one dict lookup, which is all the race detector needs.
"""

from __future__ import annotations

from typing import Dict, Tuple

Clock = Dict[int, int]
Epoch = Tuple[int, int]  # (tid, that thread's counter at the event)


def fresh(tid: int) -> Clock:
    return {tid: 1}


def join(into: Clock, other: Clock) -> None:
    """``into`` |= ``other`` (pointwise max), in place."""
    for tid, c in other.items():
        if into.get(tid, 0) < c:
            into[tid] = c


def epoch_before(epoch: Epoch, clock: Clock) -> bool:
    """True iff the event recorded by ``epoch`` happens-before a thread
    whose current clock is ``clock`` (the standard epoch <= VC check)."""
    tid, c = epoch
    return c <= clock.get(tid, 0)


def clock_before(a: Clock, b: Clock) -> bool:
    """Full-clock ordering: every component of ``a`` is covered by
    ``b``. Used by the filesystem witness, whose rare events keep whole
    snapshots instead of epochs."""
    return all(c <= b.get(tid, 0) for tid, c in a.items())


def concurrent(a: Clock, b: Clock) -> bool:
    return not clock_before(a, b) and not clock_before(b, a)
