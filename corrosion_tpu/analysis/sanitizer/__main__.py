"""``corrosion-tpu san`` / ``python -m corrosion_tpu.analysis.sanitizer``.

Replays the seeded-race/leak fixtures (``fixtures.py``) — each in its
own sanitized window — and reports per-fixture verdicts. Exit 1 when
any fixture misbehaves: a seeded bug the sanitizer missed is a false
negative (the detector rotted), a clean twin it flagged is a false
positive (the detector lies). ``--output-json`` lands the verdicts in
the shared corrosan report artifact next to the sanitized pytest
section.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="corrosion-tpu san",
        description="corrosan fixture replay: seeded concurrency bugs "
                    "the runtime sanitizer must detect",
    )
    parser.add_argument("fixtures", nargs="*", default=None,
                        help="fixture names (default: all)")
    parser.add_argument("--list-fixtures", action="store_true",
                        help="list fixtures and expected findings")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument("--output-json", metavar="PATH", default=None,
                        help="write the fixtures section of the corrosan "
                             "report artifact")
    args = parser.parse_args(argv)

    from corrosion_tpu.analysis.sanitizer.fixtures import (
        FIXTURES,
        run_all_fixtures,
    )

    if args.list_fixtures:
        for name, (_fn, expect, doc) in sorted(FIXTURES.items()):
            want = ", ".join(expect) if expect else "clean"
            print(f"{name}: {doc} [expects: {want}]")
        return 0

    results = run_all_fixtures(args.fixtures or None)
    ok = all(r.ok for r in results)
    payload = {
        "results": [r.to_json() for r in results],
        "ok": ok,
    }
    if args.output_json:
        from corrosion_tpu.analysis.sanitizer.report import write_section

        write_section(args.output_json, "fixtures", payload)
    if args.format == "json":
        json.dump(payload, sys.stdout, indent=2)
        print()
    else:
        for r in results:
            verdict = "ok" if r.ok else "FAIL"
            want = ", ".join(r.expect) if r.expect else "clean"
            got = ", ".join(r.found) if r.found else "clean"
            print(f"{verdict}: {r.name} (expected {want}; got {got})")
            if not r.ok:
                for line in r.details:
                    print(f"    {line}")
        print("corrosan fixtures: "
              + ("all verdicts correct" if ok else "VERDICT MISMATCH"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
