"""Happens-before race detection on shared attributes.

FastTrack-lite over the lock-disciplined classes corrolint already
indexes: every instance attribute of a tracked class carries shadow
state — the last write as an epoch ``(tid, clock)`` plus a read map
``tid -> clock`` — and every access checks the other side's epochs
against the accessing thread's vector clock. Two accesses with at
least one write and no happens-before path between them is a race
finding; accesses ordered through ANY instrumented synchronization
(locks, conditions, events, queues, thread start/join, executor
submit) are clean by construction, so the detector needs no lockset
heuristics and no knowledge of WHICH lock guards what.

Only objects *born inside* the sanitized window are tracked
(``__init__`` is patched to register them): a pre-existing object's
synchronization history is invisible, and shadowing it would turn
missing-history into fake races.

Sanctioned unsynchronized sites (GIL-atomic counters, single-reference
swaps) live in ``allowlist.ALLOWED_ATTR_RACES`` with reasons — the
runtime mirror of corrolint's ``unlocked-mutation`` suppressions.
"""

from __future__ import annotations

import _thread
import importlib
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.analysis.sanitizer.allowlist import ALLOWED_ATTR_RACES
from corrosion_tpu.analysis.sanitizer.frames import call_site
from corrosion_tpu.analysis.sanitizer.report import SanFinding

#: the lock-disciplined surface corrolint's lock checkers index — the
#: classes whose shared state PRs 5/6 already argued about statically
TRACKED_CLASSES: Dict[str, Tuple[str, ...]] = {
    "corrosion_tpu.pubsub": (
        "DeltaTracker", "Matcher", "SubsManager", "UpdatesManager",
    ),
    "corrosion_tpu.db.database": ("Database",),
    "corrosion_tpu.resilience.async_ckpt": ("AsyncCheckpointWriter",),
    "corrosion_tpu.resilience.supervisor": ("Supervisor",),
    "corrosion_tpu.agent.core": ("Agent",),
    "corrosion_tpu.utils.hlc": ("HLClock",),
    "corrosion_tpu.utils.metrics": ("Registry",),
}

#: attribute VALUES that are synchronization objects — reading the
#: attribute that holds a lock/queue is not a data access on shared
#: state (the primitive orders its own users)
_SYNC_TYPE_NAMES = frozenset({
    "SanLock", "SanRLock", "TrackedLock", "Condition", "Event",
    "Barrier", "Semaphore", "BoundedSemaphore", "Tripwire",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "SubQueue",
    "lock", "RLock", "_RLock", "LockRegistry",
})


class _Cell:
    __slots__ = ("write", "reads")

    def __init__(self):
        self.write: Optional[Tuple[int, int, str]] = None  # tid, clock, thread name
        self.reads: Dict[int, Tuple[int, str]] = {}


class AttrRaces:
    def __init__(self, san):
        self._san = san
        self._ilock = _thread.allocate_lock()
        self._shadow: Dict[Tuple[int, str], _Cell] = {}
        #: oid -> its shadow keys, so purging a dead object is
        #: O(its attrs) instead of an O(shadow) scan under _ilock
        self._keys_by_oid: Dict[int, set] = {}
        self._born: set = set()  # id() of objects constructed in-window
        self._dead: deque = deque()  # ids whose finalizer ran (GC-safe)
        self._findings: Dict[Tuple[str, str, str], SanFinding] = {}
        self._patched: List[Tuple[type, dict]] = []

    # --- class patching ---------------------------------------------------
    def install(self) -> None:
        for mod_name, class_names in TRACKED_CLASSES.items():
            mod = importlib.import_module(mod_name)
            for cls_name in class_names:
                self.track(getattr(mod, cls_name))

    def uninstall(self) -> None:
        for cls, originals in reversed(self._patched):
            for name, fn in originals.items():
                setattr(cls, name, fn)
        self._patched.clear()

    def track(self, cls: type) -> None:
        """Instrument one class (also the fixture seam: seeded-race
        fixtures register their toy classes here)."""
        if any(c is cls for c, _ in self._patched):
            return
        originals = {
            "__init__": cls.__init__,
            "__setattr__": cls.__setattr__,
            "__getattribute__": cls.__getattribute__,
        }
        tracker = self
        orig_init = originals["__init__"]
        orig_set = originals["__setattr__"]
        orig_get = originals["__getattribute__"]

        def __init__(obj, *args, **kwargs):
            tracker._register(obj)
            orig_init(obj, *args, **kwargs)

        def __setattr__(obj, name, value):
            orig_set(obj, name, value)
            if name[:2] != "__":
                tracker._on_access(obj, name, value, True)

        def __getattribute__(obj, name):
            value = orig_get(obj, name)
            if name[:2] != "__":
                tracker._on_access(obj, name, value, False)
            return value

        cls.__init__ = __init__
        cls.__setattr__ = __setattr__
        cls.__getattribute__ = __getattribute__
        self._patched.append((cls, originals))

    # --- shadow state -----------------------------------------------------
    def _register(self, obj) -> None:
        oid = id(obj)
        with self._ilock:
            # purge FIRST: a dead object's address can be recycled for
            # this very allocation — without the purge its stale id
            # would make the early-return skip registration (and leave
            # the corpse's shadow epochs to alias the newborn's)
            self._purge_dead()
            if oid in self._born:
                return
            self._born.add(oid)
        try:
            # the finalizer may fire mid-GC on a thread holding _ilock:
            # it must only do a lock-free append; the gate purges later
            weakref.finalize(obj, self._dead.append, oid)
        except TypeError:
            pass

    def _purge_dead(self) -> None:
        """Callers hold ``_ilock``."""
        while self._dead:
            try:
                oid = self._dead.popleft()
            except IndexError:
                return
            self._born.discard(oid)
            for key in self._keys_by_oid.pop(oid, ()):
                self._shadow.pop(key, None)

    def _allowed(self, obj_type: type, name: str) -> bool:
        return any((klass.__name__, name) in ALLOWED_ATTR_RACES
                   for klass in obj_type.__mro__)

    def _on_access(self, obj, name: str, value, is_write: bool) -> None:
        san = self._san
        if not san.active:
            return
        st = san.thread_state()
        if st.busy:
            return
        myname = san.thread_display_name(st)
        if not is_write and (
                callable(value)
                or type(value).__name__ in _SYNC_TYPE_NAMES):
            return
        st.busy = True
        try:
            oid = id(obj)
            if oid not in self._born:
                return
            obj_type = type(obj)
            if self._allowed(obj_type, name):
                return
            my_clock = st.vc.get(st.tid, 1)
            key = (oid, name)
            with self._ilock:
                self._purge_dead()
                cell = self._shadow.get(key)
                if cell is None:
                    cell = _Cell()
                    self._shadow[key] = cell
                    self._keys_by_oid.setdefault(oid, set()).add(key)
                w = cell.write
                if (w is not None and w[0] != st.tid
                        and not w[1] <= st.vc.get(w[0], 0)):
                    self._race(obj_type.__name__, name,
                               "write" if is_write else "read",
                               w[2], myname)
                if is_write:
                    for rtid, (rclock, rname) in cell.reads.items():
                        if (rtid != st.tid
                                and not rclock <= st.vc.get(rtid, 0)):
                            self._race(obj_type.__name__, name,
                                       "write", rname, myname,
                                       prior_kind="read")
                    cell.write = (st.tid, my_clock, myname)
                    cell.reads = {}
                else:
                    cell.reads[st.tid] = (my_clock, myname)
        finally:
            st.busy = False

    def _race(self, cls_name: str, attr: str, kind: str,
              other_thread: str, this_thread: str,
              prior_kind: str = "write") -> None:
        key = (cls_name, attr, kind)
        if key in self._findings:
            return
        self._findings[key] = SanFinding(
            kind="attr-race", subject=f"{cls_name}.{attr}",
            message=(
                f"unsynchronized {prior_kind} by {other_thread} races "
                f"this {kind} — no happens-before path orders them"
            ),
            site=call_site(), thread=this_thread,
        )

    def findings(self) -> List[SanFinding]:
        with self._ilock:
            return list(self._findings.values())
